"""Remote byte sources: HTTP(S) range requests as a first-class
:class:`~parquet_tpu.io.source.Source` (ROADMAP item 1 — every real
serving fleet reads from an object store, not local disk).

``as_source`` resolves ``http(s)://`` URLs here, so ``ParquetFile(url)``
and ``Dataset([url, ...])`` compose with the ENTIRE existing stack
unchanged: :class:`~parquet_tpu.io.prefetch.PrefetchSource` coalesced
readahead (the auto policy rings remote chains even on one core — network
latency hides behind decode regardless of CPU count), the scan planner,
the batched lookup path, the footer/chunk/page cache tiers (keyed on the
object's HEAD validators instead of fstat), per-op scopes, and the
resource ledger.  Around the transport sits the fault envelope that makes
a network source trustworthy enough to serve from:

- **Classification** — every failure surfaces as a
  :class:`~parquet_tpu.errors.RemoteError` carrying host / status /
  attempt / byte-range context, split retryable (connect refused/reset,
  5xx, 429 with ``Retry-After`` honored, truncated body, stall) from
  terminal (other 4xx, range-not-satisfiable).  The shared retry loop
  (:func:`~parquet_tpu.io.faults.retry_call`) consults the class, so
  :class:`~parquet_tpu.io.faults.FaultPolicy` retries/backoff/deadlines
  and ``on_corrupt='skip_row_group'`` degraded reads work unchanged and
  account in :class:`~parquet_tpu.io.faults.ReadReport`.
- **Hedged reads** — after an adaptive percentile-based delay (p95 of the
  observed ``remote.pread_s`` distribution; ``PARQUET_TPU_REMOTE_HEDGE``
  pins seconds or disables), a second attempt races the first,
  first-success-wins, the loser abandoned.  Hedge bytes are charged to
  the ``remote.hedge_in_flight`` ledger account and admitted through the
  unified ``PARQUET_TPU_READ_BUDGET`` gate like any other in-flight
  bytes.  The hedged wait loop honors the active operation deadline
  (:func:`~parquet_tpu.io.faults.active_deadline`), so a stalled primary
  cannot run past ``deadline_s``.
- **Per-host circuit breaker** — ``PARQUET_TPU_REMOTE_BREAKER``
  consecutive failures open the host's circuit: requests fail fast
  (:class:`~parquet_tpu.errors.RemoteCircuitOpenError`, retryable — the
  policy's backoff is the pause the breaker wants) without touching the
  network until the cooldown's half-open probe closes it again.  Breakers
  are per host, so one dead endpoint never blocks the healthy-host files
  of a multi-file ``Dataset``.  Transitions are metered
  (``remote.breaker_transitions{state=...}``).

The chaos side — :class:`~parquet_tpu.io.faults.
FaultInjectingRemoteTransport` and the hermetic
:class:`~parquet_tpu.io.faults.LocalRangeServer` — lives in io/faults.py
with the rest of the injection machinery.
"""

from __future__ import annotations

import re
import socket
import threading
import time
from collections import OrderedDict
from http.client import HTTPConnection, HTTPException, HTTPSConnection
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from ..utils import locks as _locks
from ..utils.env import env_float, env_int, env_str
from ..utils.locks import make_condition, make_lock
from ..errors import (DeadlineError, RemoteCircuitOpenError, RemoteError,
                      RemoteTerminalError, RemoteThrottledError,
                      RemoteTransientError)
from ..obs.ledger import ledger_account
from ..obs.metrics import counter as _counter
from ..obs.metrics import histogram as _histogram
from ..obs.scope import account as _account
from ..obs.scope import account_bytes as _account_bytes
from ..utils.pool import read_admission
from .source import Source, _check_read_args

__all__ = ["HttpSource", "ObjectStoreSource", "HttpTransport",
           "CircuitBreaker", "breaker_for", "breakers", "reset_breakers",
           "remote_debug", "hedge_delay_s", "observed_pread_ewma",
           "drain_connection_pools", "parallel_preads",
           "parallel_pread_slots", "register_auth_hook",
           "unregister_auth_hook", "list_prefix", "list_prefix_s3",
           "resolve_s3_url", "s3_endpoint", "classify_status",
           "gunzip_body"]

# resolved once: the pread hot path must not take the registry's
# get-or-create lock (only each metric's own)
_M_PREADS = _counter("remote.preads")
_M_BYTES = _counter("remote.bytes")
_M_HEDGES = _counter("remote.hedges_issued")
_M_HEDGES_WON = _counter("remote.hedges_won")
_M_FAIL_FAST = _counter("remote.breaker_fail_fast")
_M_VALIDATOR_CHANGES = _counter("remote.validator_changes")
_M_PARALLEL_PREADS = _counter("remote.parallel_preads")
_M_AUTH_REFRESHES = _counter("remote.auth_refreshes")
_M_ERRORS = {c: _counter("remote.errors", labels={"class": c})
             for c in ("retryable", "terminal", "throttled")}
_M_TRANSITIONS = {s: _counter("remote.breaker_transitions",
                              labels={"state": s})
                  for s in ("open", "half_open", "closed")}
_H_PREAD_S = _histogram("remote.pread_s")

# hedge bytes in flight: the duplicate copy a hedged read stages while
# both attempts race — added when a hedge attempt starts, released when
# it finishes (win, lose, or abandoned), so the account provably drains
# to 0 (the acceptance hammer asserts it)
_ACC_HEDGE = ledger_account("remote.hedge_in_flight")

_CONTENT_RANGE = re.compile(r"bytes\s+(\d+)-(\d+)/(\d+|\*)")

# pool size / timeout defaults live in the knob registry
# hedging before the latency distribution has warmed: a flat default
# (observed p95 takes over after _HEDGE_WARMUP_COUNT preads)
DEFAULT_HEDGE_DELAY_S = 0.05
_HEDGE_WARMUP_COUNT = 16
_HEDGE_MIN_S = 0.002
_HEDGE_MAX_S = 2.0
# observed-EWMA boundary between the two remote latency classes the
# prefetch auto-tuner keys on (io/prefetch.py _CLASS_DEFAULTS)
_FAR_LATENCY_S = 0.03


# ---------------------------------------------------------------------------
# Transport: persistent-connection range requests over http.client
# ---------------------------------------------------------------------------
class _HostPool:
    """Idle persistent connections to ONE (scheme, host) — shared by
    every transport to that host, so a ``Dataset`` over a thousand URLs
    on one endpoint reuses a handful of sockets instead of paying a TCP
    (+TLS) handshake per file.  Bounded: returns past ``cap`` close."""

    def __init__(self, cap: int):
        self.cap = cap
        self._lock = make_lock("remote.host_pool")
        self._idle: List = []

    def get(self):
        with self._lock:
            return self._idle.pop() if self._idle else None

    def put(self, conn) -> None:
        with self._lock:
            if len(self._idle) < self.cap:
                self._idle.append(conn)
                return
        conn.close()

    def drain(self) -> int:
        with self._lock:
            conns, self._idle = self._idle, []
        for c in conns:
            c.close()
        return len(conns)

    def __len__(self) -> int:
        with self._lock:
            return len(self._idle)


_POOLS: Dict[tuple, _HostPool] = {}
_POOLS_LOCK = make_lock("remote.pools_registry")


def _host_pool(scheme: str, host: str, timeout_s: float,
               cap: int) -> _HostPool:
    key = (scheme, host, timeout_s)
    with _POOLS_LOCK:
        pool = _POOLS.get(key)
        if pool is None:
            pool = _POOLS[key] = _HostPool(cap)
        return pool


def drain_connection_pools() -> int:
    """Close every idle pooled connection (tests, clean shutdown);
    returns the number closed.  In-flight requests are unaffected."""
    with _POOLS_LOCK:
        pools = list(_POOLS.values())
    return sum(p.drain() for p in pools)


class HttpTransport:
    """Raw ranged HTTP over stdlib ``http.client`` with a small per-host
    pool of persistent connections (``PARQUET_TPU_REMOTE_POOL``, default
    4; shared across every transport to the same scheme+host): concurrent
    preads — pool workers, prefetch window fills, hedge threads — each
    check out their own connection, and completed requests return it for
    reuse instead of paying a TCP (+TLS) handshake per range.
    ``timeout_s`` (``PARQUET_TPU_REMOTE_TIMEOUT``, default 30) bounds
    every socket operation — the stall detector: a hung server surfaces
    as ``socket.timeout``, classified retryable.  A POOLED connection the
    server idled out (keep-alive timeout) fails its first reuse with a
    reset/closed error — those retry transparently on a fresh connection
    (bounded by the pool depth; timeouts are NOT stale-retried: a stall
    is real signal and retrying would silently double it).

    Beyond that one stale-reuse retry the transport is mechanism only: no
    classification, no policy retries, no hedging — it returns
    ``(status, lowercase-header dict, body)`` or raises the underlying
    ``OSError``.  :class:`HttpSource` owns policy.  The chaos injector
    (:class:`~parquet_tpu.io.faults.FaultInjectingRemoteTransport`)
    wraps this interface."""

    def __init__(self, url: str, pool_size: Optional[int] = None,
                 timeout_s: Optional[float] = None):
        parts = urlsplit(url)
        if parts.scheme not in ("http", "https"):
            raise ValueError(f"HttpTransport needs an http(s) URL, "
                             f"got {url!r}")
        if not parts.netloc:
            raise ValueError(f"URL {url!r} has no host")
        self.url = url
        self.host = parts.netloc
        self._scheme = parts.scheme
        self._request_path = parts.path or "/"
        if parts.query:
            self._request_path += "?" + parts.query
        self.pool_size = (pool_size if pool_size is not None
                          else env_int("PARQUET_TPU_REMOTE_POOL"))
        self.timeout_s = (timeout_s if timeout_s is not None
                          else env_float("PARQUET_TPU_REMOTE_TIMEOUT"))
        self._pool = _host_pool(parts.scheme, parts.netloc, self.timeout_s,
                                self.pool_size)
        self._closed = False

    def _new_conn(self):
        cls = HTTPSConnection if self._scheme == "https" else HTTPConnection
        return cls(self.host, timeout=self.timeout_s)

    def _checkout(self):
        """-> (conn, reused): ``reused`` marks a pooled keep-alive
        connection, eligible for the stale-reuse retry."""
        if self._closed:
            raise ValueError(f"request on closed transport {self.url!r}")
        conn = self._pool.get()
        if conn is not None:
            return conn, True
        return self._new_conn(), False

    def _roundtrip(self, method: str,
                   headers: Optional[dict] = None,
                   path_override: Optional[str] = None,
                   body: Optional[bytes] = None
                   ) -> Tuple[int, Dict[str, str], bytes]:
        path = self._request_path if path_override is None \
            else path_override
        while True:
            conn, reused = self._checkout()
            try:
                conn.request(method, path, body=body,
                             headers=headers or {})
                resp = conn.getresponse()
                status = resp.status
                hdrs = {k.lower(): v for k, v in resp.getheaders()}
                body = resp.read()  # drain fully: a half-read response
                # poisons the persistent connection for the next request
                reusable = not resp.will_close
            except (socket.timeout,):
                conn.close()
                raise  # a stall is signal, never a stale-conn artifact
            except (HTTPException, OSError):
                conn.close()
                if reused:
                    # the server idled this keep-alive connection out
                    # between requests: not a host failure — retry once
                    # per stale conn on a fresh (or next pooled) one
                    continue
                raise
            except BaseException:
                conn.close()
                raise
            if reusable:
                self._pool.put(conn)
            else:
                conn.close()
            return status, hdrs, body

    def head(self, extra_headers: Optional[dict] = None,
             path_override: Optional[str] = None
             ) -> Tuple[int, Dict[str, str]]:
        status, hdrs, _ = self._roundtrip("HEAD", dict(extra_headers or {}),
                                          path_override)
        return status, hdrs

    def get_range(self, offset: int, size: int,
                  extra_headers: Optional[dict] = None,
                  path_override: Optional[str] = None
                  ) -> Tuple[int, Dict[str, str], bytes]:
        headers = dict(extra_headers or {})
        headers["Range"] = f"bytes={offset}-{offset + size - 1}"
        return self._roundtrip("GET", headers, path_override)

    def post(self, path: str, body: bytes,
             extra_headers: Optional[dict] = None
             ) -> Tuple[int, Dict[str, str], bytes]:
        """POST ``body`` to ``path`` on this transport's host (the fleet
        peer-protocol verb).  Same pooling/stale-reuse mechanics as the
        range GETs; safe here because every peer sub-request is
        idempotent (reads, or version-conditional commits)."""
        headers = dict(extra_headers or {})
        headers.setdefault("Content-Type", "application/json")
        headers["Content-Length"] = str(len(body))
        return self._roundtrip("POST", headers, path_override=path,
                               body=body)

    def idle_connections(self) -> int:
        return len(self._pool)

    def close(self) -> None:
        # the idle pool is host-shared (other transports ride it); this
        # transport just stops issuing — pooled sockets stay for others
        self._closed = True


# ---------------------------------------------------------------------------
# Per-host circuit breaker
# ---------------------------------------------------------------------------
def breaker_threshold() -> int:
    """``PARQUET_TPU_REMOTE_BREAKER``: consecutive failures that open a
    host's circuit (default 5; ``0`` disables breaking).  Read per check
    so tests and operators can repoint it live."""
    return env_int("PARQUET_TPU_REMOTE_BREAKER")


def breaker_cooldown_s() -> float:
    """``PARQUET_TPU_REMOTE_BREAKER_COOLDOWN``: seconds an open circuit
    waits before admitting one half-open probe (default 1.0)."""
    return env_float("PARQUET_TPU_REMOTE_BREAKER_COOLDOWN")


class CircuitBreaker:
    """Consecutive-failure circuit breaker for ONE remote host.

    ``closed`` (healthy) → ``open`` after ``breaker_threshold()``
    consecutive connection-class failures: requests fail fast with
    :class:`~parquet_tpu.errors.RemoteCircuitOpenError`, touching no
    network, until ``breaker_cooldown_s()`` elapses → ``half_open``: ONE
    probe request goes through; success closes the circuit, failure
    re-opens it (fresh cooldown).  Only connection-class failures count
    (refused/reset/timeout/5xx): a 4xx or 429 — or a transient BODY
    fault on an answering host (truncation, wrong range) — proves the
    host is reachable, so those leave the streak alone.  Every
    transition lands in ``remote.breaker_transitions{state=...}``."""

    def __init__(self, host: str):
        self.host = host
        self._lock = make_lock("remote.breaker")
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_started_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._failures

    def _transition(self, new: str) -> None:
        # under self._lock
        self._state = new
        _account(_M_TRANSITIONS[new])

    def allow(self) -> bool:
        """May a request proceed right now?  Open circuits refuse until
        the cooldown, then admit exactly one half-open probe at a time."""
        if breaker_threshold() <= 0:
            return True
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if time.monotonic() - self._opened_at \
                        < breaker_cooldown_s():
                    return False
                self._transition("half_open")
                self._probe_in_flight = False
            # half_open: one probe in flight at a time.  A probe whose
            # outcome never reported (throttled, deadline-killed, caller
            # died) must not wedge the host fail-fast forever: the probe
            # LEASE expires after one cooldown and the next request may
            # probe again.
            if self._probe_in_flight and (time.monotonic()
                                          - self._probe_started_at
                                          < breaker_cooldown_s()):
                return False
            self._probe_in_flight = True
            self._probe_started_at = time.monotonic()
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_in_flight = False
            if self._state != "closed":
                self._transition("closed")

    def record_inconclusive(self) -> None:
        """The request finished with an outcome that proves nothing about
        host health (429, a deadline that fired mid-race): release the
        half-open probe slot without moving the failure streak or the
        state — the next request may probe immediately."""
        with self._lock:
            self._probe_in_flight = False

    def record_failure(self) -> None:
        threshold = breaker_threshold()
        if threshold <= 0:
            return
        with self._lock:
            self._failures += 1
            self._probe_in_flight = False
            if self._state == "half_open" or (
                    self._state == "closed"
                    and self._failures >= threshold):
                self._transition("open")
                self._opened_at = time.monotonic()


_BREAKERS: Dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = make_lock("remote.breakers_registry")


def breaker_for(host: str) -> CircuitBreaker:
    """The process-wide breaker for ``host`` (every HttpSource to the
    same host shares one — host health is host-scoped, not per-file)."""
    with _BREAKERS_LOCK:
        b = _BREAKERS.get(host)
        if b is None:
            b = _BREAKERS[host] = CircuitBreaker(host)
        return b


def breakers() -> Dict[str, CircuitBreaker]:
    """Snapshot of every known host breaker (the /debugz view)."""
    with _BREAKERS_LOCK:
        return dict(_BREAKERS)


def reset_breakers() -> None:
    """Forget every host breaker (test isolation)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()


# ---------------------------------------------------------------------------
# Observed latency (hedge-delay seeding + prefetch latency class)
# ---------------------------------------------------------------------------
_LAT_LOCK = make_lock("remote.latency_ewma")
_LAT_EWMA: Dict[str, float] = {}  # host -> EWMA seconds


def _observe_pread(seconds: float, host: str) -> None:
    _H_PREAD_S.observe(seconds)
    with _LAT_LOCK:
        prev = _LAT_EWMA.get(host)
        _LAT_EWMA[host] = (seconds if prev is None
                           else 0.2 * seconds + 0.8 * prev)


def observed_pread_ewma(host: str) -> Optional[float]:
    """EWMA of successful pread seconds to ``host`` (None before the
    first) — what the prefetch auto-tuner's latency-class split and
    /debugz read.  Per HOST, not process-wide: one far bucket must not
    reclassify a near cache's chains as ``remote_far``."""
    with _LAT_LOCK:
        return _LAT_EWMA.get(host)


def _reset_latency() -> None:
    """Test isolation: forget the observed latency state."""
    with _LAT_LOCK:
        _LAT_EWMA.clear()


def hedge_delay_s() -> Optional[float]:
    """Delay before a pread's second (hedged) attempt launches, or None
    when hedging is off.  ``PARQUET_TPU_REMOTE_HEDGE``: ``0``/``off``
    disables, a float pins the delay in seconds, unset/``auto`` adapts —
    the p95 of the observed ``remote.pread_s`` distribution (clamped to
    [2ms, 2s]; a flat 50ms until enough preads have been observed), so
    hedges fire exactly at the measured tail, not on a guess."""
    mode = env_str("PARQUET_TPU_REMOTE_HEDGE").lower()
    if mode in ("0", "off", "false", "no"):
        return None
    if mode not in ("", "1", "auto"):
        try:
            return max(0.0, float(mode))
        except ValueError:
            pass
    if _H_PREAD_S.count < _HEDGE_WARMUP_COUNT:
        return DEFAULT_HEDGE_DELAY_S
    p95 = _H_PREAD_S.percentile(0.95)
    if p95 is None:
        return DEFAULT_HEDGE_DELAY_S
    return min(max(p95, _HEDGE_MIN_S), _HEDGE_MAX_S)


# ---------------------------------------------------------------------------
# Auth hooks (private buckets: per-host header callbacks / presign)
# ---------------------------------------------------------------------------
# prefix -> hook; longest matching prefix wins, so one registration can
# cover a whole bucket ("https://bucket.example/") while a narrower one
# overrides a path below it
_AUTH_HOOKS: Dict[str, object] = {}
_AUTH_HOOKS_LOCK = make_lock("remote.auth_hooks")


def register_auth_hook(url_prefix: str, hook) -> None:
    """Authenticate every :class:`HttpSource` whose URL starts with
    ``url_prefix``: ``hook(url, refresh)`` is called before requests
    (``refresh=False``, result cached per source) and again with
    ``refresh=True`` when the server answers 401/403 — up to
    ``PARQUET_TPU_REMOTE_AUTH_RETRY`` refreshes per request, metered as
    ``remote.auth_refreshes``.  The hook returns a header dict (e.g.
    ``{"Authorization": "Bearer ..."}``); a ``"url"`` key instead
    re-targets the request to that (presigned) URL on the same host.
    A per-source ``HttpSource(auth=...)`` callback overrides the
    registry."""
    if not callable(hook):
        raise TypeError("auth hook must be callable(url, refresh)")
    with _AUTH_HOOKS_LOCK:
        _AUTH_HOOKS[url_prefix] = hook


def unregister_auth_hook(url_prefix: str) -> None:
    with _AUTH_HOOKS_LOCK:
        _AUTH_HOOKS.pop(url_prefix, None)


def _auth_hook_for(url: str):
    with _AUTH_HOOKS_LOCK:
        best = None
        for prefix, hook in _AUTH_HOOKS.items():
            if url.startswith(prefix) and (best is None
                                           or len(prefix) > len(best[0])):
                best = (prefix, hook)
        return best[1] if best else None


def _reset_auth_hooks() -> None:
    """Test isolation: forget every registered auth hook."""
    with _AUTH_HOOKS_LOCK:
        _AUTH_HOOKS.clear()


def auth_refresh_attempts() -> int:
    """``PARQUET_TPU_REMOTE_AUTH_RETRY``: credential refreshes attempted
    per request on 401/403 before the error surfaces (default 1)."""
    return max(0, env_int("PARQUET_TPU_REMOTE_AUTH_RETRY"))


# ---------------------------------------------------------------------------
# Validator bookkeeping (remote cache identity)
# ---------------------------------------------------------------------------
_VALIDATOR_CAP = 4096  # tiny entries, but a rolling-partition fleet
# opens ever-new URLs forever: the memo must be bounded, like any tier
_VALIDATORS: "OrderedDict[str, tuple]" = OrderedDict()
_VALIDATORS_LOCK = make_lock("remote.validators")


def _note_validator(url: str, validator: tuple) -> None:
    """Record the object's HEAD validator; a CHANGED validator means the
    remote object was rewritten — every cached footer/chunk/page/memo
    entry of the url drops through the existing invalidate machinery
    (the remote analog of the path sinks' invalidate-on-commit).
    LRU-bounded: an evicted url just loses change *detection* until its
    next open — its cache entries are still guarded by the validator-
    keyed ``stat_key``, so stale bytes can never serve, exactly like a
    footer falling out of the footer LRU."""
    with _VALIDATORS_LOCK:
        old = _VALIDATORS.pop(url, None)
        _VALIDATORS[url] = validator
        while len(_VALIDATORS) > _VALIDATOR_CAP:
            _VALIDATORS.popitem(last=False)
    if old is not None and old != validator:
        from .cache import invalidate_path  # deferred: cache is heavier

        _account(_M_VALIDATOR_CHANGES)
        invalidate_path(url)


def _reset_validators() -> None:
    """Test isolation: forget every remembered validator."""
    with _VALIDATORS_LOCK:
        _VALIDATORS.clear()


# ---------------------------------------------------------------------------
# The source
# ---------------------------------------------------------------------------
class HttpSource(Source):
    """A remote object over HTTP range requests — ``as_source`` builds one
    for every ``http(s)://`` open, so the whole read stack composes (see
    module docstring).

    Construction performs a HEAD (with a small internal transient-retry:
    opens happen before any :class:`~parquet_tpu.io.faults.PolicySource`
    wraps the source) to learn ``Content-Length`` and the cache
    validators: ``stat_key`` is ``(url, etag, last_modified, size)`` —
    the remote analog of the local fstat identity, so the shared
    footer/chunk/page caches serve hot re-opens with zero network
    requests beyond the per-open HEAD.  Objects whose server sends
    neither validator get ``stat_key=None`` (never cached: identity
    would be a guess), as does any source built over a non-plain
    transport (chaos injectors may transform bytes — they must never
    populate shared caches).

    Every pread consults the host's :class:`CircuitBreaker`, races a
    hedged second attempt after :func:`hedge_delay_s` (budget-gated and
    ledger-charged), classifies failures into the
    :class:`~parquet_tpu.errors.RemoteError` hierarchy, and accounts
    ``remote.preads`` / ``remote.bytes`` / ``remote.pread_s`` plus the
    terminal-source ``read.bytes_read``."""

    def __init__(self, url: str, transport=None,
                 pool_size: Optional[int] = None,
                 timeout_s: Optional[float] = None, auth=None):
        self.url = url
        self._transport = (transport if transport is not None
                           else HttpTransport(url, pool_size=pool_size,
                                              timeout_s=timeout_s))
        self.host = (getattr(self._transport, "host", None)
                     or urlsplit(url).netloc)
        self._breaker = breaker_for(self.host)
        self._closed = False
        # auth: per-source callback wins, else the longest-prefix
        # registry hook (register_auth_hook); None = anonymous requests,
        # the zero-cost default
        self._auth_hook = auth if auth is not None else _auth_hook_for(url)
        self._auth_lock = make_lock("remote.auth_state")
        self._auth_cached: Optional[dict] = None
        status, hdrs = self._head()
        cl = hdrs.get("content-length")
        if cl is None:
            raise RemoteTerminalError(
                "HEAD response has no Content-Length — cannot size the "
                "remote object", host=self.host, status=status,
                path=self.url)
        self._size = int(cl)
        etag = hdrs.get("etag")
        last_modified = hdrs.get("last-modified")
        # bytes-identity for the shared caches: only a PLAIN transport
        # with at least one validator qualifies — without a validator a
        # rewrite would be invisible, and a wrapped (chaos) transport may
        # transform bytes
        if isinstance(self._transport, HttpTransport) \
                and (etag or last_modified):
            self.stat_key = (url, etag, last_modified, self._size)
            _note_validator(url, (etag, last_modified, self._size))
        else:
            self.stat_key = None

    # ------------------------------------------------------------ metadata
    @property
    def path(self) -> str:
        """Error-context identity (read_context / ReadError.path): the
        URL plays the file-path role for remote sources."""
        return self.url

    @property
    def latency_class(self) -> str:
        """The prefetch auto-tuner's latency class for this chain
        (io/prefetch.py): ``remote`` for ordinary network latency,
        ``remote_far`` once the observed pread EWMA crosses
        ``_FAR_LATENCY_S`` — far sources get deeper pipelines and bigger
        windows by default."""
        e = observed_pread_ewma(self.host)
        return "remote_far" if e is not None and e > _FAR_LATENCY_S \
            else "remote"

    def _auth(self, refresh: bool = False):
        """-> (extra request headers or None, presigned path override or
        None) from the auth hook; ``refresh=True`` re-invokes the hook
        (the 401→refresh path, metered ``remote.auth_refreshes``)."""
        if self._auth_hook is None:
            return None, None
        with self._auth_lock:
            if refresh or self._auth_cached is None:
                got = self._auth_hook(self.url, refresh)
                if got is None:
                    got = {}
                if not isinstance(got, dict):
                    raise RemoteTerminalError(
                        "auth hook must return a header dict (or one "
                        "with a 'url' presign key)", host=self.host,
                        path=self.url)
                self._auth_cached = dict(got)
                if refresh:
                    _account(_M_AUTH_REFRESHES)
            hdrs = dict(self._auth_cached)
        presigned = hdrs.pop("url", None)
        path_override = None
        if presigned:
            parts = urlsplit(str(presigned))
            path_override = (parts.path or "/") + \
                (("?" + parts.query) if parts.query else "")
        return (hdrs or None), path_override

    def _head(self) -> Tuple[int, Dict[str, str]]:
        from .faults import FaultPolicy, retry_call

        def once(_o, _s):
            # breaker checked PER attempt (retries must not hammer a
            # circuit their own failures just opened; the fail-fast
            # error is retryable, so the loop's backoff rides it)
            if not self._breaker.allow():
                _account(_M_FAIL_FAST)
                raise RemoteCircuitOpenError(
                    f"circuit open for {self.host}", host=self.host,
                    path=self.url)
            refreshes = 0
            while True:
                try:
                    if self._auth_hook is not None:
                        ah, override = self._auth()
                        status, hdrs = self._transport.head(
                            extra_headers=ah, path_override=override)
                    else:
                        status, hdrs = self._transport.head()
                except RemoteError:
                    raise
                except (HTTPException, socket.timeout, TimeoutError,
                        OSError) as e:
                    self._breaker.record_failure()
                    raise RemoteTransientError(
                        f"HEAD failed: {e}", host=self.host,
                        path=self.url) from e
                if status in (401, 403) and self._auth_hook is not None \
                        and refreshes < auth_refresh_attempts():
                    # stale credentials: refresh and retry in place —
                    # the host answered, so no breaker movement
                    refreshes += 1
                    self._auth(refresh=True)
                    continue
                break
            if status == 429:
                self._breaker.record_inconclusive()  # alive, just busy
                raise RemoteThrottledError(
                    "throttled on HEAD",
                    retry_after=_retry_after(hdrs), host=self.host,
                    status=status, path=self.url)
            if 500 <= status < 600:
                self._breaker.record_failure()
                raise RemoteTransientError(
                    "server error on HEAD", host=self.host, status=status,
                    path=self.url)
            if status != 200:
                self._breaker.record_success()  # answering = alive
                raise RemoteTerminalError(
                    "HEAD failed", host=self.host, status=status,
                    path=self.url)
            self._breaker.record_success()
            return status, hdrs

        # opens run BEFORE any PolicySource wraps the source, so the
        # HEAD carries its own small transient-retry (same shared loop)
        return retry_call(once, 0, 0,
                          FaultPolicy(max_retries=2, backoff_s=0.05))

    # -------------------------------------------------------------- preads
    def _fetch(self, offset: int, size: int,
               attempt: int) -> bytes:
        """One transport round trip, classified (401/403 re-invoke the
        auth hook and retry in place, bounded by
        ``PARQUET_TPU_REMOTE_AUTH_RETRY``).  Raises RemoteError
        subclasses; returns exactly ``size`` bytes."""
        refreshes = 0
        while True:
            try:
                if self._auth_hook is not None:
                    ah, override = self._auth()
                    status, hdrs, body = self._transport.get_range(
                        offset, size, extra_headers=ah,
                        path_override=override)
                else:
                    status, hdrs, body = self._transport.get_range(
                        offset, size)
            except RemoteError:
                raise
            except (HTTPException, socket.timeout, TimeoutError,
                    ConnectionError) as e:
                raise RemoteTransientError(
                    f"connection failure: {e}", host=self.host,
                    attempt=attempt, offset=offset, size=size,
                    path=self.url) from e
            except OSError as e:
                raise RemoteTransientError(
                    f"transport failure: {e}", host=self.host,
                    attempt=attempt, offset=offset, size=size,
                    path=self.url) from e
            if status in (401, 403) and self._auth_hook is not None \
                    and refreshes < auth_refresh_attempts():
                refreshes += 1
                self._auth(refresh=True)
                continue
            break
        if status == 206:
            cr = hdrs.get("content-range", "")
            m = _CONTENT_RANGE.match(cr)
            if m and int(m.group(1)) != offset:
                # a misbehaving proxy/cache served the WRONG range:
                # retryable — a fresh attempt usually lands on an honest
                # path, and persistent wrong ranges exhaust retries into
                # the degrade-or-raise path before any wrong byte is
                # decoded
                raise RemoteTransientError(
                    f"wrong range: asked for {offset}, got {cr!r}",
                    host=self.host, status=status, attempt=attempt,
                    offset=offset, size=size, path=self.url)
            data = body
        elif status == 200:
            # server ignored Range and sent the whole object: slice —
            # correct, just wasteful (counted bytes are the USEFUL bytes)
            data = body[offset : offset + size]
        elif status == 416:
            raise RemoteTerminalError(
                "range not satisfiable", host=self.host, status=status,
                attempt=attempt, offset=offset, size=size, path=self.url)
        elif status == 429:
            raise RemoteThrottledError(
                "throttled", retry_after=_retry_after(hdrs),
                host=self.host, status=status, attempt=attempt,
                offset=offset, size=size, path=self.url)
        elif 500 <= status < 600:
            raise RemoteTransientError(
                "server error", host=self.host, status=status,
                attempt=attempt, offset=offset, size=size, path=self.url)
        else:
            raise RemoteTerminalError(
                "request failed", host=self.host, status=status,
                attempt=attempt, offset=offset, size=size, path=self.url)
        if len(data) != size:
            # truncated body: the headers promised the range, the socket
            # delivered less — a torn connection, retryable
            raise RemoteTransientError(
                f"truncated body: wanted {size}, got {len(data)}",
                host=self.host, status=status, attempt=attempt,
                offset=offset, size=size, path=self.url)
        return data

    def _fetch_raced(self, offset: int, size: int) -> bytes:
        """First-success-wins race between the primary attempt and (after
        :func:`hedge_delay_s`) one hedged re-attempt.  The caller's wait
        loop honors the active operation deadline — a stalled primary
        cannot run past ``deadline_s``; abandoned attempts release their
        budget grant and ledger bytes when their transport call returns."""
        from .faults import active_deadline

        delay = hedge_delay_s()
        if delay is None:
            return self._fetch(offset, size, 0)
        cv = make_condition("remote.hedge_cv")
        results: Dict[int, tuple] = {}
        state = {"abandoned": False}

        def abandoned() -> bool:
            with cv:
                return state["abandoned"]

        def attempt(idx: int, charge: bool) -> None:
            out = ("skip", None)
            adm = None
            grant = 0
            charged = False
            try:
                if charge and not abandoned():
                    # the hedge is an EXTRA in-flight copy of the bytes:
                    # admitted through the unified read budget (its own
                    # grant — the caller's covers only the primary) and
                    # charged to the hedge ledger account.  give_up=
                    # abandoned: once the primary wins, a still-QUEUED
                    # hedge ticket withdraws instead of head-of-line-
                    # blocking every other reader's admission behind a
                    # grant nobody wants
                    adm = read_admission()
                    grant = adm.acquire(size, tier="hedge",
                                        give_up=abandoned)
                    _ACC_HEDGE.add(size)
                    charged = True
                if not abandoned():
                    try:
                        out = ("ok", self._fetch(offset, size, idx))
                    # ptlint: disable=PT005 -- not swallowed: the error is
                    # captured into the result slot and re-raised on the
                    # hedged wait's consuming thread
                    except BaseException as e:
                        out = ("err", e)
            finally:
                if charged:
                    _ACC_HEDGE.sub(size)
                if adm is not None:
                    adm.release(grant, tier="hedge")
                with cv:
                    results[idx] = out
                    cv.notify_all()

        threading.Thread(target=attempt, args=(0, False), daemon=True,
                         name="pq-remote-pread").start()
        dl = active_deadline()
        hedge_at = time.monotonic() + delay
        launched = 1
        while True:
            with cv:
                win = next((i for i in (0, 1)
                            if results.get(i, ("",))[0] == "ok"), None)
                if win is not None:
                    state["abandoned"] = True  # loser skips its fetch
                    if win == 1:
                        _account(_M_HEDGES_WON)
                    return results[win][1]
                r0 = results.get(0)
                if r0 is not None:
                    # the primary finished without success: surface its
                    # error NOW.  Hedges exist to cut tail latency, not
                    # to mask failures — the retry policy owns recovery,
                    # and waiting out a hedge that may be parked in the
                    # admission queue (or a 30s socket timeout) would
                    # turn a prompt failure into an unbounded hang.  An
                    # abandoned hedge drains its budget grant and ledger
                    # bytes in its own finally.
                    state["abandoned"] = True
                    if r0[0] == "err":
                        raise r0[1]
                    raise RemoteTransientError(
                        "hedged read produced no result", host=self.host,
                        offset=offset, size=size, path=self.url)
                # a failed/skipped HEDGE keeps waiting on the primary.
                # Sleep until the next event that needs action: the
                # hedge launch, the deadline, or an attempt's notify —
                # no polling when none is pending.
                waits = []
                if launched == 1:
                    waits.append(hedge_at - time.monotonic())
                if dl is not None:
                    rem = dl.remaining()
                    if rem is not None:
                        waits.append(rem)
                timeout = max(min(waits), 0.0) if waits else None
                if timeout is None or timeout > 0:
                    cv.wait(timeout=timeout)
            if dl is not None and dl.expired():
                with cv:
                    state["abandoned"] = True
                raise DeadlineError(
                    f"deadline exceeded during hedged remote "
                    f"pread({offset}, {size}) [host={self.host}]",
                    path=self.url)
            if launched == 1 and time.monotonic() >= hedge_at:
                launched = 2
                _account(_M_HEDGES)
                threading.Thread(target=attempt, args=(1, True),
                                 daemon=True,
                                 name="pq-remote-hedge").start()

    def pread(self, offset: int, size: int) -> bytes:
        if _locks.LOCKCHECK_ENABLED:
            _locks.note_blocking("remote.pread", detail=self.host)
        _check_read_args(offset, size)
        if self._closed:
            raise ValueError(f"read on closed source {self.url!r}")
        if size == 0:
            return b""
        if not self._breaker.allow():
            _account(_M_FAIL_FAST)
            raise RemoteCircuitOpenError(
                f"circuit open for {self.host}", host=self.host,
                offset=offset, size=size, path=self.url)
        t0 = time.perf_counter()
        try:
            data = self._fetch_raced(offset, size)
        except RemoteThrottledError:
            _account(_M_ERRORS["throttled"])
            # a 429 proves the host alive: no streak movement, but a
            # half-open probe slot must still release
            self._breaker.record_inconclusive()
            raise
        except RemoteTransientError as e:
            _account(_M_ERRORS["retryable"])
            if e.status is not None and e.status < 500:
                # the host ANSWERED (a 2xx whose body was torn or
                # mis-ranged): retryable, but not a host-health failure —
                # the breaker's contract is connection-class signals
                # only, and tripping on body faults would fail-fast an
                # answering host's every other file
                self._breaker.record_inconclusive()
            else:
                self._breaker.record_failure()
            raise
        except RemoteTerminalError:
            _account(_M_ERRORS["terminal"])
            self._breaker.record_success()  # answering 4xx = alive host
            raise
        except BaseException:
            # anything else (a deadline firing mid-race, caller
            # teardown) says nothing about host health — but it must
            # not strand the half-open probe slot
            self._breaker.record_inconclusive()
            raise
        self._breaker.record_success()
        _observe_pread(time.perf_counter() - t0, self.host)
        _account(_M_PREADS)
        _account(_M_BYTES, size)
        _account_bytes(size)  # terminal source: read.bytes_read + op scope
        return data

    def size(self) -> int:
        return self._size

    @property
    def parallel_pread_slots(self) -> int:
        """How many range requests this source can usefully issue at
        once: the per-host connection-pool depth.  The multi-range read
        planner (:func:`parallel_preads`) caps its fan-out here so
        concurrent ranges ride pooled keep-alive sockets instead of
        opening one TCP(+TLS) handshake per range.  Chaos-wrapped
        transports fall back to the pool-depth knob."""
        got = getattr(self._transport, "pool_size", None)
        if got is None:
            got = env_int("PARQUET_TPU_REMOTE_POOL")
        return max(int(got or 1), 1)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._transport.close()


class ObjectStoreSource(HttpSource):
    """Object-store reads ARE ranged HTTP: S3/GCS/R2-style endpoints
    (presigned or public URLs) serve exactly the HEAD + ``Range`` GET
    surface :class:`HttpSource` speaks, so this alias exists to name the
    intent at call sites; behavior is identical."""


def _retry_after(hdrs: Dict[str, str]) -> Optional[float]:
    v = hdrs.get("retry-after", "").strip()
    if not v:
        return None
    try:
        return max(0.0, float(v))
    except ValueError:
        return None  # HTTP-date form: treat as unspecified


def classify_status(status: int, hdrs: Dict[str, str], host: str,
                    path: str, what: str = "request") -> None:
    """Raise the :class:`~parquet_tpu.errors.RemoteError` subclass a
    non-2xx ``status`` classifies as (429 → throttled with its
    Retry-After, 5xx → transient, other 4xx → terminal); 2xx returns.
    The one classification table the prefix-listing fetch and the fleet
    peer protocol share with the pread path — the decision must not
    drift between surfaces."""
    if 200 <= status < 300:
        return
    if status == 429:
        raise RemoteThrottledError(
            f"throttled on {what}", retry_after=_retry_after(hdrs),
            host=host, status=status, path=path)
    if 500 <= status < 600:
        raise RemoteTransientError(
            f"server error on {what}", host=host, status=status,
            path=path)
    raise RemoteTerminalError(
        f"{what} failed", host=host, status=status, path=path)


def gunzip_body(data: bytes, host: str = "", path: str = "") -> bytes:
    """Decompress a ``Content-Encoding: gzip`` response body.  A
    TRUNCATED or torn stream (EOFError / zlib error mid-member) is a
    connection artifact, not data corruption — classified
    :class:`~parquet_tpu.errors.RemoteTransientError` so the shared
    retry loop re-fetches instead of surfacing a parse error."""
    import gzip as _gzip
    import zlib as _zlib

    try:
        return _gzip.decompress(data)
    except (EOFError, _zlib.error, OSError) as e:
        raise RemoteTransientError(
            f"truncated/torn gzip body: {e}", host=host,
            path=path) from e


def _parse_listing(body: bytes, base_url: str) -> List[str]:
    """File URLs from a prefix-listing response: a JSON array of names/
    URLs, a JSON object with a ``files``/``keys``/``entries`` list, or
    (fallback) HTML ``href`` attributes.  Relative names resolve against
    the listing URL; nested "directories" (trailing ``/``) and parent
    links are dropped — listings are one level, like a local glob."""
    import json as _json
    from urllib.parse import urljoin

    names: List[str] = []
    try:
        doc = _json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        doc = None
    if isinstance(doc, list):
        names = [str(n) for n in doc]
    elif isinstance(doc, dict):
        for key in ("files", "keys", "entries"):
            if isinstance(doc.get(key), list):
                names = [str(n) for n in doc[key]]
                break
    else:
        names = re.findall(r'href="([^"?#]+)"',
                           body.decode("utf-8", "replace"))
    out: List[str] = []
    for n in names:
        if not n or n.endswith("/") or n.startswith((".", "..")):
            continue
        out.append(urljoin(base_url, n))
    return sorted(set(out))


def list_prefix(url: str, policy=None) -> List[str]:
    """Expand an ``http(s)://.../prefix/`` listing URL into the sorted
    file URLs under it — the remote analog of a local glob, used by
    ``Dataset`` path expansion (and fleet configs naming table roots by
    URL).  The listing GET runs through the shared
    :func:`~parquet_tpu.io.faults.retry_call` loop (transient/throttled
    responses re-attempt under jittered backoff) and the host's circuit
    breaker.  An empty listing raises ``FileNotFoundError`` to match an
    unmatched glob."""
    from .faults import FaultPolicy, retry_call

    transport = HttpTransport(url)
    host = transport.host
    breaker = breaker_for(host)

    def once(_o, _s):
        if not breaker.allow():
            _account(_M_FAIL_FAST)
            raise RemoteCircuitOpenError(f"circuit open for {host}",
                                         host=host, path=url)
        try:
            status, hdrs, body = transport._roundtrip(
                "GET", {"Accept": "application/json"})
        except (HTTPException, socket.timeout, TimeoutError, OSError) as e:
            breaker.record_failure()
            raise RemoteTransientError(f"listing failed: {e}", host=host,
                                       path=url) from e
        if status == 429:
            breaker.record_inconclusive()
        elif 500 <= status < 600:
            breaker.record_failure()
        else:
            breaker.record_success()
        classify_status(status, hdrs, host, url, what="prefix listing")
        if hdrs.get("content-encoding", "").lower() == "gzip":
            body = gunzip_body(body, host=host, path=url)
        return _parse_listing(body, url)

    try:
        files = retry_call(once, 0, 0,
                           policy if policy is not None
                           else FaultPolicy(max_retries=2,
                                            backoff_s=0.05))
    finally:
        transport.close()
    if not files:
        raise FileNotFoundError(f"prefix listing {url!r} matched no "
                                f"files")
    return files


# ---------------------------------------------------------------------------
# s3:// — path-style object-store URLs over the same ranged-HTTP stack
# ---------------------------------------------------------------------------


def s3_endpoint() -> str:
    """``PARQUET_TPU_S3_ENDPOINT`` — the HTTP(S) endpoint ``s3://`` URLs
    resolve against, path-style (``{endpoint}/{bucket}/{key}``); empty
    when unset (``s3://`` paths are then an error)."""
    return (env_str("PARQUET_TPU_S3_ENDPOINT") or "").strip().rstrip("/")


def resolve_s3_url(url: str) -> str:
    """``s3://bucket/key`` → the path-style ``http(s)://`` URL it reads
    from.  Object-store reads ARE ranged HTTP (:class:`ObjectStoreSource`
    docstring), so resolution is pure URL rewriting — auth rides the
    endpoint's auth hook / presigning, never an SDK."""
    ep = s3_endpoint()
    if not ep:
        raise ValueError(
            f"{url!r} needs PARQUET_TPU_S3_ENDPOINT (the HTTP(S) endpoint "
            "serving path-style bucket requests); for presigned or public "
            "objects use the http(s):// URL directly")
    rest = url[len("s3://"):]
    if not rest or rest.startswith("/"):
        raise ValueError(f"bad s3 url {url!r} (want s3://bucket/key)")
    return f"{ep}/{rest}"


def _parse_s3_listing(body: bytes, host: str = "",
                      path: str = "") -> Tuple[List[str], Optional[str]]:
    """``(keys, continuation_token)`` from one ListObjectsV2 XML page
    (namespace-agnostic; token is None on the last page).  A torn or
    non-XML body is a connection artifact → transient, retried."""
    import xml.etree.ElementTree as _ET

    try:
        root = _ET.fromstring(body)
    except _ET.ParseError as e:
        raise RemoteTransientError(
            f"torn ListObjectsV2 body: {e}", host=host, path=path) from e
    keys: List[str] = []
    token: Optional[str] = None
    truncated = False
    for el in root.iter():
        tag = el.tag.rsplit("}", 1)[-1]
        if tag == "Key":
            keys.append(el.text or "")
        elif tag == "IsTruncated":
            truncated = (el.text or "").strip().lower() == "true"
        elif tag == "NextContinuationToken":
            token = (el.text or "").strip() or None
    return keys, (token if truncated else None)


def list_prefix_s3(url: str, policy=None) -> List[str]:
    """Expand an ``s3://bucket/prefix/`` URL into the sorted ``s3://``
    object URLs under it — the object-store dialect of
    :func:`list_prefix`.  Speaks ListObjectsV2 (``?list-type=2``)
    path-style against ``PARQUET_TPU_S3_ENDPOINT`` with ``delimiter=/``
    (one level, like a local glob) and follows continuation tokens;
    every page GET rides the same :func:`~parquet_tpu.io.faults
    .retry_call` loop and host circuit breaker as the pread path.  An
    empty listing raises ``FileNotFoundError`` to match an unmatched
    glob."""
    from urllib.parse import urlencode, urlsplit

    from .faults import FaultPolicy, retry_call

    rest = url[len("s3://"):]
    bucket, _, prefix = rest.partition("/")
    if not bucket:
        raise ValueError(f"bad s3 url {url!r} (want s3://bucket/prefix/)")
    base = resolve_s3_url(f"s3://{bucket}")
    transport = HttpTransport(base)
    host = transport.host
    breaker = breaker_for(host)
    base_path = urlsplit(base).path or "/"
    pol = policy if policy is not None \
        else FaultPolicy(max_retries=2, backoff_s=0.05)
    keys: List[str] = []
    token: Optional[str] = None
    try:
        while True:
            q = {"list-type": "2", "prefix": prefix, "delimiter": "/"}
            if token:
                q["continuation-token"] = token
            page_path = f"{base_path}?{urlencode(q)}"

            def once(_o, _s, page_path=page_path):
                if not breaker.allow():
                    _account(_M_FAIL_FAST)
                    raise RemoteCircuitOpenError(
                        f"circuit open for {host}", host=host, path=url)
                try:
                    status, hdrs, body = transport._roundtrip(
                        "GET", {"Accept": "application/xml"},
                        path_override=page_path)
                except (HTTPException, socket.timeout, TimeoutError,
                        OSError) as e:
                    breaker.record_failure()
                    raise RemoteTransientError(
                        f"listing failed: {e}", host=host, path=url) from e
                if status == 429:
                    breaker.record_inconclusive()
                elif 500 <= status < 600:
                    breaker.record_failure()
                else:
                    breaker.record_success()
                classify_status(status, hdrs, host, url,
                                what="ListObjectsV2")
                if hdrs.get("content-encoding", "").lower() == "gzip":
                    body = gunzip_body(body, host=host, path=url)
                return _parse_s3_listing(body, host=host, path=url)

            page_keys, token = retry_call(once, 0, 0, pol)
            keys.extend(page_keys)
            if not token:
                break
    finally:
        transport.close()
    out = sorted({f"s3://{bucket}/{k}" for k in keys
                  if k and not k.endswith("/")})
    if not out:
        raise FileNotFoundError(f"prefix listing {url!r} matched no "
                                f"files")
    return out


# ---------------------------------------------------------------------------
# Parallel multi-range preads (PR 11 follow-on, wired by the aggregation
# cascade's decode stage)
# ---------------------------------------------------------------------------


def parallel_pread_slots(source) -> int:
    """Concurrent range-request slots the chain under ``source`` supports,
    capped by ``PARQUET_TPU_REMOTE_PARALLEL`` (0/1 disables).  Walks the
    wrapper chain (PolicySource → PrefetchSource → HttpSource) for a
    terminal source advertising ``parallel_pread_slots``; local sources
    advertise nothing and answer 0 — one pread at a time is already
    optimal against the page cache."""
    cap = env_int("PARQUET_TPU_REMOTE_PARALLEL")
    if cap <= 1:
        return 0
    s, hops = source, 0
    while s is not None and hops < 8:  # defensive: wrapper cycles
        got = getattr(s, "parallel_pread_slots", None)
        if got:
            return min(int(got), cap)
        s = getattr(s, "inner", None)
        hops += 1
    return 0


def parallel_preads(source, ranges, slots: int):
    """Fetch several DISJOINT ``(offset, size)`` ranges from ``source``
    concurrently — at most ``slots`` in flight, one per connection-pool
    slot — and return ``[(offset, bytes), ...]`` in input order.

    Issued against the TOP of the source chain, so per-range retries
    (PolicySource), hedges, and breaker checks all apply per attempt;
    the active operation deadline propagates onto the worker threads via
    a copied context.  Any range's failure cancels nothing in flight but
    surfaces after the join (DeadlineError first, else the first error)
    — the caller's retry/degrade policy owns recovery.  Metered as
    ``remote.parallel_preads`` (one count per range fetched through a
    parallel batch)."""
    import contextvars
    import itertools

    if _locks.LOCKCHECK_ENABLED:
        _locks.note_blocking("remote.parallel_preads")
    ranges = list(ranges)
    results: List = [None] * len(ranges)
    errors: List = [None] * len(ranges)
    ctx = contextvars.copy_context()
    counter = itertools.count()  # shared work queue: no lockstep batches

    def worker() -> None:
        # drain the shared index counter: a slow range stalls only its
        # own slot, never a batch boundary — the other connection-pool
        # slots keep pulling work
        while True:
            i = next(counter)
            if i >= len(ranges):
                return
            off, size = ranges[i]
            try:
                # under a COPY of the caller's context, so
                # active_deadline() keeps bounding every range
                results[i] = ctx.copy().run(source.pread, off, size)
            # ptlint: disable=PT005 -- not swallowed: captured into the
            # per-range error slot and re-raised after the join below
            except BaseException as e:
                errors[i] = e

    threads = [threading.Thread(target=worker, daemon=True,
                                name="pq-parallel-pread")
               for _ in range(min(max(slots, 1), len(ranges)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dl = next((e for e in errors if isinstance(e, DeadlineError)), None)
    if dl is not None:
        raise dl
    first = next((e for e in errors if e is not None), None)
    if first is not None:
        raise first
    _account(_M_PARALLEL_PREADS, len(ranges))
    return [(off, data) for (off, _), data in zip(ranges, results)]


def remote_debug() -> dict:
    """Live remote-layer state for ``/debugz``: per-host breaker states
    and failure streaks, hedge bytes in flight, and the observed pread
    latency EWMA the hedge delay and prefetch latency class key on."""
    with _LAT_LOCK:
        ewmas = {h: round(v, 6) for h, v in sorted(_LAT_EWMA.items())}
    return {
        "breakers": {h: {"state": b.state,
                         "consecutive_failures": b.consecutive_failures}
                     for h, b in sorted(breakers().items())},
        "hedge_in_flight_bytes": _ACC_HEDGE.resident,
        "hedge_delay_s": hedge_delay_s(),
        "pread_ewma_s": ewmas,
    }
