"""Predicate pushdown: Find/Search over page indexes, SeekToRow, pruning.

Reference parity (SURVEY.md §3.3): ``parquet.Find`` binary-searches a
ColumnIndex's page min/max for a value, ``OffsetIndex.Offset(page)`` maps to
the first row, and ``Pages.SeekToRow`` skips to that page; chunk-level pruning
uses ``Statistics`` and ``BloomFilter().Check`` before touching pages.

TPU-first addition: :func:`plan_scan` produces a *batch* page plan for a
predicate across row groups (the unit the device pipeline stages), instead of
a cursor — pushdown selects H2D bytes, the chip scans what remains.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from ..format import metadata as md
from ..format.enums import BoundaryOrder, Encoding, PageType, Type

from ..algebra.compare import normalize
from ..schema.schema import Leaf
from .reader import ColumnChunkReader, ParquetFile, RowGroupReader
from .statistics import decode_stat_value

_DICT_ENCODINGS = {Encoding.PLAIN_DICTIONARY, Encoding.RLE_DICTIONARY}


def decoded_bounds(column_index: md.ColumnIndex, leaf: Leaf):
    """Per-page ``(mins, maxs)`` of ``column_index`` decoded into the
    leaf's order domain, memoized ON the ColumnIndex object (one index
    belongs to one chunk/leaf, and chunk readers memoize their parsed
    index, so the memo lives exactly as long as the file handle).  Every
    page-stat consumer — ``find``, ``pages_overlapping*``, the planner's
    page stage — decodes a chunk's bounds once per open file instead of
    once per probe: a 1k-key batch against one chunk pays one decode."""
    got = getattr(column_index, "_decoded_bounds", None)
    if got is None:
        got = ([decode_stat_value(m, leaf)
                for m in (column_index.min_values or [])],
               [decode_stat_value(m, leaf)
                for m in (column_index.max_values or [])])
        column_index._decoded_bounds = got
    return got


def find(column_index: md.ColumnIndex, value, leaf: Leaf) -> int:
    """First page ordinal whose [min,max] may contain ``value`` (== number of
    pages when none can).  Binary search when boundary_order allows, else
    linear scan — same contract as the reference's ``parquet.Find``."""
    value = normalize(leaf, value)
    n = len(column_index.null_pages or [])
    mins, maxs = decoded_bounds(column_index, leaf)
    order = BoundaryOrder(column_index.boundary_order or 0)
    nulls = column_index.null_pages or [False] * n

    def may_contain(i: int) -> bool:
        if nulls[i]:
            return False
        return mins[i] <= value <= maxs[i]

    if order == BoundaryOrder.ASCENDING:
        # first page with max >= value
        lo, hi = 0, n
        while lo < hi:
            mid = (lo + hi) // 2
            if nulls[mid] or maxs[mid] < value:
                lo = mid + 1
            else:
                hi = mid
        return lo if lo < n and may_contain(lo) else n
    if order == BoundaryOrder.DESCENDING:
        lo, hi = 0, n
        while lo < hi:
            mid = (lo + hi) // 2
            if nulls[mid] or mins[mid] > value:
                lo = mid + 1
            else:
                hi = mid
        return lo if lo < n and may_contain(lo) else n
    for i in range(n):
        if may_contain(i):
            return i
    return n


def pages_overlapping(column_index: md.ColumnIndex, leaf: Leaf,
                      lo=None, hi=None) -> List[int]:
    """All page ordinals whose [min,max] intersects [lo, hi] (None = open)."""
    lo, hi = normalize(leaf, lo), normalize(leaf, hi)
    n = len(column_index.null_pages or [])
    mins, maxs = decoded_bounds(column_index, leaf)
    nulls = column_index.null_pages or [False] * n
    out = []
    for i in range(n):
        if nulls[i]:
            continue
        if mins[i] is None or maxs[i] is None:
            out.append(i)
            continue
        if lo is not None and maxs[i] < lo:
            continue
        if hi is not None and mins[i] > hi:
            continue
        out.append(i)
    return out


def prune_row_group(rg: RowGroupReader, path, lo=None, hi=None,
                    use_bloom: bool = False, equals=None) -> bool:
    """True if the row group may contain rows matching the range/equality.

    Chunk-level pruning: Statistics first, optionally the bloom filter for
    equality probes (SURVEY.md §3.3 last line)."""
    from .bloom import bloom_may_contain
    from .statistics import may_contain_range

    chunk = rg.column(path)
    lo, hi = normalize(chunk.leaf, lo), normalize(chunk.leaf, hi)
    equals = normalize(chunk.leaf, equals)
    st = chunk.statistics()
    if not may_contain_range(st, lo, hi):
        return False
    if equals is not None and not may_contain_range(st, equals, equals):
        return False
    if use_bloom and equals is not None:
        bf = chunk.bloom_filter()
        if bf is not None and not bloom_may_contain(bf, equals, chunk.leaf):
            return False
    return True


def prune_file(pf: ParquetFile, path=None, lo=None, hi=None,
               values: Optional[Sequence] = None, where=None) -> bool:
    """True if ANY row group of the file may contain matching rows —
    footer-level pruning for the dataset layer: chunk statistics live in
    the (already parsed, possibly footer-cached) metadata, so a whole file
    is ruled out without touching chunk bytes or issuing any IO.  Bloom
    filters are deliberately not consulted here (they cost preads; the
    per-file :func:`plan_scan` probes them for survivors).

    One implementation for every stats-level prune: this is the planner's
    stage-1 cascade (``ScanPlanner.plan(..., stages=("stats",))``), the
    same code ``Dataset.prune`` and the full scan plan run — file- and
    row-group-level pruning cannot drift.  ``where`` takes a predicate
    tree (:mod:`parquet_tpu.algebra.expr`) instead of the single-column
    ``path``/``lo``/``hi``/``values`` form."""
    from .planner import ScanPlanner

    expr = _as_expr(path, lo, hi, values, where)
    return ScanPlanner(pf).any_match_stats(expr)


def _as_expr(path, lo, hi, values, where):
    """One predicate-tree input from either calling convention."""
    from ..algebra.expr import single_pred

    if where is not None:
        if path is not None or lo is not None or hi is not None \
                or values is not None:
            raise ValueError("pass either where= (a predicate tree) or the "
                             "single-column path/lo/hi/values form, not both")
        return where
    if path is None:
        raise ValueError("need a predicate: where= or path (+ lo/hi/values)")
    if hasattr(path, "column_index"):  # a schema Leaf
        path = path.dotted_path
    return single_pred(path, lo=lo, hi=hi, values=values)


def _any_in_range(sorted_vals: List, lo, hi) -> bool:
    """Does the sorted probe list intersect [lo, hi]?  (None bound = open.)"""
    if not sorted_vals:
        return False
    i = 0 if lo is None else bisect_left(sorted_vals, lo)
    return i < len(sorted_vals) and (hi is None or sorted_vals[i] <= hi)


def prune_row_group_values(rg: RowGroupReader, path, sorted_vals: List,
                           hashes: Optional[np.ndarray] = None) -> bool:
    """IN-list pruning: the row group may hold SOME probe value.  Statistics
    intersect the sorted probe list (one bisect); with ``hashes``, the bloom
    filter is probed for the whole batch at once (large batches route to the
    device probe — io/bloom.py design note)."""
    chunk = rg.column(path)
    st = chunk.statistics()
    if st is not None and st.min_value is not None and st.max_value is not None:
        if not _any_in_range(sorted_vals, st.min_value, st.max_value):
            return False
    if hashes is not None:
        bf = chunk.bloom_filter()
        if bf is not None and not bf.check_hashes_batch(hashes).any():
            return False
    return True


def pages_overlapping_values(column_index: md.ColumnIndex, leaf: Leaf,
                             sorted_vals: List) -> List[int]:
    """Page ordinals whose [min,max] contains at least one probe value."""
    n = len(column_index.null_pages or [])
    mins, maxs = decoded_bounds(column_index, leaf)
    nulls = column_index.null_pages or [False] * n
    out = []
    for i in range(n):
        if nulls[i]:
            continue
        if mins[i] is None or maxs[i] is None or _any_in_range(
                sorted_vals, mins[i], maxs[i]):
            out.append(i)
    return out


def page_row_spans(oi: md.OffsetIndex, num_rows: int
                   ) -> List[Tuple[int, int]]:
    """Per-page local ``[start, end)`` row spans from the offset index
    (the last page ends at the row group's ``num_rows``)."""
    locs = oi.page_locations or []
    out = []
    for i, pl in enumerate(locs):
        end = locs[i + 1].first_row_index if i + 1 < len(locs) else num_rows
        out.append((pl.first_row_index, end))
    return out


def pred_cover_page_ords(pred, column_index: md.ColumnIndex, leaf: Leaf,
                         spans: List[Tuple[int, int]]) -> List[int]:
    """Page ordinals whose zone maps PROVE every row matches ``pred`` —
    the answering dual of :func:`pages_overlapping` (the aggregation
    cascade counts/aggregates these pages without decoding them).
    Bounds decode once per chunk via the memo on the parsed index;
    ``None`` null_counts make nothing provable (conservative)."""
    from .planner import _bounds_cover

    nulls = list(column_index.null_pages or [])
    ncounts = column_index.null_counts
    mins, maxs = decoded_bounds(column_index, leaf)
    out = []
    for i in range(len(nulls)):
        rows = spans[i][1] - spans[i][0]
        if pred.kind == "null" and nulls[i] and rows > 0:
            out.append(i)  # a declared null page is all-null by contract
            continue
        nc = None if ncounts is None else ncounts[i]
        mn = mins[i] if i < len(mins) else None
        mx = maxs[i] if i < len(maxs) else None
        if nulls[i]:
            mn = mx = None  # null pages carry no value bounds
        if _bounds_cover(pred, mn, mx, nc, rows, page_rows=rows):
            out.append(i)
    return out


@dataclass
class PagePlan:
    """Selected pages of one chunk: which page ordinals to decode and the row
    span they cover."""

    rg_index: int
    page_ordinals: List[int]
    first_row: int  # global first row of first selected page (within rg)
    row_count: int


def plan_scan(pf: ParquetFile, path, lo=None, hi=None,
              use_bloom: bool = False,
              values: Optional[Sequence] = None,
              policy=None, report=None) -> List[PagePlan]:
    """Batch pushdown plan: for each surviving row group, the page ordinals
    whose zone maps intersect the predicate.

    ``values`` switches to IN-list semantics (``file[path] ∈ values``):
    statistics and zone maps prune against the sorted probe list, and with
    ``use_bloom`` every chunk filter is probed with the whole hashed batch at
    once (the batched-probe path of io/bloom.py).

    This is the legacy single-column face of the unified scan planner
    (io/planner.py): the predicate becomes a one-leaf tree, the planner
    runs its cheapest-first cascade (statistics → page index → bloom), and
    the surviving covering spans come back in the historical
    :class:`PagePlan` form.  Planning itself does IO (column-index /
    offset-index / bloom preads), so it participates in the resilience
    contract: failures carry file/row-group/column context, and under
    ``policy.on_corrupt='skip_row_group'`` a row group whose index
    structures are corrupt is skipped (recorded in ``report`` with its full
    row count as candidate rows) instead of failing the whole scan."""
    from .planner import ScanPlanner

    expr = _as_expr(path, lo, hi, values, None)
    planner = ScanPlanner(pf, policy=policy, report=report)
    return planner.plan(expr, use_bloom=use_bloom).page_plans()


def _npages(oi) -> int:
    return len(oi.page_locations) if oi and oi.page_locations else 0


# ---------------------------------------------------------------------------
# SeekToRow: decode a row range using the offset index
# ---------------------------------------------------------------------------


def pages_and_base(chunk: ColumnChunkReader, row_start: int, row_end: int):
    """Selected pages covering [row_start, row_end) plus the first row the
    selection actually starts at (page-aligned trim base for callers that
    decode whole pages). Shared by read_row_range and the device scan."""
    pages = list(seek_pages(chunk, row_start, row_end))
    first = 0
    oi = chunk.offset_index()
    if oi is not None and oi.page_locations:
        firsts = [pl.first_row_index for pl in oi.page_locations]
        first = firsts[max(bisect_right(firsts, row_start) - 1, 0)]
    return pages, first


def dictionary_pages(chunk: ColumnChunkReader, first_data_offset: int):
    """Yield the chunk's dictionary page (if any) given the byte offset of
    the first selected data page — the dictionary half of ``SeekToRow``,
    shared by :func:`seek_pages` and the point-lookup page fetcher
    (io/lookup.py), which both decode page subsets that may be
    dictionary-encoded."""
    m = chunk.meta
    dict_off = m.dictionary_page_offset
    if dict_off is not None and 0 < dict_off < first_data_offset:
        yield from chunk.pages_at(dict_off, first_data_offset - dict_off)
    elif dict_off is None and any(Encoding(e) in _DICT_ENCODINGS
                                  for e in (m.encodings or [])):
        # legacy writers may omit dictionary_page_offset: find the dictionary
        # page the slow way (full header scan, old behavior)
        for p in chunk.pages():
            if p.page_type == PageType.DICTIONARY_PAGE:
                yield p
                break


def seek_pages(chunk: ColumnChunkReader, row_start: int, row_end: int):
    """Yield the dictionary page (if any) + the data pages covering
    [row_start, row_end) — reference's ``Pages.SeekToRow`` + read loop.

    With an offset index this seeks straight to the selected pages' byte
    ranges (one pread per contiguous span) instead of parsing every page
    header in the chunk."""
    oi = chunk.offset_index()
    if oi is None or not oi.page_locations:
        # no index: fall back to counting rows per page (flat columns: values)
        yield from chunk.pages()
        return
    locs = oi.page_locations
    firsts = [pl.first_row_index for pl in locs]
    i0 = max(bisect_right(firsts, row_start) - 1, 0)
    i1 = min(bisect_left(firsts, row_end, lo=i0), len(locs))
    if i1 <= i0:
        return
    yield from dictionary_pages(chunk, locs[0].offset)
    span_start = locs[i0].offset
    span_len = locs[i1 - 1].offset + locs[i1 - 1].compressed_page_size - span_start
    yield from chunk.pages_at(span_start, span_len, num_pages=i1 - i0)


# tag for the columnar aligned BYTE_ARRAY form: ("ba_arrays", uint8
# values, int64 offsets) — shared with parallel/host_scan.py
BA_ARRAYS = "ba_arrays"


def read_row_range(pf: ParquetFile, path, row_start: int, row_count: int,
                   device: bool = False,
                   aligned: "Union[bool, str]" = False):
    """Decode only the pages covering [row_start, row_start+row_count) of one
    column, trimming to the exact rows — the SeekToRow-then-read flow of
    SURVEY.md §3.3.  Flat columns return a host numpy array (or list of bytes
    for BYTE_ARRAY); nested columns return a :class:`Column` whose
    ``to_arrow()`` yields exactly the requested rows.

    ``aligned=True`` (flat columns only) returns ``(values, validity)`` with
    one row-aligned entry per requested row — null slots hold a zero fill
    (``None`` for byte arrays) and ``validity`` marks them (``None`` when the
    column span has no nulls).  ``aligned="arrays"`` additionally keeps
    BYTE_ARRAY spans columnar: ``values`` is ``("ba_arrays", uint8 bytes,
    int64 offsets)`` over the DENSE present values (``validity`` maps rows
    to value ordinals) — the no-python-objects form the scan path filters
    before materializing."""
    from .column import concat_columns
    from .reader import decode_chunk_host

    leaf = pf.schema.leaf(path)
    nested = leaf.max_repetition_level > 0
    if aligned and nested:
        raise ValueError("aligned=True is only defined for flat columns")
    out_parts = []
    remaining_start = row_start
    remaining = row_count
    for rg in pf.row_groups:
        nrows = rg.num_rows
        if remaining <= 0:
            break
        if remaining_start >= nrows:
            remaining_start -= nrows
            continue
        take = min(nrows - remaining_start, remaining)
        chunk = rg.column(leaf.column_index)
        pages, first_row_of_pages = pages_and_base(
            chunk, remaining_start, remaining_start + take)
        col = decode_chunk_host(chunk, pages=iter(pages))
        if aligned:
            out_parts.append(_trim_flat_aligned(
                col, remaining_start - first_row_of_pages, take,
                arrays=aligned == "arrays"))
        else:
            trim = _trim_nested if nested else _trim_flat
            out_parts.append(
                trim(col, remaining_start - first_row_of_pages, take))
        remaining_start = 0
        remaining -= take
    if not out_parts:
        if not nested:
            if leaf.physical_type == Type.BYTE_ARRAY:
                empty = ((BA_ARRAYS, np.empty(0, np.uint8),
                          np.zeros(1, np.int64))
                         if aligned == "arrays" else [])
            elif leaf.physical_type == Type.FIXED_LEN_BYTE_ARRAY:
                empty = np.empty((0, leaf.type_length or 0), np.uint8)
            else:
                empty = np.empty(0, leaf.np_dtype() or np.uint8)
            return (empty, None) if aligned else empty
        from ..ops import levels as levels_ops
        from .column import Column

        empty_lv = np.zeros(0, np.int32)
        asm = levels_ops.assemble(empty_lv, empty_lv, leaf)
        return Column(leaf=leaf, values=np.empty(0, leaf.np_dtype() or np.uint8),
                      offsets=(np.zeros(1, np.int32)
                               if leaf.physical_type == Type.BYTE_ARRAY else None),
                      validity=asm.validity, list_offsets=asm.list_offsets,
                      list_validity=asm.list_validity, num_slots=0,
                      def_levels=empty_lv, rep_levels=empty_lv)
    if nested:
        return concat_columns(out_parts)
    if aligned:
        vals_parts = [p[0] for p in out_parts]
        val_parts = [p[1] for p in out_parts]
        if isinstance(vals_parts[0], list):
            vals = [v for part in vals_parts for v in part]
        elif isinstance(vals_parts[0], tuple):  # (BA_ARRAYS, vals, offs)
            if len(vals_parts) == 1:
                vals = vals_parts[0]
            else:
                from .column import concat_byte_arrays

                cat, offs_cat = concat_byte_arrays(
                    [p[1] for p in vals_parts],
                    [p[2] for p in vals_parts])
                vals = (BA_ARRAYS, cat, offs_cat)
        else:
            vals = (vals_parts[0] if len(vals_parts) == 1
                    else np.concatenate(vals_parts))
        if all(v is None for v in val_parts):
            return vals, None

        def _rows(p):  # row count of one aligned part
            return len(p[2]) - 1 if isinstance(p, tuple) else len(p)

        validity = np.concatenate(
            [v if v is not None else np.ones(_rows(p), bool)
             for v, p in zip(val_parts, vals_parts)])
        return vals, validity
    if len(out_parts) == 1:
        return out_parts[0]
    if isinstance(out_parts[0], list):  # BYTE_ARRAY rows come back as lists
        return [v for part in out_parts for v in part]
    return np.concatenate(out_parts)


def _trim_nested(col, offset: int, count: int):
    """Slice ``count`` rows starting at ``offset`` out of a decoded nested
    column: rows begin where ``rep == 0``, so slice the Dremel level streams
    at row boundaries, slice the dense values to the matching span, and
    re-assemble list structure for just those rows."""
    from ..ops import levels as levels_ops
    from .column import Column

    rep = np.asarray(col.rep_levels)
    d = np.asarray(col.def_levels)
    leaf = col.leaf
    row_starts = np.flatnonzero(rep == 0)
    nrows = len(row_starts)
    s0 = int(row_starts[offset]) if offset < nrows else len(rep)
    s1 = int(row_starts[offset + count]) if offset + count < nrows else len(rep)
    present = d == leaf.max_definition_level
    vstart = int(np.count_nonzero(present[:s0]))
    vend = vstart + int(np.count_nonzero(present[s0:s1]))
    if col.is_dictionary_encoded():
        col.materialize_host()
    values = np.asarray(col.values)
    if col.offsets is not None:
        offs = np.asarray(col.offsets, np.int64)
        new_values = values[offs[vstart] : offs[vend]]
        new_offsets = (offs[vstart : vend + 1] - offs[vstart]).astype(np.int32)
    else:
        new_values = values[vstart:vend]  # first axis is the value ordinal
        new_offsets = None
    dd, rr = d[s0:s1], rep[s0:s1]
    asm = levels_ops.assemble(dd, rr, leaf)
    return Column(leaf=leaf, values=new_values, offsets=new_offsets,
                  validity=asm.validity, list_offsets=asm.list_offsets,
                  list_validity=asm.list_validity, num_slots=len(dd),
                  def_levels=dd, rep_levels=rr)


def _trim_flat(col, offset: int, count: int):
    """Slice ``count`` rows starting at ``offset`` out of a decoded flat column."""
    validity = None if col.validity is None else np.asarray(col.validity)
    if col.is_dictionary_encoded():
        # host decode keeps byte-array chunks in dictionary form; the old
        # behavior (whole-chunk gather during decode) moves here, where the
        # nested trim already does the same
        col.materialize_host()
    values = np.asarray(col.values)
    if values.ndim == 2 and values.dtype == np.uint32 and values.shape[1] == 2:
        dt = np.float64 if col.leaf.physical_type == Type.DOUBLE else np.int64
        values = np.ascontiguousarray(values).view(dt).reshape(-1)
    if validity is None:
        if col.offsets is not None:
            offs = np.asarray(col.offsets, np.int64)
            return _substrings(values, offs, offset, count)
        return values[offset : offset + count]
    # dense values: map slots → value ordinals
    vstart = int(np.count_nonzero(validity[:offset]))
    vend = vstart + int(np.count_nonzero(validity[offset : offset + count]))
    if col.offsets is not None:
        offs = np.asarray(col.offsets, np.int64)
        return _substrings(values, offs, vstart, vend - vstart)
    return values[vstart:vend]


def _substrings(values, offs, start, count):
    return [values[offs[i] : offs[i + 1]].tobytes() for i in range(start, start + count)]


def _trim_flat_aligned(col, offset: int, count: int, arrays: bool = False):
    """Like :func:`_trim_flat` but row-aligned: returns ``(values, validity)``
    where ``values`` has exactly ``count`` entries (null slots hold a zero
    fill / ``None`` for byte arrays) and ``validity`` is a bool mask, or
    ``None`` for non-nullable columns.

    ``arrays=True`` keeps BYTE_ARRAY spans in columnar form — ``values``
    becomes ``("ba_arrays", uint8 bytes, int64 offsets)`` over the DENSE
    present values (validity maps rows to value ordinals).  Materializing a
    python bytes object per row was the scan path's dominant cost; callers
    that filter first only pay for selected rows."""
    if col.is_dictionary_encoded():
        col.materialize_host()  # same gate as _trim_flat
    if arrays and col.offsets is not None:
        offs = np.asarray(col.offsets, np.int64)
        if col.validity is None:
            vmask = None
            v0, v1 = offset, offset + count
        else:
            validity = np.asarray(col.validity, bool)
            vmask = validity[offset : offset + count]
            v0 = int(np.count_nonzero(validity[:offset]))
            v1 = v0 + int(np.count_nonzero(vmask))
        base = int(offs[v0])
        vals = np.asarray(col.values)[base : int(offs[v1])]
        return (BA_ARRAYS, vals, offs[v0 : v1 + 1] - base), vmask
    if col.validity is None:
        return _trim_flat(col, offset, count), None
    validity = np.asarray(col.validity, bool)
    vmask = validity[offset : offset + count]
    vstart = int(np.count_nonzero(validity[:offset]))
    vend = vstart + int(np.count_nonzero(vmask))
    values = np.asarray(col.values)
    if values.ndim == 2 and values.dtype == np.uint32 and values.shape[1] == 2:
        dt = np.float64 if col.leaf.physical_type == Type.DOUBLE else np.int64
        values = np.ascontiguousarray(values).view(dt).reshape(-1)
    if col.offsets is not None:
        offs = np.asarray(col.offsets, np.int64)
        comp = _substrings(values, offs, vstart, vend - vstart)
        out = [None] * int(count)
        for p, v in zip(np.flatnonzero(vmask), comp):
            out[p] = v
        return out, vmask
    comp = values[vstart:vend]
    dt = comp.dtype if len(comp) else values.dtype
    # FLBA columns are (n, width) byte rows: the null fill must match
    out = np.zeros((int(count),) + tuple(values.shape[1:]), dt)
    out[vmask] = comp
    return out, vmask
