"""Durable write sinks: the write-side analog of ``source.py``.

The read stack survives flaky storage (io/faults.py); this module makes the
*write* stack survive crashes.  Parquet's footer-last layout means a torn
write is detectable, but detection is not durability: a crashed writer that
opened the destination path directly leaves a half-written file AT the
destination, and a ``close()`` that never fsyncs leaves a "finished" file
that the page cache can still lose.  The jax_graft north star (SURVEY.md §5
checkpoint/resume) needs the standard stronger contract:

- **Atomic commit** (:class:`AtomicFileSink`): bytes go to
  ``<dest>.<rand>.tmp`` in the same directory; ``close()`` fsyncs the file,
  renames it over the destination, and fsyncs the directory so the rename
  itself is durable.  The destination path therefore either does not exist
  or holds a complete, footer-terminated file — never a torn one.
- **Abort** (:meth:`Sink.abort`): discard the write and remove the temp (or
  partial) file.  ``ParquetWriter.__exit__`` aborts when an exception is in
  flight instead of serializing a valid-looking footer over half-written
  row groups.

``ParquetWriter`` builds an :class:`AtomicFileSink` for every path sink by
default (``WriterOptions(atomic_commit=False)`` opts into the old direct
write via :class:`FileSink`, which still fsyncs and supports abort).
"""

from __future__ import annotations

import os
import secrets
from dataclasses import dataclass
from typing import List, Optional

from ..errors import WriteError

__all__ = ["Sink", "FileSink", "AtomicFileSink", "BufferedSink", "WriteStats",
           "fsync_dir", "write_buffer_bytes"]

# default writeback buffer: large enough that page-sized writes coalesce into
# a handful of flushes per row group, small enough to stay cache-resident
DEFAULT_WRITE_BUFFER = 4 << 20


@dataclass
class WriteStats:
    """What the pipelined write actually did (observability; surfaced as
    ``ParquetWriter.write_stats`` — the write-side mirror of
    :class:`~parquet_tpu.io.prefetch.ReadStats`).

    ``encode_s`` sums per-chunk encode wall time (wherever it ran),
    ``emit_s`` the serial offset-assign + sink-write phase, and
    ``pool_wait_s`` the time emit blocked on a background encode that had
    not finished — the write pipeline's bubble meter: ~0 means encode fully
    hid behind the previous group's emit.  ``bytes_buffered`` counts bytes
    coalesced through a :class:`BufferedSink`, ``bytes_flushed`` bytes that
    actually left toward the OS (equal to the file size for path sinks),
    and ``sink_flushes`` how many vectored flushes carried them."""

    row_groups: int = 0
    overlapped_groups: int = 0
    encode_s: float = 0.0
    emit_s: float = 0.0
    pool_wait_s: float = 0.0
    bytes_buffered: int = 0
    bytes_flushed: int = 0
    sink_flushes: int = 0

    def overlap_ratio(self) -> float:
        """Fraction of background encode time that emit did NOT wait for —
        1.0 means the pipeline fully hid encode behind emit, 0.0 means the
        write was effectively serial."""
        if not self.overlapped_groups or self.encode_s <= 0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - self.pool_wait_s / self.encode_s))

    def as_dict(self) -> dict:
        return {"row_groups": self.row_groups,
                "overlapped_groups": self.overlapped_groups,
                "encode_s": round(self.encode_s, 4),
                "emit_s": round(self.emit_s, 4),
                "pool_wait_s": round(self.pool_wait_s, 4),
                "overlap_ratio": round(self.overlap_ratio(), 4),
                "bytes_buffered": self.bytes_buffered,
                "bytes_flushed": self.bytes_flushed,
                "sink_flushes": self.sink_flushes}


def write_buffer_bytes() -> int:
    """Writeback buffer size: ``PARQUET_TPU_WRITE_BUFFER`` (bytes; ``0``
    disables coalescing) or the 4 MiB default."""
    v = os.environ.get("PARQUET_TPU_WRITE_BUFFER", "").strip()
    if v:
        try:
            return max(0, int(v))
        except ValueError:
            pass
    return DEFAULT_WRITE_BUFFER


class Sink:
    """Minimal write-side protocol the writer relies on.  Any binary
    file-like object (``write``/``writelines``/``flush``/``close``) also
    works; ``abort`` is what distinguishes a crash-safe sink."""

    def write(self, data) -> int:
        raise NotImplementedError

    def writelines(self, parts) -> None:
        for p in parts:
            self.write(p)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        """Commit: make every written byte durable at the destination."""
        raise NotImplementedError

    def abort(self) -> None:
        """Discard: release resources and leave no (partial) destination."""
        raise NotImplementedError


def fsync_dir(path: str) -> None:
    """fsync the directory holding ``path`` so a just-created or
    just-renamed entry survives power loss.  Best-effort on filesystems or
    platforms where directories cannot be opened/fsynced (the rename itself
    already happened; only its durability ordering is at stake)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, getattr(os, "O_DIRECTORY", os.O_RDONLY))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class FileSink(Sink):
    """Direct-to-destination path sink: no atomicity, but fsync-on-close and
    abort-unlinks-the-partial-file.  The non-atomic mode of the writer
    (``atomic_commit=False``) — appropriate when the destination directory
    is not writable for siblings, or an external coordinator owns commit."""

    def __init__(self, path, fsync: bool = True):
        self.path = os.fspath(path)
        self.fsync = fsync
        self._f = open(self.path, "wb")

    def write(self, data) -> int:
        return self._f.write(data)

    def writelines(self, parts) -> None:
        self._f.writelines(parts)

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if self._f is None:
            return
        f, self._f = self._f, None
        try:
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        except BaseException:
            try:  # a failed flush/fsync must not leak the fd
                f.close()
            except OSError:
                pass
            raise
        f.close()

    def abort(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            # best-effort: abort usually runs inside an exception handler,
            # and an unlink failure must not mask the original error
            pass


class AtomicFileSink(Sink):
    """All-or-nothing path sink: write to ``<dest>.<rand>.tmp`` in the same
    directory, then ``close()`` = flush → fsync(file) → rename over ``dest``
    → fsync(dir).  Until close completes, the destination is untouched; a
    crash at ANY byte offset leaves at most a stray ``*.tmp`` (cheap to
    sweep — it can never be mistaken for data).  ``abort()`` unlinks the
    temp file and is idempotent; close-after-abort raises (there is nothing
    left to commit).

    The temp file lives in the destination's directory, not ``$TMPDIR``,
    because ``rename(2)`` is only atomic within one filesystem."""

    def __init__(self, dest, fsync: bool = True):
        self.dest = os.fspath(dest)
        self.fsync = fsync
        self.committed = False
        self.temp_path: Optional[str] = \
            f"{self.dest}.{secrets.token_hex(6)}.tmp"
        self._f = open(self.temp_path, "wb")

    def write(self, data) -> int:
        if self._f is None:
            raise ValueError(f"write on closed sink for {self.dest!r}")
        return self._f.write(data)

    def writelines(self, parts) -> None:
        if self._f is None:
            raise ValueError(f"write on closed sink for {self.dest!r}")
        self._f.writelines(parts)

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        """Commit.  Any failure along the way aborts (the temp file is
        removed) and re-raises — a half-committed state is never retained,
        and the destination is never touched by a failed commit."""
        if self.committed:
            return
        if self._f is None:
            raise ValueError(
                f"commit after abort for {self.dest!r} (nothing to commit)")
        tp = self.temp_path
        f, self._f = self._f, None
        try:
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
            f.close()
            os.replace(tp, self.dest)
        except BaseException as e:
            # release the fd, sweep the temp file, and surface the commit
            # failure with both locations attached
            try:
                f.close()  # double-close of a file object is a no-op
            except OSError:
                pass
            try:
                os.unlink(tp)
            except OSError:
                pass
            self.temp_path = None
            if isinstance(e, OSError):
                raise WriteError(f"atomic commit failed: {e}",
                                 path=self.dest, temp_path=tp) from e
            raise
        self.temp_path = None
        self.committed = True
        if self.fsync:
            # the rename is on disk only once the directory entry is:
            # without this, a crash can resurrect the OLD destination
            fsync_dir(self.dest)

    def abort(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        tp, self.temp_path = self.temp_path, None
        if tp is not None and not self.committed:
            try:
                os.unlink(tp)
            except OSError:
                # best-effort: abort usually runs inside an exception
                # handler, and an unlink failure must not mask the original
                pass


class BufferedSink(Sink):
    """Coalescing writeback layer over any sink: page-sized writes
    accumulate by reference (no join copy) and flush to the inner sink as
    one vectored ``writelines`` once ``buffer_bytes`` is pending — the
    write-side analog of the prefetcher's coalesced window reads.  The
    per-page ``write()`` syscall overhead this removes is the emit phase's
    residual cost once encode is pipelined (io/writer.py).

    ``buffer_bytes=0`` is a counting pass-through (every write goes straight
    to the inner sink); the default comes from ``PARQUET_TPU_WRITE_BUFFER``.
    ``flush()``/``close()`` drain the buffer first, so the inner sink's
    commit (fsync + atomic rename for :class:`AtomicFileSink`) always covers
    every accepted byte; ``abort()`` drops the buffer and aborts the inner
    sink.  Buffered parts are kept by reference — callers must not mutate a
    buffer after writing it (the parquet writer only writes immutable
    ``bytes``).  A ``stats`` :class:`WriteStats` accounts buffered vs
    flushed bytes and flush counts."""

    def __init__(self, inner: Sink, buffer_bytes: Optional[int] = None,
                 stats: Optional[WriteStats] = None):
        self.inner = inner
        self.buffer_bytes = (write_buffer_bytes() if buffer_bytes is None
                             else max(0, int(buffer_bytes)))
        self.stats = stats
        self._parts: List[bytes] = []
        self._buffered = 0

    def write(self, data) -> int:
        n = len(data)
        if self.buffer_bytes <= 0:
            self.inner.write(data)
            if self.stats is not None:
                self.stats.bytes_flushed += n
            return n
        self._parts.append(data)
        self._buffered += n
        if self.stats is not None:
            self.stats.bytes_buffered += n
        if self._buffered >= self.buffer_bytes:
            self._flush_buffer()
        return n

    def writelines(self, parts) -> None:
        if self.buffer_bytes <= 0:
            n = 0
            parts = list(parts)
            for p in parts:
                n += len(p)
            self.inner.writelines(parts)
            if self.stats is not None:
                self.stats.bytes_flushed += n
            return
        for p in parts:
            self._parts.append(p)
            self._buffered += len(p)
            if self.stats is not None:
                self.stats.bytes_buffered += len(p)
        if self._buffered >= self.buffer_bytes:
            self._flush_buffer()

    def _flush_buffer(self) -> None:
        if not self._parts:
            return
        # hand the parts over before writing: a failed flush must not be
        # replayed (bytes may be partially down — the writer aborts on any
        # write error, and a retry would double-write the prefix)
        parts, self._parts = self._parts, []
        n, self._buffered = self._buffered, 0
        self.inner.writelines(parts)
        if self.stats is not None:
            self.stats.bytes_flushed += n
            self.stats.sink_flushes += 1

    def flush(self) -> None:
        self._flush_buffer()
        self.inner.flush()

    def close(self) -> None:
        self._flush_buffer()
        self.inner.close()

    def abort(self) -> None:
        self._parts = []
        self._buffered = 0
        ab = getattr(self.inner, "abort", None)
        if ab is not None:
            ab()
        else:
            try:
                self.inner.close()
            except OSError:
                pass
