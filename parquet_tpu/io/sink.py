"""Durable write sinks: the write-side analog of ``source.py``.

The read stack survives flaky storage (io/faults.py); this module makes the
*write* stack survive crashes.  Parquet's footer-last layout means a torn
write is detectable, but detection is not durability: a crashed writer that
opened the destination path directly leaves a half-written file AT the
destination, and a ``close()`` that never fsyncs leaves a "finished" file
that the page cache can still lose.  The jax_graft north star (SURVEY.md §5
checkpoint/resume) needs the standard stronger contract:

- **Atomic commit** (:class:`AtomicFileSink`): bytes go to
  ``<dest>.<rand>.tmp`` in the same directory; ``close()`` fsyncs the file,
  renames it over the destination, and fsyncs the directory so the rename
  itself is durable.  The destination path therefore either does not exist
  or holds a complete, footer-terminated file — never a torn one.
- **Abort** (:meth:`Sink.abort`): discard the write and remove the temp (or
  partial) file.  ``ParquetWriter.__exit__`` aborts when an exception is in
  flight instead of serializing a valid-looking footer over half-written
  row groups.

``ParquetWriter`` builds an :class:`AtomicFileSink` for every path sink by
default (``WriterOptions(atomic_commit=False)`` opts into the old direct
write via :class:`FileSink`, which still fsyncs and supports abort).
"""

from __future__ import annotations

import os
import secrets
from dataclasses import dataclass
from typing import List, Optional

from ..errors import WriteError
from ..utils.env import env_bool, env_opt_bytes
from ..utils.locks import make_lock
from ..obs import trace as _trace
from ..obs.ledger import ledger_account, maybe_check_pressure
from ..obs.metrics import counter as _counter
from ..obs.scope import account as _account

# resource-ledger account (obs/ledger.py): bytes currently coalescing in
# BufferedSinks process-wide — added as pages buffer, released as flushes
# hand them to the OS (or abort drops them), capacity = the live
# writeback knob
_ACC_WBUF = ledger_account("write.buffer", capacity=lambda:
                           write_buffer_bytes())

__all__ = ["Sink", "FileSink", "AtomicFileSink", "MmapFileSink",
           "BufferedSink", "WriteStats", "atomic_path_sink",
           "fsync_dir", "write_buffer_bytes", "write_autotune",
           "write_autotune_enabled"]

# default writeback buffer: large enough that page-sized writes coalesce into
# a handful of flushes per row group, small enough to stay cache-resident
DEFAULT_WRITE_BUFFER = 4 << 20

_HAS_WRITEV = hasattr(os, "writev")
try:
    _IOV_MAX = os.sysconf("SC_IOV_MAX")
    if _IOV_MAX <= 0:
        _IOV_MAX = 1024
except (AttributeError, OSError, ValueError):
    _IOV_MAX = 1024


@dataclass
class WriteStats:
    """What the pipelined write actually did (observability; surfaced as
    ``ParquetWriter.write_stats`` — the write-side mirror of
    :class:`~parquet_tpu.io.prefetch.ReadStats`).

    ``encode_s`` sums per-chunk encode wall time (wherever it ran),
    ``emit_s`` the serial offset-assign + sink-write phase, and
    ``pool_wait_s`` the time emit blocked on a background encode that had
    not finished — the write pipeline's bubble meter: ~0 means encode fully
    hid behind the previous group's emit.  ``bytes_buffered`` counts bytes
    coalesced through a :class:`BufferedSink`, ``bytes_flushed`` bytes that
    actually left toward the OS (equal to the file size for path sinks),
    ``sink_flushes`` how many coalesced flushes carried them, and
    ``writev_flushes`` how many of those went through the true vectored
    ``os.writev`` path (raw-fd sinks) instead of ``writelines``."""

    row_groups: int = 0
    overlapped_groups: int = 0
    encode_s: float = 0.0
    emit_s: float = 0.0
    pool_wait_s: float = 0.0
    bytes_buffered: int = 0
    bytes_flushed: int = 0
    sink_flushes: int = 0
    writev_flushes: int = 0

    def overlap_ratio(self) -> float:
        """Fraction of background encode time that emit did NOT wait for —
        1.0 means the pipeline fully hid encode behind emit, 0.0 means the
        write was effectively serial."""
        if not self.overlapped_groups or self.encode_s <= 0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - self.pool_wait_s / self.encode_s))

    def as_dict(self) -> dict:
        return {"row_groups": self.row_groups,
                "overlapped_groups": self.overlapped_groups,
                "encode_s": round(self.encode_s, 4),
                "emit_s": round(self.emit_s, 4),
                "pool_wait_s": round(self.pool_wait_s, 4),
                "overlap_ratio": round(self.overlap_ratio(), 4),
                "bytes_buffered": self.bytes_buffered,
                "bytes_flushed": self.bytes_flushed,
                "sink_flushes": self.sink_flushes,
                "writev_flushes": self.writev_flushes}

    def publish(self) -> None:
        """Fold this writer's totals into the process-wide metrics
        registry (parquet_tpu/obs) and the current op scope — called at
        successful close.  Idempotent: a double-close (or a direct second
        call) publishes exactly once, so registry totals can never
        double."""
        if getattr(self, "_published", False):
            return
        self._published = True
        _account(_counter("write.row_groups"), self.row_groups)
        _account(_counter("write.overlapped_groups"), self.overlapped_groups)
        _account(_counter("write.encode_s"), self.encode_s)
        _account(_counter("write.emit_s"), self.emit_s)
        _account(_counter("write.pool_wait_s"), self.pool_wait_s)
        _account(_counter("write.bytes_buffered"), self.bytes_buffered)
        _account(_counter("write.bytes_flushed"), self.bytes_flushed)
        _account(_counter("write.sink_flushes"), self.sink_flushes)
        _account(_counter("write.writev_flushes"), self.writev_flushes)


# write-side auto-tuner (the mirror of io/prefetch.py's depth/window tuner):
# a writer that still needed many coalesced flushes PER ROW GROUP had a
# buffer too small for its page sizes — grow it for the next writer; one
# whose groups fit in a flush or two steps back toward the default
_WRITE_TUNE_RAISE_FLUSHES_PER_RG = 8.0
_WRITE_TUNE_DECAY_FLUSHES_PER_RG = 1.5
_WRITE_TUNE_MAX_BUFFER = 64 << 20


def write_autotune_enabled() -> bool:
    """``PARQUET_TPU_WRITE_AUTOTUNE`` opt-out (default on)."""
    return env_bool("PARQUET_TPU_WRITE_AUTOTUNE")


class _WriteAutoTuneState:
    """Process-wide feedback from observed :class:`WriteStats` to the next
    writer's writeback buffer size (ROADMAP follow-on: grow
    ``PARQUET_TPU_WRITE_BUFFER`` when ``sink_flushes`` per row group stays
    high).  An explicit env pin or ``PARQUET_TPU_WRITE_AUTOTUNE=0``
    bypasses the state entirely."""

    def __init__(self):
        self._lock = make_lock("sink.write_autotune")
        self.buffer = None  # None = default

    def suggest(self):
        with self._lock:
            return self.buffer

    def observe(self, stats: WriteStats) -> None:
        if stats.row_groups <= 0 or stats.bytes_buffered <= 0:
            return  # nothing buffered: pass-through writer, no signal
        per_rg = stats.sink_flushes / stats.row_groups
        with self._lock:
            b = self.buffer or DEFAULT_WRITE_BUFFER
            if per_rg > _WRITE_TUNE_RAISE_FLUSHES_PER_RG \
                    and b < _WRITE_TUNE_MAX_BUFFER:
                self.buffer = b * 2
            elif per_rg < _WRITE_TUNE_DECAY_FLUSHES_PER_RG \
                    and b > DEFAULT_WRITE_BUFFER:
                b //= 2
                self.buffer = None if b <= DEFAULT_WRITE_BUFFER else b

    def reset(self) -> None:
        with self._lock:
            self.buffer = None


_WRITE_AUTOTUNE = _WriteAutoTuneState()


def write_autotune() -> _WriteAutoTuneState:
    """The process-wide write auto-tune state (tests reset it)."""
    return _WRITE_AUTOTUNE


def _env_write_buffer() -> Optional[int]:
    """``PARQUET_TPU_WRITE_BUFFER`` as a pin, or None when unset OR
    unparseable — the single classifier both the size resolution and the
    autotune-eligibility gate consult, so a garbage value cannot count as
    "pinned" in one place while being ignored in the other."""
    return env_opt_bytes("PARQUET_TPU_WRITE_BUFFER")


def write_buffer_bytes() -> int:
    """Writeback buffer size: ``PARQUET_TPU_WRITE_BUFFER`` (bytes; ``0``
    disables coalescing) wins outright; otherwise the auto-tuned size from
    observed flush rates, falling back to the 4 MiB default."""
    pinned = _env_write_buffer()
    if pinned is not None:
        return pinned
    if write_autotune_enabled():
        tuned = _WRITE_AUTOTUNE.suggest()
        if tuned:
            return tuned
    return DEFAULT_WRITE_BUFFER


class Sink:
    """Minimal write-side protocol the writer relies on.  Any binary
    file-like object (``write``/``writelines``/``flush``/``close``) also
    works; ``abort`` is what distinguishes a crash-safe sink."""

    def write(self, data) -> int:
        raise NotImplementedError

    def writelines(self, parts) -> None:
        for p in parts:
            self.write(p)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        """Commit: make every written byte durable at the destination."""
        raise NotImplementedError

    def abort(self) -> None:
        """Discard: release resources and leave no (partial) destination."""
        raise NotImplementedError


def fsync_dir(path: str) -> None:
    """fsync the directory holding ``path`` so a just-created or
    just-renamed entry survives power loss.  Best-effort on filesystems or
    platforms where directories cannot be opened/fsynced (the rename itself
    already happened; only its durability ordering is at stake)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, getattr(os, "O_DIRECTORY", os.O_RDONLY))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _invalidate_dest(path) -> None:
    """Drop any cached footers/chunks of a just-committed destination.
    The caches' fstat identity handles rename-replaces and mtime-moving
    rewrites on its own; this closes the residual in-place same-size
    same-clock-tick window for writes made through this library."""
    from .cache import invalidate_path

    invalidate_path(path)


def _flushed_fileno(f):
    """Flush a file object's python-level buffer and return its fd (None
    when closed) — the one raw_fd contract both path sinks share."""
    if f is None:
        return None
    f.flush()
    return f.fileno()


class FileSink(Sink):
    """Direct-to-destination path sink: no atomicity, but fsync-on-close and
    abort-unlinks-the-partial-file.  The non-atomic mode of the writer
    (``atomic_commit=False``) — appropriate when the destination directory
    is not writable for siblings, or an external coordinator owns commit."""

    def __init__(self, path, fsync: bool = True):
        self.path = os.fspath(path)
        self.fsync = fsync
        self._f = open(self.path, "wb")

    def write(self, data) -> int:
        return self._f.write(data)

    def writelines(self, parts) -> None:
        self._f.writelines(parts)

    def flush(self) -> None:
        self._f.flush()

    def raw_fd(self):
        """OS-level fd for true vectored writes (the BufferedSink writev
        path).  The python-level buffer is flushed first so byte order is
        preserved across mixed fd/file-object writes; None when closed."""
        return _flushed_fileno(self._f)

    def close(self) -> None:
        if self._f is None:
            return
        f, self._f = self._f, None
        try:
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        except BaseException:
            try:  # a failed flush/fsync must not leak the fd
                f.close()
            except OSError:
                pass
            raise
        f.close()
        _invalidate_dest(self.path)

    def abort(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            # best-effort: abort usually runs inside an exception handler,
            # and an unlink failure must not mask the original error
            pass


class AtomicFileSink(Sink):
    """All-or-nothing path sink: write to ``<dest>.<rand>.tmp`` in the same
    directory, then ``close()`` = flush → fsync(file) → rename over ``dest``
    → fsync(dir).  Until close completes, the destination is untouched; a
    crash at ANY byte offset leaves at most a stray ``*.tmp`` (cheap to
    sweep — it can never be mistaken for data).  ``abort()`` unlinks the
    temp file and is idempotent; close-after-abort raises (there is nothing
    left to commit).

    The temp file lives in the destination's directory, not ``$TMPDIR``,
    because ``rename(2)`` is only atomic within one filesystem."""

    def __init__(self, dest, fsync: bool = True):
        self.dest = os.fspath(dest)
        self.fsync = fsync
        self.committed = False
        self.temp_path: Optional[str] = \
            f"{self.dest}.{secrets.token_hex(6)}.tmp"
        self._f = open(self.temp_path, "wb")

    def write(self, data) -> int:
        if self._f is None:
            raise ValueError(f"write on closed sink for {self.dest!r}")
        return self._f.write(data)

    def writelines(self, parts) -> None:
        if self._f is None:
            raise ValueError(f"write on closed sink for {self.dest!r}")
        self._f.writelines(parts)

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def raw_fd(self):
        """OS-level fd of the TEMP file for true vectored writes (see
        :meth:`FileSink.raw_fd`); None when closed or committed."""
        return _flushed_fileno(self._f)

    def close(self) -> None:
        """Commit.  Any failure along the way aborts (the temp file is
        removed) and re-raises — a half-committed state is never retained,
        and the destination is never touched by a failed commit."""
        if self.committed:
            return
        if self._f is None:
            raise ValueError(
                f"commit after abort for {self.dest!r} (nothing to commit)")
        tp = self.temp_path
        f, self._f = self._f, None
        try:
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
            f.close()
            os.replace(tp, self.dest)
        except BaseException as e:
            # release the fd, sweep the temp file, and surface the commit
            # failure with both locations attached
            try:
                f.close()  # double-close of a file object is a no-op
            except OSError:
                pass
            try:
                os.unlink(tp)
            except OSError:
                pass
            self.temp_path = None
            if isinstance(e, OSError):
                raise WriteError(f"atomic commit failed: {e}",
                                 path=self.dest, temp_path=tp) from e
            raise
        self.temp_path = None
        self.committed = True
        if self.fsync:
            # the rename is on disk only once the directory entry is:
            # without this, a crash can resurrect the OLD destination
            fsync_dir(self.dest)
        _invalidate_dest(self.dest)

    def abort(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        tp, self.temp_path = self.temp_path, None
        if tp is not None and not self.committed:
            try:
                os.unlink(tp)
            except OSError:
                # best-effort: abort usually runs inside an exception
                # handler, and an unlink failure must not mask the original
                pass


class MmapFileSink(Sink):
    """mmap-backed atomic path sink (the ``PARQUET_TPU_MMAP_SINK``
    experiment): bytes copy into a memory-mapped temp file grown in
    8 MiB steps instead of going through buffered ``write()`` calls;
    ``close()`` = flush(map) → truncate-to-length → fsync → rename over
    the destination → fsync(dir) — the exact commit contract of
    :class:`AtomicFileSink`, so the crash matrix covers it unchanged.

    Measured verdict (bench cfg6 ``mmap_sink`` A/B): ~0.75x of the
    writev path — the map's fault+copy cost loses to vectored writes on
    page-cache-backed filesystems.  KEPT strictly as an opt-in because
    it removes syscall pressure under heavy seccomp/audit regimes; not
    the default."""

    _GROW = 8 << 20

    def __init__(self, dest, fsync: bool = True):
        import mmap

        self.dest = os.fspath(dest)
        self.fsync = fsync
        self.committed = False
        self.temp_path: Optional[str] = \
            f"{self.dest}.{secrets.token_hex(6)}.tmp"
        self._f = open(self.temp_path, "w+b")
        self._f.truncate(self._GROW)
        self._mm = mmap.mmap(self._f.fileno(), self._GROW)
        self._len = 0

    def _ensure(self, need: int) -> None:
        if need <= len(self._mm):
            return
        size = len(self._mm)
        while size < need:
            size += self._GROW
        self._f.truncate(size)
        self._mm.resize(size)

    def write(self, data) -> int:
        if self._f is None:
            raise ValueError(f"write on closed sink for {self.dest!r}")
        # normalize to a byte view without copying (bytes(data) would
        # memcpy every payload once more before the map copy)
        mv = data if isinstance(data, (bytes, bytearray)) \
            else memoryview(data).cast("B")
        n = len(mv)
        self._ensure(self._len + n)
        self._mm[self._len : self._len + n] = mv
        self._len += n
        return n

    def writelines(self, parts) -> None:
        for p in parts:
            self.write(p)

    def flush(self) -> None:
        if self._mm is not None:
            self._mm.flush()

    def close(self) -> None:
        """Commit: flush the map, trim to the written length, fsync,
        rename, fsync(dir) — failures abort (temp removed) and re-raise,
        exactly like :class:`AtomicFileSink.close`."""
        if self.committed:
            return
        if self._f is None:
            raise ValueError(
                f"commit after abort for {self.dest!r} (nothing to commit)")
        tp = self.temp_path
        f, self._f = self._f, None
        mm, self._mm = self._mm, None
        try:
            mm.flush()
            mm.close()
            f.truncate(self._len)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
            f.close()
            os.replace(tp, self.dest)
        except BaseException as e:
            try:
                f.close()
            except OSError:
                pass
            try:
                os.unlink(tp)
            except OSError:
                pass
            self.temp_path = None
            if isinstance(e, OSError):
                raise WriteError(f"mmap sink commit failed: {e}",
                                 path=self.dest, temp_path=tp) from e
            raise
        self.temp_path = None
        self.committed = True
        if self.fsync:
            fsync_dir(self.dest)
        _account(_counter("write.mmap_commits"))
        _invalidate_dest(self.dest)

    def abort(self) -> None:
        f, self._f = self._f, None
        mm, self._mm = self._mm, None
        if mm is not None:
            try:
                mm.close()
            except (OSError, ValueError):
                pass
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        tp, self.temp_path = self.temp_path, None
        if tp is not None and not self.committed:
            try:
                os.unlink(tp)
            except OSError:
                pass


def atomic_path_sink(dest, fsync: bool = True) -> Sink:
    """The atomic path sink the writer (and the crash harness) commit
    through: :class:`MmapFileSink` when ``PARQUET_TPU_MMAP_SINK`` opts
    in, else :class:`AtomicFileSink` — one selector so the crash matrix
    always covers whichever variant production writes use."""
    if env_bool("PARQUET_TPU_MMAP_SINK"):
        return MmapFileSink(dest, fsync=fsync)
    return AtomicFileSink(dest, fsync=fsync)


def _writev_all(fd, parts) -> None:
    """Write every part to ``fd`` with ``os.writev`` — one syscall per
    ``IOV_MAX`` group instead of one per part — resuming short (partial)
    writes mid-part until every byte is down."""
    queue = [memoryview(p) for p in parts if len(p)]
    i = 0
    while i < len(queue):
        batch = queue[i:i + _IOV_MAX]
        written = os.writev(fd, batch)
        if written <= 0:
            raise OSError(f"writev wrote {written} of "
                          f"{sum(len(m) for m in batch)} bytes")
        for mv in batch:
            n = len(mv)
            if written >= n:
                written -= n
                i += 1
            else:
                queue[i] = mv[written:]
                break


class BufferedSink(Sink):
    """Coalescing writeback layer over any sink: page-sized writes
    accumulate by reference (no join copy) and flush to the inner sink as
    one vectored write once ``buffer_bytes`` is pending — a true
    ``os.writev`` when the inner sink exposes a raw fd (``raw_fd()``;
    FileSink/AtomicFileSink do), a ``writelines`` fallback otherwise — the
    write-side analog of the prefetcher's coalesced window reads.  The
    per-page ``write()`` syscall overhead this removes is the emit phase's
    residual cost once encode is pipelined (io/writer.py).

    ``buffer_bytes=0`` is a counting pass-through (every write goes straight
    to the inner sink); the default comes from ``PARQUET_TPU_WRITE_BUFFER``.
    ``flush()``/``close()`` drain the buffer first, so the inner sink's
    commit (fsync + atomic rename for :class:`AtomicFileSink`) always covers
    every accepted byte; ``abort()`` drops the buffer and aborts the inner
    sink.  Buffered parts are kept by reference — callers must not mutate a
    buffer after writing it (the parquet writer only writes immutable
    ``bytes``).  A ``stats`` :class:`WriteStats` accounts buffered vs
    flushed bytes and flush counts."""

    def __init__(self, inner: Sink, buffer_bytes: Optional[int] = None,
                 stats: Optional[WriteStats] = None):
        self.inner = inner
        self.buffer_bytes = (write_buffer_bytes() if buffer_bytes is None
                             else max(0, int(buffer_bytes)))
        self.stats = stats
        # auto-tune eligibility: the writer observes this sink's WriteStats
        # into the process tuner only when the size came from the tuner's
        # own resolution path (no explicit arg, no env pin) — mirrors the
        # prefetcher's _tunable gate
        self._tunable = (buffer_bytes is None and write_autotune_enabled()
                         and _env_write_buffer() is None)
        self._parts: List[bytes] = []
        self._buffered = 0

    def write(self, data) -> int:
        n = len(data)
        if self.buffer_bytes <= 0:
            self.inner.write(data)
            if self.stats is not None:
                self.stats.bytes_flushed += n
            return n
        self._parts.append(data)
        self._buffered += n
        _ACC_WBUF.add(n)
        if self.stats is not None:
            self.stats.bytes_buffered += n
        if self._buffered >= self.buffer_bytes:
            self._flush_buffer()
        else:
            # growth site: the write buffer can push the process over a
            # watermark between flushes (two env reads when none is set)
            maybe_check_pressure()
        return n

    def writelines(self, parts) -> None:
        if self.buffer_bytes <= 0:
            n = 0
            parts = list(parts)
            for p in parts:
                n += len(p)
            self.inner.writelines(parts)
            if self.stats is not None:
                self.stats.bytes_flushed += n
            return
        for p in parts:
            self._parts.append(p)
            self._buffered += len(p)
            _ACC_WBUF.add(len(p))
            if self.stats is not None:
                self.stats.bytes_buffered += len(p)
        if self._buffered >= self.buffer_bytes:
            self._flush_buffer()
        else:
            maybe_check_pressure()

    def _flush_buffer(self) -> None:
        if not self._parts:
            return
        if _trace.TRACE_ENABLED:
            with _trace.span("sink.flush", bytes=self._buffered,
                             parts=len(self._parts)):
                self._flush_buffer_impl()
            return
        self._flush_buffer_impl()

    def _flush_buffer_impl(self) -> None:
        # hand the parts over before writing: a failed flush must not be
        # replayed (bytes may be partially down — the writer aborts on any
        # write error, and a retry would double-write the prefix)
        parts, self._parts = self._parts, []
        n, self._buffered = self._buffered, 0
        _ACC_WBUF.sub(n)  # released at hand-over: a failed flush's bytes
        # are dropped, not re-buffered, so the ledger must not hold them
        fd = None
        if _HAS_WRITEV:
            raw = getattr(self.inner, "raw_fd", None)
            if raw is not None:
                fd = raw()
        if fd is not None:
            _writev_all(fd, parts)
            if self.stats is not None:
                self.stats.writev_flushes += 1
        else:
            self.inner.writelines(parts)
        if self.stats is not None:
            self.stats.bytes_flushed += n
            self.stats.sink_flushes += 1

    def flush(self) -> None:
        self._flush_buffer()
        self.inner.flush()

    def close(self) -> None:
        self._flush_buffer()
        self.inner.close()

    def abort(self) -> None:
        self._parts = []
        _ACC_WBUF.sub(self._buffered)
        self._buffered = 0
        ab = getattr(self.inner, "abort", None)
        if ab is not None:
            ab()
        else:
            try:
                self.inner.close()
            except OSError:
                pass
