"""Random-access byte sources (the ``io.ReaderAt`` analog, SURVEY.md §1 L0).

Supports paths (os.pread — no whole-file buffering, scan-friendly), bytes, and
file-like objects.  All reads are positional and thread-safe, matching the
reference's documented concurrent-read guarantees (SURVEY.md §2.5a).
"""

from __future__ import annotations

import io
import os
import threading
import time
from typing import Union

import numpy as np


class Source:
    def pread(self, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def pread_view(self, offset: int, size: int):
        """Like :meth:`pread`, but may return any zero-copy buffer (a
        memoryview or numpy view) when the backing store allows it; callers
        must treat the result as read-only.  Default: a plain bytes copy."""
        return self.pread(offset, size)

    def size(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileSource(Source):
    def __init__(self, path: str):
        self.path = path
        self._fd = os.open(path, os.O_RDONLY)
        self._size = os.fstat(self._fd).st_size

    def _checked_fd(self) -> int:
        fd = self._fd
        if fd is None:
            raise ValueError(f"read on closed source {self.path!r}")
        return fd

    def pread(self, offset: int, size: int) -> bytes:
        fd = self._checked_fd()
        # POSIX pread may return fewer bytes than requested without being at
        # EOF (signals, NFS): accumulate until full or truly short
        parts = []
        got = 0
        while got < size:
            chunk = os.pread(fd, size - got, offset + got)
            if not chunk:
                raise IOError(
                    f"short read at {offset}: wanted {size}, got {got}")
            parts.append(chunk)
            got += len(chunk)
        return parts[0] if len(parts) == 1 else b"".join(parts)

    def pread_view(self, offset: int, size: int) -> np.ndarray:
        """Read straight into a numpy buffer — one copy (kernel→array)
        instead of pread's kernel→bytes→join."""
        fd = self._checked_fd()
        buf = np.empty(size, np.uint8)
        mv = memoryview(buf)
        got = 0
        while got < size:
            n = os.preadv(fd, [mv[got:]], offset + got)
            if n <= 0:
                raise IOError(
                    f"short read at {offset}: wanted {size}, got {got}")
            got += n
        return buf

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        # idempotent: double-close is a no-op, not an EBADF crash
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


def _check_read_args(offset: int, size: int) -> None:
    """Reject negative offsets/sizes: a negative offset silently slices from
    the END of a python buffer and returns wrong bytes."""
    if offset < 0 or size < 0:
        raise IOError(f"invalid read: offset={offset} size={size} "
                      "(negative offsets/sizes are corruption, not wrap-around)")


class BytesSource(Source):
    def __init__(self, data: Union[bytes, bytearray, memoryview]):
        self._data = memoryview(data)

    def pread(self, offset: int, size: int) -> bytes:
        _check_read_args(offset, size)
        out = self._data[offset : offset + size]
        if len(out) != size:
            raise IOError(f"short read at {offset}")
        return bytes(out)

    def pread_view(self, offset: int, size: int):
        _check_read_args(offset, size)
        out = self._data[offset : offset + size]
        if len(out) != size:
            raise IOError(f"short read at {offset}")
        if not self._data.readonly:
            # a bytearray-backed source: decoded columns may lazily reference
            # chunk bytes, and a caller mutating its buffer after read()
            # would silently corrupt them — zero-copy only from immutable
            # backings
            return bytes(out)
        return out

    def size(self) -> int:
        return len(self._data)


class FileLikeSource(Source):
    """Wraps a seekable file-like object; serializes seek+read."""

    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()
        f.seek(0, io.SEEK_END)
        self._size = f.tell()

    def pread(self, offset: int, size: int) -> bytes:
        f = self._f
        if f is None:
            raise ValueError("read on closed source")
        with self._lock:
            f.seek(offset)
            out = f.read(size)
        if len(out) != size:
            raise IOError(f"short read at {offset}")
        return out

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        # idempotent; closes the wrapped file object (the wrapper owns the
        # read position anyway — nobody else can use it concurrently).
        # Taken under the lock so an in-flight pread finishes its seek+read
        # before the underlying file goes away.
        with self._lock:
            f = self._f
            if f is not None:
                self._f = None
                f.close()


class RetryingSource(Source):
    """Bounded-retry wrapper over any Source — the retryable-host-IO analog
    of SURVEY.md §5 (flaky network filesystems / object-store FUSE mounts).

    Retries transient ``OSError``s with exponential backoff plus uniform
    ±``jitter`` (decorrelates retry storms across concurrent readers); short
    reads at true EOF are not transient and propagate immediately
    (``IOError`` raised with "short read" is not retried to keep corruption
    loud).  For retry + deadline + degraded-read semantics threaded through
    the whole read stack, use :class:`~parquet_tpu.io.faults.FaultPolicy`
    instead — this wrapper stays for bare-source callers.
    """

    def __init__(self, inner: Source, retries: int = 3,
                 backoff_s: float = 0.05, jitter: float = 0.25):
        self.inner = inner
        self.retries = retries
        self.backoff_s = backoff_s
        self.jitter = jitter

    @property
    def path(self):
        return getattr(self.inner, "path", None)

    def _retry(self, fn, offset: int, size: int):
        from .faults import FaultPolicy, is_corrupt_oserror  # deferred:
        # faults imports source

        delays = None  # built lazily: the happy path never constructs one
        while True:
            try:
                return fn(offset, size)
            except OSError as e:
                if is_corrupt_oserror(e):
                    raise  # corruption, not transience
                if delays is None:
                    delays = FaultPolicy(max_retries=self.retries,
                                         backoff_s=self.backoff_s,
                                         jitter=self.jitter).delays()
                delay = next(delays, None)
                if delay is None:
                    raise
                time.sleep(delay)

    def pread(self, offset: int, size: int) -> bytes:
        return self._retry(self.inner.pread, offset, size)

    def pread_view(self, offset: int, size: int):
        # delegate (don't fall back to Source's copying default): keeps
        # FileSource's zero-copy preadv path under retry
        return self._retry(self.inner.pread_view, offset, size)

    def size(self) -> int:
        return self.inner.size()

    def close(self) -> None:
        self.inner.close()


def as_source(obj) -> Source:
    if isinstance(obj, Source):
        return obj
    if isinstance(obj, (str, os.PathLike)):
        return FileSource(os.fspath(obj))
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return BytesSource(obj)
    if hasattr(obj, "read") and hasattr(obj, "seek"):
        return FileLikeSource(obj)
    raise TypeError(f"cannot make a Source from {type(obj)!r}")
