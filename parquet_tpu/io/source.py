"""Random-access byte sources (the ``io.ReaderAt`` analog, SURVEY.md §1 L0).

Supports paths (os.pread — no whole-file buffering, scan-friendly), bytes, and
file-like objects.  All reads are positional and thread-safe, matching the
reference's documented concurrent-read guarantees (SURVEY.md §2.5a).
"""

from __future__ import annotations

import io
import os
import threading
from typing import Union


class Source:
    def pread(self, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileSource(Source):
    def __init__(self, path: str):
        self.path = path
        self._fd = os.open(path, os.O_RDONLY)
        self._size = os.fstat(self._fd).st_size

    def pread(self, offset: int, size: int) -> bytes:
        out = os.pread(self._fd, size, offset)
        if len(out) != size:
            raise IOError(f"short read at {offset}: wanted {size}, got {len(out)}")
        return out

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


class BytesSource(Source):
    def __init__(self, data: Union[bytes, bytearray, memoryview]):
        self._data = memoryview(data)

    def pread(self, offset: int, size: int) -> bytes:
        out = self._data[offset : offset + size]
        if len(out) != size:
            raise IOError(f"short read at {offset}")
        return bytes(out)

    def size(self) -> int:
        return len(self._data)


class FileLikeSource(Source):
    """Wraps a seekable file-like object; serializes seek+read."""

    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()
        f.seek(0, io.SEEK_END)
        self._size = f.tell()

    def pread(self, offset: int, size: int) -> bytes:
        with self._lock:
            self._f.seek(offset)
            out = self._f.read(size)
        if len(out) != size:
            raise IOError(f"short read at {offset}")
        return out

    def size(self) -> int:
        return self._size


def as_source(obj) -> Source:
    if isinstance(obj, Source):
        return obj
    if isinstance(obj, (str, os.PathLike)):
        return FileSource(os.fspath(obj))
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return BytesSource(obj)
    if hasattr(obj, "read") and hasattr(obj, "seek"):
        return FileLikeSource(obj)
    raise TypeError(f"cannot make a Source from {type(obj)!r}")
