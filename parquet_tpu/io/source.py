"""Random-access byte sources (the ``io.ReaderAt`` analog, SURVEY.md §1 L0).

Supports paths (os.pread — no whole-file buffering, scan-friendly), bytes, and
file-like objects.  All reads are positional and thread-safe, matching the
reference's documented concurrent-read guarantees (SURVEY.md §2.5a).
"""

from __future__ import annotations

import io
import mmap as _mmap
import os
from typing import Union

import numpy as np

from ..errors import ShortReadError
from ..utils import locks as _locks
from ..utils.env import env_bool
from ..utils.locks import make_lock

# every terminal read accounts its bytes here (read.bytes_read + the
# current op scope): wrappers (policy/retry/prefetch) delegate down to
# exactly one of these classes, so bytes count once, at the bottom
from ..obs.scope import account_bytes as _account_bytes


class Source:
    def pread(self, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def pread_view(self, offset: int, size: int):
        """Like :meth:`pread`, but may return any zero-copy buffer (a
        memoryview or numpy view) when the backing store allows it; callers
        must treat the result as read-only.  Default: a plain bytes copy."""
        return self.pread(offset, size)

    def size(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileSource(Source):
    def __init__(self, path: str):
        self.path = path
        self._fd = os.open(path, os.O_RDONLY)
        st = os.fstat(self._fd)
        self._size = st.st_size
        # identity of the bytes THIS fd actually reads (io/cache.py key):
        # fstat of the open fd, not a later path stat — a concurrent
        # atomic-rename replace must not pair the old bytes with the new
        # file's identity and poison the shared caches.  st_ino is part of
        # the identity because a rename-replace lands a NEW inode whose
        # mtime_ns can fall in the same coarse-clock tick with an equal
        # size — mtime+size alone would alias the two files
        self.stat_key = (os.path.abspath(path), st.st_ino, st.st_mtime_ns,
                         st.st_size)

    def _checked_fd(self) -> int:
        fd = self._fd
        if fd is None:
            raise ValueError(f"read on closed source {self.path!r}")
        return fd

    def pread(self, offset: int, size: int) -> bytes:
        if _locks.LOCKCHECK_ENABLED:
            _locks.note_blocking("source.pread", detail=self.path)
        fd = self._checked_fd()
        # POSIX pread may return fewer bytes than requested without being at
        # EOF (signals, NFS): accumulate until full or truly short
        parts = []
        got = 0
        while got < size:
            chunk = os.pread(fd, size - got, offset + got)
            if not chunk:
                raise ShortReadError(
                    f"short read at {offset}: wanted {size}, got {got}")
            parts.append(chunk)
            got += len(chunk)
        _account_bytes(size)
        return parts[0] if len(parts) == 1 else b"".join(parts)

    def pread_view(self, offset: int, size: int) -> np.ndarray:
        """Read straight into a numpy buffer — one copy (kernel→array)
        instead of pread's kernel→bytes→join."""
        if _locks.LOCKCHECK_ENABLED:
            _locks.note_blocking("source.pread", detail=self.path)
        fd = self._checked_fd()
        buf = np.empty(size, np.uint8)
        mv = memoryview(buf)
        got = 0
        while got < size:
            n = os.preadv(fd, [mv[got:]], offset + got)
            if n <= 0:
                raise ShortReadError(
                    f"short read at {offset}: wanted {size}, got {got}")
            got += n
        _account_bytes(size)
        return buf

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        # idempotent: double-close is a no-op, not an EBADF crash
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


class MmapSource(Source):
    """Memory-mapped local file: ``pread_view`` is a zero-copy view of the
    page cache (``pread`` still returns bytes).  On the streamed lineitem
    read this removed the kernel→user memcpy FileSource's preadv pays —
    measured ~1.35x on a warm cache — and it gives the prefetch layer
    (io/prefetch.py) ``madvise(WILLNEED)`` as a thread-free async readahead
    primitive.  Default for path opens (see :func:`as_source`); opt out
    with ``PARQUET_TPU_MMAP=0`` (special files, platforms where mapping
    regresses).

    Views returned by ``pread_view`` alias the map and keep it alive after
    :meth:`close` (the mapping is only unmapped once the last view dies) —
    callers must treat them as read-only, same contract as every source.
    Truncation of the underlying file while mapped surfaces as SIGBUS on
    access, like any mapped reader; network mounts where that is a real
    risk should use :class:`FileSource` (the injector/chaos stack wraps
    either)."""

    def __init__(self, path: str):
        self.path = path
        fd = os.open(path, os.O_RDONLY)
        try:
            st = os.fstat(fd)
            self._size = st.st_size
            if self._size == 0:
                raise IOError(f"cannot mmap empty file {path!r}")
            # bytes-identity for the shared caches — fstat of the fd the
            # map was built from (see FileSource.stat_key)
            self.stat_key = (os.path.abspath(path), st.st_ino,
                             st.st_mtime_ns, st.st_size)
            self._mm = _mmap.mmap(fd, self._size, prot=_mmap.PROT_READ)
        except BaseException:
            os.close(fd)
            raise
        # drop-behind needs a file descriptor: releasing page-cache
        # residency is posix_fadvise(DONTNEED) — madvise(MADV_DONTNEED)
        # on a MAP_SHARED file mapping only drops this process's PTEs,
        # the kernel page cache keeps the pages.  The fd is retained
        # ONLY when the mode is on at open (mmap does not pin it):
        # unconditional retention would double fd pressure for every
        # serving fleet that never drops behind.  madvise_* can still
        # re-open lazily if called with the mode off (tests, direct use).
        if dropbehind_enabled():
            self._fd = fd
        else:
            self._fd = None
            os.close(fd)
        # tier=False: held across a lazy os.open by documented contract
        self._fd_lock = make_lock("source.mmap_fd", tier=False)
        self._view = memoryview(self._mm)

    def _fadvise_fd(self):
        """The retained drop-behind fd, opened lazily (under a lock — a
        check-then-assign race would leak the loser's fd for the process
        lifetime) when the source was created with the mode off.  A
        lazily-opened fd could name a file that REPLACED the mapped one
        (rename-replace) — harmless here: fadvise is pure advice, and
        the mapped bytes are untouched."""
        with self._fd_lock:
            if self._fd is None and self._view is not None:
                try:
                    self._fd = os.open(self.path, os.O_RDONLY)
                except OSError:
                    return None
            return self._fd

    def _checked_view(self):
        v = self._view
        if v is None:
            raise ValueError(f"read on closed source {self.path!r}")
        return v

    def pread(self, offset: int, size: int) -> bytes:
        if _locks.LOCKCHECK_ENABLED:
            _locks.note_blocking("source.pread", detail=self.path)
        _check_read_args(offset, size)
        out = self._checked_view()[offset : offset + size]
        if len(out) != size:
            raise ShortReadError(f"short read at {offset}: wanted {size}, "
                                 f"got {len(out)}")
        _account_bytes(size)
        return bytes(out)

    def pread_view(self, offset: int, size: int) -> np.ndarray:
        _check_read_args(offset, size)
        out = np.frombuffer(self._checked_view()[offset : offset + size],
                            np.uint8)
        if len(out) != size:
            raise ShortReadError(f"short read at {offset}: wanted {size}, "
                                 f"got {len(out)}")
        _account_bytes(size)
        return out

    def madvise_willneed(self, offset: int, size: int) -> None:
        """Hint the kernel to read [offset, offset+size) ahead — async,
        thread-free readahead (best-effort: errors are ignored)."""
        mm = self._mm
        if mm is None or size <= 0:
            return
        # madvise wants page-aligned offsets; round down/up
        page = _mmap.PAGESIZE
        lo = max(0, (offset // page) * page)
        hi = min(self._size, offset + size)
        try:
            mm.madvise(_mmap.MADV_WILLNEED, lo, hi - lo)
        except (OSError, ValueError, AttributeError):
            pass

    def madvise_sequential(self) -> None:
        """Declare the map sequentially-read (the kernel widens readahead
        and recycles pages behind the reader more eagerly) — the
        drop-behind mode's companion hint.  Both the mapping (madvise)
        and the file descriptor (posix_fadvise) are hinted; best-effort."""
        mm = self._mm
        if mm is None:
            return
        try:
            mm.madvise(_mmap.MADV_SEQUENTIAL)
        except (OSError, ValueError, AttributeError):
            pass
        fd = self._fadvise_fd()
        if fd is not None:
            try:
                os.posix_fadvise(fd, 0, self._size,
                                 os.POSIX_FADV_SEQUENTIAL)
            except (OSError, AttributeError):
                pass

    def madvise_dontneed(self, offset: int, size: int) -> int:
        """Release the page-cache residency of the pages FULLY inside
        [offset, offset+size) — the drop-behind half of a one-shot
        streamed drain (a multi-GB cold scan must not evict the working
        set the lookup serving path depends on).  The actual release is
        ``posix_fadvise(fd, ..., POSIX_FADV_DONTNEED)`` on the retained
        fd: ``madvise(MADV_DONTNEED)`` on a MAP_SHARED file mapping only
        drops this process's page tables, not the kernel page cache — so
        both are issued (fadvise frees the cache, madvise trims RSS).
        The range rounds INWARD to page boundaries so a partially-
        consumed page is never dropped; returns the bytes hinted (0 on
        failure — best-effort).  Live ``pread_view`` views stay VALID
        after a drop (pages refault from disk on next touch); dropping
        merely forfeits cache residency."""
        mm = self._mm
        if mm is None or size <= 0:
            return 0
        page = _mmap.PAGESIZE
        lo = ((offset + page - 1) // page) * page
        hi = min(self._size, ((offset + size) // page) * page)
        if hi <= lo:
            return 0
        # ORDER MATTERS: unmap the PTEs first — the kernel's fadvise
        # eviction (invalidate_mapping_pages) skips pages still mapped
        # into page tables, and a just-drained span was faulted in
        # through this very mapping
        try:
            mm.madvise(_mmap.MADV_DONTNEED, lo, hi - lo)
        except (OSError, ValueError, AttributeError):
            pass
        fd = self._fadvise_fd()
        if fd is None:
            return 0
        try:
            os.posix_fadvise(fd, lo, hi - lo, os.POSIX_FADV_DONTNEED)
        except (OSError, AttributeError):
            return 0
        return hi - lo

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        # idempotent; live pread_view views keep the map itself alive (the
        # memoryview/ndarray holds the buffer), but new reads are refused
        if self._view is not None:
            self._view = None
            mm, self._mm = self._mm, None
            with self._fd_lock:  # pairs with _fadvise_fd's lazy open
                fd, self._fd = self._fd, None
            if fd is not None:
                os.close(fd)
            try:
                mm.close()
            except BufferError:
                pass  # exported views still alive: unmapped when they die


def dropbehind_enabled() -> bool:
    """``PARQUET_TPU_MMAP_DROPBEHIND=1``: one-shot streamed drains over an
    :class:`MmapSource` advise sequential access up front and RELEASE the
    consumed span behind the read frontier (``posix_fadvise(DONTNEED)``
    on the retained fd for the page cache + ``madvise`` for RSS), so a
    cold multi-GB scan passes THROUGH the page cache instead of evicting
    the hot footers/pages the serving paths live on.  Off by default:
    dropping is wrong for re-read workloads (the warm-cache speedups the
    bench measures) — it is the knob for known-one-shot bulk drains."""
    return env_bool("PARQUET_TPU_MMAP_DROPBEHIND")


def _check_read_args(offset: int, size: int) -> None:
    """Reject negative offsets/sizes: a negative offset silently slices from
    the END of a python buffer and returns wrong bytes."""
    if offset < 0 or size < 0:
        raise IOError(f"invalid read: offset={offset} size={size} "
                      "(negative offsets/sizes are corruption, not wrap-around)")


class BytesSource(Source):
    def __init__(self, data: Union[bytes, bytearray, memoryview]):
        self._data = memoryview(data)

    def pread(self, offset: int, size: int) -> bytes:
        _check_read_args(offset, size)
        out = self._data[offset : offset + size]
        if len(out) != size:
            raise ShortReadError(f"short read at {offset}")
        _account_bytes(size)
        return bytes(out)

    def pread_view(self, offset: int, size: int):
        _check_read_args(offset, size)
        out = self._data[offset : offset + size]
        if len(out) != size:
            raise ShortReadError(f"short read at {offset}")
        _account_bytes(size)
        if not self._data.readonly:
            # a bytearray-backed source: decoded columns may lazily reference
            # chunk bytes, and a caller mutating its buffer after read()
            # would silently corrupt them — zero-copy only from immutable
            # backings
            return bytes(out)
        return out

    def size(self) -> int:
        return len(self._data)


class FileLikeSource(Source):
    """Wraps a seekable file-like object; serializes seek+read."""

    def __init__(self, f):
        self._f = f
        # tier=False: the lock IS the seek+read serialization contract
        self._lock = make_lock("source.filelike_fd", tier=False)
        f.seek(0, io.SEEK_END)
        self._size = f.tell()

    def pread(self, offset: int, size: int) -> bytes:
        # closed-check INSIDE the lock: a concurrent close() between an
        # outside check and the seek would surface as the file object's own
        # "seek of closed file" instead of our contract error — and the
        # seek+read pair itself must stay atomic now that the prefetch
        # layer, host_scan, and mesh staging all pread concurrently
        if _locks.LOCKCHECK_ENABLED:
            _locks.note_blocking("source.pread", detail="file-like")
        with self._lock:
            f = self._f
            if f is None:
                raise ValueError("read on closed source")
            f.seek(offset)
            out = f.read(size)
        if len(out) != size:
            raise ShortReadError(f"short read at {offset}")
        _account_bytes(size)
        return out

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        # idempotent; closes the wrapped file object (the wrapper owns the
        # read position anyway — nobody else can use it concurrently).
        # Taken under the lock so an in-flight pread finishes its seek+read
        # before the underlying file goes away.
        with self._lock:
            f = self._f
            if f is not None:
                self._f = None
                f.close()


class PreloadedSource(Source):
    """Serve preads from an in-memory set of already-fetched byte ranges,
    falling through to the inner source for anything outside them.

    The consumer of a multi-range read plan (the aggregation cascade's
    decode stage) fetches its disjoint ranges CONCURRENTLY first
    (:func:`parquet_tpu.io.remote.parallel_preads` — one connection-pool
    slot per range on remote sources), then installs this wrapper so the
    existing page machinery reads each range from memory instead of
    re-issuing one serial pread per span.  Transient, caller-owned, and
    never cached: ``stat_key`` is absent, so no shared tier can key on
    the wrapper."""

    def __init__(self, inner: Source, blocks):
        self.inner = inner
        # sorted (offset, bytes) pairs; containment lookups bisect
        self._blocks = sorted(blocks, key=lambda b: b[0])
        self._starts = [b[0] for b in self._blocks]

    def pread(self, offset: int, size: int) -> bytes:
        _check_read_args(offset, size)
        from bisect import bisect_right

        i = bisect_right(self._starts, offset) - 1
        if i >= 0:
            b0, data = self._blocks[i]
            if offset + size <= b0 + len(data):
                lo = offset - b0
                return bytes(data[lo : lo + size])
        return self.inner.pread(offset, size)

    def size(self) -> int:
        return self.inner.size()

    def close(self) -> None:
        self._blocks = []
        self._starts = []


class RetryingSource(Source):
    """Bounded-retry wrapper over any Source — the retryable-host-IO analog
    of SURVEY.md §5 (flaky network filesystems / object-store FUSE mounts).

    Retries transient ``OSError``s with exponential backoff plus uniform
    ±``jitter`` (decorrelates retry storms across concurrent readers); short
    reads at true EOF are not transient and propagate immediately
    (``IOError`` raised with "short read" is not retried to keep corruption
    loud).  For retry + deadline + degraded-read semantics threaded through
    the whole read stack, use :class:`~parquet_tpu.io.faults.FaultPolicy`
    instead — this wrapper stays for bare-source callers.
    """

    def __init__(self, inner: Source, retries: int = 3,
                 backoff_s: float = 0.05, jitter: float = 0.25):
        self.inner = inner
        self.retries = retries
        self.backoff_s = backoff_s
        self.jitter = jitter
        self._policy = None  # built lazily: faults imports this module

    @property
    def path(self):
        return getattr(self.inner, "path", None)

    def _retry(self, fn, offset: int, size: int):
        # deferred: faults imports source
        from .faults import _M_RETRIES, FaultPolicy, retry_call
        from ..obs.scope import account as _saccount

        pol = self._policy
        if pol is None:
            pol = self._policy = FaultPolicy(max_retries=self.retries,
                                             backoff_s=self.backoff_s,
                                             jitter=self.jitter)
        # one retry loop for the whole stack (retry_call): classification
        # and backoff can't drift from PolicySource's, and these retries
        # land in the same read.retries registry counter / op-scope
        # mirror, so bare-source and policy retries account identically
        return retry_call(fn, offset, size, pol,
                          on_retry=lambda: _saccount(_M_RETRIES))

    def pread(self, offset: int, size: int) -> bytes:
        return self._retry(self.inner.pread, offset, size)

    def pread_view(self, offset: int, size: int):
        # delegate (don't fall back to Source's copying default): keeps
        # FileSource's zero-copy preadv path under retry
        return self._retry(self.inner.pread_view, offset, size)

    def size(self) -> int:
        return self.inner.size()

    def close(self) -> None:
        self.inner.close()


def as_source(obj) -> Source:
    if isinstance(obj, Source):
        return obj
    if isinstance(obj, (str, os.PathLike)):
        path = os.fspath(obj)
        if path.startswith(("http://", "https://")):
            # remote object over HTTP range requests: the whole read
            # stack (prefetch, planner, lookup, caches, policies)
            # composes over it unchanged — see io/remote.py
            from .remote import HttpSource  # deferred: remote imports us

            return HttpSource(path)
        if path.startswith("s3://"):
            # object-store path: rewritten path-style against
            # PARQUET_TPU_S3_ENDPOINT — object-store reads ARE ranged
            # HTTP, so the same remote stack serves it unchanged
            from .remote import ObjectStoreSource, resolve_s3_url

            return ObjectStoreSource(resolve_s3_url(path))
        # mmap by default: zero-copy page-cache views + madvise readahead
        # (see MmapSource).  PARQUET_TPU_MMAP=0 opts out; any mmap failure
        # (empty file, FIFO/device, exotic fs) falls back to pread
        if env_bool("PARQUET_TPU_MMAP"):
            try:
                return MmapSource(path)
            except (OSError, ValueError):
                pass
        return FileSource(path)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return BytesSource(obj)
    if hasattr(obj, "read") and hasattr(obj, "seek"):
        return FileLikeSource(obj)
    raise TypeError(f"cannot make a Source from {type(obj)!r}")
