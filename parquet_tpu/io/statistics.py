"""Typed statistics decode/encode (zone maps).

Reference parity: ``format — Statistics`` + the typed min/max accessors on
``ColumnChunk`` (SURVEY.md §2.1 Indexes row).  Parquet stores min/max as plain
little-endian bytes of the physical type (logical order for
min_value/max_value).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..format import metadata as md
from ..format.enums import Type
from ..schema.schema import Leaf


@dataclass
class TypedStatistics:
    min_value: Any = None
    max_value: Any = None
    null_count: Optional[int] = None
    distinct_count: Optional[int] = None


def decode_stat_value(raw: Optional[bytes], leaf: Leaf):
    """Decode statistics bytes into the leaf's order domain (delegates to
    algebra/compare so pruning, Find, and boundary-order checks all use one
    logical ordering — unsigned ints non-negative, decimals unscaled int)."""
    from ..algebra.compare import decode_order_value

    return decode_order_value(raw, leaf)


def encode_stat_value(value, physical: Type) -> bytes:
    if value is None:
        return b""
    if physical == Type.BOOLEAN:
        return bytes([1 if value else 0])
    if physical == Type.INT32:
        return np.int32(value).tobytes()
    if physical == Type.INT64:
        return np.int64(value).tobytes()
    if physical == Type.FLOAT:
        return np.float32(value).tobytes()
    if physical == Type.DOUBLE:
        return np.float64(value).tobytes()
    return bytes(value)


def may_contain_range(st: Optional[TypedStatistics], lo=None,
                      hi=None) -> bool:
    """Conservative order-domain zone-map check: False only when the
    statistics PROVE no value in ``[lo, hi]`` exists.  Missing statistics
    and probes not comparable with the decoded stats domain (e.g. raw
    bytes against a DECIMAL column) are inconclusive and answer True —
    the one interval rule shared by row-group pruning (io/search.py) and
    the scan planner's stats stage (io/planner.py), so the two can't
    drift."""
    if st is None or st.min_value is None or st.max_value is None:
        return True
    try:
        if lo is not None and st.max_value < lo:
            return False
        if hi is not None and st.min_value > hi:
            return False
    except TypeError:
        return True
    return True


def decode_statistics(stats: Optional[md.Statistics], leaf: Leaf
                      ) -> Optional[TypedStatistics]:
    if stats is None:
        return None
    mn = stats.min_value if stats.min_value is not None else stats.min
    mx = stats.max_value if stats.max_value is not None else stats.max
    return TypedStatistics(
        min_value=decode_stat_value(mn, leaf),
        max_value=decode_stat_value(mx, leaf),
        null_count=stats.null_count,
        distinct_count=stats.distinct_count,
    )
