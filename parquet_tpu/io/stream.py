"""Bounded-memory streaming reads: O(pages-per-batch), never O(chunk).

Reference parity: the reference reads with O(page) memory — ``config.go —
PageBufferSize`` bounds what a reader holds, and ``GenericReader[T].Read``
streams batches (SURVEY.md §5, "bounded-batch streaming").  This module is
that mode for the new framework: :func:`iter_batches` yields row-aligned
:class:`~parquet_tpu.io.reader.Table` batches while holding, per column, only
the decoded pages that cover the current batch.

Mechanics: each (row-group, column) gets a cursor over
``ColumnChunkReader.pages_streamed()`` (incremental preads — the file is
never read a whole chunk at a time), decoding one page per pull with the
chunk's dictionary decoded once.  Batch boundaries rarely align with page
boundaries, so rows are sliced out of decoded page columns by slicing the
Dremel level streams and re-running the (linear, metadata-scale) level
assembler on the slice — this handles flat, struct, and arbitrarily nested
list columns with one rule.

Pages are assumed record-aligned (a row never splits across pages), which
every mainstream writer guarantees and DataPageV2 requires.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..errors import CorruptedError, DeadlineError
from ..utils.env import env_bool
from ..format.enums import PageType
from ..obs import scope as _oscope
from ..obs import trace as _trace
from ..ops import levels as levels_ops
from .column import Column
from .faults import FaultPolicy, ReadReport, read_context, resolve_policy
from .reader import (ParquetFile, Table, decode_chunk_host,
                     decode_dictionary_page, verify_page_crc)

__all__ = ["iter_batches"]

# same measured crossover as parallel/host_scan.py and the whole-file read:
# below ~2M cells the per-task pool dispatch beats the decode win
_PARALLEL_MIN_CELLS = 2_000_000


@dataclass
class _PagePiece:
    col: Column
    rows: int
    # row → slot start positions within this piece (identity for flat)
    row_starts: Optional[np.ndarray] = None


def piece_from_column(col: Column) -> "_PagePiece":
    """Wrap a decoded column (any page subset) as a sliceable piece: row
    count and row→slot starts derived from the rep levels (identity for
    flat columns).  Shared by the streaming cursor and the row cursor's
    seek path."""
    rep = col.rep_levels
    if rep is not None:
        starts = levels_ops.row_slot_starts(np.asarray(rep))
        return _PagePiece(col=col, rows=len(starts), row_starts=starts)
    return _PagePiece(col=col, rows=col.num_slots or col.num_values,
                      row_starts=None)


@dataclass
class _ChunkCursor:
    """Incremental decoder for one column chunk: pulls pages on demand,
    holds only decoded-but-unconsumed pieces.  ``source`` overrides where
    the windowed preads go (the per-drain prefetcher)."""

    chunk: object  # ColumnChunkReader
    source: object = None
    pages: Iterator = None
    dictionary: object = None
    pieces: List[_PagePiece] = field(default_factory=list)
    consumed: int = 0  # rows consumed from pieces[0]
    exhausted: bool = False

    def __post_init__(self):
        self.pages = self.chunk.pages_streamed(source=self.source)

    def _pull_pages(self, need_rows: int) -> bool:
        """Pull the pages covering the next ``need_rows`` rows and decode
        them in ONE ``decode_chunk_host`` call (the fused multi-page path the
        whole-chunk read uses) instead of a per-page call — the per-page
        Python/dispatch overhead was the streaming read's entire deficit vs
        the whole-file read.  Page row counts come from the headers
        (DataPageHeaderV2.num_rows; v1 num_values, which over-counts rows
        for repeated columns — an over-estimate only makes a pull stop
        early, and ``take`` pulls again)."""
        batch = []
        est = 0
        for page in self.pages:
            if page.page_type == PageType.DICTIONARY_PAGE:
                verify_page_crc(self.chunk, page)
                self.dictionary = decode_dictionary_page(self.chunk, page)
                continue
            batch.append(page)
            v2 = getattr(page.header, "data_page_header_v2", None)
            # num_values over-counts rows for repeated columns and is 0 for
            # unknown page types (both only make the pull stop early or
            # late by one page — take() pulls again)
            est += v2.num_rows if v2 is not None else page.num_values
            if est >= need_rows:
                break
        if not batch:
            self.exhausted = True
            return False
        col = decode_chunk_host(self.chunk, pages=iter(batch),
                                dictionary=self.dictionary)
        self.pieces.append(piece_from_column(col))
        return True

    def take(self, n_rows: int):
        """Consume up to ``n_rows`` rows → (sliced column pieces, rows)."""
        out: List[Column] = []
        need = n_rows
        while need > 0:
            if not self.pieces and not self._pull_pages(need):
                break
            piece = self.pieces[0]
            avail = piece.rows - self.consumed
            if avail <= 0:
                self.pieces.pop(0)
                self.consumed = 0
                continue
            take = min(avail, need)
            out.append(_slice_rows(piece, self.consumed,
                                   self.consumed + take))
            self.consumed += take
            need -= take
            if self.consumed >= piece.rows:
                self.pieces.pop(0)
                self.consumed = 0
        return out, n_rows - need


def _slice_rows(piece: _PagePiece, r0: int, r1: int) -> Column:
    """Rows [r0, r1) of a decoded page column, as a self-contained Column.

    Levels are sliced in slot space and re-assembled (linear in the slice);
    values/indices/offsets are sliced in value space via the def levels.
    """
    col = piece.col
    leaf = col.leaf
    if r0 == 0 and r1 >= piece.rows:
        return col
    max_def = leaf.max_definition_level
    d = None if col.def_levels is None else np.asarray(col.def_levels)
    r = None if col.rep_levels is None else np.asarray(col.rep_levels)
    s0, s1 = levels_ops.slot_span(r, r0, r1, 0 if r is None else len(r),
                                  row_starts=piece.row_starts)
    if d is None:
        v0, v1 = s0, s1  # required flat: slots == values
        d_sl = r_sl = None
    else:
        v0 = levels_ops.present_count(d, 0, s0, max_def)
        v1 = v0 + levels_ops.present_count(d, s0, s1, max_def)
        d_sl = d[s0:s1]
        r_sl = None if r is None else r[s0:s1]
    asm = levels_ops.assemble(d_sl, r_sl, leaf)
    values = col.values
    offsets = None
    dict_indices = None
    if col.is_dictionary_encoded():
        dict_indices = np.asarray(col.dict_indices)[v0:v1]
        values = None
    elif col.offsets is not None:
        offs = np.asarray(col.offsets)
        base = int(offs[v0])
        offsets = (offs[v0 : v1 + 1] - base).astype(offs.dtype)
        values = np.asarray(values)[base : int(offs[v1])]
    elif values is not None:
        values = np.asarray(values)[v0:v1]
    return Column(leaf=leaf, values=values, offsets=offsets,
                  validity=asm.validity, list_offsets=asm.list_offsets,
                  list_validity=asm.list_validity, num_slots=s1 - s0,
                  dictionary=col.dictionary,
                  dictionary_host=col.dictionary_host,
                  dict_indices=dict_indices,
                  def_levels=d_sl, rep_levels=r_sl)


def iter_batches(pf: ParquetFile, columns: Optional[Sequence[str]] = None,
                 batch_rows: int = 65536,
                 strict_batch_rows: bool = False,
                 policy: Optional[FaultPolicy] = None,
                 report: Optional[ReadReport] = None) -> Iterator[Table]:
    """Stream the file as row-aligned :class:`Table` batches of at most
    ``batch_rows`` rows, holding O(pages-per-batch) memory per column.

    ``columns`` selects leaves by dotted path (default: all).  Batches are
    snapped to row-group boundaries when at least half of ``batch_rows``
    is pending (same behavior as pyarrow's ``iter_batches`` — avoids the
    cross-group column concat); only under-half remainders of small row
    groups accumulate across the boundary.  Batch sizes therefore VARY,
    bounded by ``batch_rows`` (a behavior change in r4 — callers that
    relied on fixed-size batches should pass ``strict_batch_rows=True``,
    which restores exactly ``batch_rows`` rows per batch except the last
    at the cost of cross-group concatenation).  Concatenating every batch
    equals a full :meth:`ParquetFile.read`.

    ``policy`` (default: the file's open-time policy) applies the
    resilience layer (io/faults.py): source preads retry transient errors,
    the whole drain runs under one ``deadline_s`` clock (started at the
    first pull), and with ``on_corrupt='skip_row_group'`` a corrupt row
    group's **un-yielded** rows are dropped — batches already yielded from
    it stay valid — with the loss accounted in ``report``.
    """
    if batch_rows <= 0:
        raise ValueError("batch_rows must be positive")
    gen = _iter_batches_gen(pf, columns, batch_rows, strict_batch_rows,
                            policy, report)
    # request scope around each pull (obs/scope.py): the drain gets its
    # own op identity unless the caller already opened one
    return _oscope.scoped_iter("file.iter_batches", gen, file=pf._path)


def _iter_batches_gen(pf, columns, batch_rows, strict_batch_rows, policy,
                      report) -> Iterator[Table]:
    pol, report = resolve_policy(pf, policy, report)
    skip = pol is not None and pol.skip_corrupt
    leaves = [pf.schema.leaf(c) for c in columns] if columns is not None \
        else list(pf.schema.leaves)
    paths = [leaf.dotted_path for leaf in leaves]
    with pf._resilient_op(policy, report, "iter_batches"):
        yield from _iter_batches_impl(pf, paths, batch_rows,
                                      strict_batch_rows, skip, report)


def _take_contextual(pf, cursor, path, rg_index, take):
    """One column's take, wrapped in read_context so failures — on this
    thread or a pool worker — surface as located ReadErrors.  The
    ``decode.stream`` span carries the thread it decoded on: with the
    pooled fan-out active, columns of one batch step show as parallel
    bars on different worker tracks."""
    dec_span = (_trace.span("decode.stream", rg=rg_index, col=path,
                            rows=take)
                if _trace.TRACE_ENABLED else _trace.NULL_SPAN)
    with dec_span, \
            read_context(path=pf._path, row_group=rg_index, column=path):
        pieces, got = cursor.take(take)
        if got != take:
            raise CorruptedError(
                f"streaming cursor yielded {got} of {take} rows "
                "(page stream shorter than row-group metadata)")
        return pieces


def _iter_batches_impl(pf, paths, batch_rows, strict_batch_rows, skip,
                       report, row_groups=None,
                       rg_done=None) -> Iterator[Table]:
    """``row_groups`` restricts the drain to those row-group indices (in
    the given order); ``rg_done(rg_index, {path: [Column, ...]})`` fires
    after each row group fully streams (never for a skipped group) with
    the column pieces that went into the yielded batches — the whole-file
    streamed read uses it to populate the decoded-chunk cache at
    row-group granularity."""
    from ..utils.pool import available_cpus, in_shared_pool
    from .prefetch import make_prefetcher

    rg_sel = list(row_groups) if row_groups is not None \
        else list(range(len(pf.row_groups)))
    n_rg = len(rg_sel)
    # ---- layer 1: prefetching IO (io/prefetch.py).  One per drain; plans
    # are registered per row group, double-buffered: when row group N's
    # cursors are built, N+1's chunk ranges are planned too, so page decode
    # of N overlaps readahead of N+1.
    pre = make_prefetcher(pf.source, n_streams=len(paths))
    stats = pre.stats if pre is not None else None
    planned = -1

    def plan_rg(pos: int) -> None:
        nonlocal planned
        if pre is None or pos >= n_rg or pos <= planned:
            return
        planned = pos
        for p in paths:
            pre.plan(*pf.row_group(rg_sel[pos]).column(p).byte_range)

    # ---- layer 2: parallel streamed decode.  Per batch step, the
    # per-column takes (pread + decompress + decode — all GIL-releasing in
    # the codec/native layers) fan out across the shared pool.  Serial
    # below the measured crossover, on one core (threads are a pure loss
    # against a warm page cache there), and when already inside a pool
    # worker (no nested-fanout deadlocks).
    use_pool = (len(paths) > 1 and available_cpus() > 1
                and not in_shared_pool()
                and pf.num_rows * len(paths) >= _PARALLEL_MIN_CELLS
                and env_bool("PARQUET_TPU_STREAM_PARALLEL"))

    pos_iter = iter(range(n_rg))
    cursors: Optional[Dict[str, _ChunkCursor]] = None
    rg_rows_left = 0
    pending: Dict[str, List[Column]] = {p: [] for p in paths}
    pending_rows = 0
    rg_parts: Dict[str, List[Column]] = {p: [] for p in paths}

    def flush() -> Table:
        nonlocal pending, pending_rows
        # parts-form Table: per-leaf concat stays lazy, and to_arrow takes
        # the chunked path (zero-concat chunked arrays + DictionaryArray
        # passthrough for arrow-dictionary-typed fields) exactly like the
        # whole-file read
        t = Table(pf.schema, None, pending_rows,
                  parts={p: list(parts) for p, parts in pending.items()},
                  dict_fields=pf.arrow_dictionary_fields)
        if report is not None:
            report.rows_read += pending_rows
            t.report = report
        t.read_stats = stats
        pending = {p: [] for p in paths}
        pending_rows = 0
        return t

    def take_all(take: int) -> None:
        """All columns' takes for one step, pooled or serial; extends
        ``pending`` only after every column succeeded (order-stable)."""
        if use_pool:
            from ..utils.pool import submit as pool_submit

            futs = [(p, pool_submit(_take_contextual, pf, cursors[p], p,
                                    rg_index, take)) for p in paths]
            results, first_err = {}, None
            for p, f in futs:
                try:
                    results[p] = f.result()
                except DeadlineError:
                    raise
                except Exception as e:
                    if first_err is None:
                        first_err = e
            if first_err is not None:
                raise first_err
        else:
            results = {p: _take_contextual(pf, cursors[p], p, rg_index,
                                           take) for p in paths}
        for p in paths:
            pending[p].extend(results[p])
            if rg_done is not None:
                rg_parts[p].extend(results[p])

    try:
        while True:
            if rg_rows_left == 0:
                pos = next(pos_iter, None)
                if pos is None:
                    break
                rg_index = rg_sel[pos]
                rg = pf.row_group(rg_index)
                plan_rg(pos)
                plan_rg(pos + 1)  # double buffer: readahead of N+1
                cursors = {p: _ChunkCursor(chunk=rg.column(p), source=pre)
                           for p in paths}
                rg_rows_left = rg.num_rows
                if rg_done is not None:
                    rg_parts = {p: [] for p in paths}
            take = min(batch_rows - pending_rows, rg_rows_left)
            # snapshot so a mid-take corruption can roll back this step's
            # partial, column-misaligned contributions
            marks = {p: len(pending[p]) for p in paths}
            try:
                take_all(take)
            except DeadlineError:
                raise
            except CorruptedError as e:
                if not skip:
                    raise
                for p in paths:
                    del pending[p][marks[p]:]
                # rows of this group already yielded (or aligned in pending
                # from earlier steps) decoded fine and stay; only the
                # remainder drops
                report.record_skip(rg_index, rows=rg_rows_left, error=e)
                rg_rows_left = 0
                if pre is not None:
                    # the abandoned group's plans would otherwise pin their
                    # issued windows for the rest of the drain (they retire
                    # on consumption, which will never come)
                    for p in paths:
                        pre.unplan(*rg.column(p).byte_range)
                continue
            pending_rows += take
            rg_rows_left -= take
            if rg_rows_left == 0 and rg_done is not None:
                rg_done(rg_index, rg_parts)
            # Flush at row-group boundaries too (batches are "at most
            # batch_rows" — a snapped batch is legal and value-identical in
            # concatenation): a batch spanning row groups would pay a full
            # column concat at flush, the measured remainder of the
            # streaming read's deficit vs the whole-file read.  Keep
            # accumulating only when the pending batch is under half target
            # (tiny row groups).
            if pending_rows >= batch_rows or (
                    not strict_batch_rows and rg_rows_left == 0
                    and pending_rows * 2 >= batch_rows):
                yield flush()
        if pending_rows:
            yield flush()
    finally:
        if pre is not None:
            pre.close()  # cancel queued windows; the file stays open
