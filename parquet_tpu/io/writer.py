"""Write path: column buffers → encoded pages → row groups → footer.

Reference parity (SURVEY.md §3.2): ``GenericWriter[T].Write``/``Close`` —
deconstruct rows into per-leaf column buffers, dictionary-insert when
dict-encoding, flush row groups (encode → compress → page headers →
statistics / column+offset indexes / bloom filters), then footer (thrift
FileMetaData, "PAR1") — footer-last atomicity (SURVEY.md §5
checkpoint/resume: a crashed write is invalid, a finished one immutable).

TPU-first differences: input is columnar from the start (numpy / jax arrays /
pyarrow — no row shredding needed for flat data; Dremel levels are computed
by the vectorized write-direction math in ops/levels.py), encoders are the
vectorized numpy oracles (device encode is a later optimization — write is
not the north-star hot path), and decoded 64-bit device pairs are accepted
directly.

Pipelining (the write-side twin of io/prefetch.py): the encode phase is
pure and offset-free (:class:`_EncodedChunk`; offsets are assigned at emit
time), so ``write_row_group`` double-buffers — group N+1 encodes on the
shared pool while group N's chunks flush through ``_emit_chunk`` to the
sink.  Group N+1's encode only STARTS after group N's encode finished
(never concurrently with it), so the sticky dictionary-fallback state and
therefore the output bytes are identical with overlap on or off.  Path
sinks additionally ride a :class:`~parquet_tpu.io.sink.BufferedSink` that
coalesces page writes into vectored flushes (``os.writev`` on raw-fd
sinks).  ``PARQUET_TPU_WRITE_OVERLAP`` (``0`` off / auto / ``force``) and
``PARQUET_TPU_WRITE_BUFFER`` are the knobs — with neither pinned, the
buffer auto-tunes from observed ``sink_flushes`` per row group
(``PARQUET_TPU_WRITE_AUTOTUNE=0`` opts out);
:class:`~parquet_tpu.io.sink.WriteStats` (``writer.write_stats``) meters
the pipeline.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import struct
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .. import codecs
from ..errors import MAX_ROW_GROUPS, TooManyRowGroupsError
from ..format import enums, metadata as md, thrift
from ..utils.env import env_bytes, env_int, env_str
from ..utils.locks import make_condition
from ..obs.ledger import (ledger_account as _ledger_account,
                          maybe_check_pressure as _maybe_pressure)
from ..format.enums import (CompressionCodec, ConvertedType, Encoding,
                            FieldRepetitionType as Rep, PageType, Type)
from ..ops import levels as levels_ops, ref
from ..schema import schema as sch
from ..schema.schema import Leaf, Schema
from ..obs import scope as _oscope
from ..obs import trace as _otrace
from ..schema.types import LogicalKind

# shared stateless pass-through for writer methods running under a
# caller's ambient op scope (nullcontext is safely re-enterable)
_NULL_CM = contextlib.nullcontext()

DEFAULT_CREATED_BY = "parquet-tpu version 0.1.0"

# below this much input per row group, pool dispatch (and the deferred-emit
# bookkeeping of the overlap pipeline) costs more than it hides — the same
# measured crossover as the parallel-encode gate
_PARALLEL_ENCODE_BYTES = 8 << 20




def write_depth() -> int:
    """``PARQUET_TPU_WRITE_DEPTH``: how many fully-ENCODED row groups may
    queue behind a slow sink before ``write_row_group`` blocks (≥1;
    default 1 = today's behavior, emit inline on the caller thread).
    Depth ≥ 2 moves emit onto a per-writer background thread: the caller
    keeps encoding while earlier groups' pages flush — the carried-over
    ROADMAP write-overlap-depth follow-on, with the memory it pins
    bounded by the ledger's ``write.pended`` account."""
    d = env_int("PARQUET_TPU_WRITE_DEPTH")
    return d if d >= 1 else 1


def write_pended_cap_bytes() -> int:
    """``PARQUET_TPU_WRITE_PENDED``: byte cap on encoded groups queued
    for emit (default 256 MiB; the depth bound still applies).  The cap
    the ROADMAP item was waiting on — supplied by the ledger account."""
    return env_bytes("PARQUET_TPU_WRITE_PENDED")


# resource-ledger account (obs/ledger.py): bytes of encoded row groups
# queued for emit across every depth>1 writer in the process
_ACC_PENDED = _ledger_account("write.pended",
                              capacity=write_pended_cap_bytes)


def _encs_nbytes(encs) -> int:
    """Resident bytes of one collected encoded group: compressed page
    bodies + dictionary pages + bloom blobs (headers are noise)."""
    total = 0
    for enc in encs:
        if enc.dict_page is not None:
            total += len(enc.dict_page[1])
        for page in enc.pages:
            total += len(page[1])
        if enc.bloom_blob is not None:
            total += len(enc.bloom_blob)
    return total


def _overlap_mode() -> str:
    """Resolve ``PARQUET_TPU_WRITE_OVERLAP`` to off | auto | force.

    ``force`` pipelines every row group regardless of size (equivalence
    tests, benches on small data); auto (the default) overlaps only where
    it pays: >1 CPU and ≥ :data:`_PARALLEL_ENCODE_BYTES` of input per
    group.  Inside a shared-pool worker the write always stays serial —
    collecting a future from within the pool can deadlock the pool."""
    v = env_str("PARQUET_TPU_WRITE_OVERLAP").lower()
    if v in ("0", "off", "false", "no"):
        return "off"
    if v == "force":
        return "force"
    return "auto"


@dataclass
class WriterOptions:
    """Reference parity: config.go — WriterConfig + functional options
    (Compression, DataPageVersion, PageBufferSize, MaxRowsPerRowGroup,
    CreatedBy, KeyValueMetadata, SortingColumns, bloom filters...)."""

    compression: Union[str, CompressionCodec] = CompressionCodec.SNAPPY
    data_page_version: int = 1
    data_page_size: int = 1 << 20  # bytes of values per page (PageBufferSize)
    row_group_size: int = 1 << 20  # max rows per row group (MaxRowsPerRowGroup)
    dictionary: Union[bool, Sequence[str]] = True
    dictionary_page_limit: int = 1 << 20  # fall back to plain beyond this
    write_statistics: bool = True
    write_page_index: bool = True
    # spec-standard and cheap (one zlib.crc32 per page); lets readers catch
    # bit rot at the page that rotted instead of as a codec decode error
    write_crc: bool = True
    # path sinks write to <dest>.<rand>.tmp and fsync+rename on close(), so
    # the destination is either absent or a complete committed file — never
    # torn (io/sink.py).  False falls back to direct-to-path writes.
    atomic_commit: bool = True
    fsync: bool = True
    bloom_filters: Dict[str, int] = dc_field(default_factory=dict)  # path → bits/value
    created_by: str = DEFAULT_CREATED_BY
    key_value_metadata: Dict[str, str] = dc_field(default_factory=dict)
    sorting_columns: List[Tuple[str, bool, bool]] = dc_field(default_factory=list)
    # (path, descending, nulls_first) — recorded in row-group metadata
    column_encoding: Dict[str, Encoding] = dc_field(default_factory=dict)
    # page-index min/max truncation for byte-ordered types (reference
    # ColumnIndexSizeLimit; pyarrow's column_index_truncate_length). 0 = off.
    column_index_truncate_length: int = 64

    def __post_init__(self):
        if self.row_group_size < 1:
            raise ValueError("row_group_size must be >= 1")
        if self.data_page_size < 1:
            raise ValueError("data_page_size must be >= 1")
        if self.column_index_truncate_length < 0:
            raise ValueError("column_index_truncate_length must be >= 0")
        if self.data_page_version not in (1, 2):
            raise ValueError("data_page_version must be 1 or 2")

    def codec_id(self) -> CompressionCodec:
        if isinstance(self.compression, str):
            return {
                "none": CompressionCodec.UNCOMPRESSED,
                "uncompressed": CompressionCodec.UNCOMPRESSED,
                "snappy": CompressionCodec.SNAPPY,
                "gzip": CompressionCodec.GZIP,
                "zstd": CompressionCodec.ZSTD,
                "brotli": CompressionCodec.BROTLI,
                "lz4": CompressionCodec.LZ4_RAW,
                "lz4_raw": CompressionCodec.LZ4_RAW,
            }[self.compression.lower()]
        return CompressionCodec(self.compression)

    def use_dictionary(self, path: str) -> bool:
        if isinstance(self.dictionary, bool):
            return self.dictionary
        return path in self.dictionary


@dataclass
class ColumnData:
    """Normalized per-leaf input: dense present values + structure."""

    values: Any  # numpy array (fixed) or uint8 bytes for BYTE_ARRAY
    offsets: Optional[np.ndarray] = None  # BYTE_ARRAY offsets
    validity: Optional[np.ndarray] = None  # per slot
    list_offsets: Optional[np.ndarray] = None  # single-level list support
    list_validity: Optional[np.ndarray] = None
    # raw Dremel level streams (rows.py row path); when set they bypass
    # _build_levels, enabling arbitrary-depth nested writes
    def_levels: Optional[np.ndarray] = None
    rep_levels: Optional[np.ndarray] = None


@dataclass
class _EncodedChunk:
    """Offset-free result of the pure encode phase of one column chunk."""

    leaf: Leaf
    dict_page: Optional[tuple]  # (PageHeader, compressed bytes)
    pages: List[tuple]  # (PageHeader, compressed body, rows, stats, n_vals)
    stats: Optional[md.Statistics]
    bloom_blob: Optional[bytes]
    encodings_used: set
    n_slots: int


class ParquetWriter:
    """Streaming writer: accumulate columns, flush row groups, footer on close."""

    def __init__(self, sink, schema: Schema, options: Optional[WriterOptions] = None):
        from .sink import WriteStats

        self.schema = schema
        self.options = options or WriterOptions()
        self.write_stats = WriteStats()
        self._own_sink = isinstance(sink, (str, os.PathLike))
        # request scope for the writer LIFETIME (obs/scope.py): created
        # here, activated around each public method body (a writer is a
        # multi-call operation), finished at close/abort.  A caller's
        # active op_scope wins — the writer then attributes ambiently.
        self._op = (_oscope.OpScope(
            "write.file",
            {"sink": os.fspath(sink) if self._own_sink
             else type(sink).__name__})
            if _oscope.current_op() is None else None)
        if self._own_sink:
            from .sink import BufferedSink, FileSink, atomic_path_sink

            base = (atomic_path_sink(sink, fsync=self.options.fsync)
                    if self.options.atomic_commit
                    else FileSink(sink, fsync=self.options.fsync))
            try:
                # magic goes through the BASE sink, before the coalescing
                # layer: fail fast on an unwritable sink instead of
                # deferring the first write — and its error — into the
                # first row group's flush
                base.write(md.MAGIC)
            except BaseException:
                # a failed first write must not leak the freshly opened
                # file or leave its temp/partial file behind
                base.abort()
                raise
            # writeback coalescing for every path sink (buffer size 0 keeps
            # a counting pass-through, so stats stay uniform)
            self._f = BufferedSink(base, stats=self.write_stats)
            self.write_stats.bytes_flushed += len(md.MAGIC)
        else:
            self._f = sink
            self._f.write(md.MAGIC)
        self._pos = 4
        self._row_groups: List[md.RowGroup] = []
        self._column_indexes: List[List[Optional[md.ColumnIndex]]] = []
        self._offset_indexes: List[List[Optional[md.OffsetIndex]]] = []
        self._bloom_blobs: List[List[Optional[bytes]]] = []
        self._num_rows = 0
        self._closed = False
        self._aborted = False
        self._codec = codecs.get_codec(self.options.codec_id())
        self._dict_overflowed: set = set()  # sticky per-column fallback
        # buffered rows for write() accumulation
        self._buffer: Optional[Dict[str, ColumnData]] = None
        self._buffered_rows = 0
        # pipeline slot: (encode futures in leaf order, num_rows) of the one
        # row group whose background encode may still be running while its
        # predecessor's pages flush — emitted by the next write_row_group,
        # flush(), or close()
        self._inflight: Optional[Tuple[list, int]] = None
        # write-overlap depth > 1 (PARQUET_TPU_WRITE_DEPTH): a bounded
        # queue of fully-ENCODED groups drained by a per-writer emitter
        # thread, so a slow sink no longer stalls the caller between
        # groups.  Emits stay strictly FIFO on ONE thread — offsets are
        # assigned in queue order, so output bytes are identical to
        # depth 1.  Memory pinned by the queue lives in the ledger's
        # write.pended account, capped by PARQUET_TPU_WRITE_PENDED.
        self._depth = write_depth()
        self._pend_q: "deque" = deque()  # (ctx, encs, num_rows, nbytes)
        self._pend_cv = make_condition("write.pended_cv")
        self._pend_bytes = 0
        self._emit_err: Optional[BaseException] = None
        self._emitter: Optional[threading.Thread] = None
        self._emitter_stop = False
        self._discard_pended = False

    # ------------------------------------------------------------------
    def write(self, columns: Dict[str, ColumnData], num_rows: int) -> None:
        """Buffer columnar data; full row groups are written as they fill
        (MaxRowsPerRowGroup), the sub-group tail stays buffered so streaming
        writes never fragment the file into tiny groups."""
        self._check_open()
        if self._buffer is None:
            # shallow wrap: buffering never mutates array contents (extend
            # rebinds via np.concatenate, slicing takes views), so sharing
            # the caller's arrays is safe and avoids doubling peak memory on
            # one-shot writes
            self._buffer = {k: _shallow_cd(v) for k, v in columns.items()}
        else:
            for k, v in columns.items():
                _extend_cd(self._buffer[k], v)
        self._buffered_rows += num_rows
        if self._buffered_rows >= self.options.row_group_size:
            self._drain(final=False)

    def flush(self) -> None:
        """Write everything buffered, including the sub-group tail, any
        row group whose background encode is still in flight, and (depth
        > 1) every encoded group queued for the background emitter."""
        with self._op_active():
            self._check_open()
            self._drain(final=True)
            self._drain_inflight()
            self._drain_pended()

    def _check_open(self) -> None:
        # buffering rows into a finalized writer would drop them silently —
        # the buffer can never drain once close()/abort() ran
        if self._closed or self._aborted:
            raise ValueError("write on a "
                             + ("closed" if self._closed else "aborted")
                             + " writer")

    def _drain(self, final: bool) -> None:
        if self._buffer is None or self._buffered_rows == 0:
            return
        total = self._buffered_rows
        rgs = self.options.row_group_size
        emit = total if final else (total // rgs) * rgs
        if emit == 0:
            return
        if emit == total and total <= rgs:
            self.write_row_group(self._buffer, total)
            self._buffer = None
            self._buffered_rows = 0
            return
        key_leaf = {k: next((l for l in self.schema.leaves
                             if l.dotted_path == k or l.path[0] == k), None)
                    for k in self._buffer}
        ctxs = {k: {} for k in self._buffer}  # per-column slice-table cache
        for start in range(0, emit, rgs):
            end = min(start + rgs, emit)
            part = {k: _slice_cd(key_leaf[k], cd, start, end, ctxs[k])
                    if key_leaf[k] is not None else cd
                    for k, cd in self._buffer.items()}
            self.write_row_group(part, end - start)
        if emit == total:
            self._buffer = None
            self._buffered_rows = 0
        else:  # retain the tail — COPIED so the drained buffer's memory frees
            self._buffer = {
                k: _copy_cd(_slice_cd(key_leaf[k], cd, emit, total, ctxs[k]))
                if key_leaf[k] is not None else cd
                for k, cd in self._buffer.items()}
            self._buffered_rows = total - emit

    # ------------------------------------------------------------------
    def write_row_group(self, columns: Dict[str, ColumnData], num_rows: int) -> None:
        """Encode + emit one row group, pipelined (module docstring):

        1. wait for the PREVIOUS group's background encode (not its emit),
        2. submit THIS group's encode to the shared pool,
        3. emit the previous group's pages to the sink.

        Step 3's sink IO overlaps step 2's encode compute; the strict
        encode ordering (collect before submit) keeps the sticky
        dictionary-fallback state — and the output bytes — identical to
        the serial path.  The deferred group is emitted by the next call,
        :meth:`flush`, or :meth:`close`.

        Array ownership: the writer shares the caller's arrays without
        copying (the same zero-copy contract :meth:`write` has always
        had), and with overlap active this group's encode may still be
        reading them after this call returns — do not mutate arrays handed
        to the writer until it has flushed (rebinding fresh arrays per
        group, as every built-in front end does, is always safe)."""
        with self._op_active():
            self._write_row_group_impl(columns, num_rows)

    def _op_active(self):
        """Activation of this writer's own op scope — the encode pool
        submissions inside inherit it.  Checked per CALL, not just at
        construction: a caller's op_scope active right now always wins
        (the documented precedence), even for a writer built outside
        any scope."""
        if self._op is None or _oscope.current_op() is not None:
            return _NULL_CM
        return self._op.active()

    def _write_row_group_impl(self, columns: Dict[str, ColumnData],
                              num_rows: int) -> None:
        self._check_open()
        if self._emit_err is not None:
            self._raise_emit_err()
        if len(self._row_groups) + len(self._pend_q) \
                + (1 if self._inflight is not None else 0) >= MAX_ROW_GROUPS:
            raise TooManyRowGroupsError(
                f"file would exceed {MAX_ROW_GROUPS} row groups "
                "(RowGroup.ordinal is an i16); raise row_group_size")
        leaves = self.schema.leaves
        datas = []
        for leaf in leaves:
            data = columns.get(leaf.dotted_path) or columns.get(leaf.path[0])
            if data is None:
                raise KeyError(f"missing column {leaf.dotted_path!r}")
            datas.append(data)
        # encode is pure per column and offset-free (codecs are thread-safe:
        # snappy is stateless, zstd contexts are thread-local); emit is
        # serial since page offsets depend on file position.  On a
        # multi-core host the encode phase fans out across columns — the
        # native encoders and compressors release the GIL — at the cost of
        # buffering the row group's compressed pages until emit.  On one
        # core a pool measured ~15% SLOWER (GIL'd numpy dispatch), so the
        # serial one-chunk-buffered interleave is kept there.
        from ..utils.pool import available_cpus, in_shared_pool
        from ..utils.pool import submit as pool_submit

        ncpu = available_cpus()
        work_bytes = sum(getattr(np.asarray(d.values), "nbytes", 0)
                         for d in datas)
        # small row groups stay serial even on multi-core: GIL'd numpy
        # dispatch beats the parallelism below ~8 MB of input.  The fan-out
        # runs on the process-wide shared pool (utils/pool.py) — a fresh
        # ThreadPoolExecutor here cost pool setup PER ROW GROUP on
        # multi-row-group writes; mark_pooled keeps the workers' native
        # thread splits at 1 (no pool x native oversubscription).
        mode = _overlap_mode()
        pooled = (ncpu > 1 and len(leaves) > 1
                  and work_bytes >= _PARALLEL_ENCODE_BYTES
                  and not in_shared_pool())
        overlap = mode != "off" and not in_shared_pool() and (
            mode == "force"
            or (ncpu > 1 and work_bytes >= _PARALLEL_ENCODE_BYTES))
        # step 1: the previous group's encode must COMPLETE before this
        # group's encode starts — concurrent encodes would race on the
        # sticky dictionary-fallback state and make the bytes depend on
        # scheduling.  Its results are held (not yet emitted) so this
        # group's encode can be in flight behind its emit.
        prev = self._inflight
        self._inflight = None
        if prev is not None:
            prev = (self._collect(prev[0]), prev[1])
        if overlap or pooled:
            encs = [pool_submit(self._timed_encode, leaf, data, num_rows)
                    for leaf, data in zip(leaves, datas)]
        else:
            encs = self._timed_encode_iter(leaves, datas, num_rows)
        if prev is not None:
            try:
                self._dispatch_emit(*prev)
            except BaseException:
                # the previous group's emit failed with THIS group's encode
                # already submitted: tear those futures down (abort() can't
                # reach them — they were never stored in _inflight)
                if overlap or pooled:
                    from ..utils.pool import cancel_futures

                    cancel_futures(encs)
                raise
        if overlap:
            self._inflight = (encs, num_rows)
            self.write_stats.overlapped_groups += 1
        else:
            self._dispatch_emit(self._collect(encs) if pooled else encs,
                                num_rows)

    def _timed_encode(self, leaf: Leaf, data: ColumnData, num_rows: int):
        # the write.encode span runs on whatever thread encodes — pool
        # worker under the overlap pipeline, caller thread serially — so
        # encode/emit overlap shows as parallel bars on two tracks
        enc_span = (_otrace.span("write.encode", col=leaf.dotted_path,
                                 rows=num_rows)
                    if _otrace.TRACE_ENABLED else _otrace.NULL_SPAN)
        with enc_span:
            t0 = time.perf_counter()
            enc = self._encode_chunk(leaf, data, num_rows)
            return enc, time.perf_counter() - t0

    def _timed_encode_iter(self, leaves, datas, num_rows):
        """Serial path: lazy per-chunk encode (consumed interleaved with
        emit — the measured-fast one-chunk-buffered form on one core)."""
        for leaf, data in zip(leaves, datas):
            enc, dt = self._timed_encode(leaf, data, num_rows)
            self.write_stats.encode_s += dt
            yield enc

    def _collect(self, futures) -> list:
        """Resolve a submitted group's encode futures in leaf order; the
        blocking portion is the pipeline bubble (``pool_wait_s``)."""
        t0 = time.perf_counter()
        out = []
        try:
            for i, f in enumerate(futures):
                enc, dt = f.result()
                self.write_stats.encode_s += dt
                out.append(enc)
        except BaseException:
            # one chunk's encode failed: the siblings' results are dead —
            # tear them down so no exception goes unretrieved
            from ..utils.pool import cancel_futures

            cancel_futures(futures[i + 1:])
            raise
        finally:
            self.write_stats.pool_wait_s += time.perf_counter() - t0
        return out

    def _drain_inflight(self) -> None:
        if self._inflight is None:
            return
        encs, num_rows = self._inflight
        self._inflight = None
        self._dispatch_emit(self._collect(encs), num_rows)

    # -------------------------------------------------- depth>1 emit queue
    def _dispatch_emit(self, encs, num_rows: int) -> None:
        """Route one encode-complete group to emit: inline at depth 1
        (today's path, generator consumed lazily) — at depth ≥ 2, pend it
        on the bounded queue for the emitter thread.  Pending blocks while
        the queue holds ``depth`` groups or the ledger's ``write.pended``
        account is over its cap (with at least one group pended — a
        single giant group must admit alone, never deadlock)."""
        if self._depth <= 1:
            self._emit_group(encs, num_rows)
            return
        if not isinstance(encs, list):
            # serial-encode generator: materialize on the CALLER thread —
            # encode order (and the sticky dictionary-fallback state, and
            # therefore the bytes) must not depend on emitter scheduling
            encs = list(encs)
        nb = _encs_nbytes(encs)
        cap = write_pended_cap_bytes()
        ctx = contextvars.copy_context()  # the op scope follows the emit
        with self._pend_cv:
            while self._emit_err is None and self._pend_q and (
                    len(self._pend_q) >= self._depth
                    or (cap > 0 and self._pend_bytes + nb > cap)):
                self._pend_cv.wait()
            if self._emit_err is not None:
                self._raise_emit_err()
            self._pend_q.append((ctx, encs, num_rows, nb))
            self._pend_bytes += nb
            _ACC_PENDED.add(nb)
            self._ensure_emitter_locked()
            self._pend_cv.notify_all()
        _maybe_pressure()  # pended encodes are a growth site too

    def _ensure_emitter_locked(self) -> None:
        if self._emitter is None or not self._emitter.is_alive():
            self._emitter_stop = False
            self._emitter = threading.Thread(
                target=self._emit_loop, name="pq-write-emit", daemon=True)
            self._emitter.start()

    def _emit_loop(self) -> None:
        """The per-writer emitter: pops encoded groups strictly FIFO and
        runs ``_emit_group`` — the ONE thread assigning offsets and
        touching the sink while the queue drains, so output bytes are
        identical to inline emit.  A group stays at the queue head while
        it emits (its pages are still resident; the ledger must say so).
        On error the queue drops (those groups can never emit over a
        failed sink) and the error re-raises on the caller's next call."""
        while True:
            with self._pend_cv:
                while not self._pend_q and not self._emitter_stop \
                        and not self._discard_pended:
                    self._pend_cv.wait()
                if self._discard_pended or (self._emitter_stop
                                            and not self._pend_q):
                    self._drop_pended_locked()
                    return
                ctx, encs, num_rows, nb = self._pend_q[0]
            err = None
            try:
                ctx.copy().run(self._emit_group, encs, num_rows)
            # ptlint: disable=PT005 -- not swallowed: emitter-thread
            # errors go sticky into _emit_err and re-raise on the
            # caller's next write/flush/close
            except BaseException as e:  # InjectedWriterCrash included
                err = e
            with self._pend_cv:
                self._pend_q.popleft()
                self._pend_bytes -= nb
                _ACC_PENDED.sub(nb)
                if err is not None:
                    self._emit_err = err
                    self._drop_pended_locked()  # dead groups: the sink
                    # failed; release their bytes, they can never emit
                self._pend_cv.notify_all()
                if err is not None or self._emitter_stop:
                    return

    def _drop_pended_locked(self) -> None:
        while self._pend_q:
            _, _, _, nb = self._pend_q.popleft()
            self._pend_bytes -= nb
            _ACC_PENDED.sub(nb)
        self._pend_cv.notify_all()

    def _drain_pended(self) -> None:
        """Block until every pended group emitted (flush/close barrier);
        re-raises a background emit failure on the caller thread."""
        if self._depth <= 1:
            return
        with self._pend_cv:
            while self._pend_q and self._emit_err is None:
                self._pend_cv.wait()
            if self._emit_err is not None:
                self._raise_emit_err()

    def _raise_emit_err(self):
        # sticky: once the background emit failed, the file can never be
        # completed — every later call surfaces the same root cause
        raise self._emit_err

    def _stop_emitter(self) -> None:
        with self._pend_cv:
            self._emitter_stop = True
            self._pend_cv.notify_all()
            t = self._emitter
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join()

    def _teardown_pended(self) -> None:
        """Failure-path teardown (abort, failed close): queued groups must
        never emit over a sink that is about to be aborted, and their
        ledger bytes must release — a leaked ``write.pended`` balance
        would fake memory pressure for the rest of the process.  Joins
        the emitter BEFORE the caller aborts the sink, so a mid-emit
        write can't race the teardown."""
        with self._pend_cv:
            self._discard_pended = True
            self._pend_cv.notify_all()
        self._stop_emitter()
        with self._pend_cv:  # emitter gone (or never started): sweep
            self._drop_pended_locked()

    def _emit_group(self, encs, num_rows: int) -> None:
        """Serial emit of one fully-encoded row group: assign offsets,
        write pages, append row-group metadata.  ``encs`` is a list (pooled
        encodes) or the lazy serial generator."""
        opts = self.options
        chunks: List[md.ColumnChunk] = []
        cis: List[Optional[md.ColumnIndex]] = []
        ois: List[Optional[md.OffsetIndex]] = []
        blooms: List[Optional[bytes]] = []
        rg_start = self._pos
        total_bytes = 0
        total_comp = 0
        emit_span = (_otrace.span("write.emit",
                                  rg=len(self._row_groups), rows=num_rows)
                     if _otrace.TRACE_ENABLED else _otrace.NULL_SPAN)
        with emit_span:  # `with`: a failed emit must still record the span
            for enc in encs:
                t0 = time.perf_counter()
                chunk, ci, oi, bloom, ubytes, cbytes = self._emit_chunk(enc)
                self.write_stats.emit_s += time.perf_counter() - t0
                chunks.append(chunk)
                cis.append(ci)
                ois.append(oi)
                blooms.append(bloom)
                total_bytes += ubytes
                total_comp += cbytes
        sorting = [
            md.SortingColumn(
                column_idx=self.schema.leaf(p).column_index,
                descending=desc, nulls_first=nf)
            for p, desc, nf in opts.sorting_columns
        ] or None
        self._row_groups.append(md.RowGroup(
            columns=chunks, total_byte_size=total_bytes, num_rows=num_rows,
            sorting_columns=sorting, file_offset=rg_start,
            total_compressed_size=total_comp, ordinal=len(self._row_groups)))
        self._column_indexes.append(cis)
        self._offset_indexes.append(ois)
        self._bloom_blobs.append(blooms)
        self._num_rows += num_rows
        self.write_stats.row_groups += 1

    # ------------------------------------------------------------------
    def _encode_chunk(self, leaf: Leaf, data: ColumnData, num_rows: int):
        """Pure encode phase of one chunk: levels, dictionary, page bodies,
        statistics, bloom — no file offsets, so row-group columns encode
        concurrently.  Returns an :class:`_EncodedChunk` for _emit_chunk."""
        opts = self.options
        physical = leaf.physical_type
        path = leaf.dotted_path

        # ---- levels -------------------------------------------------------
        def_levels, rep_levels = _build_levels(leaf, data, num_rows)
        n_slots = len(def_levels) if def_levels is not None else num_rows
        nvalues = (int(np.count_nonzero(def_levels == leaf.max_definition_level))
                   if def_levels is not None else num_rows)

        # ---- choose encoding ---------------------------------------------
        forced = opts.column_encoding.get(path)
        dict_values = dict_offsets = indices = None
        if (forced is None and opts.use_dictionary(path)
                and physical != Type.BOOLEAN
                and path not in self._dict_overflowed):
            dict_values, dict_offsets, indices = _build_dictionary(
                leaf, data, opts.dictionary_page_limit)
            if indices is None and nvalues:
                # overflow/limit on a chunk that HAD values: later row
                # groups of this column carry the same distribution — skip
                # their builds (and the sampling probes) instead of
                # rediscovering the overflow per group; the sticky fallback
                # mainstream writers use.  An empty/all-null chunk says
                # nothing about cardinality and must not disable the column.
                self._dict_overflowed.add(path)
        if indices is not None:
            value_encoding = Encoding.RLE_DICTIONARY
        elif forced is not None:
            value_encoding = forced
        else:
            value_encoding = Encoding.PLAIN

        # ---- statistics / bloom ------------------------------------------
        stats = None
        if opts.write_statistics:
            if indices is not None and nvalues:
                # every dictionary entry is referenced by construction:
                # chunk min/max == dictionary min/max (O(dict), not O(rows))
                mn, mx = _min_max_from_dict(leaf, dict_values, dict_offsets,
                                            None, 0)
                stats = md.Statistics(null_count=n_slots - nvalues,
                                      min_value=mn, max_value=mx,
                                      min=mn, max=mx)
            else:
                stats = _compute_statistics(leaf, data, n_slots, nvalues)
        bloom_blob = None
        if path in opts.bloom_filters:
            from .bloom import build_split_block_filter

            bloom_blob = build_split_block_filter(
                leaf, data, dict_values, dict_offsets, opts.bloom_filters[path])

        encodings_used = {Encoding.RLE}
        dict_page = None
        if indices is not None:
            dict_n = (len(dict_offsets) - 1 if dict_offsets is not None
                      else len(dict_values))
            raw_dict = ref.encode_plain(
                dict_values, physical,
                offsets=dict_offsets) if physical == Type.BYTE_ARRAY else ref.encode_plain(
                dict_values, physical)
            comp = self._codec.encode(raw_dict)
            hdr = md.PageHeader(
                type=int(PageType.DICTIONARY_PAGE),
                uncompressed_page_size=len(raw_dict),
                compressed_page_size=len(comp),
                crc=(zlib.crc32(comp) & 0xFFFFFFFF) if opts.write_crc else None,
                dictionary_page_header=md.DictionaryPageHeader(
                    num_values=dict_n,
                    encoding=int(Encoding.PLAIN), is_sorted=False))
            dict_page = (hdr, comp)
            encodings_used.add(Encoding.PLAIN)
            encodings_used.add(Encoding.RLE_DICTIONARY)
        else:
            dict_n = 0
            encodings_used.add(value_encoding)

        # per-chunk order-domain ranks of the dictionary: page statistics
        # become a rank gather + min/max instead of a bincount over the
        # whole dictionary per page (local — chunks encode concurrently)
        rank_cache = None
        if opts.write_statistics and indices is not None and dict_n:
            rank_cache = _dict_rank_cache(
                leaf, dict_values, dict_offsets, dict_n)

        # ---- paginate -----------------------------------------------------
        rows_per_page = _rows_per_page(leaf, data, nvalues, n_slots, opts.data_page_size)
        pages: List[tuple] = []  # (hdr, comp_body, take_rows, pstat, n_vals)
        slot_cursor = 0
        value_cursor = 0
        row_cursor = 0
        while row_cursor < num_rows or (num_rows == 0 and not pages):
            take_rows = min(rows_per_page, num_rows - row_cursor) if num_rows else 0
            s0, s1, v0, v1 = _page_slice(leaf, data, def_levels, rep_levels,
                                         row_cursor, take_rows, slot_cursor,
                                         value_cursor)
            body, n_slot_page, n_val_page, pstat = self._encode_page(
                leaf, data, def_levels, rep_levels, s0, s1, v0, v1,
                value_encoding, indices, dict_values, dict_n, dict_offsets,
                rank_cache)
            comp_body, hdr = self._page_header(leaf, body, n_slot_page,
                                               n_val_page, value_encoding,
                                               def_levels, rep_levels, s0, s1,
                                               pstat)
            pages.append((hdr, comp_body, take_rows, pstat, n_val_page))
            row_cursor += take_rows
            slot_cursor = s1
            value_cursor = v1
            if num_rows == 0:
                break
        return _EncodedChunk(leaf=leaf, dict_page=dict_page, pages=pages,
                             stats=stats, bloom_blob=bloom_blob,
                             encodings_used=encodings_used, n_slots=n_slots)

    def _emit_chunk(self, enc: "_EncodedChunk"):
        """Serial emit phase: assign file offsets, write pages, build the
        chunk metadata + page index."""
        opts = self.options
        leaf = enc.leaf
        # deferred: algebra/__init__ imports back into io.writer (cycle)
        from ..algebra.compare import truncate_stat_max, truncate_stat_min

        chunk_start = self._pos
        # pages accumulate and hit the sink in ONE write per chunk — the
        # per-page write() call overhead was a measured ~13% of write time.
        # Offsets advance on a LOCAL cursor; self._pos commits only at the
        # write, so a mid-loop exception cannot desync the writer's position
        # from the bytes actually on disk.
        parts: List[bytes] = []
        pos = chunk_start
        uncomp_acc = 0

        def emit(header: md.PageHeader, comp_body) -> None:
            nonlocal pos, uncomp_acc
            blob = thrift.serialize(header)
            parts.append(blob)
            parts.append(comp_body)
            pos += len(blob) + len(comp_body)
            uncomp_acc += header.uncompressed_page_size + len(blob)

        dict_page_offset = None
        if enc.dict_page is not None:
            dict_page_offset = pos
            emit(*enc.dict_page)
        data_page_offset = pos
        first_row = 0
        page_locs: List[md.PageLocation] = []
        ci_nulls: List[bool] = []
        ci_mins: List[bytes] = []
        ci_maxs: List[bytes] = []
        ci_null_counts: List[int] = []
        for hdr, comp_body, take_rows, pstat, n_val_page in enc.pages:
            page_off = pos
            emit(hdr, comp_body)
            page_locs.append(md.PageLocation(
                offset=page_off,
                compressed_page_size=pos - page_off,
                first_row_index=first_row))
            if pstat is not None:
                ci_nulls.append(n_val_page == 0)
                mn, mx = pstat.min_value or b"", pstat.max_value or b""
                lim = opts.column_index_truncate_length
                if (lim and leaf.physical_type in (
                        Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY)
                        and leaf.logical_kind not in (LogicalKind.DECIMAL,
                                                      LogicalKind.FLOAT16)):
                    # bytewise-ordered types only: decimals order by
                    # two's-complement value and float16 by float order,
                    # where a byte prefix is NOT a bound
                    mn = truncate_stat_min(mn, lim)
                    tmx = truncate_stat_max(mx, lim)
                    mx = tmx if tmx is not None else mx
                ci_mins.append(mn)
                ci_maxs.append(mx)
                ci_null_counts.append(pstat.null_count or 0)
            first_row += take_rows

        self._f.writelines(parts)
        self._pos = pos
        total_comp_size = pos - chunk_start
        meta = md.ColumnMetaData(
            type=int(leaf.physical_type),
            encodings=sorted({int(e) for e in enc.encodings_used}),
            path_in_schema=list(leaf.path),
            codec=int(opts.codec_id()),
            num_values=enc.n_slots,
            total_uncompressed_size=uncomp_acc,
            total_compressed_size=total_comp_size,
            data_page_offset=data_page_offset,
            dictionary_page_offset=dict_page_offset,
            statistics=enc.stats,
        )
        chunk = md.ColumnChunk(file_offset=chunk_start, meta_data=meta)
        ci = oi = None
        if opts.write_page_index:
            oi = md.OffsetIndex(page_locations=page_locs)
            if ci_mins:
                ci = md.ColumnIndex(
                    null_pages=ci_nulls, min_values=ci_mins,
                    max_values=ci_maxs,
                    boundary_order=int(_boundary_order(ci_mins, ci_maxs, leaf,
                                                       ci_nulls)),
                    null_counts=ci_null_counts)
        return chunk, ci, oi, enc.bloom_blob, uncomp_acc, total_comp_size

    # ------------------------------------------------------------------
    def _page_header(self, leaf, body, n_slots, n_vals, value_encoding,
                     def_levels, rep_levels, s0, s1, pstat):
        opts = self.options
        if opts.data_page_version == 2:
            # levels sit uncompressed in front of the (compressed) values
            rep_bytes, def_bytes, values = body
            comp_values = self._codec.encode(values)
            payload = rep_bytes + def_bytes + comp_values
            hdr = md.PageHeader(
                type=int(PageType.DATA_PAGE_V2),
                uncompressed_page_size=len(rep_bytes) + len(def_bytes) + len(values),
                compressed_page_size=len(payload),
                crc=(zlib.crc32(payload) & 0xFFFFFFFF) if opts.write_crc else None,
                data_page_header_v2=md.DataPageHeaderV2(
                    num_values=n_slots,
                    num_nulls=n_slots - n_vals,
                    num_rows=self._page_num_rows(leaf, rep_levels, s0, s1, n_slots),
                    encoding=int(value_encoding),
                    definition_levels_byte_length=len(def_bytes),
                    repetition_levels_byte_length=len(rep_bytes),
                    is_compressed=True,
                    statistics=pstat))
            return payload, hdr
        raw = body  # v1: levels already embedded
        comp = self._codec.encode(raw)
        hdr = md.PageHeader(
            type=int(PageType.DATA_PAGE),
            uncompressed_page_size=len(raw),
            compressed_page_size=len(comp),
            crc=(zlib.crc32(comp) & 0xFFFFFFFF) if opts.write_crc else None,
            data_page_header=md.DataPageHeader(
                num_values=n_slots,
                encoding=int(value_encoding),
                definition_level_encoding=int(Encoding.RLE),
                repetition_level_encoding=int(Encoding.RLE),
                statistics=pstat))
        return comp, hdr

    @staticmethod
    def _page_num_rows(leaf, rep_levels, s0, s1, n_slots):
        if rep_levels is None:
            return n_slots
        return int(np.count_nonzero(rep_levels[s0:s1] == 0))

    def _encode_page(self, leaf, data, def_levels, rep_levels, s0, s1, v0, v1,
                     value_encoding, indices, dict_values, dict_n=0,
                     dict_offsets=None, rank_cache=None):
        """Encode one page → body (+counts, stats).  v1: bytes; v2: 3-tuple."""
        opts = self.options
        physical = leaf.physical_type
        n_slot_page = s1 - s0
        n_val_page = v1 - v0
        # levels
        rep_bytes = b""
        def_bytes = b""
        if rep_levels is not None:
            w = _bw(leaf.max_repetition_level)
            enc = ref.encode_rle(rep_levels[s0:s1], w)
            rep_bytes = enc if opts.data_page_version == 2 else struct.pack("<I", len(enc)) + enc
        if def_levels is not None:
            w = _bw(leaf.max_definition_level)
            enc = ref.encode_rle(def_levels[s0:s1], w)
            def_bytes = enc if opts.data_page_version == 2 else struct.pack("<I", len(enc)) + enc
        # values
        if indices is not None:
            idx = indices[v0:v1]
            # bit width ≥ 1: several readers reject zero-width index streams
            width = max(_bw(max(dict_n - 1, 0)), 1)
            values = ref.encode_rle_dict_indices(idx, width)
        else:
            values = _encode_values(leaf, data, v0, v1, value_encoding)
        pstat = None
        if opts.write_statistics:
            if indices is not None:
                # dictionary-encoded page: min/max over the page's REFERENCED
                # dictionary entries, not its materialized values — the stats
                # pass drops from O(page values) to O(dict) (measured as the
                # single largest cost of writing a categorical column)
                if rank_cache is not None and v1 > v0:
                    ranks, sorted_ids = rank_cache
                    r = ranks[indices[v0:v1]]
                    sel = np.array([sorted_ids[r.min()], sorted_ids[r.max()]],
                                   dtype=np.int64)
                    mn, mx = _min_max_from_dict(
                        leaf, dict_values, dict_offsets, sel, dict_n)
                else:
                    mn, mx = _min_max_from_dict(
                        leaf, dict_values, dict_offsets,
                        indices[v0:v1], dict_n)
                pstat = md.Statistics(
                    null_count=(s1 - s0) - (v1 - v0),
                    min_value=mn, max_value=mx, min=mn, max=mx)
            else:
                pstat = self._page_statistics(leaf, data, def_levels,
                                              s0, s1, v0, v1)
        if opts.data_page_version == 2:
            return (rep_bytes, def_bytes, values), n_slot_page, n_val_page, pstat
        return rep_bytes + def_bytes + values, n_slot_page, n_val_page, pstat

    def _page_statistics(self, leaf, data, def_levels, s0, s1, v0, v1):
        nulls = (s1 - s0) - (v1 - v0)
        mn, mx = _min_max(leaf, data, v0, v1)
        return md.Statistics(
            null_count=nulls,
            min_value=mn, max_value=mx,
            min=mn, max=mx)

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Finalize: drain buffers, write blooms / page index / footer, and
        commit the sink.  ``_closed`` flips only after EVERYTHING — including
        the path sink's fsync+rename — succeeded; a failure mid-footer
        aborts the sink (no committed destination file is left behind) and
        re-raises with the writer in the aborted state."""
        if self._closed:
            return
        if self._aborted:
            raise ValueError("cannot close an aborted writer")
        with self._op_active():
            try:
                self._close_impl()
            except BaseException:
                self._aborted = True
                self._teardown_pended()  # discard queued groups + release
                # their ledger bytes before the sink abort
                if self._own_sink:
                    self._f.abort()
                if self._op is not None:
                    # abort() early-returns once _aborted — finalize the
                    # op HERE or the failed write (exactly the op slow-op
                    # capture exists for) never records
                    self._op.finish()
                raise
            self._closed = True
            self._stop_emitter()  # idle by now (_close_impl drained)
            # one publish per writer: the unified registry gets this
            # write's totals exactly once, at the moment the bytes are
            # committed (publish() itself is idempotent as a backstop)
            self.write_stats.publish()
        if self._op is not None:
            self._op.finish()
        if getattr(self._f, "_tunable", False):
            # feed the flush rate back to the process-wide buffer tuner
            # (sink.py): the NEXT writer's writeback buffer grows when this
            # one still flushed many times per row group
            from .sink import write_autotune

            write_autotune().observe(self.write_stats)

    def abort(self) -> None:
        """Discard the write: no footer is serialized, a writer-owned path
        sink removes its temp (or partial) file so no destination is left
        behind, and any background encode still in flight is cancelled
        (queued tasks never run; a started one finishes into the void — it
        is pure compute that touches neither the sink nor writer state).
        Caller-owned sinks are left untouched (their bytes are the caller's
        to clean up).  Idempotent; a no-op after a successful
        :meth:`close`."""
        if self._closed or self._aborted:
            return
        self._aborted = True
        self._buffer = None
        self._buffered_rows = 0
        if self._inflight is not None:
            from ..utils.pool import cancel_futures

            encs, _ = self._inflight
            self._inflight = None
            cancel_futures(encs)
        # depth>1: discard queued groups and join the emitter before the
        # sink abort (the head group mid-emit finishes into the doomed
        # temp file — harmless, the abort unlinks it)
        self._teardown_pended()
        if self._own_sink:
            self._f.abort()
        if self._op is not None:
            self._op.finish()

    def _close_impl(self) -> None:
        self.flush()
        opts = self.options
        # bloom filters (before page index, like common writers)
        for rg_i, rg in enumerate(self._row_groups):
            for col_i, chunk in enumerate(rg.columns):
                blob = self._bloom_blobs[rg_i][col_i]
                if blob is None:
                    continue
                chunk.meta_data.bloom_filter_offset = self._pos
                self._f.write(blob)
                self._pos += len(blob)
                chunk.meta_data.bloom_filter_length = len(blob)
        # page index: all ColumnIndex then all OffsetIndex (spec layout)
        if opts.write_page_index:
            for rg_i, rg in enumerate(self._row_groups):
                for col_i, chunk in enumerate(rg.columns):
                    ci = self._column_indexes[rg_i][col_i]
                    if ci is None:
                        continue
                    blob = thrift.serialize(ci)
                    chunk.column_index_offset = self._pos
                    chunk.column_index_length = len(blob)
                    self._f.write(blob)
                    self._pos += len(blob)
            for rg_i, rg in enumerate(self._row_groups):
                for col_i, chunk in enumerate(rg.columns):
                    oi = self._offset_indexes[rg_i][col_i]
                    if oi is None:
                        continue
                    blob = thrift.serialize(oi)
                    chunk.offset_index_offset = self._pos
                    chunk.offset_index_length = len(blob)
                    self._f.write(blob)
                    self._pos += len(blob)
        fmd = md.FileMetaData(
            version=2,
            schema=self.schema.to_elements(),
            num_rows=self._num_rows,
            row_groups=self._row_groups,
            key_value_metadata=[md.KeyValue(key=k, value=v)
                                for k, v in opts.key_value_metadata.items()] or None,
            created_by=opts.created_by,
            column_orders=[md.ColumnOrder(TYPE_ORDER=md.TypeDefinedOrder())
                           for _ in self.schema.leaves])
        blob = thrift.serialize(fmd)
        # footer + length + magic in ONE write: a torn tail then lacks the
        # terminal PAR1 and can never parse as a complete file
        self._f.write(blob + struct.pack("<I", len(blob)) + md.MAGIC)
        self._f.flush()
        if self._own_sink:
            self._f.close()  # sink commit: fsync (+ atomic rename)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # an in-flight exception means the stream is mid-row-group or
        # mid-footer: serializing a footer now would produce a VALID-LOOKING
        # file over torn data — abort (unlink temp / partial) instead.  A
        # caller who already abort()ed inside the block gets a clean exit.
        if exc_type is not None:
            self.abort()
        elif not self._aborted:
            self.close()


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _bw(v: int) -> int:
    return int(v).bit_length()


def _dict_size(dict_values) -> int:
    if isinstance(dict_values, tuple):
        return len(dict_values[1]) - 1
    return len(dict_values)


def _slice_cd(leaf: Leaf, cd: ColumnData, r0: int, r1: int,
              ctx: Optional[dict] = None) -> ColumnData:
    """Rows [r0, r1) of buffered ColumnData (row-group splitting).  Uses the
    shared Dremel span arithmetic (ops/levels); ``ctx`` (a mutable per-column
    dict) caches the row-start and cumulative-present tables so splitting a
    buffer into P parts is O(N), not O(N·P)."""
    max_def = leaf.max_definition_level
    ctx = ctx if ctx is not None else {}

    def cum_present(mask_src) -> np.ndarray:
        if "cum" not in ctx:
            cum = np.zeros(len(mask_src) + 1, np.int64)
            np.cumsum(mask_src, out=cum[1:])
            ctx["cum"] = cum
        return ctx["cum"]

    def vals_span(v0, v1):
        if cd.offsets is not None:
            offs = np.asarray(cd.offsets)
            base = int(offs[v0])
            return (np.asarray(cd.values)[base : int(offs[v1])],
                    offs[v0 : v1 + 1] - base)
        return np.asarray(cd.values)[v0:v1], None

    if cd.def_levels is not None or cd.rep_levels is not None:
        d, r = cd.def_levels, cd.rep_levels
        n_slots = len(d) if d is not None else len(r)
        if r is not None and "starts" not in ctx:
            ctx["starts"] = levels_ops.row_slot_starts(r)
        s0, s1 = levels_ops.slot_span(r, r0, r1, n_slots,
                                      row_starts=ctx.get("starts"))
        if d is None:
            v0, v1 = s0, s1
        else:
            cum = cum_present(np.asarray(d) == max_def)
            v0, v1 = int(cum[s0]), int(cum[s1])
        vals, offs = vals_span(v0, v1)
        return ColumnData(values=vals, offsets=offs,
                          def_levels=None if d is None else d[s0:s1],
                          rep_levels=None if r is None else r[s0:s1])
    if cd.list_offsets is not None:
        lo = np.asarray(cd.list_offsets)
        e0, e1 = int(lo[r0]), int(lo[r1])
        validity = cd.validity
        if validity is None:
            v0, v1 = e0, e1
        else:
            validity = np.asarray(validity)
            cum = cum_present(validity)
            v0, v1 = int(cum[e0]), int(cum[e1])
        vals, offs = vals_span(v0, v1)
        return ColumnData(
            values=vals, offsets=offs,
            validity=None if cd.validity is None else validity[e0:e1],
            list_offsets=lo[r0 : r1 + 1] - e0,
            list_validity=None if cd.list_validity is None
            else np.asarray(cd.list_validity)[r0:r1])
    if cd.validity is None:
        vals, offs = vals_span(r0, r1)
        return ColumnData(values=vals, offsets=offs)
    validity = np.asarray(cd.validity)
    cum = cum_present(validity)
    v0, v1 = int(cum[r0]), int(cum[r1])
    vals, offs = vals_span(v0, v1)
    return ColumnData(values=vals, offsets=offs, validity=validity[r0:r1])


def _shallow_cd(cd: ColumnData) -> ColumnData:
    """New ColumnData object sharing the caller's arrays (field rebinding in
    the buffer must not reach the caller; array contents are never mutated)."""
    import dataclasses

    return dataclasses.replace(cd)


def _copy_cd(cd: ColumnData) -> ColumnData:
    return ColumnData(values=np.asarray(cd.values).copy(),
                      offsets=None if cd.offsets is None else cd.offsets.copy(),
                      validity=None if cd.validity is None else cd.validity.copy(),
                      list_offsets=None if cd.list_offsets is None else cd.list_offsets.copy(),
                      list_validity=None if cd.list_validity is None else cd.list_validity.copy(),
                      def_levels=None if cd.def_levels is None else cd.def_levels.copy(),
                      rep_levels=None if cd.rep_levels is None else cd.rep_levels.copy())


def _extend_cd(dst: ColumnData, src: ColumnData) -> None:
    if (dst.def_levels is None) != (src.def_levels is None) or (
            dst.rep_levels is None) != (src.rep_levels is None):
        raise ValueError(
            "cannot mix raw-level ColumnData (rows path) with vectorized "
            "ColumnData in one buffered chunk; flush between them")
    dst.values = np.concatenate([np.asarray(dst.values), np.asarray(src.values)])
    if dst.def_levels is not None:
        dst.def_levels = np.concatenate([dst.def_levels, src.def_levels])
    if dst.rep_levels is not None:
        dst.rep_levels = np.concatenate([dst.rep_levels, src.rep_levels])
    if dst.offsets is not None:
        base = dst.offsets[-1]
        dst.offsets = np.concatenate([dst.offsets[:-1], src.offsets + base])
    if dst.validity is not None or src.validity is not None:
        a = dst.validity if dst.validity is not None else np.ones(_cd_len_v(dst) - _cd_len_v(src), bool)
        b = src.validity if src.validity is not None else np.ones(_cd_len_v(src), bool)
        dst.validity = np.concatenate([a, b])
    if dst.list_offsets is not None:
        base = dst.list_offsets[-1]
        dst.list_offsets = np.concatenate([dst.list_offsets[:-1], src.list_offsets + base])
        if dst.list_validity is not None or src.list_validity is not None:
            a = dst.list_validity if dst.list_validity is not None else None
            dst.list_validity = np.concatenate([
                a if a is not None else np.ones(len(dst.list_offsets) - len(src.list_offsets), bool),
                src.list_validity if src.list_validity is not None
                else np.ones(len(src.list_offsets) - 1, bool)])


def _cd_len_v(cd: ColumnData) -> int:
    if cd.offsets is not None:
        return len(cd.offsets) - 1
    return len(np.asarray(cd.values))


def _build_levels(leaf: Leaf, data: ColumnData, num_rows: int):
    max_def = leaf.max_definition_level
    max_rep = leaf.max_repetition_level
    if data.def_levels is not None or data.rep_levels is not None:
        return data.def_levels, data.rep_levels
    if max_rep == 0:
        if max_def == 0:
            return None, None
        # nested optional groups (struct fields): validity covers the chain;
        # intermediate struct nulls are collapsed to leaf nulls (v1 writer).
        d = levels_ops.levels_for_flat(data.validity, num_rows, max_def)
        return d, None
    if data.list_offsets is None:
        raise ValueError(f"column {leaf.dotted_path}: repeated leaf needs list_offsets")
    d, r = levels_ops.levels_for_list(
        np.asarray(data.list_offsets), data.list_validity, data.validity, leaf)
    return d, r


def _build_dictionary(leaf: Leaf, data: ColumnData, limit_bytes: int):
    physical = leaf.physical_type
    vals = np.asarray(data.values)
    if physical == Type.BYTE_ARRAY:
        from .. import native as _native

        offs = np.asarray(data.offsets, dtype=np.int64)
        n = len(offs) - 1
        if n == 0:
            return None, None, None
        max_unique = n // 2 + 16
        nat = _native.dict_build_ba(vals, offs, max_unique)
        if nat == "overflow":
            return None, None, None
        if nat is not None:
            # C++ hash-table dedup (hashprobe analog); first-seen order
            indices, first_rows = nat
            lens = (offs[1:] - offs[:-1])[first_rows]
            doffs = np.zeros(len(first_rows) + 1, np.int64)
            np.cumsum(lens, out=doffs[1:])
            if int(doffs[-1]) + 4 * len(first_rows) > limit_bytes:
                return None, None, None
            idx = np.repeat(offs[:-1][first_rows], lens) + _iota_segments(lens)
            dvals = vals[idx] if len(idx) else vals[:0]
            return dvals, doffs, indices
        items = [vals[offs[i]:offs[i + 1]].tobytes() for i in range(n)]
        uniq = sorted(set(items))
        if sum(len(u) + 4 for u in uniq) > limit_bytes or len(uniq) > max_unique:
            return None, None, None
        lookup = {u: i for i, u in enumerate(uniq)}
        indices = np.fromiter((lookup[it] for it in items), dtype=np.int64, count=n)
        dvals = np.frombuffer(b"".join(uniq), np.uint8)
        doffs = np.zeros(len(uniq) + 1, np.int64)
        np.cumsum([len(u) for u in uniq], out=doffs[1:])
        return dvals, doffs, indices
    if physical in (Type.INT96, Type.FIXED_LEN_BYTE_ARRAY):
        return None, None, None  # keep plain for v1
    if len(vals) == 0:
        return None, None, None
    max_unique = len(vals) // 2 + 16
    from .. import native as _native

    nat = _native.dict_build_fixed(vals, max_unique)
    if nat == "overflow":
        return None, None, None
    if nat is not None:
        uniq, indices = nat  # C++ hash dedup, first-seen order
    else:
        uniq, indices = np.unique(vals, return_inverse=True)
        indices = indices.astype(np.int64)
    if uniq.nbytes > limit_bytes or len(uniq) > max_unique:
        return None, None, None
    return uniq, None, indices


def _encode_values(leaf: Leaf, data: ColumnData, v0: int, v1: int,
                   encoding: Encoding) -> bytes:
    physical = leaf.physical_type
    vals = np.asarray(data.values)
    if physical == Type.BYTE_ARRAY:
        offs = np.asarray(data.offsets, dtype=np.int64)
        sub_offs = offs[v0 : v1 + 1] - offs[v0]
        sub_vals = vals[offs[v0] : offs[v1]]
        if encoding == Encoding.PLAIN:
            return ref.encode_plain(sub_vals, physical, offsets=sub_offs)
        if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
            return ref.encode_delta_length_byte_array(sub_vals, sub_offs)
        if encoding == Encoding.DELTA_BYTE_ARRAY:
            return ref.encode_delta_byte_array(sub_vals, sub_offs)
        raise ValueError(f"bad encoding {encoding} for BYTE_ARRAY")
    sub = vals[v0:v1]
    if encoding == Encoding.PLAIN:
        return ref.encode_plain(sub, physical)
    if encoding == Encoding.DELTA_BINARY_PACKED:
        return ref.encode_delta_binary_packed(sub.astype(np.int64))
    if encoding == Encoding.BYTE_STREAM_SPLIT:
        width = {Type.FLOAT: 4, Type.DOUBLE: 8, Type.INT32: 4, Type.INT64: 8}.get(
            physical, leaf.type_length)
        raw = np.frombuffer(np.ascontiguousarray(sub).tobytes(), np.uint8)
        return ref.encode_byte_stream_split(raw, len(sub), width)
    if encoding == Encoding.RLE and physical == Type.BOOLEAN:
        body = ref.encode_rle(sub.astype(np.int64), 1)
        return struct.pack("<I", len(body)) + body
    raise ValueError(f"unsupported write encoding {encoding!r}")


def _rows_per_page(leaf: Leaf, data: ColumnData, nvalues: int, n_slots: int,
                   page_bytes: int) -> int:
    width = {Type.BOOLEAN: 1, Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4,
             Type.DOUBLE: 8, Type.INT96: 12}.get(leaf.physical_type)
    if width is None:
        if data.offsets is not None and len(data.offsets) > 1:
            width = max(int(data.offsets[-1]) // max(len(data.offsets) - 1, 1), 1) + 4
        else:
            width = leaf.type_length or 16
    per = max(page_bytes // max(width, 1), 1)
    return per


def _page_slice(leaf, data, def_levels, rep_levels, row0, nrows, s0, v0):
    """Map a row range onto slot + value ranges.  The Dremel span arithmetic
    is shared with the streaming reader (ops/levels: slot_span /
    present_count); ``s0``/``v0`` are the caller's cursors, which advance in
    lockstep with the row cursor."""
    n_slots = len(rep_levels) if rep_levels is not None else 0
    _, s1 = levels_ops.slot_span(rep_levels, row0, row0 + nrows, n_slots)
    return s0, s1, v0, v0 + levels_ops.present_count(
        def_levels, s0, s1, leaf.max_definition_level)


def _compute_statistics(leaf, data: ColumnData, n_slots, nvalues):
    mn, mx = _min_max(leaf, data, 0, nvalues)
    return md.Statistics(null_count=n_slots - nvalues, min_value=mn,
                         max_value=mx, min=mn, max=mx)


def _dict_rank_cache(leaf: Leaf, dict_values, dict_offsets, dict_n: int):
    """Order-domain ranks of the dictionary entries, computed once per
    chunk: (ranks[id] -> rank, sorted_ids[rank] -> id).  Page statistics
    then cost a rank gather + min/max over the page's index span instead of
    a bincount over the whole dictionary per page.  None when entries are
    not cleanly rankable (NaN floats, INT96) — callers fall back to the
    bincount path."""
    from ..algebra import compare

    if leaf.physical_type == Type.INT96:
        return None
    try:
        dense = compare._dense_order_values(
            leaf, ColumnData(values=dict_values, offsets=dict_offsets),
            0, dict_n)
    except Exception:
        return None
    if dense.dtype.kind == "f" and np.isnan(dense).any():
        return None
    sorted_ids = np.argsort(dense, kind="stable")
    ranks = np.empty(dict_n, np.int64)
    ranks[sorted_ids] = np.arange(dict_n)
    return ranks, sorted_ids


def _min_max_from_dict(leaf: Leaf, dict_values, dict_offsets, idx_span,
                       dict_n: int):
    """Encoded (min, max) for a dictionary-encoded span: select the
    referenced dictionary entries (bincount over the index span; the whole
    dictionary when ``idx_span`` is None) and min/max over THOSE — O(dict)
    instead of O(values)."""
    from ..algebra import compare

    if idx_span is None:
        sel_vals, sel_offs = dict_values, dict_offsets
        count = (len(dict_offsets) - 1 if dict_offsets is not None
                 else len(dict_values))
    else:
        if len(idx_span) == 0:
            return None, None
        # tiny spans (the rank cache passes exactly {min_id, max_id}) skip
        # the dict_n-sized bincount allocation
        ids = (np.unique(idx_span) if len(idx_span) <= 64 else
               np.flatnonzero(np.bincount(idx_span, minlength=max(dict_n, 1))))
        if dict_offsets is not None:
            sel_vals, sel_offs = ref.gather_dictionary(
                (dict_values, dict_offsets), ids.astype(np.int64))
        else:
            sel_vals, sel_offs = np.asarray(dict_values)[ids], None
        count = len(ids)
    mn, mx = compare.min_max(
        leaf, ColumnData(values=sel_vals, offsets=sel_offs), 0, count)
    if mn is None:
        return None, None
    return (compare.encode_order_value(mn, leaf),
            compare.encode_order_value(mx, leaf))


def _min_max(leaf: Leaf, data: ColumnData, v0: int, v1: int):
    """Encoded (min, max) statistics bytes for a dense value span.

    Ordering and encoding delegate to algebra/compare (reference
    compare.go): unsigned logical ints compare and encode unsigned, decimals
    compare by unscaled integer, FLBA emits bytewise min/max."""
    from ..algebra import compare

    mn, mx = compare.min_max(leaf, data, v0, v1)
    if mn is None:
        return None, None
    return (compare.encode_order_value(mn, leaf),
            compare.encode_order_value(mx, leaf))


def _boundary_order(mins: List[bytes], maxs: List[bytes], leaf: Leaf,
                    null_pages: Optional[List[bool]] = None):
    from ..format.enums import BoundaryOrder
    from .statistics import decode_stat_value

    if null_pages is not None:
        # all-null pages carry placeholder min/max (null_pages flags them);
        # the ordering is defined over the remaining pages only
        mins = [m for m, np_ in zip(mins, null_pages) if not np_]
        maxs = [m for m, np_ in zip(maxs, null_pages) if not np_]
    if len(mins) <= 1:
        return BoundaryOrder.ASCENDING
    dmins = [decode_stat_value(m, leaf) for m in mins]
    dmaxs = [decode_stat_value(m, leaf) for m in maxs]
    if any(v is None for v in dmins) or any(v is None for v in dmaxs):
        return BoundaryOrder.UNORDERED
    asc = all(dmins[i] <= dmins[i + 1] for i in range(len(dmins) - 1)) and \
        all(dmaxs[i] <= dmaxs[i + 1] for i in range(len(dmaxs) - 1))
    if asc:
        return BoundaryOrder.ASCENDING
    desc = all(dmins[i] >= dmins[i + 1] for i in range(len(dmins) - 1)) and \
        all(dmaxs[i] >= dmaxs[i + 1] for i in range(len(dmaxs) - 1))
    return BoundaryOrder.DESCENDING if desc else BoundaryOrder.UNORDERED


# ---------------------------------------------------------------------------
# High-level helpers: arrow/dict-of-arrays in, file out
# ---------------------------------------------------------------------------


def write_table(table, sink, options: Optional[WriterOptions] = None,
                schema: Optional[Schema] = None):
    """Write a pyarrow.Table or {name: numpy array} mapping to Parquet.

    Reference parity: ``parquet.WriteFile`` / ``GenericWriter[T]`` front end
    (typed writes become columnar here — the TPU framework is columnar-first).
    """
    import pyarrow as pa

    if isinstance(table, dict):
        table = pa.table(table)
    if schema is None:
        schema = schema_from_arrow(table.schema)
    options = options or WriterOptions()
    w = ParquetWriter(sink, schema, options)
    try:
        n = table.num_rows
        rg_size = min(options.row_group_size, n) if n else n
        for start in range(0, max(n, 1), max(rg_size, 1)):
            end = min(start + rg_size, n) if rg_size else n
            part = table.slice(start, end - start) if (start or end < n) else table
            cols = columns_from_arrow(part, schema)
            w.write_row_group(cols, part.num_rows)
            if n == 0:
                break
        w.close()
    except BaseException:
        # same contract as the context manager: a failed write aborts (path
        # sinks unlink their temp/partial file) instead of leaking it
        w.abort()
        raise
    return w


def schema_from_arrow(aschema) -> Schema:
    """Map a pyarrow schema to a parquet schema tree."""
    import pyarrow as pa

    def field_node(f: "pa.Field") -> sch.Node:
        rep = Rep.OPTIONAL if f.nullable else Rep.REQUIRED
        t = f.type
        if pa.types.is_list(t) or pa.types.is_large_list(t):
            elem = field_node(pa.field("element", t.value_type,
                                       nullable=t.value_field.nullable))
            return sch.list_of(f.name, elem, rep)
        if pa.types.is_struct(t):
            children = [field_node(t.field(i)) for i in range(t.num_fields)]
            return sch.group(f.name, children, rep)
        if pa.types.is_map(t):
            key = field_node(pa.field("key", t.key_type, nullable=False))
            val = field_node(pa.field("value", t.item_type))
            return sch.map_of(f.name, key, val, rep)
        phys, kind, params, tl = _arrow_leaf_type(t)
        return sch.leaf(f.name, phys, rep, kind, type_length=tl, **params)

    root = sch.Node(name="schema", children=[field_node(f) for f in aschema])
    return Schema(root)


def _arrow_leaf_type(t):
    import pyarrow as pa

    K = LogicalKind
    if pa.types.is_null(t):
        # arrow's untyped all-null column: parquet Null logical type over
        # optional INT32 (pyarrow's mapping)
        return Type.INT32, K.UNKNOWN, {}, None
    if pa.types.is_boolean(t):
        return Type.BOOLEAN, K.NONE, {}, None
    if pa.types.is_int8(t):
        return Type.INT32, K.INT, {"bit_width": 8, "signed": True}, None
    if pa.types.is_int16(t):
        return Type.INT32, K.INT, {"bit_width": 16, "signed": True}, None
    if pa.types.is_int32(t):
        return Type.INT32, K.NONE, {}, None
    if pa.types.is_int64(t):
        return Type.INT64, K.NONE, {}, None
    if pa.types.is_uint8(t):
        return Type.INT32, K.INT, {"bit_width": 8, "signed": False}, None
    if pa.types.is_uint16(t):
        return Type.INT32, K.INT, {"bit_width": 16, "signed": False}, None
    if pa.types.is_uint32(t):
        return Type.INT32, K.INT, {"bit_width": 32, "signed": False}, None
    if pa.types.is_uint64(t):
        return Type.INT64, K.INT, {"bit_width": 64, "signed": False}, None
    if pa.types.is_float16(t):
        return Type.FIXED_LEN_BYTE_ARRAY, K.FLOAT16, {}, 2
    if pa.types.is_float32(t):
        return Type.FLOAT, K.NONE, {}, None
    if pa.types.is_float64(t):
        return Type.DOUBLE, K.NONE, {}, None
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return Type.BYTE_ARRAY, K.STRING, {}, None
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return Type.BYTE_ARRAY, K.NONE, {}, None
    if pa.types.is_fixed_size_binary(t):
        return Type.FIXED_LEN_BYTE_ARRAY, K.NONE, {}, t.byte_width
    if pa.types.is_date32(t):
        return Type.INT32, K.DATE, {}, None
    if pa.types.is_timestamp(t):
        unit = {"ms": "timestamp_millis", "us": "timestamp_micros",
                "ns": "timestamp_nanos"}.get(t.unit, "timestamp_micros")
        return Type.INT64, unit, {"utc": t.tz is not None}, None
    if pa.types.is_time32(t):
        return Type.INT32, K.TIME_MILLIS, {"utc": True}, None
    if pa.types.is_time64(t):
        return Type.INT64, K.TIME_MICROS, {"utc": True}, None
    if pa.types.is_decimal(t):
        if t.precision <= 9:
            return Type.INT32, K.DECIMAL, {"scale": t.scale, "precision": t.precision}, None
        if t.precision <= 18:
            return Type.INT64, K.DECIMAL, {"scale": t.scale, "precision": t.precision}, None
        return Type.FIXED_LEN_BYTE_ARRAY, K.DECIMAL, \
            {"scale": t.scale, "precision": t.precision}, 16
    raise TypeError(f"unsupported arrow type {t!r}")


def columns_from_arrow(table, schema: Schema) -> Dict[str, ColumnData]:
    """Per-leaf ColumnData from an arrow table (or slice) — the single arrow
    ingestion entry point (used by write_table and TableBuffer.write_arrow),
    so struct-null def-level fidelity is applied uniformly."""
    import pyarrow as pa

    cols: Dict[str, ColumnData] = {}
    for leaf in schema.leaves:
        arr = table[leaf.path[0]]
        if isinstance(arr, pa.ChunkedArray):
            # a single-chunk column (the common write_table slice) is a
            # zero-copy view; combine_chunks would memcpy the whole slice
            arr = (arr.chunk(0) if arr.num_chunks == 1
                   else arr.combine_chunks())
        cd = _column_from_arrow(arr, leaf)
        if (len(leaf.path) > 1 and leaf.max_repetition_level == 0
                and cd.def_levels is None
                and _struct_chain_has_nulls(arr, leaf)):
            # an intermediate struct layer is null somewhere: emit exact
            # def levels so None-struct vs struct-of-None round-trips
            cd.def_levels = _struct_def_levels(arr, schema, leaf)
        cols[leaf.dotted_path] = cd
    return cols


def _struct_chain_has_nulls(arr, leaf: Leaf) -> bool:
    """True if any non-leaf struct layer on the path to ``leaf`` has nulls."""
    import pyarrow as pa

    a = arr
    for name in leaf.path[1:]:
        if not pa.types.is_struct(a.type):
            return False
        if a.null_count:
            return True
        a = a.field(name)
    return False


def _struct_def_levels(arr, schema: Schema, leaf: Leaf) -> np.ndarray:
    """Exact per-row def levels for a flat (max_rep == 0) struct chain.

    Walks the schema nodes along ``leaf.path`` top-down, counting one def
    level per OPTIONAL layer that is present, and stopping the count at the
    first null ancestor (child slots under a null parent are unspecified in
    arrow, so an ``alive`` mask gates deeper contributions).
    """
    import pyarrow as pa

    node = schema.root
    n = len(arr)
    d = np.zeros(n, np.int32)
    alive = np.ones(n, bool)
    a = arr
    for i, name in enumerate(leaf.path):
        node = next(c for c in node.children if c.name == name)
        if node.repetition == Rep.OPTIONAL:
            if a.null_count:
                ok = alive & ~np.asarray(a.is_null())
            else:
                ok = alive
            d[ok] += 1
            alive = ok
        if i + 1 < len(leaf.path):
            a = a.field(leaf.path[i + 1])
    return d


def _column_from_arrow(arr, leaf: Leaf, pos: int = 1) -> ColumnData:
    """Extract flat buffers from an arrow array for one leaf.

    ``arr`` is the top-level (or descended) arrow array; ``pos`` indexes the
    next component of ``leaf.path`` still to resolve below it. Struct layers
    descend by field name with parent-struct nulls folded into the child
    (the v1 writer collapses intermediate struct nulls to leaf nulls — see
    write_row_group); list/map machinery consumes its two path components
    ('list'/'element', 'key_value'/'key|value') per level. Deeply mixed
    chains (a list *below* a struct that is itself a list element) are not
    expressible in the single-level ColumnData form and keep the pre-existing
    pure-list-chain limitation.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    t = arr.type
    if pa.types.is_struct(t):
        if arr.null_count and leaf.max_repetition_level > 0:
            raise NotImplementedError(
                f"column {leaf.dotted_path}: null struct values mixed with "
                "repetition are not supported by the arrow ingestion path "
                "(write via rows/typed API for exact def levels)")
        # fold parent-struct nulls into the child so dense value extraction
        # (drop_null below) excludes slots under a null ancestor; exact def
        # levels for the chain are emitted separately (_struct_def_levels)
        child = arr.field(leaf.path[pos])
        if arr.null_count:
            child = pc.if_else(pc.is_valid(arr), child,
                               pa.scalar(None, type=child.type))
        return _column_from_arrow(child, leaf, pos + 1)
    if pa.types.is_list(t) or pa.types.is_large_list(t) or pa.types.is_map(t):
        # walk the (possibly multi-level) list chain collecting per-level
        # offsets/validity, then emit either the single-level ColumnData form
        # or raw Dremel levels (levels_for_nested) for depth > 1
        offsets_per_level, validity_per_level = [], []
        a = arr
        while True:
            ty = a.type
            if pa.types.is_map(ty):
                child = a.keys if leaf.path[pos + 1] == "key" else a.items
            elif pa.types.is_list(ty) or pa.types.is_large_list(ty):
                child = a.values
            else:
                break
            lv = ~np.asarray(a.is_null()) if a.null_count else None
            raw = np.asarray(a.offsets, dtype=np.int64)
            pos += 2
            if raw[0] != 0 or len(child) != raw[-1]:  # sliced parent array
                child = child.slice(raw[0], raw[-1] - raw[0])
            offs = raw - raw[0]
            if lv is not None:
                # arrow permits a NULL list's offset span to still cover
                # child values; parquet has no slots for them — drop the
                # spanned values and zero the null rows' lengths
                lens = np.diff(offs)
                if lens[~lv].any():
                    child = child.filter(pa.array(np.repeat(lv, lens)))
                    offs = np.zeros(len(offs), np.int64)
                    np.cumsum(np.where(lv, lens, 0), out=offs[1:])
            offsets_per_level.append(offs)
            validity_per_level.append(lv)
            a = child
        inner = _column_from_arrow(a, leaf, pos)
        if len(offsets_per_level) == 1:
            inner.list_offsets = offsets_per_level[0]
            inner.list_validity = validity_per_level[0]
            return inner
        d, r = levels_ops.levels_for_nested(
            offsets_per_level, validity_per_level, inner.validity, leaf)
        inner.def_levels = d
        inner.rep_levels = r
        return inner
    if pa.types.is_null(t):  # untyped all-null column: zero dense values
        return ColumnData(values=np.empty(0, np.int32),
                          validity=np.zeros(len(arr), bool))
    validity = None
    if arr.null_count:
        validity = ~np.asarray(arr.is_null())
    if pa.types.is_string(t) or pa.types.is_binary(t) or \
            pa.types.is_large_string(t) or pa.types.is_large_binary(t):
        # dense present values, read straight from the arrow buffers
        # (offsets + data) — no python bytes objects on the write hot path
        dense = arr.drop_null()
        large = pa.types.is_large_string(t) or pa.types.is_large_binary(t)
        bufs = dense.buffers()
        odt = np.int64 if large else np.int32
        o0 = dense.offset
        offs_raw = np.frombuffer(bufs[1], odt)[o0 : o0 + len(dense) + 1] \
            .astype(np.int64)
        data = np.frombuffer(bufs[2], np.uint8)[offs_raw[0] : offs_raw[-1]] \
            if len(dense) else np.empty(0, np.uint8)
        return ColumnData(values=data, offsets=offs_raw - offs_raw[0],
                          validity=validity)
    if pa.types.is_boolean(t):
        dense = arr.drop_null()
        return ColumnData(values=np.asarray(dense), validity=validity)
    if pa.types.is_float16(t):
        dense = np.asarray(arr.drop_null()).astype(np.float16)
        return ColumnData(values=dense.view(np.uint8).reshape(-1, 2), validity=validity)
    if pa.types.is_fixed_size_binary(t):
        dense = arr.drop_null()
        w = t.byte_width
        flat = np.frombuffer(dense.buffers()[1], np.uint8)[
            dense.offset * w : (dense.offset + len(dense)) * w]
        return ColumnData(values=flat.reshape(-1, w), validity=validity)
    if pa.types.is_decimal(t):
        dense = arr.drop_null()
        ints = np.asarray([int(x.as_py().scaleb(t.scale)) for x in dense], dtype=np.int64)
        phys = leaf.physical_type
        if phys == Type.INT32:
            return ColumnData(values=ints.astype(np.int32), validity=validity)
        if phys == Type.INT64:
            return ColumnData(values=ints, validity=validity)
        w = leaf.type_length
        be = np.zeros((len(ints), w), np.uint8)
        for k in range(w):
            be[:, w - 1 - k] = (ints >> (8 * k)) & 0xFF
        return ColumnData(values=be, validity=validity)
    # fixed-width numerics incl. date/time/timestamp
    dense = arr.drop_null()
    np_arr = np.asarray(dense.cast(_storage_type(t)))
    return ColumnData(values=np_arr, validity=validity)


def _storage_type(t):
    import pyarrow as pa

    if pa.types.is_date32(t):
        return pa.int32()
    if pa.types.is_timestamp(t) or pa.types.is_time64(t):
        return pa.int64()
    if pa.types.is_time32(t):
        return pa.int32()
    return t


def _iota_segments(lengths: np.ndarray) -> np.ndarray:
    total = int(lengths.sum())
    if total == 0:
        return np.empty(0, np.int64)
    seg_starts = np.zeros(len(lengths), np.int64)
    np.cumsum(lengths[:-1], out=seg_starts[1:])
    return np.arange(total, dtype=np.int64) - np.repeat(seg_starts, lengths)
