"""Native host shim loader: compiles native.cpp → _native.so on first use.

Reference parity: stands in for the reference's amd64 assembly + unsafe Go
host kernels (SURVEY.md §2.3).  Pure C ABI over ctypes (no pybind11 in this
image).  Falls back silently to the numpy oracles when a compiler is missing
— the exact ``purego`` build-tag pattern of the reference.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import math

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native.cpp")
_SO = os.path.join(_HERE, "_native.so")
from ..utils.locks import make_lock

_lock = make_lock("native.build")
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_u8p_w = np.ctypeslib.ndpointer(np.uint8, flags=("C_CONTIGUOUS", "WRITEABLE"))
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p_w = np.ctypeslib.ndpointer(np.int64, flags=("C_CONTIGUOUS", "WRITEABLE"))
_i32p_w = np.ctypeslib.ndpointer(np.int32, flags=("C_CONTIGUOUS", "WRITEABLE"))


def _build() -> bool:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
             "-pthread", _SRC, "-o", _SO + ".tmp"],
            check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except Exception:
        return False


def _auto_threads() -> int:
    """Default native thread split: all cores, capped at 8 — but 1 inside a
    shared-pool worker (the pool already owns the cores; pool width x native
    threads would oversubscribe).  One rule for every threaded native entry
    point so the guard can't drift per call site."""
    from ..utils.pool import available_cpus, in_shared_pool

    return 1 if in_shared_pool() else min(available_cpus(), 8)


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        from ..utils.env import env_bool

        if env_bool("PARQUET_TPU_NO_NATIVE"):
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.pq_plain_byte_array.restype = ctypes.c_int64
        lib.pq_plain_byte_array.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, _i64p, ctypes.c_void_p]
        lib.pq_assemble_levels.restype = ctypes.c_int64
        lib.pq_assemble_levels.argtypes = [
            _i32p, _i32p, ctypes.c_int64, _i32p, _i32p, ctypes.c_int32,
            ctypes.c_int32, _i64p_w, _u8p_w, _i64p_w, _u8p_w]
        lib.pq_expand_runs.restype = ctypes.c_int64
        lib.pq_expand_runs.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _i64p, ctypes.c_void_p, _i64p,
            _i64p, np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags=("C_CONTIGUOUS", "WRITEABLE")),
            ctypes.c_int64]
        lib.pq_assemble_list_runs.restype = ctypes.c_int64
        lib.pq_assemble_list_runs.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _i64p, ctypes.c_void_p, _i64p,
            _i64p, _i32p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, _i64p, ctypes.c_void_p, _i64p,
            _i64p, _i32p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            _i64p_w, _u8p_w, _u8p_w, _i64p_w]
        lib.pq_delta_prescan.restype = ctypes.c_int64
        lib.pq_delta_prescan.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, _i64p_w, _i64p_w,
            np.ctypeslib.ndpointer(np.int32, flags=("C_CONTIGUOUS", "WRITEABLE")),
            _i64p_w, ctypes.c_int64]
        lib.pq_gather_ba.restype = ctypes.c_int64
        lib.pq_gather_ba.argtypes = [
            ctypes.c_void_p, _i64p, ctypes.c_int64, _i64p, ctypes.c_int64,
            _i64p_w, ctypes.c_void_p]
        lib.pq_encode_plain_ba.restype = ctypes.c_int64
        lib.pq_encode_plain_ba.argtypes = [ctypes.c_void_p, _i64p,
                                           ctypes.c_int64, ctypes.c_int64,
                                           _u8p_w]
        lib.pq_encode_delta.restype = ctypes.c_int64
        lib.pq_encode_delta.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int32,
                                        ctypes.c_int32, _u8p_w, ctypes.c_int64]
        lib.pq_encode_rle.restype = ctypes.c_int64
        lib.pq_encode_rle.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int32,
                                      ctypes.c_int32, _u8p_w, ctypes.c_int64]
        lib.pq_pack_bits.restype = ctypes.c_int64
        lib.pq_pack_bits.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int32,
                                     _u8p_w]
        lib.pq_dict_build_i64.restype = ctypes.c_int64
        lib.pq_dict_build_i64.argtypes = [_i64p, ctypes.c_int64,
                                          ctypes.c_int64, _i64p_w, _i64p_w]
        lib.pq_scan_rle_runs.restype = ctypes.c_int64
        lib.pq_scan_rle_runs.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            _u8p_w, _i64p, _i64p, _i64p]
        lib.pq_expand_gather.restype = ctypes.c_int64
        lib.pq_expand_gather.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _i64p, ctypes.c_void_p, _i64p,
            _i64p, _i32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32]
        lib.pq_delta_decode.restype = ctypes.c_int64
        lib.pq_delta_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _i64p, _i32p, _i64p, _i64p,
            _i64p, _i64p, _i64p, _i64p, ctypes.c_int64, _i64p_w,
            ctypes.c_int32]
        lib.pq_scan_page_headers.restype = ctypes.c_int64
        lib.pq_scan_page_headers.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            _i64p_w]
        lib.pq_scan_page_headers_partial.restype = ctypes.c_int64
        lib.pq_scan_page_headers_partial.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            _i64p_w, _i64p_w]
        lib.pq_count_target_in_runs.restype = ctypes.c_int64
        lib.pq_count_target_in_runs.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, _i64p, _i64p,
            _i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64]
        lib.pq_dict_chunk_scan.restype = ctypes.c_int64
        lib.pq_dict_chunk_scan.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, _i64p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            _u8p_w, ctypes.c_int64,
            _i64p_w, _u8p_w, _i64p_w, _i64p_w, _i32p_w, ctypes.c_int64,
            _i64p_w, ctypes.c_int32]
        lib.pq_decompress_pages.restype = ctypes.c_int64
        lib.pq_decompress_pages.argtypes = [
            _i64p, _i64p, ctypes.c_int64, ctypes.c_int32, _u8p_w, _i64p,
            ctypes.c_int32]
        lib.pq_plain_ba_batch.restype = ctypes.c_int64
        lib.pq_plain_ba_batch.argtypes = [
            _i64p, _i64p, _i64p, ctypes.c_int64, _i64p_w, _u8p_w]
        lib.pq_rle_dict_batch.restype = ctypes.c_int64
        lib.pq_rle_dict_batch.argtypes = [
            _i64p, _i64p, _i64p, _u8p, ctypes.c_int64, _i32p_w]
        lib.pq_xxh64.restype = ctypes.c_uint64
        lib.pq_xxh64.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
        lib.pq_xxh64_batch.restype = None
        lib.pq_xxh64_batch.argtypes = [ctypes.c_void_p, _i64p, ctypes.c_int64, _u64p]
        lib.pq_delta_byte_array_expand.restype = ctypes.c_int64
        lib.pq_delta_byte_array_expand.argtypes = [
            _i64p, ctypes.c_void_p, _i64p, ctypes.c_int64, _u8p_w, _i64p]
        lib.pq_dict_build_ba.restype = ctypes.c_int64
        lib.pq_dict_build_ba.argtypes = [
            ctypes.c_void_p, _i64p, ctypes.c_int64, _i64p, ctypes.c_int64]
        lib.pq_minmax_ba.restype = None
        lib.pq_minmax_ba.argtypes = [ctypes.c_void_p, _i64p, ctypes.c_int64,
                                     ctypes.c_int64, _i64p, _i64p]
        lib.pq_dict_first_occurrence.restype = None
        lib.pq_dict_first_occurrence.argtypes = [_i64p, ctypes.c_int64,
                                                 ctypes.c_int64, _i64p]
        _lib = lib
        return _lib


# ---------------------------------------------------------------------------
# numpy-friendly wrappers (None return → caller falls back to the oracle)
# ---------------------------------------------------------------------------


def plain_byte_array(buf: np.ndarray, n: int):
    lib = get_lib()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf)
    offsets = np.empty(n + 1, dtype=np.int64)
    total = lib.pq_plain_byte_array(buf.ctypes.data, len(buf), n, offsets, None)
    if total < 0:
        raise ValueError("PLAIN BYTE_ARRAY truncated")
    values = np.empty(max(total, 1), dtype=np.uint8)
    lib.pq_plain_byte_array(buf.ctypes.data, len(buf), n, offsets,
                            values.ctypes.data)
    return values[:total], offsets.astype(np.int32)


def plain_ba_batch(srcs, counts):
    """Parse many pages' PLAIN BYTE_ARRAY sections in one native call,
    producing the CHUNK-level (values, int64 offsets) directly (offsets
    rebased across pages — no python merge).  ``srcs`` are bytes-like page
    value sections, ``counts`` the value count per page.  None when the
    shim is unavailable; raises ValueError on truncation."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(srcs)
    ptrs, lens, keep = _src_pointers(srcs)
    total_src = int(lens[:n].sum()) if n else 0
    cnts = np.ascontiguousarray(counts, np.int64)
    if bool((cnts < 0).any()):
        return None
    n_vals = int(cnts.sum())
    offsets = np.empty(n_vals + 1, np.int64)
    values = np.empty(max(total_src, 1), np.uint8)
    total = lib.pq_plain_ba_batch(ptrs, lens, cnts, n, offsets, values)
    if total < 0:
        raise ValueError(
            f"PLAIN BYTE_ARRAY truncated in page {-int(total) - 1}")
    if total * 2 < len(values):
        # short-string chunks: the worst-case buffer (raw section size,
        # i.e. value bytes + 4 per string) would pin 2-5x the data for the
        # column's lifetime — compact when the slack is half or more
        return values[:total].copy(), offsets
    return values[:total], offsets


def rle_dict_batch(srcs, counts, prefixes):
    """Decode many pages' RLE_DICTIONARY index sections in one native call
    → one chunk-level int32 index array.  ``srcs`` are bytes-like page
    payloads (post-decompression), ``counts`` values per page,
    ``prefixes`` per-page bools: True = a v1 optional page whose payload
    leads with a length-prefixed def-level stream (must be one all-1s RLE
    run — all-present; otherwise the caller's python path handles nulls).
    None when the shim is unavailable OR any page needs the fallback."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(srcs)
    ptrs, lens, keep = _src_pointers(srcs)
    cnts = np.ascontiguousarray(counts, np.int64)
    if bool((cnts < 0).any()):
        return None
    pref = np.ascontiguousarray(prefixes, np.uint8)
    out = np.empty(max(int(cnts.sum()), 1), np.int32)
    total = lib.pq_rle_dict_batch(ptrs, lens, cnts, pref, n, out)
    if total < 0:
        return None  # page with nulls / unexpected framing: python path
    return out[:total]


def assemble_levels(defs: np.ndarray, reps: np.ndarray, ks, dks, max_def: int):
    """Dremel assembly: returns (list_offsets, list_validity, leaf_validity)
    per repeated level, or None when the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(defs)
    nlev = len(ks)
    defs = np.ascontiguousarray(defs, np.int32)
    reps = np.ascontiguousarray(reps, np.int32)
    offsets_flat = np.empty(nlev * (n + 1), np.int64)
    valid_flat = np.empty(max(nlev * n, 1), np.uint8)
    inst_counts = np.empty(nlev, np.int64)
    leaf_valid = np.empty(max(n, 1), np.uint8)
    leaf_count = lib.pq_assemble_levels(
        defs, reps, n, np.ascontiguousarray(ks, np.int32),
        np.ascontiguousarray(dks, np.int32), nlev, max_def,
        offsets_flat, valid_flat, inst_counts, leaf_valid)
    offsets, validity = [], []
    for i in range(nlev):
        c = int(inst_counts[i])
        # copies, not views: a view would pin the whole nlev*n scratch buffer
        # for the lifetime of the decoded Column
        offsets.append(offsets_flat[i * (n + 1) : i * (n + 1) + c + 1].copy())
        validity.append(valid_flat[i * n : i * n + c].astype(bool))
    return offsets, validity, leaf_valid[:leaf_count].astype(bool)


def assemble_list_runs(buf: np.ndarray, def_tables: tuple, rep_tables: tuple,
                       n: int, dk: int, max_def: int):
    """Fused single-level list assembly from level run tables: returns
    (list_offsets, list_validity, leaf_validity) without materializing
    per-slot def/rep levels, or None when the native lib is unavailable.

    ``def_tables``/``rep_tables`` are (ends, kinds, payloads, bit_offsets,
    widths) over the shared level byte stream ``buf``.
    """
    lib = get_lib()
    if lib is None or n == 0:
        return None
    buf = np.ascontiguousarray(buf)
    # keep every coerced table alive by name for the duration of the C call
    de, dkk, dp, db, dw = (np.ascontiguousarray(a, t) for a, t in
                           zip(def_tables, (np.int64, np.uint8, np.int64,
                                            np.int64, np.int32)))
    re_, rk, rp, rb, rw = (np.ascontiguousarray(a, t) for a, t in
                           zip(rep_tables, (np.int64, np.uint8, np.int64,
                                            np.int64, np.int32)))
    offsets = np.empty(n + 1, np.int64)
    lvalid = np.empty(max(n, 1), np.uint8)
    leaf_valid = np.empty(max(n, 1), np.uint8)
    counts = np.empty(2, np.int64)
    rc = lib.pq_assemble_list_runs(
        buf.ctypes.data if len(buf) else None, len(buf),
        de, dkk.ctypes.data, dp, db, dw, len(de),
        buf.ctypes.data if len(buf) else None, len(buf),
        re_, rk.ctypes.data, rp, rb, rw, len(re_),
        n, dk, max_def, offsets, lvalid, leaf_valid, counts)
    if rc != 0:
        return None
    ninst, nelem = int(counts[0]), int(counts[1])
    return (offsets[: ninst + 1].copy(), lvalid[:ninst].astype(bool),
            leaf_valid[:nelem].astype(bool))


def delta_prescan(data: np.ndarray, pos: int = 0):
    """Miniblock table of one DELTA_BINARY_PACKED stream, or None when the
    lib is unavailable / the stream is malformed (caller uses the Python
    scanner, which raises precise errors)."""
    lib = get_lib()
    if lib is None:
        return None
    data = np.ascontiguousarray(data)
    header = np.empty(4, np.int64)
    # exact miniblock bound from the stream header (4 uvarints, cheap):
    # w=0 miniblocks occupy no payload, so a data-length bound would be wrong
    from ..ops import ref as _ref

    try:
        bs, p = _ref.read_uvarint(data, pos)
        nmb, p = _ref.read_uvarint(data, p)
        total, _ = _ref.read_uvarint(data, p)
    except Exception:
        return None
    if nmb == 0 or bs == 0 or bs % nmb:
        return None
    vpm = bs // nmb
    if vpm == 0:
        return None
    # each miniblock consumes one width byte from the stream, so the count
    # can never exceed the remaining bytes — bounds np.empty against absurd
    # untrusted `total` values (header bytes are attacker-controlled)
    cap = min(total // vpm + nmb + 2, len(data) - pos + 2)
    offsets = np.empty(cap, np.int64)
    widths = np.empty(cap, np.int32)
    mins = np.empty(cap, np.int64)
    k = lib.pq_delta_prescan(data.ctypes.data if len(data) else None,
                             len(data), pos, header, offsets, widths, mins,
                             cap)
    if k < 0:
        return None
    return (int(header[0]), int(header[1]), int(header[2]),
            offsets[:k].copy(), widths[:k].copy(), mins[:k].copy(),
            int(header[3]))


def gather_ba(dvals: np.ndarray, doffs: np.ndarray, indices: np.ndarray):
    """Dictionary gather for BYTE_ARRAY: (values, int64 offsets), or None."""
    lib = get_lib()
    if lib is None:
        return None
    dvals = np.ascontiguousarray(dvals)
    doffs = np.ascontiguousarray(doffs, np.int64)
    indices = np.ascontiguousarray(indices, np.int64)
    n = len(indices)
    out_offs = np.empty(n + 1, np.int64)
    total = lib.pq_gather_ba(dvals.ctypes.data if len(dvals) else None, doffs,
                             len(doffs) - 1, indices, n, out_offs, None)
    if total < 0:
        # detected corruption, NOT unavailability: an out-of-range dictionary
        # index must never fall back to numpy (whose fancy indexing would
        # silently wrap negatives)
        raise ValueError("dictionary index out of range")
    out_vals = np.empty(max(total, 1), np.uint8)
    lib.pq_gather_ba(dvals.ctypes.data if len(dvals) else None, doffs,
                     len(doffs) - 1, indices, n, out_offs,
                     out_vals.ctypes.data)
    return out_vals[:total], out_offs


def encode_plain_ba(vals: np.ndarray, offs: np.ndarray) -> Optional[bytes]:
    """PLAIN BYTE_ARRAY stream ([4B LE length][bytes]...), or None."""
    lib = get_lib()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals)
    offs = np.ascontiguousarray(offs, np.int64)
    n = len(offs) - 1
    out = np.empty(max(int(offs[-1]), 0) + 4 * max(n, 0) + 1, np.uint8)
    wrote = lib.pq_encode_plain_ba(vals.ctypes.data if len(vals) else None,
                                   offs, n, len(vals), out)
    if wrote < 0:
        # detected corruption (non-monotonic / out-of-range offsets), NOT
        # unavailability — never hand these to the numpy fallback
        raise ValueError("malformed BYTE_ARRAY offsets")
    return out[:wrote].tobytes()


def encode_delta(values: np.ndarray, block_size: int = 128,
                 n_miniblocks: int = 4) -> Optional[bytes]:
    """DELTA_BINARY_PACKED stream, byte-identical to the Python oracle, or
    None when the lib is unavailable / the layout is unsupported."""
    lib = get_lib()
    if lib is None or len(values) == 0:
        return None
    values = np.ascontiguousarray(values, np.int64)
    n = len(values)
    # worst case: every delta at 64 bits + headers per block
    nblocks = (n + block_size - 1) // block_size + 1
    cap = 64 + n * 8 + nblocks * (16 + n_miniblocks) + block_size * 8
    out = np.empty(cap, np.uint8)
    wrote = lib.pq_encode_delta(values, n, block_size, n_miniblocks, out, cap)
    if wrote < 0:
        return None
    return out[:wrote].tobytes()


def encode_rle(values: np.ndarray, bit_width: int,
               min_repeat: int = 8) -> Optional[bytes]:
    """Hybrid RLE/bit-packed stream, byte-identical to ref.encode_rle, or
    None when unavailable / the width is unsupported."""
    lib = get_lib()
    if lib is None or bit_width > 56 or len(values) == 0:
        return None
    values = np.ascontiguousarray(values, np.int64)
    n = len(values)
    vbytes = (bit_width + 7) // 8
    cap = 64 + (n + 8) * bit_width // 8 + (n // 8 + 2) * (10 + vbytes)
    out = np.empty(cap, np.uint8)
    wrote = lib.pq_encode_rle(values, n, bit_width, min_repeat, out, cap)
    if wrote < 0:
        return None
    return out[:wrote].tobytes()


def pack_bits(values: np.ndarray, bit_width: int) -> Optional[bytes]:
    """LSB-first bit packing (write path), or None when unavailable/wide."""
    lib = get_lib()
    if lib is None or bit_width > 56:
        return None
    values = np.ascontiguousarray(values, np.int64)
    out = np.empty((len(values) * bit_width + 7) // 8 + 8, np.uint8)
    wrote = lib.pq_pack_bits(values, len(values), bit_width, out)
    if wrote < 0:
        return None
    return out[:wrote].tobytes()


def _window_predicts_overflow(distinct: int, window: int,
                              max_unique: int) -> bool:
    """Cardinality-estimator bail test: from one window's distinct count,
    estimate global cardinality K via E[distinct] = K(1 - exp(-w/K))
    (uniform-draw model) and predict overflow only when the estimate
    clearly exceeds ``max_unique``.  The previous raw >= 7/8-unique test
    falsely predicted overflow for columns whose cardinality is high in a
    32k window yet still under max_unique (e.g. ~45%-of-n cardinality
    against a n/2 budget) and silently disabled dictionary encoding
    (advisor r4).  Skewed data biases K low, i.e. toward attempting the
    build — the safe direction (a wasted build, never a wrong refusal)."""
    if distinct >= window:  # all-unique window: the estimator diverges
        return True
    frac = distinct / window
    if frac <= 0:
        return False
    lo_x, hi_x = 1e-9, 60.0  # solve (1 - e^-x)/x = frac for x = w/K
    for _ in range(40):
        mid = (lo_x + hi_x) / 2
        if (1 - math.exp(-mid)) / mid > frac:
            lo_x = mid
        else:
            hi_x = mid
    est_k = window / ((lo_x + hi_x) / 2)
    return est_k > 1.25 * max_unique


def dict_build_fixed(vals: np.ndarray, max_unique: int):
    """First-occurrence dedup of a fixed-width column (any 4/8-byte dtype,
    compared bitwise).  Returns (uniques in vals.dtype, int64 indices),
    "overflow" past max_unique, or None when the lib is unavailable."""
    lib = get_lib()
    if lib is None or len(vals) == 0:
        return None
    orig = vals.dtype
    if vals.dtype.itemsize == 8:
        keys = np.ascontiguousarray(vals).view(np.int64)
    elif vals.dtype.itemsize == 4:
        # widen via the 32-bit bit pattern so float32 NaNs stay bit-exact
        keys = np.ascontiguousarray(vals).view(np.int32).astype(np.int64)
    else:
        return None
    keys = np.ascontiguousarray(keys)
    n = len(keys)
    # Sample-based early bail: near-unique columns (the overflow case)
    # otherwise pay a full hash pass just to discover they can't dictionary-
    # encode.  Two windows — prefix AND middle — must BOTH estimate a
    # cardinality clearly past max_unique (see _window_predicts_overflow):
    # data whose first occurrences cluster early (sorted keys, then
    # repeats) shows repeats in the middle window and still gets its full
    # build.  Heuristic only affects whether dictionary encoding is
    # attempted, never correctness.
    sample = 1 << 14
    if n > 4 * sample and max_unique >= sample:
        s_idx = np.empty(sample, np.int64)
        s_uniq = np.empty(sample, np.int64)
        nu_a = lib.pq_dict_build_i64(keys[:sample], sample, sample,
                                     s_idx, s_uniq)
        if _window_predicts_overflow(nu_a, sample, max_unique):
            mid = n // 2
            nu_b = lib.pq_dict_build_i64(keys[mid: mid + sample], sample,
                                         sample, s_idx, s_uniq)
            if _window_predicts_overflow(nu_b, sample, max_unique):
                return "overflow"
    indices = np.empty(n, np.int64)
    uniques = np.empty(max(max_unique, 1), np.int64)
    nu = lib.pq_dict_build_i64(keys, n, max_unique, indices, uniques)
    if nu < 0:
        return "overflow"
    uniq = uniques[:nu]
    if vals.dtype.itemsize == 4:
        uniq = uniq.astype(np.int32).view(orig)
    else:
        uniq = uniq.view(orig)
    return uniq.copy(), indices


def expand_runs(buf: np.ndarray, ends: np.ndarray, kinds: np.ndarray,
                payloads: np.ndarray, bit_offsets: np.ndarray,
                widths: np.ndarray, n: int):
    """Expand a merged RLE/bit-packed run table to int32 values (host)."""
    lib = get_lib()
    if lib is None or n == 0:
        return None
    buf = np.ascontiguousarray(buf)
    kinds = np.ascontiguousarray(kinds, dtype=np.uint8)
    out = np.empty(n, dtype=np.int32)
    wrote = lib.pq_expand_runs(
        buf.ctypes.data if len(buf) else None, len(buf),
        np.ascontiguousarray(ends, np.int64), kinds.ctypes.data,
        np.ascontiguousarray(payloads, np.int64),
        np.ascontiguousarray(bit_offsets, np.int64),
        np.ascontiguousarray(widths, np.int32), len(kinds), out, n)
    return out[:wrote]


def select_runs(buf: np.ndarray, kinds, counts, payloads, offsets,
                bit_width: int, take: np.ndarray):
    """Point-select from an RLE/bit-packed run table (the masked-emit hot
    loop, io/fused.py): expand ONLY the runs the sorted ``take`` ordinals
    touch — one native expand pass over the touched subset — then gather.
    Beats per-value bit gathers when takes cluster densely inside runs.
    Returns int64 values, or None when the lib is unavailable / the width is
    out of the int32 expansion range (caller uses the bit-gather oracle)."""
    lib = get_lib()
    if lib is None or bit_width > 31 or len(take) == 0:
        return None
    counts = np.asarray(counts, np.int64)
    take = np.asarray(take, np.int64)
    ends = np.cumsum(counts)
    run = np.searchsorted(ends, take, side="right")
    starts = ends - counts
    touched = np.unique(run)
    t_counts = counts[touched]
    sub_ends = np.cumsum(t_counts)
    total = int(sub_ends[-1])
    expanded = expand_runs(
        buf, sub_ends, np.asarray(kinds, np.uint8)[touched],
        np.asarray(payloads, np.int64)[touched],
        np.asarray(offsets, np.int64)[touched] * 8,
        np.full(len(touched), bit_width, np.int32), total)
    if expanded is None:
        return None
    sub_base = sub_ends - t_counts
    rank = np.searchsorted(touched, run)
    return expanded[sub_base[rank] + (take - starts[run])].astype(np.int64)


def delta_decode(buf: np.ndarray, mb_bitoffs, mb_widths, mb_mins,
                 page_mb_start, page_first, page_count, page_vpm,
                 nthreads: int = 0):
    """Fused DELTA_BINARY_PACKED decode from prescan miniblock tables:
    unpack + min-add + prefix sum in one multithreaded native pass (pages
    are independent).  Returns int64 values or None when the native library
    is unavailable; raises ValueError on malformed tables."""
    lib = get_lib()
    if lib is None:
        return None
    counts = np.ascontiguousarray(page_count, np.int64)
    out_start = np.zeros(len(counts) + 1, np.int64)
    np.cumsum(counts, out=out_start[1:])
    out = np.empty(int(out_start[-1]), np.int64)
    buf = np.ascontiguousarray(buf)
    if not nthreads:
        nthreads = _auto_threads()
    rc = lib.pq_delta_decode(
        buf.ctypes.data if len(buf) else None, len(buf),
        np.ascontiguousarray(mb_bitoffs, np.int64),
        np.ascontiguousarray(mb_widths, np.int32),
        np.ascontiguousarray(mb_mins, np.int64),
        np.ascontiguousarray(page_mb_start, np.int64),
        np.ascontiguousarray(page_first, np.int64),
        counts, out_start,
        np.ascontiguousarray(page_vpm, np.int64),
        len(counts), out, nthreads)
    if rc != 0:
        raise ValueError("malformed DELTA_BINARY_PACKED miniblock tables")
    return out


def expand_gather(buf: np.ndarray, tables: tuple, n: int,
                  dictionary: np.ndarray, nthreads: int = 0):
    """Fused RLE/bit-packed index expand + dictionary gather: run tables →
    gathered values in one multithreaded native pass (no index stream).
    ``tables`` = (ends, kinds, payloads, bit_offsets, widths) in the int64
    host domain.  Returns the gathered array or None (unavailable shape →
    caller uses expand + numpy gather)."""
    lib = get_lib()
    if lib is None or n == 0:
        return None
    elem = dictionary.dtype.itemsize
    if elem not in (4, 8) or dictionary.ndim != 1:
        return None
    ends, kinds, payloads, offs, widths32 = tables
    buf = np.ascontiguousarray(buf)
    dvals = np.ascontiguousarray(dictionary)
    out = np.empty(n, dtype=dictionary.dtype)
    if not nthreads:
        nthreads = _auto_threads()
    rc = lib.pq_expand_gather(
        buf.ctypes.data if len(buf) else None, len(buf),
        np.ascontiguousarray(ends, np.int64),
        np.ascontiguousarray(kinds, np.uint8).ctypes.data,
        np.ascontiguousarray(payloads, np.int64),
        np.ascontiguousarray(offs, np.int64),
        np.ascontiguousarray(widths32, np.int32), len(ends), n,
        dvals.ctypes.data, len(dvals), elem,
        out.ctypes.data, nthreads)
    if rc != 0:
        raise ValueError("malformed dictionary run stream "
                         "(index out of range or bad width)")
    return out


# column indexes of a pq_scan_page_headers row — keep in sync with the
# PG_* enum in native.cpp
PG_HEADER_POS = 0
PG_DATA_POS = 1
PG_TYPE = 2
PG_COMP = 3
PG_UNCOMP = 4
PG_CRC = 5
PG_NVALS = 6
PG_ENC = 7
PG_DEF_ENC = 8
PG_REP_ENC = 9
PG_RL_BYTES = 10
PG_DL_BYTES = 11
PG_NNULLS = 12
PG_IS_COMPRESSED = 13
PG_DICT_NVALS = 14
PG_NROWS = 15
PG_NFIELDS = 16


def scan_page_headers(buf, total_values: int):
    """Batch-parse a chunk's PageHeader stream.  Returns an (npages,
    PG_NFIELDS) int64 array, or None when the native library is unavailable
    or the stream has a construct the fast scanner doesn't handle (caller
    falls back to the Python thrift walk, which owns error reporting)."""
    lib = get_lib()
    if lib is None:
        return None
    b = buf if isinstance(buf, np.ndarray) else np.frombuffer(buf, np.uint8)
    b = np.ascontiguousarray(b)
    # worst realistic case is ~one value per page; grow geometrically from a
    # generous page-size estimate instead of allocating total_values rows
    cap = max(16, min(int(total_values), len(b) // 64 + 8))
    while True:
        out = np.empty((cap, PG_NFIELDS), dtype=np.int64)
        k = lib.pq_scan_page_headers(b.ctypes.data if len(b) else None,
                                     len(b), total_values, cap, out)
        if k == -2:
            if cap > int(total_values) + 8:
                return None  # more pages than values: malformed; let Python raise
            cap *= 4
            continue
        if k < 0:
            return None
        return out[:k]


def scan_page_headers_partial(buf, total_values: int):
    """Windowed header scan: parse as many complete pages as the buffer
    holds.  Returns (rows, consumed_bytes, values_seen) — rows may be empty
    when not even one header+payload fits — or None without the lib."""
    lib = get_lib()
    if lib is None:
        return None
    b = buf if isinstance(buf, np.ndarray) else np.frombuffer(buf, np.uint8)
    b = np.ascontiguousarray(b)
    cap = max(16, min(int(total_values), len(b) // 64 + 8))
    consumed = np.zeros(2, np.int64)
    while True:
        out = np.empty((cap, PG_NFIELDS), dtype=np.int64)
        k = lib.pq_scan_page_headers_partial(
            b.ctypes.data if len(b) else None, len(b), total_values, cap,
            out, consumed)
        if k == cap:  # may have stopped only for capacity: grow and retry
            cap *= 4
            continue
        if k < 0:
            return None
        return out[:k], int(consumed[0]), int(consumed[1])


def count_target_in_runs(body: np.ndarray, kinds, cnts, payloads, offs,
                         width: int, target: int):
    """Count run-table values equal to ``target`` (def == max_def present
    count) in one native pass, or None without the lib."""
    lib = get_lib()
    if lib is None or width <= 0 or width > 32:
        return None
    body = np.ascontiguousarray(body)
    kinds = np.ascontiguousarray(kinds, np.uint8)
    n = lib.pq_count_target_in_runs(
        body.ctypes.data if len(body) else None, len(body),
        kinds.ctypes.data, np.ascontiguousarray(cnts, np.int64),
        np.ascontiguousarray(payloads, np.int64),
        np.ascontiguousarray(offs, np.int64), len(kinds), width, target)
    return None if n < 0 else int(n)


def dict_chunk_scan(buf, pages_rows: np.ndarray, codec_id: int,
                    max_def: int, max_rep: int):
    """Fused whole-chunk dictionary-index scan: decompress every data page
    (UNCOMPRESSED/SNAPPY/ZSTD), verify all-present def levels, and scan the
    index runs into one combined chunk-level run table in a single native
    call (the per-page Python loop was ~60% of build_plan's host time at
    64 MB / 400 pages).

    Returns ``(ends, kinds, payloads, bit_offsets, widths, nvals, body)``
    with offsets indexing ``body`` (the concatenated decompressed pages), or
    None when the chunk needs the general Python planner (nulls, rep levels,
    non-dict pages, foreign codec, no native lib)."""
    lib = get_lib()
    if lib is None:
        return None
    b = buf if isinstance(buf, np.ndarray) else np.frombuffer(buf, np.uint8)
    b = np.ascontiguousarray(b)
    rows = np.ascontiguousarray(pages_rows, np.int64)
    n_pages = len(rows)
    data = rows[(rows[:, PG_TYPE] == 0) | (rows[:, PG_TYPE] == 3)]
    if not len(data):
        return None
    out_cap = int(data[:, PG_UNCOMP].sum()) + 8
    nvals_cap = int(data[:, PG_NVALS].sum())
    run_cap = nvals_cap + n_pages + 8
    out_bytes = np.empty(out_cap, np.uint8)
    ends = np.empty(run_cap, np.int64)
    kinds = np.empty(run_cap, np.uint8)
    payloads = np.empty(run_cap, np.int64)
    boffs = np.empty(run_cap, np.int64)
    widths = np.empty(run_cap, np.int32)
    info = np.zeros(2, np.int64)
    k = lib.pq_dict_chunk_scan(
        b.ctypes.data if len(b) else None, len(b), rows.reshape(-1),
        n_pages, codec_id, max_def, max_rep,
        out_bytes, out_cap, ends, kinds, payloads, boffs, widths, run_cap,
        info, _auto_threads())
    if k < 0:
        return None
    return (ends[:k], kinds[:k], payloads[:k], boffs[:k] * 8, widths[:k],
            int(info[0]), out_bytes[: info[1]])


def scan_rle_runs(buf: np.ndarray, n: int, bit_width: int):
    lib = get_lib()
    if lib is None or n == 0:
        return None
    buf = np.ascontiguousarray(buf)
    cap = n + 1
    kinds = np.empty(cap, dtype=np.uint8)
    counts = np.empty(cap, dtype=np.int64)
    payloads = np.empty(cap, dtype=np.int64)
    offsets = np.empty(cap, dtype=np.int64)
    k = lib.pq_scan_rle_runs(buf.ctypes.data, len(buf), n, bit_width,
                             kinds, counts, payloads, offsets)
    if k < 0:
        raise ValueError("malformed RLE hybrid stream")
    return kinds[:k], counts[:k], payloads[:k], offsets[:k]


def xxh64(data, seed: int = 0):
    lib = get_lib()
    if lib is None:
        return None
    b = np.frombuffer(data, np.uint8) if not isinstance(data, np.ndarray) else data
    b = np.ascontiguousarray(b)
    return int(lib.pq_xxh64(b.ctypes.data if len(b) else None, len(b), seed))


def xxh64_batch(data: np.ndarray, offsets: np.ndarray):
    lib = get_lib()
    if lib is None:
        return None
    data = np.ascontiguousarray(data)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets) - 1
    out = np.empty(n, dtype=np.uint64)
    lib.pq_xxh64_batch(data.ctypes.data if len(data) else None, offsets, n, out)
    return out


def delta_byte_array_expand(prefix_lens, suffix_data, suffix_offsets, out_offsets):
    lib = get_lib()
    if lib is None:
        return None
    n = len(prefix_lens)
    prefix_lens = np.ascontiguousarray(prefix_lens, dtype=np.int64)
    suffix_data = np.ascontiguousarray(suffix_data)
    suffix_offsets = np.ascontiguousarray(suffix_offsets, dtype=np.int64)
    out_offsets = np.ascontiguousarray(out_offsets, dtype=np.int64)
    total = int(out_offsets[-1]) if n else 0
    out = np.empty(max(total, 1), dtype=np.uint8)
    lib.pq_delta_byte_array_expand(prefix_lens,
                                   suffix_data.ctypes.data if len(suffix_data) else None,
                                   suffix_offsets, n, out, out_offsets)
    return out[:total]


def _src_pointers(srcs):
    """Marshal bytes-like page payloads into (ptrs, lens, keep) for native
    calls that read per-page raw pointers.  ``keep`` must stay referenced
    for the duration of the call."""
    n = len(srcs)
    ptrs = np.empty(max(n, 1), np.int64)
    lens = np.empty(max(n, 1), np.int64)
    keep = []
    for i, s in enumerate(srcs):
        a = s if isinstance(s, np.ndarray) else np.frombuffer(s, np.uint8)
        if not a.flags.c_contiguous:
            a = np.ascontiguousarray(a)
        keep.append(a)
        ptrs[i] = a.ctypes.data if len(a) else 0
        lens[i] = len(a)
    return ptrs, lens, keep


def decompress_pages(srcs, out_sizes, codec_id: int, nthreads: int = 1):
    """Decompress many page payloads in ONE native call (snappy/zstd via
    the dlopen'd system libs; 0 = memcpy).  ``srcs`` is a sequence of
    bytes-like payloads (any layout — pointers are taken per page),
    ``out_sizes`` their expected uncompressed sizes.  Returns
    ``(buffer, offsets)`` with page i at ``buffer[offsets[i]:offsets[i+1]]``,
    or None when the shim/codec is unavailable or any page fails (callers
    fall back to the per-page codec path, which raises the precise error)."""
    lib = get_lib()
    if lib is None or codec_id not in (0, 1, 6):
        return None
    n = len(srcs)
    if n == 0:
        return np.empty(0, np.uint8), np.zeros(1, np.int64)
    # header-supplied sizes are UNTRUSTED: a negative size (e.g. v2's
    # uncompressed - levels underflowing on a crafted header) would make
    # the native call write before/past the output buffer
    sizes_arr = np.asarray(out_sizes, np.int64)
    if len(sizes_arr) != n or bool((sizes_arr < 0).any()):
        return None
    ptrs, lens, keep = _src_pointers(srcs)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(sizes_arr, out=offs[1:])
    out = np.empty(max(int(offs[-1]), 1), np.uint8)
    rc = lib.pq_decompress_pages(ptrs, lens, n, codec_id, out, offs,
                                 max(int(nthreads), 1))
    if rc != 0:
        return None
    return out, offs


def dict_build_ba(data: np.ndarray, offsets: np.ndarray, max_unique: int,
                  sample_bail: bool = True):
    """Returns (indices, first_occurrence_rows), "overflow", or None.

    ``sample_bail=False`` disables the near-unique early bail — required
    when the input is a CONCATENATION of internally-unique sets (e.g.
    unifying per-row-group dictionaries): every sample window then lies
    inside one unique set and predicts overflow even though cross-set
    duplicates abound."""
    lib = get_lib()
    if lib is None:
        return None
    data = np.ascontiguousarray(data)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets) - 1
    indices = np.empty(max(n, 1), dtype=np.int64)
    # Sample-based early bail, mirroring dict_build_fixed: near-unique
    # string columns should not pay a half-column hash build just to learn
    # they overflow.  Both a prefix and a middle window must ESTIMATE a
    # cardinality clearly past max_unique (_window_predicts_overflow;
    # first occurrences clustering early would fool a prefix-only sample).
    # Affects only whether dictionary encoding is attempted, never
    # correctness.
    sample = 1 << 15
    if sample_bail and n > 4 * sample and max_unique >= sample:
        s_idx = np.empty(sample, np.int64)
        nu_a = lib.pq_dict_build_ba(data.ctypes.data, offsets,
                                    sample, s_idx, sample)
        if _window_predicts_overflow(nu_a, sample, max_unique):
            mid = n // 2
            nu_b = lib.pq_dict_build_ba(data.ctypes.data,
                                        offsets[mid:], sample, s_idx,
                                        sample)
            if _window_predicts_overflow(nu_b, sample, max_unique):
                return "overflow"
    k = lib.pq_dict_build_ba(data.ctypes.data if len(data) else None,
                             offsets, n, indices, max_unique)
    if k < 0:
        return "overflow"
    first = np.empty(max(k, 1), dtype=np.int64)
    lib.pq_dict_first_occurrence(indices, n, k, first)
    return indices[:n], first[:k]

def minmax_ba(data: np.ndarray, offsets: np.ndarray, v0: int, v1: int):
    """(min_idx, max_idx) over byte-string values [v0, v1) in unsigned
    lexicographic order; None when the shim is unavailable."""
    lib = get_lib()
    if lib is None or v1 <= v0:
        return None
    data = np.ascontiguousarray(data)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    mi = np.zeros(1, np.int64)
    ma = np.zeros(1, np.int64)
    lib.pq_minmax_ba(data.ctypes.data if len(data) else None, offsets,
                     v0, v1, mi, ma)
    return int(mi[0]), int(ma[0])
