// Host-side native kernels (C++), loaded via ctypes.
//
// Reference parity: the reference backs its sequential host loops with amd64
// assembly + unsafe Go (SURVEY.md §2.3: encoding/plain BYTE_ARRAY scan,
// encoding/rle run parsing, bloom/xxhash, hashprobe dictionary dedup,
// encoding/delta byte-array prefix reconstruction).  These are exactly the
// loops that cannot vectorize onto TPU lanes (data-dependent byte walks), so
// they get native host code here; everything data-parallel lives in the
// XLA/Pallas kernels instead.
//
// Build: parquet_tpu/native/build.py → _native.so (g++ -O3).  Pure C ABI —
// no pybind11 (not in this image); numpy arrays cross as raw pointers.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#if defined(__SSSE3__)
#include <immintrin.h>  // SSSE3 pshufb (snappy short-offset replication)
#endif
#if defined(__AVX512F__) && defined(__BMI2__)
#ifndef __SSSE3__
#include <immintrin.h>
#endif
#define PQ_HAVE_AVX512 1
#endif

namespace {

// Expand the low `k` bits of `bits` into k 0/1 bytes at dst (order-preserving).
// The magic multiply spreads 8 bits across the 8 bytes of a u64 in one step.
inline void expand_bits_to_bytes(uint64_t bits, int k, uint8_t* dst) {
  int t = 0;
  for (; t + 8 <= k; t += 8, bits >>= 8) {
    // replicate the byte, isolate bit i in byte i, normalize to 0/1
    uint64_t m = ((bits & 0xFF) * 0x0101010101010101ULL) & 0x8040201008040201ULL;
    uint64_t spread = ((m + 0x7F7F7F7F7F7F7F7FULL) >> 7) & 0x0101010101010101ULL;
    std::memcpy(dst + t, &spread, 8);
  }
  for (; t < k; ++t, bits >>= 1) dst[t] = (uint8_t)(bits & 1);
}

// Bounds-checked LSB-first uvarint emit shared by the native encoders.
inline bool put_uvarint(uint8_t* out, int64_t cap, int64_t& o, uint64_t v) {
  while (v >= 0x80) {
    if (o >= cap) return false;
    out[o++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  if (o >= cap) return false;
  out[o++] = (uint8_t)v;
  return true;
}

inline uint64_t load8_clamped(const uint8_t* buf, int64_t buf_len, int64_t byte0) {
  uint64_t word = 0;
  if (byte0 + 8 <= buf_len) {
    std::memcpy(&word, buf + byte0, 8);
  } else {
    for (int b = 0; b < 8 && byte0 + b < buf_len; ++b)
      word |= (uint64_t)buf[byte0 + b] << (8 * b);
  }
  return word;
}

// Unpack cnt w-bit values starting at bit offset `bit` into dst.  One 8-byte
// load yields floor(57/w) values (57 = 64 minus the worst bit phase) — level
// streams are 1-3 bits wide, so this is ~20-57 values per load.
inline void unpack_bits_span(const uint8_t* buf, int64_t buf_len, int64_t bit,
                             int32_t w, int64_t cnt, int32_t* dst) {
  const uint64_t mask = (w >= 32) ? 0xFFFFFFFFull : ((1ull << w) - 1);
  if (w <= 28) {
    const int kper = 57 / w;
    int64_t j = 0;
    while (j < cnt) {
      uint64_t word = load8_clamped(buf, buf_len, bit >> 3) >> (bit & 7);
      int m = (int)((cnt - j < kper) ? (cnt - j) : kper);
      for (int t = 0; t < m; ++t)
        dst[j + t] = (int32_t)((word >> (t * w)) & mask);
      j += m;
      bit += (int64_t)m * w;
    }
  } else {
    for (int64_t j = 0; j < cnt; ++j) {
      uint64_t word = load8_clamped(buf, buf_len, bit >> 3);
      dst[j] = (int32_t)((word >> (bit & 7)) & mask);
      bit += w;
    }
  }
}

#ifdef PQ_HAVE_AVX512
// 64-slot bitmap compaction shared by pq_assemble_levels and the fused list
// assembler: write instance validity + leaf validity bytes via pext/spread,
// and per-instance offsets (elements strictly before the instance bit) via a
// tzcnt walk.  Advances *ninst/*elems.
inline void compact_block64(uint64_t inst_w, uint64_t elem_w, uint64_t valge_w,
                            uint64_t eq_w, int64_t* offsets, uint8_t* lvalid,
                            uint8_t* leaf_valid /* may be null */,
                            int64_t* ninst, int64_t* elems) {
  expand_bits_to_bytes(_pext_u64(valge_w, inst_w),
                       (int)_mm_popcnt_u64(inst_w), lvalid + *ninst);
  if (leaf_valid)
    expand_bits_to_bytes(_pext_u64(eq_w, elem_w), (int)_mm_popcnt_u64(elem_w),
                         leaf_valid + *elems);
  uint64_t iw = inst_w;
  while (iw) {
    const int p = (int)_tzcnt_u64(iw);
    iw = _blsr_u64(iw);
    offsets[(*ninst)++] =
        *elems + _mm_popcnt_u64(elem_w & (((uint64_t)1 << p) - 1));
  }
  *elems += _mm_popcnt_u64(elem_w);
}
#endif

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// PLAIN BYTE_ARRAY: walk [4B LE length][bytes]... building offsets, and
// optionally compacting the value bytes (prefixes stripped) into out_values.
// Returns total value bytes, or -1 on truncation.
// ---------------------------------------------------------------------------
int64_t pq_plain_byte_array(const uint8_t* data, int64_t size, int64_t n,
                            int64_t* offsets /* n+1 */,
                            uint8_t* out_values /* may be null */) {
  int64_t pos = 0;
  int64_t total = 0;
  offsets[0] = 0;
  for (int64_t i = 0; i < n; i++) {
    if (pos + 4 > size) return -1;
    uint32_t len;
    std::memcpy(&len, data + pos, 4);
    pos += 4;
    if (pos + (int64_t)len > size) return -1;
    if (out_values) std::memcpy(out_values + total, data + pos, len);
    pos += len;
    total += len;
    offsets[i + 1] = total;
  }
  return total;
}

// ---------------------------------------------------------------------------
// PLAIN BYTE_ARRAY encode: values+offsets -> [4B LE length][bytes]...
// (write twin of pq_plain_byte_array).  Returns bytes written.
// ---------------------------------------------------------------------------
int64_t pq_encode_plain_ba(const uint8_t* vals, const int64_t* offs, int64_t n,
                           int64_t vals_len, uint8_t* out) {
  if (n > 0 && (offs[0] != 0 || offs[n] > vals_len)) return -1;
  int64_t o = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t len = offs[i + 1] - offs[i];
    // caller-supplied offsets are untrusted: a negative or oversized length
    // would wrap the uint32 and memcpy far past both buffers
    if (len < 0 || len > 0xFFFFFFFFll) return -1;
    const uint32_t len32 = (uint32_t)len;
    std::memcpy(out + o, &len32, 4);
    o += 4;
    std::memcpy(out + o, vals + offs[i], (size_t)len);
    o += len;
  }
  return o;
}

// ---------------------------------------------------------------------------
// Expand a merged run table (host twin of the device rle_expand kernel, used
// for nested-column level streams that the host record assembler consumes).
// Runs tile the output contiguously: run i covers [ends[i-1], ends[i]).
// Returns values written.
// ---------------------------------------------------------------------------
int64_t pq_expand_runs(const uint8_t* buf, int64_t buf_len, const int64_t* ends,
                       const uint8_t* kinds, const int64_t* payloads,
                       const int64_t* bit_offsets, const int32_t* widths,
                       int64_t nruns, int32_t* out, int64_t n) {
  int64_t pos = 0;
  for (int64_t i = 0; i < nruns && pos < n; ++i) {
    int64_t cnt = ends[i] - pos;
    if (cnt > n - pos) cnt = n - pos;
    if (cnt <= 0) continue;
    if (kinds[i] == 0) {
      const int32_t v = (int32_t)payloads[i];
      for (int64_t j = 0; j < cnt; ++j) out[pos + j] = v;
    } else {
      unpack_bits_span(buf, buf_len, bit_offsets[i], widths[i], cnt, out + pos);
    }
    pos += cnt;
  }
  return pos;
}

// ---------------------------------------------------------------------------
// Dremel record assembly: def/rep level streams → per-repeated-level
// (offsets, validity) + leaf validity, single pass per level.
// ks/dks: rep and def level of each repeated ancestor, outermost first.
// offsets_flat: nlev*(n+1) i64; valid_flat: nlev*n u8; inst_counts: nlev i64.
// leaf_valid: n u8.  Returns leaf element count.
// ---------------------------------------------------------------------------
int64_t pq_assemble_levels(const int32_t* defs, const int32_t* reps, int64_t n,
                           const int32_t* ks, const int32_t* dks, int32_t nlev,
                           int32_t max_def, int64_t* offsets_flat,
                           uint8_t* valid_flat, int64_t* inst_counts,
                           uint8_t* leaf_valid) {
#ifdef PQ_HAVE_AVX512
  // Vectorized: 64-slot bitmaps from AVX-512 compares, then per-word
  // stream compaction — offsets via tzcnt walk over instance bits (instances
  // are ~rows, far fewer than slots), validity bytes via pext + bit spread.
  const int64_t nw = n / 64;
  for (int32_t i = 0; i < nlev; ++i) {
    const int32_t k = ks[i], dk = dks[i];
    const int32_t dprev = (i > 0) ? dks[i - 1] : INT32_MIN;
    const int32_t knext = (i + 1 < nlev) ? ks[i + 1] : INT32_MAX;
    int64_t* offs = offsets_flat + (int64_t)i * (n + 1);
    uint8_t* val = valid_flat + (int64_t)i * n;
    int64_t ninst = 0, elems = 0;
    const __m512i kv = _mm512_set1_epi32(k);
    const __m512i dprevv = _mm512_set1_epi32(dprev);
    const __m512i knextv = _mm512_set1_epi32(knext);
    const __m512i dkv = _mm512_set1_epi32(dk);
    const __m512i dkm1v = _mm512_set1_epi32(dk - 1);
    for (int64_t wi = 0; wi < nw; ++wi) {
      uint64_t inst_w = 0, elem_w = 0, valge_w = 0;
      const int64_t j0 = wi * 64;
      for (int g = 0; g < 4; ++g) {
        const __m512i dv = _mm512_loadu_si512(defs + j0 + g * 16);
        const __m512i rv = _mm512_loadu_si512(reps + j0 + g * 16);
        uint64_t im = _mm512_cmplt_epi32_mask(rv, kv) &
                      _mm512_cmple_epi32_mask(dprevv, dv);
        uint64_t em = _mm512_cmplt_epi32_mask(rv, knextv) &
                      _mm512_cmple_epi32_mask(dkv, dv);
        uint64_t vm = _mm512_cmple_epi32_mask(dkm1v, dv);
        inst_w |= im << (g * 16);
        elem_w |= em << (g * 16);
        valge_w |= vm << (g * 16);
      }
      compact_block64(inst_w, elem_w, valge_w, 0, offs, val, nullptr, &ninst,
                      &elems);
    }
    for (int64_t j = nw * 64; j < n; ++j) {
      const int32_t dj = defs[j], rj = reps[j];
      offs[ninst] = elems;
      val[ninst] = dj >= dk - 1;
      ninst += (rj < k) & (dj >= dprev);
      elems += (rj < knext) & (dj >= dk);
    }
    offs[ninst] = elems;
    inst_counts[i] = ninst;
  }
  const int32_t dr = dks[nlev - 1];
  const __m512i drv = _mm512_set1_epi32(dr);
  const __m512i mdv = _mm512_set1_epi32(max_def);
  int64_t cnt = 0;
  for (int64_t wi = 0; wi < nw; ++wi) {
    uint64_t ge_w = 0, eq_w = 0;
    for (int g = 0; g < 4; ++g) {
      const __m512i dv = _mm512_loadu_si512(defs + wi * 64 + g * 16);
      ge_w |= (uint64_t)_mm512_cmple_epi32_mask(drv, dv) << (g * 16);
      eq_w |= (uint64_t)_mm512_cmpeq_epi32_mask(dv, mdv) << (g * 16);
    }
    const int kk = (int)_mm_popcnt_u64(ge_w);
    expand_bits_to_bytes(_pext_u64(eq_w, ge_w), kk, leaf_valid + cnt);
    cnt += kk;
  }
  for (int64_t j = nw * 64; j < n; ++j) {
    const int32_t dj = defs[j];
    leaf_valid[cnt] = dj == max_def;
    cnt += dj >= dr;
  }
  return cnt;
#else
  for (int32_t i = 0; i < nlev; ++i) {
    const int32_t k = ks[i], dk = dks[i];
    const int32_t dprev = (i > 0) ? dks[i - 1] : INT32_MIN;
    const int32_t knext = (i + 1 < nlev) ? ks[i + 1] : INT32_MAX;
    int64_t* offs = offsets_flat + (int64_t)i * (n + 1);
    uint8_t* val = valid_flat + (int64_t)i * n;
    int64_t ninst = 0, elems = 0;
    // branchless: always store at the cursor, advance conditionally (stale
    // stores are overwritten by the next instance / the final sentinel)
    for (int64_t j = 0; j < n; ++j) {
      const int32_t dj = defs[j], rj = reps[j];
      offs[ninst] = elems;
      val[ninst] = dj >= dk - 1;
      ninst += (rj < k) & (dj >= dprev);
      elems += (rj < knext) & (dj >= dk);
    }
    offs[ninst] = elems;
    inst_counts[i] = ninst;
  }
  const int32_t dr = dks[nlev - 1];
  int64_t cnt = 0;
  for (int64_t j = 0; j < n; ++j) {
    const int32_t dj = defs[j];
    leaf_valid[cnt] = dj == max_def;
    cnt += dj >= dr;
  }
  return cnt;
#endif
}

// ---------------------------------------------------------------------------
// LSB-first bit packing (write-path twin of unpack_bits_span; the hottest
// loop of the RLE/dict encoder).  w <= 56 keeps acc|= from overflowing with
// nb < 8 residual bits.  Returns bytes written, or -1 for unsupported width.
// ---------------------------------------------------------------------------
int64_t pq_pack_bits(const int64_t* vals, int64_t n, int32_t w, uint8_t* out) {
  if (w <= 0) return 0;
  if (w > 56) return -1;
  const uint64_t mask = (1ull << w) - 1;
  uint64_t acc = 0;
  int nb = 0;
  int64_t o = 0;
  for (int64_t i = 0; i < n; ++i) {
    acc |= ((uint64_t)vals[i] & mask) << nb;
    nb += w;
    while (nb >= 8) {
      out[o++] = (uint8_t)acc;
      acc >>= 8;
      nb -= 8;
    }
  }
  if (nb) out[o++] = (uint8_t)acc;
  return o;
}

// ---------------------------------------------------------------------------
// BYTE_ARRAY dictionary gather: indices -> concatenated value bytes +
// offsets.  Two-call pattern: out_vals == null computes offsets and returns
// the total byte count; second call memcpys the bytes.
// ---------------------------------------------------------------------------
int64_t pq_gather_ba(const uint8_t* dvals, const int64_t* doffs, int64_t ndict,
                     const int64_t* indices, int64_t n, int64_t* out_offs,
                     uint8_t* out_vals) {
  int64_t total = 0;
  if (!out_vals) {
    out_offs[0] = 0;
    for (int64_t i = 0; i < n; ++i) {
      const int64_t d = indices[i];
      if (d < 0 || d >= ndict) return -1;
      total += doffs[d + 1] - doffs[d];
      out_offs[i + 1] = total;
    }
    return total;
  }
  for (int64_t i = 0; i < n; ++i) {
    const int64_t d = indices[i];
    std::memcpy(out_vals + out_offs[i], dvals + doffs[d],
                (size_t)(doffs[d + 1] - doffs[d]));
  }
  return out_offs[n];
}

// ---------------------------------------------------------------------------
// RLE/bit-packed hybrid encoder (write-path twin of pq_scan_rle_runs),
// byte-identical to the Python oracle: runs >= max(min_repeat, 8) become RLE
// runs (after donating alignment values to the preceding packed span);
// everything between becomes one bit-packed span of whole 8-value groups.
// Returns bytes written, -1 on insufficient cap, -2 for unsupported width.
// ---------------------------------------------------------------------------
int64_t pq_encode_rle(const int64_t* vals, int64_t n, int32_t w,
                      int32_t min_repeat, uint8_t* out, int64_t cap) {
  if (w <= 0 || w > 56 || n == 0) return -2;
  int64_t o = 0;
  const auto put_uv = [&](uint64_t v) { return put_uvarint(out, cap, o, v); };
  const int vbytes = (w + 7) / 8;
  const uint64_t vmask = (vbytes >= 8) ? ~0ull : ((1ull << (8 * vbytes)) - 1);
  const uint64_t mask = (1ull << w) - 1;
  const int64_t thresh = min_repeat < 8 ? 8 : min_repeat;
  const auto emit_packed = [&](int64_t s, int64_t cnt) -> bool {
    if (!cnt) return true;
    const int64_t ngroups = (cnt + 7) / 8;
    if (!put_uv(((uint64_t)ngroups << 1) | 1)) return false;
    uint64_t acc = 0;
    int nb = 0;
    for (int64_t i = 0; i < ngroups * 8; ++i) {
      const uint64_t v = (i < cnt) ? ((uint64_t)vals[s + i] & mask) : 0;
      acc |= v << nb;
      nb += w;
      while (nb >= 8) {
        if (o >= cap) return false;
        out[o++] = (uint8_t)acc;
        acc >>= 8;
        nb -= 8;
      }
    }
    return true;  // 8*w bits per group: nb always ends at 0
  };
  int64_t pos = 0, i = 0;
  while (i < n) {
    const int64_t v = vals[i];
    int64_t j = i + 1;
    while (j < n && vals[j] == v) ++j;
    const int64_t len = j - i;
    if (len >= thresh) {
      const int64_t pad = (8 - ((i - pos) & 7)) & 7;
      if (len - pad >= min_repeat) {
        if (!emit_packed(pos, i + pad - pos)) return -1;
        if (!put_uv((uint64_t)(len - pad) << 1)) return -1;
        const uint64_t ev = (uint64_t)v & vmask;
        for (int b = 0; b < vbytes; ++b) {
          if (o >= cap) return -1;
          out[o++] = (uint8_t)(ev >> (8 * b));
        }
        pos = j;
      }
    }
    i = j;
  }
  if (!emit_packed(pos, n - pos)) return -1;
  return o;
}

// ---------------------------------------------------------------------------
// DELTA_BINARY_PACKED encoder (write-path twin of pq_delta_prescan),
// byte-identical to the Python oracle: per block, zigzag min delta, per-
// miniblock bit widths, LSB-first packed adjusted deltas (128-bit
// accumulator: widths reach 64).  Returns bytes, -1 on cap, -2 unsupported.
// ---------------------------------------------------------------------------
int64_t pq_encode_delta(const int64_t* vals, int64_t n, int32_t block_size,
                        int32_t nmb, uint8_t* out, int64_t cap) {
  if (block_size <= 0 || nmb <= 0 || nmb > 256 || block_size % nmb) return -2;
  int64_t o = 0;
  const auto put_uv = [&](uint64_t v) { return put_uvarint(out, cap, o, v); };
  const auto zz = [](int64_t v) {
    return ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
  };
  if (!put_uv((uint64_t)block_size) || !put_uv((uint64_t)nmb) ||
      !put_uv((uint64_t)n))
    return -1;
  if (n == 0) return put_uv(0) ? o : -1;
  if (!put_uv(zz(vals[0]))) return -1;
  if (n == 1) return o;
  const int vpm = block_size / nmb;
  std::vector<uint64_t> adj(block_size);
  for (int64_t bstart = 0; bstart < n - 1; bstart += block_size) {
    const int64_t cnt =
        (n - 1 - bstart < block_size) ? (n - 1 - bstart) : block_size;
    int64_t mind = INT64_MAX;
    for (int64_t i = 0; i < cnt; ++i) {
      const int64_t d = (int64_t)((uint64_t)vals[bstart + i + 1] -
                                  (uint64_t)vals[bstart + i]);
      adj[i] = (uint64_t)d;
      if (d < mind) mind = d;
    }
    if (!put_uv(zz(mind))) return -1;
    for (int64_t i = 0; i < cnt; ++i) adj[i] -= (uint64_t)mind;
    uint8_t widths[256];
    for (int m = 0; m < nmb; ++m) {
      const int64_t lo = (int64_t)m * vpm;
      uint64_t mx = 0;
      for (int64_t i = lo; i < lo + vpm && i < cnt; ++i)
        mx |= adj[i];  // OR has the same MSB as max
      widths[m] = (lo >= cnt || mx == 0) ? 0 : (uint8_t)(64 - __builtin_clzll(mx));
    }
    if (o + nmb > cap) return -1;
    std::memcpy(out + o, widths, nmb);
    o += nmb;
    const int last_nonempty = (int)((cnt - 1) / vpm);
    for (int m = 0; m <= last_nonempty; ++m) {
      const int w = widths[m];
      if (w == 0) continue;
      const int64_t lo = (int64_t)m * vpm;
      unsigned __int128 acc = 0;
      int nb = 0;
      const uint64_t mask = (w >= 64) ? ~0ull : ((1ull << w) - 1);
      for (int i = 0; i < vpm; ++i) {
        const uint64_t v = (lo + i < cnt) ? (adj[lo + i] & mask) : 0;
        acc |= (unsigned __int128)v << nb;
        nb += w;
        while (nb >= 8) {
          if (o >= cap) return -1;
          out[o++] = (uint8_t)acc;
          acc >>= 8;
          nb -= 8;
        }
      }
      if (nb) {
        if (o >= cap) return -1;
        out[o++] = (uint8_t)acc;
      }
    }
  }
  return o;
}

// ---------------------------------------------------------------------------
// DELTA_BINARY_PACKED miniblock pre-scan (host half of the delta split):
// walks uvarint headers once, O(miniblocks).  header_out = {first, total,
// vpm, end_pos}; returns miniblock count, or -1 on truncation/overflow
// (caller falls back to the Python scanner).
// ---------------------------------------------------------------------------
int64_t pq_delta_prescan(const uint8_t* data, int64_t size, int64_t pos,
                         int64_t* header_out, int64_t* offsets,
                         int32_t* widths, int64_t* mins, int64_t cap) {
  const auto uvarint = [&](int64_t& p, uint64_t& v) -> bool {
    v = 0;
    int sh = 0;
    while (true) {
      if (p >= size || sh > 63) return false;
      const uint8_t b = data[p++];
      if (sh == 63 && (b & 0x7E)) return false;  // >= 2^64: reject, don't wrap
      v |= (uint64_t)(b & 0x7F) << sh;
      if (!(b & 0x80)) return true;
      sh += 7;
    }
  };
  const auto unzigzag = [](uint64_t r) {
    return (int64_t)(r >> 1) ^ -(int64_t)(r & 1);
  };
  uint64_t bs, nmb, total, fraw;
  if (!uvarint(pos, bs) || !uvarint(pos, nmb) || !uvarint(pos, total) ||
      !uvarint(pos, fraw))
    return -1;
  // header values are untrusted file bytes: reject shapes whose payload
  // arithmetic could overflow or never advance (bs=0 loops; a total with
  // bit 63 set casts negative and would skip the scan loop as "success";
  // vpm*w*... must stay far inside int64; a real vpm is <= a few hundred)
  if (nmb == 0 || bs == 0 || bs % nmb || bs > (1u << 30)) return -1;
  if (total >> 63) return -1;
  const int64_t vpm = (int64_t)(bs / nmb);
  if (vpm == 0) return -1;
  header_out[0] = unzigzag(fraw);
  header_out[1] = (int64_t)total;
  header_out[2] = vpm;
  int64_t got = 1, k = 0;
  while (got < (int64_t)total) {
    uint64_t mdr;
    if (!uvarint(pos, mdr)) return -1;
    const int64_t mind = unzigzag(mdr);
    if (pos + (int64_t)nmb > size) return -1;
    const uint8_t* wb = data + pos;
    pos += (int64_t)nmb;
    for (uint64_t m = 0; m < nmb && got < (int64_t)total; ++m) {
      if (k >= cap) return -1;
      const int32_t w = wb[m];
      if (w > 64) return -1;
      offsets[k] = pos * 8;
      widths[k] = w;
      mins[k] = mind;
      pos += vpm * w / 8;  // bounded: vpm <= 2^30, w <= 64
      if (pos < 0 || pos > size + (int64_t)(bs * 8)) return -1;
      ++k;
      const int64_t rem = (int64_t)total - got;
      got += rem < vpm ? rem : vpm;
    }
  }
  header_out[3] = pos;
  return k;
}

// Full-avalanche 64-bit finalizer (splitmix64).  Hash-table indexes below
// are taken from the LOW bits, so every input bit must reach them: a single
// multiply+shift leaves the index a function of the key's low bits only, and
// keys differing in mid/high bytes (dictionary strings packed to words,
// varying in trailing characters) cluster into a few slots, degrading linear
// probing to long chains (measured 5x slowdown on packed "catNNN" keys).
static inline uint64_t pq_mix64(uint64_t h) {
  h ^= h >> 30;
  h *= 0xBF58476D1CE4E5B9ull;
  h ^= h >> 27;
  h *= 0x94D049BB133111EBull;
  h ^= h >> 31;
  return h;
}

// ---------------------------------------------------------------------------
// Fixed-width dictionary build (hashprobe analog for INT32/INT64/FLOAT/DOUBLE
// viewed as int64 bits): open-addressing first-occurrence dedup.
// Returns unique count, or -1 when max_unique would be exceeded.
// ---------------------------------------------------------------------------
int64_t pq_dict_build_i64(const int64_t* vals, int64_t n, int64_t max_unique,
                          int64_t* indices, int64_t* uniques) {
  // grow geometrically from a small table (rebuilt from `uniques` at 50%
  // load) instead of pre-sizing to 2*max_unique: a 100M-row mostly-duplicate
  // column must not transiently allocate gigabytes before discovering its
  // cardinality
  int64_t cap = 1024;
  std::vector<int64_t> slot(cap, -1);
  std::vector<int64_t> key(cap);
  int64_t nu = 0;
  const auto hash_full = [](int64_t v) { return pq_mix64((uint64_t)v); };
  const auto grow = [&]() {
    cap <<= 1;
    slot.assign(cap, -1);
    key.resize(cap);
    for (int64_t u = 0; u < nu; ++u) {
      int64_t p = (int64_t)(hash_full(uniques[u]) & (uint64_t)(cap - 1));
      while (slot[p] >= 0) p = (p + 1) & (cap - 1);
      slot[p] = u;
      key[p] = uniques[u];
    }
  };
  constexpr int64_t kAhead = 16;  // hide the random-probe cache miss
  for (int64_t i = 0; i < n; ++i) {
    if (i + kAhead < n) {
      const int64_t pf =
          (int64_t)(hash_full(vals[i + kAhead]) & (uint64_t)(cap - 1));
      __builtin_prefetch(&slot[pf]);
      __builtin_prefetch(&key[pf]);
    }
    const int64_t v = vals[i];
    int64_t p = (int64_t)(hash_full(v) & (uint64_t)(cap - 1));
    while (true) {
      const int64_t s = slot[p];
      if (s < 0) {
        if (nu >= max_unique) return -1;
        if (2 * (nu + 1) > cap) {
          grow();
          p = (int64_t)(hash_full(v) & (uint64_t)(cap - 1));
          continue;
        }
        slot[p] = nu;
        key[p] = v;
        uniques[nu] = v;
        indices[i] = nu;
        ++nu;
        break;
      }
      if (key[p] == v) {
        indices[i] = s;
        break;
      }
      p = (p + 1) & (cap - 1);
    }
  }
  return nu;
}

// ---------------------------------------------------------------------------
// Fused single-repetition-level list assembly straight from the two level
// run tables (no per-slot def/rep materialization).  Host work stays
// metadata-scale: RLE x RLE segments are handled with vector fills; only
// bit-packed spans unpack per slot.  Semantics match pq_assemble_levels for
// nlev == 1: instance iff rep == 0, element iff def >= dk, list non-null iff
// def >= dk-1 at its start slot, leaf valid iff def == max_def.
// out_counts = {ninst, nelems}; returns 0, or -1 on a run table that does
// not tile [0, n).
// ---------------------------------------------------------------------------
struct RunCursor {
  const uint8_t* buf;
  int64_t buf_len;
  const int64_t* ends;
  const uint8_t* kinds;
  const int64_t* pays;
  const int64_t* bits;
  const int32_t* widths;
  int64_t nruns;
  int64_t idx = 0;
  int64_t start = 0;  // first slot of current run

  bool advance_to(int64_t pos) {  // enter the run containing pos
    while (idx < nruns && ends[idx] <= pos) {
      start = ends[idx];
      ++idx;
    }
    return idx < nruns;
  }
  // fill dst[0..cnt) with per-slot values of [pos, pos+cnt), walking runs
  bool fill(int64_t pos, int64_t cnt, int32_t* dst) {
    int64_t done = 0;
    while (done < cnt) {
      if (!advance_to(pos + done)) return false;
      int64_t take = ends[idx] - (pos + done);
      if (take > cnt - done) take = cnt - done;
      if (kinds[idx] == 0) {
        const int32_t v = (int32_t)pays[idx];
        for (int64_t j = 0; j < take; ++j) dst[done + j] = v;
      } else {
        unpack_span(pos + done, take, dst + done);
      }
      done += take;
    }
    return true;
  }
  bool is_rle() const { return kinds[idx] == 0; }
  int32_t value() const { return (int32_t)pays[idx]; }
  int64_t end() const { return ends[idx]; }
  // unpack [pos, pos+cnt) of a bit-packed run into dst
  void unpack_span(int64_t pos, int64_t cnt, int32_t* dst) const {
    const int32_t w = widths[idx];
    unpack_bits_span(buf, buf_len, bits[idx] + (pos - start) * w, w, cnt, dst);
  }
};

int64_t pq_assemble_list_runs(
    const uint8_t* dbuf, int64_t dlen, const int64_t* d_ends,
    const uint8_t* d_kinds, const int64_t* d_pays, const int64_t* d_bits,
    const int32_t* d_widths, int64_t d_nruns, const uint8_t* rbuf, int64_t rlen,
    const int64_t* r_ends, const uint8_t* r_kinds, const int64_t* r_pays,
    const int64_t* r_bits, const int32_t* r_widths, int64_t r_nruns, int64_t n,
    int32_t dk, int32_t max_def, int64_t* offsets, uint8_t* lvalid,
    uint8_t* leaf_valid, int64_t* out_counts) {
  RunCursor dc{dbuf, dlen, d_ends, d_kinds, d_pays, d_bits, d_widths, d_nruns};
  RunCursor rc{rbuf, rlen, r_ends, r_kinds, r_pays, r_bits, r_widths, r_nruns};
  int64_t pos = 0, ninst = 0, elems = 0;
  while (pos < n) {
    if (!dc.advance_to(pos) || !rc.advance_to(pos)) return -1;
    int64_t end = dc.end() < rc.end() ? dc.end() : rc.end();
    if (end > n) end = n;
    const int64_t len = end - pos;
    if (dc.is_rle() && rc.is_rle() && len >= 256) {
      const int32_t dv = dc.value(), rv = rc.value();
      const bool elem = dv >= dk;
      if (rv == 0) {
        if (elem) {
          for (int64_t t = 0; t < len; ++t) offsets[ninst + t] = elems + t;
        } else {
          for (int64_t t = 0; t < len; ++t) offsets[ninst + t] = elems;
        }
        std::memset(lvalid + ninst, dv >= dk - 1 ? 1 : 0, len);
        ninst += len;
      }
      if (elem) {
        std::memset(leaf_valid + elems, dv == max_def ? 1 : 0, len);
        elems += len;
      }
    } else {
      // short/mixed span: run-table-driven fills into L1-resident chunks
      // (continuous across run boundaries — per-run cost is just the fill
      // switch), then compact via 64-slot bitmaps so stores happen only at
      // instances/elements
      alignas(64) int32_t dtmp[576], rtmp[576];
      end = pos + 512 < n ? pos + 512 : n;
      {
        const int64_t seg = pos;
        const int64_t cnt = end - seg;
        if (!dc.fill(seg, cnt, dtmp) || !rc.fill(seg, cnt, rtmp)) return -1;
#ifdef PQ_HAVE_AVX512
        const __m512i zerov = _mm512_setzero_si512();
        const __m512i dkv = _mm512_set1_epi32(dk);
        const __m512i dkm1v = _mm512_set1_epi32(dk - 1);
        const __m512i mdv = _mm512_set1_epi32(max_def);
        for (int64_t j0 = 0; j0 < cnt; j0 += 64) {
          uint64_t inst_w = 0, elem_w = 0, valge_w = 0, eq_w = 0;
          for (int g = 0; g < 4; ++g) {
            const __m512i dv = _mm512_loadu_si512(dtmp + j0 + g * 16);
            const __m512i rv = _mm512_loadu_si512(rtmp + j0 + g * 16);
            inst_w |= (uint64_t)_mm512_cmpeq_epi32_mask(rv, zerov) << (g * 16);
            elem_w |= (uint64_t)_mm512_cmple_epi32_mask(dkv, dv) << (g * 16);
            valge_w |= (uint64_t)_mm512_cmple_epi32_mask(dkm1v, dv) << (g * 16);
            eq_w |= (uint64_t)_mm512_cmpeq_epi32_mask(dv, mdv) << (g * 16);
          }
          if (cnt - j0 < 64) {  // mask out the tail's garbage lanes
            const uint64_t live = (~0ull) >> (64 - (cnt - j0));
            inst_w &= live;
            elem_w &= live;
            valge_w &= live;
            eq_w &= live;
          }
          compact_block64(inst_w, elem_w, valge_w, eq_w, offsets, lvalid,
                          leaf_valid, &ninst, &elems);
        }
#else
        // branchless: always store at the cursor, advance conditionally
        for (int64_t j = 0; j < cnt; ++j) {
          const int32_t dv = dtmp[j], rv = rtmp[j];
          offsets[ninst] = elems;
          lvalid[ninst] = dv >= dk - 1;
          ninst += (rv == 0);
          leaf_valid[elems] = dv == max_def;
          elems += (dv >= dk);
        }
#endif
      }
    }
    pos = end;
  }
  offsets[ninst] = elems;
  out_counts[0] = ninst;
  out_counts[1] = elems;
  return 0;
}

// ---------------------------------------------------------------------------
// RLE/bit-packed hybrid run scan (the host half of the two-pass split).
// Outputs one row per run; returns run count, or -1 on malformed input.
// Caller sizes outputs to n (a run covers >= 1 value).
// ---------------------------------------------------------------------------
int64_t pq_scan_rle_runs(const uint8_t* data, int64_t size, int64_t n,
                         int32_t bit_width, uint8_t* kinds, int64_t* counts,
                         int64_t* payloads, int64_t* byte_offsets) {
  int64_t pos = 0;
  int64_t remaining = n;
  int64_t k = 0;
  const int vbytes = (bit_width + 7) / 8;
  while (remaining > 0) {
    // uvarint header
    uint64_t header = 0;
    int shift = 0;
    while (true) {
      if (pos >= size) return -1;
      uint8_t b = data[pos++];
      header |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) return -1;
    }
    // a zero-count run (header >> 1 == 0) covers no values: it never
    // decrements `remaining`, so a crafted stream of them would grow the
    // run table without bound (the caller sizes its arrays as n+1 on the
    // guarantee every run covers >= 1 value) — reject as malformed
    if ((header >> 1) == 0) return -1;
    if (header & 1) {
      int64_t ngroups = (int64_t)(header >> 1);
      int64_t count = ngroups * 8;
      kinds[k] = 1;
      counts[k] = count < remaining ? count : remaining;
      payloads[k] = 0;
      byte_offsets[k] = pos;
      pos += ngroups * bit_width;
      if (pos > size) return -1;
      remaining -= count;
    } else {
      int64_t count = (int64_t)(header >> 1);
      if (pos + vbytes > size) return -1;
      uint64_t value = 0;
      for (int j = 0; j < vbytes; j++) value |= (uint64_t)data[pos + j] << (8 * j);
      // mask to the declared width: the padding bits of the vbytes payload
      // are unspecified, and every consumer (incl. int32 expansion) must see
      // the same value as the Python oracle
      if (bit_width < 64) value &= (1ull << bit_width) - 1;
      pos += vbytes;
      kinds[k] = 0;
      counts[k] = count < remaining ? count : remaining;
      payloads[k] = (int64_t)value;
      byte_offsets[k] = pos;
      remaining -= count;
    }
    k++;
  }
  return k;
}

// ---------------------------------------------------------------------------
// Fused DELTA_BINARY_PACKED decode (multithreaded, one pass): miniblock
// tables (from pq_delta_prescan) → int64 values, unpack + min-add + prefix
// sum inline.  The host route for delta chunks on non-TPU backends
// (BASELINE config 4); pages are independent (each restarts at its own
// first value), so the thread partition is per page.
// ---------------------------------------------------------------------------

static inline uint64_t load_bits64(const uint8_t* buf, int64_t buf_len,
                                   int64_t bit, int w) {
  // w <= 64; value may span 9 bytes — combine two clamped 8-byte loads
  const int64_t byte0 = bit >> 3;
  const int sh = (int)(bit & 7);
  uint64_t lo = load8_clamped(buf, buf_len, byte0) >> sh;
  if (sh + w > 64) {
    uint64_t hi = load8_clamped(buf, buf_len, byte0 + 8);
    lo |= hi << (64 - sh);
  }
  return (w >= 64) ? lo : (lo & (((uint64_t)1 << w) - 1));
}

int64_t pq_delta_decode(const uint8_t* buf, int64_t buf_len,
                        const int64_t* mb_bitoffs, const int32_t* mb_widths,
                        const int64_t* mb_mins, const int64_t* page_mb_start,
                        const int64_t* page_first, const int64_t* page_count,
                        const int64_t* page_out_start, const int64_t* page_vpm,
                        int64_t npages, int64_t* out, int32_t nthreads) {
  auto decode_page = [&](int64_t p) -> bool {
    const int64_t total = page_count[p];
    if (total <= 0) return total == 0;
    const int64_t vpm = page_vpm[p];
    if (vpm <= 0) return false;
    int64_t* o = out + page_out_start[p];
    uint64_t v = (uint64_t)page_first[p];
    o[0] = (int64_t)v;
    int64_t got = 1;
    for (int64_t m = page_mb_start[p]; m < page_mb_start[p + 1] && got < total;
         ++m) {
      const int w = mb_widths[m];
      if (w < 0 || w > 64) return false;
      const uint64_t mn = (uint64_t)mb_mins[m];
      const int64_t take = (total - got < vpm) ? (total - got) : vpm;
      if (w == 0) {
        for (int64_t j = 0; j < take; ++j) {
          v += mn;
          o[got + j] = (int64_t)v;
        }
      } else {
        int64_t bit = mb_bitoffs[m];
        if (bit < 0 || bit + (int64_t)w * take > buf_len * 8) return false;
        if (w <= 28) {
          // narrow widths (the common case): batch-unpack via one 8-byte
          // load per 57/w values, same scheme as unpack_bits_span
          const int kper = 57 / w;
          const uint64_t mask = ((uint64_t)1 << w) - 1;
          int64_t j = 0;
          while (j < take) {
            uint64_t word =
                load8_clamped(buf, buf_len, bit >> 3) >> (bit & 7);
            int mcount = (int)((take - j < kper) ? (take - j) : kper);
            for (int t = 0; t < mcount; ++t) {
              v += ((word >> (t * w)) & mask) + mn;
              o[got + j + t] = (int64_t)v;
            }
            j += mcount;
            bit += (int64_t)mcount * w;
          }
        } else {
          for (int64_t j = 0; j < take; ++j) {
            v += load_bits64(buf, buf_len, bit, w) + mn;
            o[got + j] = (int64_t)v;
            bit += w;
          }
        }
      }
      got += take;
    }
    return got >= total;
  };
  int T = nthreads;
  if (T < 1) T = 1;
  if (T > 16) T = 16;
  if ((int64_t)T > npages) T = (int)npages ? (int)npages : 1;
  if (T == 1) {
    for (int64_t p = 0; p < npages; ++p)
      if (!decode_page(p)) return -1;
    return 0;
  }
  std::vector<std::thread> threads;
  std::vector<char> ok((size_t)T, 1);
  const int64_t per = (npages + T - 1) / T;
  auto run = [&](int t) {
    const int64_t lo = per * t, hi = std::min(npages, per * (t + 1));
    for (int64_t p = lo; p < hi; ++p)
      if (!decode_page(p)) { ok[(size_t)t] = 0; return; }
  };
  for (int t = 1; t < T; ++t) threads.emplace_back(run, t);
  run(0);
  for (auto& th : threads) th.join();
  for (int t = 0; t < T; ++t)
    if (!ok[(size_t)t]) return -1;
  return 0;
}

}  // extern "C" (the helpers below use templates — C++ linkage)

// ---------------------------------------------------------------------------
// Fused RLE/bit-packed expand + dictionary gather (multithreaded).
// The host route for mixed-run dictionary chunks (BASELINE config 2): one
// pass from the run table straight to gathered values — no materialized
// index stream, output-partitioned across threads at run boundaries.
// ---------------------------------------------------------------------------

namespace {

template <int ELEM>
bool expand_gather_span(const uint8_t* buf, int64_t buf_len,
                        const int64_t* ends, const uint8_t* kinds,
                        const int64_t* payloads, const int64_t* bit_offsets,
                        const int32_t* widths, int64_t nruns,
                        const uint8_t* dict, int64_t dict_n,
                        int64_t lo, int64_t hi, uint8_t* out) {
  // first run containing value index `lo` (ends are cumulative counts)
  int64_t r = std::upper_bound(ends, ends + nruns, lo) - ends;
  int64_t v = lo;
  while (v < hi && r < nruns) {
    const int64_t run_start = r ? ends[r - 1] : 0;
    const int64_t run_end = ends[r] < hi ? ends[r] : hi;
    if (kinds[r] == 0) {  // RLE: one dictionary value fills the span
      const int64_t idx = payloads[r];
      if (idx < 0 || idx >= dict_n) return false;
      const uint8_t* src = dict + idx * ELEM;
      for (int64_t j = v; j < run_end; ++j)
        std::memcpy(out + j * ELEM, src, ELEM);
    } else {  // bit-packed span: unpack the index inline, gather
      const int32_t w = widths[r];
      if (w < 0 || w > 32) return false;
      const uint64_t mask = (w >= 32) ? 0xFFFFFFFFull : ((1ull << w) - 1);
      int64_t bit = bit_offsets[r] + (v - run_start) * (int64_t)w;
      if (w <= 28) {
        const int kper = w ? 57 / w : 1;
        // every representable index is in range when the width's mask is
        // below the dictionary size — hoist the per-value bounds check
        const bool safe = (int64_t)mask < dict_n;
        int64_t j = v;
        while (j < run_end) {
          uint64_t word = load8_clamped(buf, buf_len, bit >> 3) >> (bit & 7);
          int m = (int)((run_end - j < kper) ? (run_end - j) : kper);
          if (safe) {
            for (int t = 0; t < m; ++t)
              std::memcpy(out + (j + t) * ELEM,
                          dict + ((word >> (t * w)) & mask) * ELEM, ELEM);
          } else {
            for (int t = 0; t < m; ++t) {
              const int64_t idx = (int64_t)((word >> (t * w)) & mask);
              if (idx >= dict_n) return false;
              std::memcpy(out + (j + t) * ELEM, dict + idx * ELEM, ELEM);
            }
          }
          j += m;
          bit += (int64_t)m * w;
        }
      } else {
        for (int64_t j = v; j < run_end; ++j) {
          uint64_t word = load8_clamped(buf, buf_len, bit >> 3);
          const int64_t idx = (int64_t)((word >> (bit & 7)) & mask);
          if (idx >= dict_n) return false;
          std::memcpy(out + j * ELEM, dict + idx * ELEM, ELEM);
          bit += w;
        }
      }
    }
    v = run_end;
    if (v >= ends[r]) ++r;
  }
  return v >= hi;
}

}  // namespace

extern "C" int64_t pq_expand_gather(
    const uint8_t* buf, int64_t buf_len, const int64_t* ends,
    const uint8_t* kinds, const int64_t* payloads, const int64_t* bit_offsets,
    const int32_t* widths, int64_t nruns, int64_t n, const uint8_t* dict,
    int64_t dict_n, int32_t elem, uint8_t* out, int32_t nthreads) {
  if (n <= 0) return 0;
  if (elem != 4 && elem != 8) return -1;
  auto span = [&](int64_t lo, int64_t hi) -> bool {
    return elem == 4
               ? expand_gather_span<4>(buf, buf_len, ends, kinds, payloads,
                                       bit_offsets, widths, nruns, dict,
                                       dict_n, lo, hi, out)
               : expand_gather_span<8>(buf, buf_len, ends, kinds, payloads,
                                       bit_offsets, widths, nruns, dict,
                                       dict_n, lo, hi, out);
  };
  int T = nthreads;
  if (T < 1) T = 1;
  if (T > 16) T = 16;
  if ((int64_t)T > n / 65536) T = (int)(n / 65536) ? (int)(n / 65536) : 1;
  if (T == 1) return span(0, n) ? 0 : -1;
  std::vector<std::thread> threads;
  std::vector<char> ok((size_t)T, 1);
  const int64_t per = (n + T - 1) / T;
  for (int t = 1; t < T; ++t) {
    const int64_t lo = per * t, hi = std::min(n, per * (t + 1));
    threads.emplace_back([&, t, lo, hi] { ok[(size_t)t] = span(lo, hi); });
  }
  ok[0] = span(0, std::min(per, n));
  for (auto& th : threads) th.join();
  for (int t = 0; t < T; ++t)
    if (!ok[(size_t)t]) return -1;
  return 0;
}

// ---------------------------------------------------------------------------
// Batch page-header scan: walk a column chunk's compact-thrift PageHeader
// stream in one native call (SURVEY.md §3.1 file walk — the reference's
// ReadPageHeader loop; per-page Python thrift parsing was the measured
// dominant cost of the e2e pipeline's host phase).  Only the PageHeader
// subset the decoder needs is extracted; any malformed construct returns -1
// and the caller falls back to the Python reader, which owns error wording.
// ---------------------------------------------------------------------------

namespace {

struct TRd {
  const uint8_t* p;
  int64_t pos, size;
  bool err;
};

inline uint64_t trd_uvarint(TRd& r) {
  uint64_t v = 0;
  int shift = 0;
  while (true) {
    if (r.pos >= r.size || shift > 63) { r.err = true; return 0; }
    uint8_t b = r.p[r.pos++];
    v |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) return v;
    shift += 7;
  }
}

inline int64_t trd_zigzag(TRd& r) {
  uint64_t v = trd_uvarint(r);
  return (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
}

// compact-protocol wire types
enum { CT_STOP = 0, CT_TRUE = 1, CT_FALSE = 2, CT_I8 = 3, CT_I16 = 4,
       CT_I32 = 5, CT_I64 = 6, CT_DOUBLE = 7, CT_BINARY = 8, CT_LIST = 9,
       CT_SET = 10, CT_MAP = 11, CT_STRUCT = 12 };

void trd_skip(TRd& r, int wire, int depth) {
  if (r.err || depth > 16) { r.err = true; return; }
  switch (wire) {
    case CT_TRUE: case CT_FALSE:
      return;  // value lives in the type nibble
    case CT_I8:
      r.pos += 1; if (r.pos > r.size) r.err = true; return;
    case CT_I16: case CT_I32: case CT_I64:
      trd_uvarint(r); return;
    case CT_DOUBLE:
      r.pos += 8; if (r.pos > r.size) r.err = true; return;
    case CT_BINARY: {
      uint64_t n = trd_uvarint(r);
      if (r.err || n > (uint64_t)(r.size - r.pos)) { r.err = true; return; }
      r.pos += (int64_t)n; return;
    }
    case CT_LIST: case CT_SET: {
      if (r.pos >= r.size) { r.err = true; return; }
      uint8_t h = r.p[r.pos++];
      uint64_t n = h >> 4;
      int ew = h & 0x0F;
      if (n == 0xF) n = trd_uvarint(r);
      // any element consumes >= 1 byte, so a count beyond the remaining
      // buffer is malformed — guards the unsigned->signed cast too
      if (n > (uint64_t)(r.size - r.pos)) { r.err = true; return; }
      if (ew == CT_TRUE || ew == CT_FALSE) {  // bools: one byte per element
        r.pos += (int64_t)n;
        return;
      }
      for (uint64_t i = 0; i < n && !r.err; ++i) trd_skip(r, ew, depth + 1);
      return;
    }
    case CT_MAP: {
      uint64_t n = trd_uvarint(r);
      if (r.err) return;
      if (n == 0) return;
      // each pair consumes >= 1 byte: bound the loop against the buffer
      if (n > (uint64_t)(r.size - r.pos)) { r.err = true; return; }
      if (r.pos >= r.size) { r.err = true; return; }
      uint8_t kv = r.p[r.pos++];
      for (uint64_t i = 0; i < n && !r.err; ++i) {
        trd_skip(r, kv >> 4, depth + 1);
        trd_skip(r, kv & 0x0F, depth + 1);
      }
      return;
    }
    case CT_STRUCT: {
      while (!r.err) {
        if (r.pos >= r.size) { r.err = true; return; }
        uint8_t h = r.p[r.pos++];
        if (h == CT_STOP) return;
        if (!(h >> 4)) trd_zigzag(r);  // long-form field id
        trd_skip(r, h & 0x0F, depth + 1);
      }
      return;
    }
    default:
      r.err = true;
      return;
  }
}

// Walk one struct, dispatching (field id, wire) to `fn`; unknown fields skip.
template <typename F>
inline void trd_struct(TRd& r, F&& fn) {
  int64_t fid = 0;
  while (!r.err) {
    if (r.pos >= r.size) { r.err = true; return; }
    uint8_t h = r.p[r.pos++];
    if (h == CT_STOP) return;
    int delta = h >> 4, wire = h & 0x0F;
    fid = delta ? fid + delta : trd_zigzag(r);
    if (!fn(fid, wire)) trd_skip(r, wire, 0);
  }
}

}  // namespace

// out columns per page (int64 each) — keep in sync with native/__init__.py
enum { PG_HEADER_POS = 0, PG_DATA_POS, PG_TYPE, PG_COMP, PG_UNCOMP, PG_CRC,
       PG_NVALS, PG_ENC, PG_DEF_ENC, PG_REP_ENC, PG_RL_BYTES, PG_DL_BYTES,
       PG_NNULLS, PG_IS_COMPRESSED, PG_DICT_NVALS, PG_NROWS, PG_NFIELDS };

static int64_t scan_page_headers_impl(const uint8_t* buf, int64_t size,
                                      int64_t total_values,
                                      int64_t max_pages, int64_t* out,
                                      bool partial, int64_t* consumed_out) {
  int64_t pos = 0, values_seen = 0, k = 0;
  while (values_seen < total_values && pos < size) {
    if (k >= max_pages) {
      if (partial) break;
      return -2;
    }
    TRd r{buf, pos, size, false};
    int64_t* row = out + k * PG_NFIELDS;
    for (int i = 0; i < PG_NFIELDS; ++i) row[i] = -1;
    row[PG_HEADER_POS] = pos;
    trd_struct(r, [&](int64_t fid, int wire) -> bool {
      switch (fid) {
        case 1: if (wire != CT_I32) return false;
                row[PG_TYPE] = trd_zigzag(r); return true;
        case 2: if (wire != CT_I32) return false;
                row[PG_UNCOMP] = trd_zigzag(r); return true;
        case 3: if (wire != CT_I32) return false;
                row[PG_COMP] = trd_zigzag(r); return true;
        case 4: if (wire != CT_I32) return false;
                // thrift i32 crc is signed; normalize to the u32 value
                row[PG_CRC] = (int64_t)(uint32_t)trd_zigzag(r); return true;
        case 5:  // data_page_header
          if (wire != CT_STRUCT) return false;
          trd_struct(r, [&](int64_t f2, int w2) -> bool {
            if (w2 != CT_I32) return false;
            switch (f2) {
              case 1: row[PG_NVALS] = trd_zigzag(r); return true;
              case 2: row[PG_ENC] = trd_zigzag(r); return true;
              case 3: row[PG_DEF_ENC] = trd_zigzag(r); return true;
              case 4: row[PG_REP_ENC] = trd_zigzag(r); return true;
              default: return false;
            }
          });
          return true;
        case 7:  // dictionary_page_header
          if (wire != CT_STRUCT) return false;
          trd_struct(r, [&](int64_t f2, int w2) -> bool {
            if (w2 != CT_I32) return false;
            switch (f2) {
              case 1: row[PG_DICT_NVALS] = trd_zigzag(r); return true;
              case 2: row[PG_ENC] = trd_zigzag(r); return true;
              default: return false;
            }
          });
          return true;
        case 8:  // data_page_header_v2
          if (wire != CT_STRUCT) return false;
          trd_struct(r, [&](int64_t f2, int w2) -> bool {
            if (w2 == CT_TRUE || w2 == CT_FALSE) {
              if (f2 == 7) { row[PG_IS_COMPRESSED] = (w2 == CT_TRUE); return true; }
              return true;  // other bools carry no payload bytes
            }
            if (w2 != CT_I32) return false;
            switch (f2) {
              case 1: row[PG_NVALS] = trd_zigzag(r); return true;
              case 2: row[PG_NNULLS] = trd_zigzag(r); return true;
              case 3: row[PG_NROWS] = trd_zigzag(r); return true;
              case 4: row[PG_ENC] = trd_zigzag(r); return true;
              case 5: row[PG_DL_BYTES] = trd_zigzag(r); return true;
              case 6: row[PG_RL_BYTES] = trd_zigzag(r); return true;
              default: return false;
            }
          });
          return true;
        default:
          return false;  // statistics / index page header / unknown: skip
      }
    });
    if (r.err) {
      // in partial mode a header running past the buffer is just the
      // window edge: stop and report progress, the caller re-reads from
      // `consumed` with a bigger window (true corruption surfaces there)
      if (partial) break;
      return -1;
    }
    int64_t clen = row[PG_COMP];
    if (clen < 0 || row[PG_TYPE] < 0 || row[PG_UNCOMP] < 0) {
      if (partial) break;
      return -1;
    }
    if (clen > size - r.pos) {  // payload past the buffer (no overflow)
      if (partial) break;
      return -1;
    }
    row[PG_DATA_POS] = r.pos;
    if (row[PG_TYPE] == 0 || row[PG_TYPE] == 3) {  // DATA_PAGE / V2
      if (row[PG_NVALS] < 0) {
        if (partial) break;
        return -1;
      }
      values_seen += row[PG_NVALS];
    }
    pos = r.pos + clen;
    ++k;
  }
  if (consumed_out) {
    consumed_out[0] = pos;
    consumed_out[1] = values_seen;
  }
  return k;
}

extern "C" int64_t pq_scan_page_headers(const uint8_t* buf, int64_t size,
                                        int64_t total_values,
                                        int64_t max_pages, int64_t* out) {
  return scan_page_headers_impl(buf, size, total_values, max_pages, out,
                                false, nullptr);
}

// Partial/windowed variant: stops (instead of erroring) at the first page
// whose header or payload runs past the buffer, reporting pages parsed and
// consumed_out = {bytes consumed, data values seen}.
extern "C" int64_t pq_scan_page_headers_partial(
    const uint8_t* buf, int64_t size, int64_t total_values,
    int64_t max_pages, int64_t* out, int64_t* consumed_out) {
  return scan_page_headers_impl(buf, size, total_values, max_pages, out,
                                true, consumed_out);
}

extern "C" {

// ---------------------------------------------------------------------------
// xxhash64 (bloom filter hashing; spec-mandated XXH64 seed 0)
// ---------------------------------------------------------------------------
static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

uint64_t pq_xxh64(const uint8_t* p, int64_t len, uint64_t seed) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    do {
      uint64_t k;
      std::memcpy(&k, p, 8); v1 = rotl64(v1 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v2 = rotl64(v2 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v3 = rotl64(v3 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v4 = rotl64(v4 + k * P2, 31) * P1; p += 8;
    } while (p + 32 <= end);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = (h ^ (rotl64(v1 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v2 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v3 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v4 * P2, 31) * P1)) * P1 + P4;
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    uint64_t k;
    std::memcpy(&k, p, 8);
    h ^= rotl64(k * P2, 31) * P1;
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t k;
    std::memcpy(&k, p, 4);
    h ^= (uint64_t)k * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (uint64_t)(*p++) * P5;
    h = rotl64(h, 11) * P1;
  }
  h ^= h >> 33; h *= P2; h ^= h >> 29; h *= P3; h ^= h >> 32;
  return h;
}

void pq_xxh64_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                    uint64_t* out) {
  for (int64_t i = 0; i < n; i++)
    out[i] = pq_xxh64(data + offsets[i], offsets[i + 1] - offsets[i], 0);
}

// ---------------------------------------------------------------------------
// DELTA_BYTE_ARRAY reconstruction: values[i] = values[i-1][:prefix[i]] + suffix[i]
// (the inherently sequential front-coding chain — SURVEY.md §2.2)
// ---------------------------------------------------------------------------
int64_t pq_delta_byte_array_expand(const int64_t* prefix_lens,
                                   const uint8_t* suffix_data,
                                   const int64_t* suffix_offsets, int64_t n,
                                   uint8_t* out_values,
                                   const int64_t* out_offsets) {
  int64_t prev = 0;
  for (int64_t i = 0; i < n; i++) {
    const int64_t o = out_offsets[i];
    const int64_t pl = prefix_lens[i];
    const int64_t sl = suffix_offsets[i + 1] - suffix_offsets[i];
    if (pl > 0) std::memmove(out_values + o, out_values + prev, pl);
    if (sl > 0) std::memcpy(out_values + o + pl, suffix_data + suffix_offsets[i], sl);
    prev = o;
  }
  return n ? out_offsets[n] : 0;
}

// ---------------------------------------------------------------------------
// Byte-array dictionary build (hashprobe analog): dedup via hash map.
// Returns unique count; fills indices[n] and, when out_* non-null, the
// unique strings compacted in first-seen order.
// ---------------------------------------------------------------------------
struct DictState {
  std::unordered_map<std::string, int64_t> map;
  std::vector<std::string> uniques;
};

int64_t pq_dict_build_ba(const uint8_t* data, const int64_t* offsets,
                         int64_t n, int64_t* indices, int64_t max_unique) {
  // Open-addressing first-occurrence dedup, same scheme as
  // pq_dict_build_i64: slots hold unique ids, keys are compared by memcmp
  // against the FIRST occurrence's bytes (no per-value allocation — the
  // previous unordered_map<string> build paid a heap string per value and
  // was the single largest cost of writing a categorical string column).
  // All loads are fixed-size 8-byte memcpy (a single inlined mov) — a
  // variable-length memcpy is a real library call and dominated the
  // per-value cost.  Loads near the end of the buffer fall back to the
  // slow path so we never read past offsets[n].
  const int64_t total = offsets[n];
  constexpr uint64_t kMix = 0x9E3779B97F4A7C15ull;
  const auto load_masked = [&](int64_t off, int64_t len) -> uint64_t {
    // len in [0, 8]; all-empty-string columns pass data == NULL, so never
    // touch the pointer for a zero-length load
    if (len == 0) return 0;
    if (off + 8 <= total) {
      uint64_t w;
      memcpy(&w, data + off, 8);
      return len >= 8 ? w : w & ((1ull << (8 * len)) - 1);
    }
    uint64_t w = 0;
    memcpy(&w, data + off, (size_t)len);
    return w;
  };
  // hash of the full string; also yields the first 8 bytes zero-padded
  // (k8) — with the length checked separately, k8 settles equality for
  // len <= 8 without touching memcmp
  const auto hkey = [&](int64_t i, uint64_t* k8) -> uint64_t {
    int64_t o = offsets[i];
    int64_t len = offsets[i + 1] - o;
    uint64_t h = kMix ^ (uint64_t)len;
    uint64_t w0 = 0;
    bool first = true;
    while (len >= 8) {
      uint64_t w;
      memcpy(&w, data + o, 8);
      if (first) {
        w0 = w;
        first = false;
      }
      h = (h ^ w) * kMix;
      h ^= h >> 29;
      o += 8;
      len -= 8;
    }
    if (len) {
      uint64_t w = load_masked(o, len);
      if (first) w0 = w;
      h = (h ^ w) * kMix;
      h ^= h >> 29;
    }
    // final avalanche: the index comes from the LOW bits, and the per-word
    // mix above does not push a word's high bytes down into them — strings
    // differing only in trailing characters would otherwise cluster (see
    // pq_mix64).
    h = pq_mix64(h);
    *k8 = w0;
    return h;
  };
  // Short-string fast path: when every value fits in 7 bytes, the whole
  // (bytes, length) identity packs into one tagged word — bytes in the low
  // 56 bits, length in the top byte — so probing is a single-word compare
  // with no memcmp and 16-byte slots.  This is the dominant dictionary
  // write shape (categorical/enum-like string columns: flags, codes,
  // ship modes) and runs ~2x the general loop below.
  {
    int64_t maxlen = 0;
    for (int64_t i = 0; i < n && maxlen <= 7; ++i) {
      const int64_t l = offsets[i + 1] - offsets[i];
      if (l > maxlen) maxlen = l;
    }
    if (maxlen <= 7) {
      // Packed keys are computed on the fly (two loads + mask + tag) — no
      // n-sized transient, so a 100M-row column costs only its table, which
      // grows geometrically from 1024 like pq_dict_build_i64's.
      const auto pack = [&](int64_t i) -> uint64_t {
        const int64_t o = offsets[i];
        const uint64_t len = (uint64_t)(offsets[i + 1] - o);
        if (o + 8 <= total) {
          uint64_t w;
          memcpy(&w, data + o, 8);
          return (w & (((uint64_t)1 << (8 * len)) - 1)) | (len << 56);
        }
        return load_masked(o, (int64_t)len) | (len << 56);
      };
      const auto hashw = pq_mix64;
      int64_t cap = 1024;
      std::vector<int64_t> slot(cap, -1);
      std::vector<uint64_t> key(cap);
      std::vector<uint64_t> ukey;  // unique id -> packed key, for rebuilds
      ukey.reserve(1024);
      int64_t nu = 0;
      const auto grow = [&]() {
        cap <<= 1;
        slot.assign(cap, -1);
        key.resize(cap);
        for (int64_t u = 0; u < nu; ++u) {
          int64_t p = (int64_t)(hashw(ukey[u]) & (uint64_t)(cap - 1));
          while (slot[p] >= 0) p = (p + 1) & (cap - 1);
          slot[p] = u;
          key[p] = ukey[u];
        }
      };
      constexpr int64_t kAhead = 16;  // hide the random-probe cache miss
      for (int64_t i = 0; i < n; ++i) {
        if (i + kAhead < n) {
          const int64_t pf = (int64_t)(hashw(pack(i + kAhead)) &
                                       (uint64_t)(cap - 1));
          __builtin_prefetch(&slot[pf]);
          __builtin_prefetch(&key[pf]);
        }
        const uint64_t v = pack(i);
        int64_t p = (int64_t)(hashw(v) & (uint64_t)(cap - 1));
        while (true) {
          const int64_t s = slot[p];
          if (s < 0) {
            if (nu >= max_unique) return -(i + 1);
            if (2 * (nu + 1) > cap) {
              grow();
              p = (int64_t)(hashw(v) & (uint64_t)(cap - 1));
              continue;
            }
            slot[p] = nu;
            key[p] = v;
            ukey.push_back(v);
            indices[i] = nu;
            ++nu;
            break;
          }
          if (key[p] == v) {
            indices[i] = s;
            break;
          }
          p = (p + 1) & (cap - 1);
        }
      }
      return nu;
    }
  }
  struct BaSlot {       // one cache-line-friendly 32-byte entry per slot
    uint64_t h;         // full hash
    uint64_t k8;        // first 8 bytes, zero-padded
    int64_t len;        // byte length
    int64_t id;         // unique id, -1 = empty
  };
  int64_t cap = 1024;
  std::vector<BaSlot> slots(cap, BaSlot{0, 0, 0, -1});
  std::vector<int64_t> first_i;  // unique id -> first value index
  first_i.reserve(1024);
  const auto grow = [&]() {
    cap <<= 1;
    slots.assign(cap, BaSlot{0, 0, 0, -1});
    for (size_t u = 0; u < first_i.size(); ++u) {
      const int64_t fi = first_i[u];
      uint64_t k8;
      uint64_t h = hkey(fi, &k8);
      int64_t p = (int64_t)(h & (uint64_t)(cap - 1));
      while (slots[p].id >= 0) p = (p + 1) & (cap - 1);
      slots[p] = BaSlot{h, k8, offsets[fi + 1] - offsets[fi], (int64_t)u};
    }
  };
  for (int64_t i = 0; i < n; ++i) {
    uint64_t k8;
    const uint64_t h = hkey(i, &k8);
    const int64_t len = offsets[i + 1] - offsets[i];
    int64_t p = (int64_t)(h & (uint64_t)(cap - 1));
    while (true) {
      const BaSlot& e = slots[p];
      if (e.id < 0) {
        if ((int64_t)first_i.size() >= max_unique)
          return -(i + 1);  // cardinality blew the limit
        if (2 * ((int64_t)first_i.size() + 1) > cap) {
          grow();
          p = (int64_t)(h & (uint64_t)(cap - 1));
          continue;
        }
        slots[p] = BaSlot{h, k8, len, (int64_t)first_i.size()};
        indices[i] = (int64_t)first_i.size();
        first_i.push_back(i);
        break;
      }
      if (e.h == h && e.len == len && e.k8 == k8) {
        const int64_t fi = first_i[e.id];
        if (len <= 8 ||
            memcmp(data + offsets[fi] + 8, data + offsets[i] + 8,
                   (size_t)(len - 8)) == 0) {
          indices[i] = e.id;
          break;
        }
      }
      p = (p + 1) & (cap - 1);
    }
  }
  return (int64_t)first_i.size();
}

// second pass: caller uses indices to materialize uniques (first occurrence)
// min/max over a span of length-prefixed byte strings (unsigned
// lexicographic — BYTE_ARRAY's order domain).  Writes the min and max VALUE
// indexes; used by per-page statistics so the hot write path never
// materializes python bytes objects.
void pq_minmax_ba(const uint8_t* data, const int64_t* offsets, int64_t v0,
                  int64_t v1, int64_t* out_min, int64_t* out_max) {
  int64_t mi = v0, ma = v0;
  for (int64_t i = v0 + 1; i < v1; i++) {
    const uint8_t* a = data + offsets[i];
    int64_t alen = offsets[i + 1] - offsets[i];
    const uint8_t* m = data + offsets[mi];
    int64_t mlen = offsets[mi + 1] - offsets[mi];
    int cmp = memcmp(a, m, alen < mlen ? alen : mlen);
    if (cmp < 0 || (cmp == 0 && alen < mlen)) mi = i;
    const uint8_t* x = data + offsets[ma];
    int64_t xlen = offsets[ma + 1] - offsets[ma];
    cmp = memcmp(a, x, alen < xlen ? alen : xlen);
    if (cmp > 0 || (cmp == 0 && alen > xlen)) ma = i;
  }
  *out_min = mi;
  *out_max = ma;
}

void pq_dict_first_occurrence(const int64_t* indices, int64_t n,
                              int64_t n_unique, int64_t* first_idx) {
  for (int64_t u = 0; u < n_unique; u++) first_idx[u] = -1;
  for (int64_t i = 0; i < n; i++)
    if (first_idx[indices[i]] < 0) first_idx[indices[i]] = i;
}

// ---------------------------------------------------------------------------
// Hadoop-framed LZ4 / generic frame walker is python-side; CRC32 via zlib.
// ---------------------------------------------------------------------------

}  // extern "C"

// ---------------------------------------------------------------------------
// Count level values equal to `target` across a scanned run table (the
// per-page present-count of build_plan: def == max_def).  RLE runs are a
// compare on the payload; bit-packed runs walk the packed bits once.  The
// numpy twin (_count_target_in_runs' gather_bits) was half of config-4's
// host phase at 64 MB.
// ---------------------------------------------------------------------------
extern "C" int64_t pq_count_target_in_runs(
    const uint8_t* body, int64_t body_len, const uint8_t* kinds,
    const int64_t* cnts, const int64_t* payloads, const int64_t* offs,
    int64_t k, int32_t width, int64_t target) {
  if (width <= 0 || width > 32) return -1;
  const uint64_t mask = (width >= 64) ? ~0ull : ((1ull << width) - 1);
  if ((uint64_t)target > mask) return 0;
  int64_t total = 0;
  for (int64_t r = 0; r < k; ++r) {
    if (kinds[r] == 0) {
      if (payloads[r] == target) total += cnts[r];
      continue;
    }
    const int64_t n = cnts[r];
    int64_t bit = offs[r] * 8;
    for (int64_t i = 0; i < n; ++i, bit += width) {
      const int64_t byte0 = bit >> 3;
      const int sh = (int)(bit & 7);
      uint64_t v = load8_clamped(body, body_len, byte0) >> sh;
      if ((v & mask) == (uint64_t)target) ++total;
    }
  }
  return total;
}

// ---------------------------------------------------------------------------
// Fused whole-chunk dictionary-index scan (SURVEY.md §3.1 hot path): one
// native call replaces the per-page Python loop of build_plan for the host
// dict route — per page: decompress (snappy/zstd via dlopen'd system libs,
// the same ones codecs/ uses from Python), verify the def-level stream is
// all-present, and scan the RLE/bit-packed index runs into ONE combined
// chunk-level run table whose byte offsets index the decompressed stream.
// ~400 pages of a 64 MB chunk cost ~40 ms of Python/ctypes dispatch on the
// per-page path; this pass is one call.  Any page this scan can't prove
// simple (nulls, rep levels, non-dict encoding, foreign codec, legacy
// BIT_PACKED levels) bails the WHOLE chunk back to the Python planner,
// which owns the general semantics.
// ---------------------------------------------------------------------------

#include <dlfcn.h>

namespace {

typedef int (*snappy_fn)(const char*, size_t, char*, size_t*);
typedef size_t (*zstd_fn)(void*, size_t, const void*, size_t);
typedef unsigned (*zstd_err_fn)(size_t);

inline void* dl_first(const char* a, const char* b) {
  void* h = dlopen(a, RTLD_NOW);
  return h ? h : dlopen(b, RTLD_NOW);
}

inline snappy_fn get_snappy_uncompress() {
  static snappy_fn fn = [] {
    void* h = dl_first("libsnappy.so.1", "libsnappy.so");
    return h ? (snappy_fn)dlsym(h, "snappy_uncompress") : nullptr;
  }();
  return fn;
}

inline zstd_fn get_zstd_decompress() {
  static zstd_fn fn = [] {
    void* h = dl_first("libzstd.so.1", "libzstd.so");
    return h ? (zstd_fn)dlsym(h, "ZSTD_decompress") : nullptr;
  }();
  return fn;
}

inline zstd_err_fn get_zstd_iserror() {
  static zstd_err_fn fn = [] {
    void* h = dl_first("libzstd.so.1", "libzstd.so");
    return h ? (zstd_err_fn)dlsym(h, "ZSTD_isError") : nullptr;
  }();
  return fn;
}

// ---------------------------------------------------------------------------
// Fast snappy raw-stream decoder.  The dlopen'd system libsnappy measured
// 0.5-0.6 GB/s on match-heavy pages (sorted int64 columns) on this class of
// host; this decoder uses 16-byte blind copies for literals and long-offset
// matches and a stack-staged doubled pattern for short-offset matches (the
// RLE-like case that dominates compressible columns).  Falls back to byte
// loops within 16 bytes of either buffer end, so it never writes past dst
// or reads past src.  Returns false on any malformed input (caller then
// retries with the system library, which owns precise error behavior).
// Format per the public snappy spec: varint uncompressed length, then
// literal/copy tags.
inline bool snappy_fast_uncompress(const uint8_t* src, int64_t src_len,
                                   uint8_t* dst, int64_t dst_len) {
  const uint8_t* sp = src;
  const uint8_t* send = src + src_len;
  uint64_t ulen = 0;
  int shift = 0;
  while (true) {
    if (sp >= send || shift > 28) return false;
    const uint8_t b = *sp++;
    ulen |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  if ((int64_t)ulen != dst_len) return false;
  uint8_t* dp = dst;
  uint8_t* dend = dst + dst_len;
  while (sp < send) {
    const uint8_t tag = *sp++;
    if ((tag & 3) == 0) {  // literal
      int64_t len = (tag >> 2) + 1;
      if (len > 60) {
        const int nb = (int)len - 60;  // 1..4 length bytes
        if (sp + nb > send) return false;
        uint32_t l = 0;
        memcpy(&l, sp, (size_t)nb);
        sp += nb;
        len = (int64_t)l + 1;
      }
      if (len > send - sp || len > dend - dp) return false;
      if (len <= 16 && send - sp >= 16 && dend - dp >= 16) {
        memcpy(dp, sp, 16);  // blind wide copy, bounds pre-checked
      } else {
        memcpy(dp, sp, (size_t)len);
      }
      sp += len;
      dp += len;
      continue;
    }
    int64_t len, off;
    if ((tag & 3) == 1) {  // copy1: 4..11 bytes, 11-bit offset
      if (sp >= send) return false;
      len = ((tag >> 2) & 7) + 4;
      off = ((int64_t)(tag & 0xE0) << 3) | *sp++;
    } else if ((tag & 3) == 2) {  // copy2: 16-bit offset
      if (send - sp < 2) return false;
      uint16_t o;
      memcpy(&o, sp, 2);
      sp += 2;
      len = (tag >> 2) + 1;
      off = o;
    } else {  // copy4: 32-bit offset
      if (send - sp < 4) return false;
      uint32_t o;
      memcpy(&o, sp, 4);
      sp += 4;
      len = (tag >> 2) + 1;
      off = o;
    }
    if (off <= 0 || off > dp - dst || len > dend - dp) return false;
    const uint8_t* cp = dp - off;
    if (off >= 16) {
      if (dend - dp >= len + 16) {  // slack for blind 16-byte strides
        uint8_t* o_ = dp;
        const uint8_t* c_ = cp;
        for (int64_t l = len; l > 0; l -= 16) {
          memcpy(o_, c_, 16);
          o_ += 16;
          c_ += 16;
        }
      } else {
        // no wide slack: forward chunks of `off` bytes — each chunk's
        // source lies fully behind its destination, and later chunks see
        // the bytes earlier ones wrote (the self-referencing semantics)
        int64_t done = 0;
        while (done < len) {
          const int64_t n = off < len - done ? off : len - done;
          memcpy(dp + done, cp + done, (size_t)n);
          done += n;
        }
      }
      dp += len;
      continue;
    }
    // short offset: replicate the pattern to a full 16-byte vector with
    // one pshufb (mask[i] = i % off), then blind 16-byte stores advancing
    // by the largest multiple of off <= 16 so the phase stays aligned
    if (dend - dp >= len + 16) {
#if defined(__SSSE3__)
      static const uint8_t kPatShuf[16][16] = {
          {0}, {0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0},
          {0,1,0,1,0,1,0,1,0,1,0,1,0,1,0,1},
          {0,1,2,0,1,2,0,1,2,0,1,2,0,1,2,0},
          {0,1,2,3,0,1,2,3,0,1,2,3,0,1,2,3},
          {0,1,2,3,4,0,1,2,3,4,0,1,2,3,4,0},
          {0,1,2,3,4,5,0,1,2,3,4,5,0,1,2,3},
          {0,1,2,3,4,5,6,0,1,2,3,4,5,6,0,1},
          {0,1,2,3,4,5,6,7,0,1,2,3,4,5,6,7},
          {0,1,2,3,4,5,6,7,8,0,1,2,3,4,5,6},
          {0,1,2,3,4,5,6,7,8,9,0,1,2,3,4,5},
          {0,1,2,3,4,5,6,7,8,9,10,0,1,2,3,4},
          {0,1,2,3,4,5,6,7,8,9,10,11,0,1,2,3},
          {0,1,2,3,4,5,6,7,8,9,10,11,12,0,1,2},
          {0,1,2,3,4,5,6,7,8,9,10,11,12,13,0,1},
          {0,1,2,3,4,5,6,7,8,9,10,11,12,13,14,0}};
      // cp+16 read is safe: cp = dp - off with off < 16 and dp has >= 16
      // bytes of slack checked above
      const __m128i v = _mm_shuffle_epi8(
          _mm_loadu_si128((const __m128i*)cp),
          _mm_loadu_si128((const __m128i*)kPatShuf[off]));
      const int stride = (16 / (int)off) * (int)off;
      for (int64_t w = 0; w < len; w += stride)
        _mm_storeu_si128((__m128i*)(dp + w), v);
#else
      uint8_t pat[32];
      for (int i = 0; i < (int)off; ++i) pat[i] = cp[i];
      int plen = (int)off;
      while (plen < 16) {
        memcpy(pat + plen, pat, (size_t)plen);  // disjoint within pat
        plen <<= 1;
      }
      const int stride = (16 / (int)off) * (int)off;
      for (int64_t w = 0; w < len; w += stride) memcpy(dp + w, pat, 16);
#endif
      dp += len;
    } else {
      for (int64_t i = 0; i < len; ++i) dp[i] = cp[i];  // overlap-safe tail
      dp += len;
    }
  }
  return dp == dend;
}

// decompress `src` into `dst` (exactly dst_len bytes expected). codec is the
// parquet CompressionCodec id: 0 UNCOMPRESSED, 1 SNAPPY, 6 ZSTD.
inline bool page_decompress(int codec, const uint8_t* src, int64_t src_len,
                            uint8_t* dst, int64_t dst_len) {
  if (codec == 0) {
    if (src_len != dst_len) return false;
    std::memcpy(dst, src, (size_t)src_len);
    return true;
  }
  if (codec == 1) {
    if (snappy_fast_uncompress(src, src_len, dst, dst_len)) return true;
    // fast decoder refuses malformed streams; the system library settles
    // whether the input is genuinely bad (and owns exotic cases)
    snappy_fn fn = get_snappy_uncompress();
    if (!fn) return false;
    size_t out_len = (size_t)dst_len;
    if (fn((const char*)src, (size_t)src_len, (char*)dst, &out_len) != 0)
      return false;
    return (int64_t)out_len == dst_len;
  }
  if (codec == 6) {
    zstd_fn fn = get_zstd_decompress();
    zstd_err_fn err = get_zstd_iserror();
    if (!fn || !err) return false;
    size_t r = fn(dst, (size_t)dst_len, src, (size_t)src_len);
    if (err(r)) return false;
    return (int64_t)r == dst_len;
  }
  return false;
}

inline int level_bit_width(int32_t max_level) {
  int w = 0;
  while ((1 << w) - 1 < max_level) ++w;
  return w;
}

// Parse a def-level RLE stream and require it to be a single RLE run of
// `max_def` covering >= nvals values (the all-present page). Returns false
// for anything else (caller bails the chunk).
inline bool def_stream_all_present(const uint8_t* p, int64_t len,
                                   int64_t nvals, int32_t max_def) {
  int w = level_bit_width(max_def);
  int64_t pos = 0;
  uint64_t header = 0;
  int shift = 0;
  while (true) {
    if (pos >= len) return false;
    uint8_t b = p[pos++];
    header |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 63) return false;
  }
  if (header & 1) return false;  // bit-packed run: not the all-present shape
  int64_t count = (int64_t)(header >> 1);
  if (count < nvals) return false;
  const int vbytes = (w + 7) / 8;
  if (pos + vbytes > len) return false;
  uint64_t value = 0;
  for (int j = 0; j < vbytes; ++j) value |= (uint64_t)p[pos + j] << (8 * j);
  if (w < 64) value &= (1ull << w) - 1;
  return (int64_t)value == (int64_t)max_def;
}

struct DictPageScan {
  int64_t nvals = 0;     // data values in this page
  int64_t run_base = 0;  // first run slot in the shared output arrays
  int64_t nruns = 0;     // runs written
  int64_t out_base = 0;  // page body base in out_bytes
  int ok = 1;            // 0 = bail the chunk
};

}  // namespace

extern "C" {

// Returns total run count (>= 0), or a bail code: -1 malformed, -2 page
// shape outside the fused fast path (caller falls back to the Python
// planner), -3 insufficient capacity.  out_info = {nvals_total, bytes_used}.
// `pages` rows use the pq_scan_page_headers layout (PG_* columns).
int64_t pq_dict_chunk_scan(const uint8_t* chunk, int64_t chunk_len,
                           const int64_t* pages, int64_t n_pages,
                           int32_t codec, int32_t max_def, int32_t max_rep,
                           uint8_t* out_bytes, int64_t out_cap,
                           int64_t* ends, uint8_t* kinds, int64_t* payloads,
                           int64_t* boffs, int32_t* widths, int64_t run_cap,
                           int64_t* out_info, int32_t nthreads) {
  if (max_rep > 0) return -2;
  if (codec != 0 && codec != 1 && codec != 6) return -2;
  std::vector<DictPageScan> ps((size_t)n_pages);
  // layout pass: per-page output/run bases so the parallel phase is
  // write-disjoint. Run capacity per page = nvals + 1 (every run covers >= 1
  // of the page's values, +1 for the width-0 synthetic run).
  int64_t bytes_total = 0, runs_total_cap = 0, nvals_total = 0;
  for (int64_t i = 0; i < n_pages; ++i) {
    const int64_t* row = pages + i * PG_NFIELDS;
    const int64_t pt = row[PG_TYPE];
    DictPageScan& s = ps[(size_t)i];
    if (pt != 0 && pt != 3) continue;  // dict page handled by caller
    const int64_t enc = row[PG_ENC];
    if (enc != 2 && enc != 8) return -2;  // not PLAIN_/RLE_DICTIONARY
    if (pt == 0 && max_def > 0 && row[PG_DEF_ENC] != 3) return -2;  // legacy
    if (pt == 3 && max_def > 0 && row[PG_NNULLS] != 0) return -2;
    s.nvals = row[PG_NVALS];
    if (s.nvals < 0) return -1;
    s.out_base = bytes_total;
    s.run_base = runs_total_cap;
    int64_t body_uncomp = row[PG_UNCOMP];
    if (pt == 3) {
      const int64_t rl = row[PG_RL_BYTES] < 0 ? 0 : row[PG_RL_BYTES];
      const int64_t dl = row[PG_DL_BYTES] < 0 ? 0 : row[PG_DL_BYTES];
      body_uncomp -= rl + dl;
    }
    if (body_uncomp < 0) return -1;
    bytes_total += body_uncomp;
    runs_total_cap += s.nvals + 1;
    nvals_total += s.nvals;
  }
  if (bytes_total > out_cap || runs_total_cap > run_cap) return -3;

  std::atomic<bool> bail{false};
  auto scan_page_impl = [&](int64_t i) -> bool {
    const int64_t* row = pages + i * PG_NFIELDS;
    const int64_t pt = row[PG_TYPE];
    DictPageScan& s = ps[(size_t)i];
    if (pt != 0 && pt != 3) return true;  // dict page handled by caller
    const int64_t dpos = row[PG_DATA_POS];
    const int64_t clen = row[PG_COMP];
    if (dpos < 0 || clen < 0 || dpos + clen > chunk_len) return false;
    const uint8_t* payload = chunk + dpos;
    uint8_t* body = out_bytes + s.out_base;
    int64_t body_len;
    int64_t pos = 0;  // index-section start within body
    if (pt == 0) {
      body_len = row[PG_UNCOMP];
      if (!page_decompress(codec, payload, clen, body, body_len))
        return false;
      if (max_def > 0) {
        if (pos + 4 > body_len) return false;
        uint32_t dl;
        std::memcpy(&dl, body + pos, 4);
        if (pos + 4 + (int64_t)dl > body_len) return false;
        if (!def_stream_all_present(body + pos + 4, dl, s.nvals, max_def))
          return false;
        pos += 4 + dl;
      }
    } else {  // v2: levels sit uncompressed ahead of the body
      const int64_t rl = row[PG_RL_BYTES] < 0 ? 0 : row[PG_RL_BYTES];
      const int64_t dl = row[PG_DL_BYTES] < 0 ? 0 : row[PG_DL_BYTES];
      if (rl + dl > clen) return false;
      body_len = row[PG_UNCOMP] - rl - dl;
      const int page_codec = row[PG_IS_COMPRESSED] == 0 ? 0 : codec;
      if (!page_decompress(page_codec, payload + rl + dl, clen - rl - dl,
                           body, body_len))
        return false;
    }
    if (s.nvals == 0) { s.nruns = 0; return true; }
    if (pos >= body_len) return false;
    const int w = body[pos];
    ++pos;
    uint8_t* pk = kinds + s.run_base;
    int64_t* pp = payloads + s.run_base;
    int64_t* pb = boffs + s.run_base;
    int32_t* pw = widths + s.run_base;
    int64_t* pe = ends + s.run_base;  // holds per-run COUNTS until merge
    if (w == 0) {  // single-entry dictionary: one synthetic RLE run
      pk[0] = 0;
      pp[0] = 0;
      pb[0] = s.out_base;
      pw[0] = 1;
      pe[0] = s.nvals;
      s.nruns = 1;
      return true;
    }
    if (w > 32) return false;
    int64_t k = pq_scan_rle_runs(body + pos, body_len - pos, s.nvals, w, pk,
                                 pe, pp, pb);
    if (k < 0 || k > s.nvals + 1) return false;
    for (int64_t r = 0; r < k; ++r) {
      pb[r] += s.out_base + pos;  // relative -> absolute in out_bytes
      pw[r] = w;
    }
    s.nruns = k;
    return true;
  };
  // a single failed page bails the WHOLE chunk to the Python planner, so
  // stop decompressing remaining pages as soon as any worker fails
  auto scan_page = [&](int64_t i) {
    if (bail.load(std::memory_order_relaxed)) return;
    if (!scan_page_impl(i)) {
      ps[(size_t)i].ok = 0;
      bail.store(true, std::memory_order_relaxed);
    }
  };

  int T = nthreads;
  if (T < 1) T = 1;
  if (T > 16) T = 16;
  if ((int64_t)T > n_pages) T = (int)n_pages ? (int)n_pages : 1;
  if (T <= 1) {
    for (int64_t i = 0; i < n_pages; ++i) scan_page(i);
  } else {
    std::vector<std::thread> threads;
    std::atomic<int64_t> next{0};
    auto worker = [&] {
      int64_t i;
      while ((i = next.fetch_add(1)) < n_pages) scan_page(i);
    };
    for (int t = 1; t < T; ++t) threads.emplace_back(worker);
    worker();
    for (auto& th : threads) th.join();
  }
  for (int64_t i = 0; i < n_pages; ++i)
    if (!ps[(size_t)i].ok) return -2;

  // merge: compact the per-page run slices down to a contiguous table and
  // turn per-run counts into cumulative ends.
  int64_t nruns = 0, total = 0;
  for (int64_t i = 0; i < n_pages; ++i) {
    const DictPageScan& s = ps[(size_t)i];
    if (!s.nruns) continue;
    if (nruns != s.run_base) {
      std::memmove(kinds + nruns, kinds + s.run_base, (size_t)s.nruns);
      std::memmove(payloads + nruns, payloads + s.run_base,
                   (size_t)s.nruns * 8);
      std::memmove(boffs + nruns, boffs + s.run_base, (size_t)s.nruns * 8);
      std::memmove(widths + nruns, widths + s.run_base, (size_t)s.nruns * 4);
      std::memmove(ends + nruns, ends + s.run_base, (size_t)s.nruns * 8);
    }
    for (int64_t r = 0; r < s.nruns; ++r) {
      total += ends[nruns + r];
      ends[nruns + r] = total;
    }
    nruns += s.nruns;
  }
  if (total != nvals_total) return -1;
  out_info[0] = nvals_total;
  out_info[1] = bytes_total;
  return nruns;
}

// ---------------------------------------------------------------------------
// Batched PLAIN BYTE_ARRAY parse: many pages' 4-byte-length-prefixed
// string sections → ONE chunk-level (values, offsets) pair, offsets
// already rebased to the concatenated output.  Replaces a size pass + a
// copy pass per page plus a python offsets merge.  offsets_out needs
// sum(counts)+1 slots; values_out capacity >= sum(src_lens) (the
// prefixed form is strictly larger than the raw bytes).  Returns total
// value bytes, or -(page+1) for the first truncated page.
// ---------------------------------------------------------------------------
extern "C" int64_t pq_plain_ba_batch(
    const int64_t* src_ptrs, const int64_t* src_lens, const int64_t* counts,
    int64_t n_pages, int64_t* offsets_out, uint8_t* values_out) {
  int64_t base = 0;
  int64_t oi = 0;
  offsets_out[oi++] = 0;
  for (int64_t p = 0; p < n_pages; ++p) {
    const uint8_t* src = (const uint8_t*)(uintptr_t)src_ptrs[p];
    const int64_t len = src_lens[p];
    int64_t pos = 0;
    const int64_t cnt = counts[p];
    for (int64_t i = 0; i < cnt; ++i) {
      if (pos + 4 > len) return -(p + 1);
      uint32_t l;
      memcpy(&l, src + pos, 4);
      pos += 4;
      if ((int64_t)l > len - pos) return -(p + 1);
      memcpy(values_out + base, src + pos, l);
      base += l;
      pos += l;
      offsets_out[oi++] = base;
    }
  }
  return base;
}

// ---------------------------------------------------------------------------
// Batched RLE_DICTIONARY index decode: one native call per chunk replaces a
// Python scan/expand/astype round-trip per page (~0.3 ms each; a 4M-row
// dictionary string chunk has ~200 pages).  Per page: an optional
// length-prefixed def-level stream that must be ONE RLE run of 1s covering
// the page (all-present; anything else returns the page for the Python
// fallback), then [1-byte bit width][hybrid RLE/bit-packed indices].
// has_prefix[p]: 1 = v1 optional page (parse the prefix), 0 = the body
// starts at the bit-width byte (required columns, or v2 pages whose levels
// live outside the body).  Output int32 indices, concatenated.
// Returns total values written, or -(p+1) for the first failing page.
// ---------------------------------------------------------------------------
extern "C" int64_t pq_rle_dict_batch(
    const int64_t* src_ptrs, const int64_t* src_lens, const int64_t* counts,
    const uint8_t* has_prefix, int64_t n_pages, int32_t* out) {
  int64_t base = 0;
  for (int64_t p = 0; p < n_pages; ++p) {
    const uint8_t* d = (const uint8_t*)(uintptr_t)src_ptrs[p];
    const int64_t len = src_lens[p];
    const int64_t cnt = counts[p];
    int64_t pos = 0;
    if (has_prefix[p]) {
      if (pos + 4 > len) return -(p + 1);
      uint32_t dl;
      memcpy(&dl, d + pos, 4);
      pos += 4;
      const int64_t dend = pos + (int64_t)dl;
      if (dend > len) return -(p + 1);
      // single RLE run of value 1 covering every slot, else fallback
      uint64_t h = 0;
      int shift = 0;
      int64_t q = pos;
      while (true) {
        if (q >= dend || shift > 56) return -(p + 1);
        const uint8_t b = d[q++];
        h |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
      }
      if ((h & 1) != 0) return -(p + 1);          // bit-packed def levels
      if ((int64_t)(h >> 1) < cnt) return -(p + 1);  // short run
      if (q >= dend || d[q] != 1) return -(p + 1);   // has nulls
      pos = dend;
    }
    if (pos >= len) return -(p + 1);
    const int w = d[pos++];
    int32_t* o = out + base;
    if (w == 0) {
      for (int64_t i = 0; i < cnt; ++i) o[i] = 0;
      base += cnt;
      continue;
    }
    if (w > 31) return -(p + 1);
    const uint32_t mask = (w == 32) ? 0xFFFFFFFFu : ((1u << w) - 1);
    int64_t got = 0;
    while (got < cnt) {
      // uvarint run header
      uint64_t h = 0;
      int shift = 0;
      while (true) {
        if (pos >= len || shift > 56) return -(p + 1);
        const uint8_t b = d[pos++];
        h |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
      }
      if (h & 1) {  // bit-packed: (h>>1) groups of 8 values, w bits each
        const int64_t n_grp = (int64_t)(h >> 1);
        // cap BEFORE multiplying: a crafted 9-byte varint makes n_grp*w
        // overflow int64 and bypass the bounds check (negative-size memcpy)
        if (n_grp <= 0 || n_grp > (len - pos) / w) return -(p + 1);
        const int64_t nbytes = n_grp * w;  // 8 values * w bits = w bytes/grp
        int64_t take = n_grp * 8;
        if (take > cnt - got) take = cnt - got;  // final group may pad
        const uint8_t* bp = d + pos;
        int64_t i = 0;
        // fast path: full 8-byte window loads while they stay in bounds
        // (condition: bit + 64 <= nbytes*8, i.e. bit <= (nbytes-8)*8)
        const int64_t safe = (nbytes >= 8) ? (nbytes - 8) * 8 : -1;
        for (; i < take && i * w <= safe; ++i) {
          const int64_t bit = i * w;
          uint64_t word;
          memcpy(&word, bp + (bit >> 3), 8);
          o[got + i] = (int32_t)((uint32_t)(word >> (bit & 7)) & mask);
        }
        for (; i < take; ++i) {  // tail: byte-at-a-time masked load
          const int64_t bit = i * w;
          uint64_t word = 0;
          const int64_t k0 = bit >> 3;
          const int64_t nb = nbytes - k0 < 8 ? nbytes - k0 : 8;
          memcpy(&word, bp + k0, (size_t)nb);
          o[got + i] = (int32_t)((uint32_t)(word >> (bit & 7)) & mask);
        }
        got += take;
        pos += nbytes;
      } else {  // RLE run: (h>>1) copies of a ((w+7)/8)-byte LE value
        int64_t run = (int64_t)(h >> 1);
        const int vb = (w + 7) / 8;
        if (pos + vb > len) return -(p + 1);
        uint32_t v = 0;
        memcpy(&v, d + pos, (size_t)vb);
        v &= mask;
        pos += vb;
        if (run > cnt - got) run = cnt - got;
        for (int64_t i = 0; i < run; ++i) o[got + i] = (int32_t)v;
        got += run;
      }
    }
    base += cnt;
  }
  return base;
}

// ---------------------------------------------------------------------------
// Batched page decompression: one native call replaces a Python/ctypes
// codec round-trip per page (~0.1 ms each; the 2.7 GB lineitem file has
// ~6,400 pages, where the per-page overhead was the read path's single
// largest cost).  Per-page SOURCE POINTERS so any payload layout works
// (whole-chunk zero-copy views, streamed windows).  Output spans are
// caller-laid-out in one buffer via out_offs.  Threaded across pages.
// Codec ids as page_decompress: 0 UNCOMPRESSED, 1 SNAPPY, 6 ZSTD.
// Returns 0, or -(i+1) for the first failing page.
// ---------------------------------------------------------------------------
extern "C" int64_t pq_decompress_pages(
    const int64_t* src_ptrs, const int64_t* src_lens, int64_t n_pages,
    int32_t codec, uint8_t* out, const int64_t* out_offs, int32_t nthreads) {
  if (n_pages <= 0) return 0;
  std::atomic<int64_t> fail{0};
  auto run = [&](int t, int T) {
    for (int64_t i = t; i < n_pages; i += T) {
      if (!page_decompress(codec, (const uint8_t*)(uintptr_t)src_ptrs[i],
                           src_lens[i], out + out_offs[i],
                           out_offs[i + 1] - out_offs[i])) {
        int64_t cur = 0;
        fail.compare_exchange_strong(cur, -(i + 1));
      }
    }
  };
  int T = nthreads > 0 ? nthreads : 1;
  if ((int64_t)T > n_pages) T = (int)n_pages;
  if (T <= 1) {
    run(0, 1);
  } else {
    std::vector<std::thread> threads;
    threads.reserve((size_t)(T - 1));
    for (int t = 1; t < T; ++t) threads.emplace_back(run, t, T);
    run(0, T);
    for (auto& th : threads) th.join();
  }
  return fail.load();
}

}  // extern "C"
