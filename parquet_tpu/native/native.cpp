// Host-side native kernels (C++), loaded via ctypes.
//
// Reference parity: the reference backs its sequential host loops with amd64
// assembly + unsafe Go (SURVEY.md §2.3: encoding/plain BYTE_ARRAY scan,
// encoding/rle run parsing, bloom/xxhash, hashprobe dictionary dedup,
// encoding/delta byte-array prefix reconstruction).  These are exactly the
// loops that cannot vectorize onto TPU lanes (data-dependent byte walks), so
// they get native host code here; everything data-parallel lives in the
// XLA/Pallas kernels instead.
//
// Build: parquet_tpu/native/build.py → _native.so (g++ -O3).  Pure C ABI —
// no pybind11 (not in this image); numpy arrays cross as raw pointers.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// PLAIN BYTE_ARRAY: walk [4B LE length][bytes]... building offsets, and
// optionally compacting the value bytes (prefixes stripped) into out_values.
// Returns total value bytes, or -1 on truncation.
// ---------------------------------------------------------------------------
int64_t pq_plain_byte_array(const uint8_t* data, int64_t size, int64_t n,
                            int64_t* offsets /* n+1 */,
                            uint8_t* out_values /* may be null */) {
  int64_t pos = 0;
  int64_t total = 0;
  offsets[0] = 0;
  for (int64_t i = 0; i < n; i++) {
    if (pos + 4 > size) return -1;
    uint32_t len;
    std::memcpy(&len, data + pos, 4);
    pos += 4;
    if (pos + (int64_t)len > size) return -1;
    if (out_values) std::memcpy(out_values + total, data + pos, len);
    pos += len;
    total += len;
    offsets[i + 1] = total;
  }
  return total;
}

// ---------------------------------------------------------------------------
// Expand a merged run table (host twin of the device rle_expand kernel, used
// for nested-column level streams that the host record assembler consumes).
// Runs tile the output contiguously: run i covers [ends[i-1], ends[i]).
// Returns values written.
// ---------------------------------------------------------------------------
int64_t pq_expand_runs(const uint8_t* buf, int64_t buf_len, const int64_t* ends,
                       const uint8_t* kinds, const int64_t* payloads,
                       const int64_t* bit_offsets, const int32_t* widths,
                       int64_t nruns, int32_t* out, int64_t n) {
  int64_t pos = 0;
  for (int64_t i = 0; i < nruns && pos < n; ++i) {
    int64_t cnt = ends[i] - pos;
    if (cnt > n - pos) cnt = n - pos;
    if (cnt <= 0) continue;
    if (kinds[i] == 0) {
      const int32_t v = (int32_t)payloads[i];
      for (int64_t j = 0; j < cnt; ++j) out[pos + j] = v;
    } else {
      const int32_t w = widths[i];
      const uint64_t mask = (w >= 64) ? ~0ull : ((1ull << w) - 1);
      int64_t bit = bit_offsets[i];
      for (int64_t j = 0; j < cnt; ++j) {
        const int64_t byte0 = bit >> 3;
        uint64_t word = 0;
        if (byte0 + 8 <= buf_len) {
          std::memcpy(&word, buf + byte0, 8);
        } else {
          for (int b = 0; b < 8 && byte0 + b < buf_len; ++b)
            word |= (uint64_t)buf[byte0 + b] << (8 * b);
        }
        out[pos + j] = (int32_t)((word >> (bit & 7)) & mask);
        bit += w;
      }
    }
    pos += cnt;
  }
  return pos;
}

// ---------------------------------------------------------------------------
// Dremel record assembly: def/rep level streams → per-repeated-level
// (offsets, validity) + leaf validity, single pass per level.
// ks/dks: rep and def level of each repeated ancestor, outermost first.
// offsets_flat: nlev*(n+1) i64; valid_flat: nlev*n u8; inst_counts: nlev i64.
// leaf_valid: n u8.  Returns leaf element count.
// ---------------------------------------------------------------------------
int64_t pq_assemble_levels(const int32_t* defs, const int32_t* reps, int64_t n,
                           const int32_t* ks, const int32_t* dks, int32_t nlev,
                           int32_t max_def, int64_t* offsets_flat,
                           uint8_t* valid_flat, int64_t* inst_counts,
                           uint8_t* leaf_valid) {
  for (int32_t i = 0; i < nlev; ++i) {
    const int32_t k = ks[i], dk = dks[i];
    const int32_t dprev = (i > 0) ? dks[i - 1] : INT32_MIN;
    const int32_t knext = (i + 1 < nlev) ? ks[i + 1] : INT32_MAX;
    int64_t* offs = offsets_flat + (int64_t)i * (n + 1);
    uint8_t* val = valid_flat + (int64_t)i * n;
    int64_t ninst = 0, elems = 0;
    // branchless: always store at the cursor, advance conditionally (stale
    // stores are overwritten by the next instance / the final sentinel)
    for (int64_t j = 0; j < n; ++j) {
      const int32_t dj = defs[j], rj = reps[j];
      offs[ninst] = elems;
      val[ninst] = dj >= dk - 1;
      ninst += (rj < k) & (dj >= dprev);
      elems += (rj < knext) & (dj >= dk);
    }
    offs[ninst] = elems;
    inst_counts[i] = ninst;
  }
  const int32_t dr = dks[nlev - 1];
  int64_t cnt = 0;
  for (int64_t j = 0; j < n; ++j) {
    const int32_t dj = defs[j];
    leaf_valid[cnt] = dj == max_def;
    cnt += dj >= dr;
  }
  return cnt;
}

// ---------------------------------------------------------------------------
// RLE/bit-packed hybrid run scan (the host half of the two-pass split).
// Outputs one row per run; returns run count, or -1 on malformed input.
// Caller sizes outputs to n (a run covers >= 1 value).
// ---------------------------------------------------------------------------
int64_t pq_scan_rle_runs(const uint8_t* data, int64_t size, int64_t n,
                         int32_t bit_width, uint8_t* kinds, int64_t* counts,
                         int64_t* payloads, int64_t* byte_offsets) {
  int64_t pos = 0;
  int64_t remaining = n;
  int64_t k = 0;
  const int vbytes = (bit_width + 7) / 8;
  while (remaining > 0) {
    // uvarint header
    uint64_t header = 0;
    int shift = 0;
    while (true) {
      if (pos >= size) return -1;
      uint8_t b = data[pos++];
      header |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) return -1;
    }
    if (header & 1) {
      int64_t ngroups = (int64_t)(header >> 1);
      int64_t count = ngroups * 8;
      kinds[k] = 1;
      counts[k] = count < remaining ? count : remaining;
      payloads[k] = 0;
      byte_offsets[k] = pos;
      pos += ngroups * bit_width;
      if (pos > size) return -1;
      remaining -= count;
    } else {
      int64_t count = (int64_t)(header >> 1);
      if (pos + vbytes > size) return -1;
      uint64_t value = 0;
      for (int j = 0; j < vbytes; j++) value |= (uint64_t)data[pos + j] << (8 * j);
      pos += vbytes;
      kinds[k] = 0;
      counts[k] = count < remaining ? count : remaining;
      payloads[k] = (int64_t)value;
      byte_offsets[k] = pos;
      remaining -= count;
    }
    k++;
  }
  return k;
}

// ---------------------------------------------------------------------------
// xxhash64 (bloom filter hashing; spec-mandated XXH64 seed 0)
// ---------------------------------------------------------------------------
static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

uint64_t pq_xxh64(const uint8_t* p, int64_t len, uint64_t seed) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    do {
      uint64_t k;
      std::memcpy(&k, p, 8); v1 = rotl64(v1 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v2 = rotl64(v2 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v3 = rotl64(v3 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v4 = rotl64(v4 + k * P2, 31) * P1; p += 8;
    } while (p + 32 <= end);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = (h ^ (rotl64(v1 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v2 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v3 * P2, 31) * P1)) * P1 + P4;
    h = (h ^ (rotl64(v4 * P2, 31) * P1)) * P1 + P4;
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    uint64_t k;
    std::memcpy(&k, p, 8);
    h ^= rotl64(k * P2, 31) * P1;
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t k;
    std::memcpy(&k, p, 4);
    h ^= (uint64_t)k * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (uint64_t)(*p++) * P5;
    h = rotl64(h, 11) * P1;
  }
  h ^= h >> 33; h *= P2; h ^= h >> 29; h *= P3; h ^= h >> 32;
  return h;
}

void pq_xxh64_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                    uint64_t* out) {
  for (int64_t i = 0; i < n; i++)
    out[i] = pq_xxh64(data + offsets[i], offsets[i + 1] - offsets[i], 0);
}

// ---------------------------------------------------------------------------
// DELTA_BYTE_ARRAY reconstruction: values[i] = values[i-1][:prefix[i]] + suffix[i]
// (the inherently sequential front-coding chain — SURVEY.md §2.2)
// ---------------------------------------------------------------------------
int64_t pq_delta_byte_array_expand(const int64_t* prefix_lens,
                                   const uint8_t* suffix_data,
                                   const int64_t* suffix_offsets, int64_t n,
                                   uint8_t* out_values,
                                   const int64_t* out_offsets) {
  int64_t prev = 0;
  for (int64_t i = 0; i < n; i++) {
    const int64_t o = out_offsets[i];
    const int64_t pl = prefix_lens[i];
    const int64_t sl = suffix_offsets[i + 1] - suffix_offsets[i];
    if (pl > 0) std::memmove(out_values + o, out_values + prev, pl);
    if (sl > 0) std::memcpy(out_values + o + pl, suffix_data + suffix_offsets[i], sl);
    prev = o;
  }
  return n ? out_offsets[n] : 0;
}

// ---------------------------------------------------------------------------
// Byte-array dictionary build (hashprobe analog): dedup via hash map.
// Returns unique count; fills indices[n] and, when out_* non-null, the
// unique strings compacted in first-seen order.
// ---------------------------------------------------------------------------
struct DictState {
  std::unordered_map<std::string, int64_t> map;
  std::vector<std::string> uniques;
};

int64_t pq_dict_build_ba(const uint8_t* data, const int64_t* offsets,
                         int64_t n, int64_t* indices, int64_t max_unique) {
  std::unordered_map<std::string, int64_t> map;
  map.reserve((size_t)(n / 4 + 8));
  int64_t next = 0;
  for (int64_t i = 0; i < n; i++) {
    std::string key((const char*)data + offsets[i],
                    (size_t)(offsets[i + 1] - offsets[i]));
    auto it = map.find(key);
    if (it == map.end()) {
      if (next >= max_unique) return -(i + 1);  // cardinality blew the limit
      it = map.emplace(std::move(key), next++).first;
    }
    indices[i] = it->second;
  }
  return next;
}

// second pass: caller uses indices to materialize uniques (first occurrence)
// min/max over a span of length-prefixed byte strings (unsigned
// lexicographic — BYTE_ARRAY's order domain).  Writes the min and max VALUE
// indexes; used by per-page statistics so the hot write path never
// materializes python bytes objects.
void pq_minmax_ba(const uint8_t* data, const int64_t* offsets, int64_t v0,
                  int64_t v1, int64_t* out_min, int64_t* out_max) {
  int64_t mi = v0, ma = v0;
  for (int64_t i = v0 + 1; i < v1; i++) {
    const uint8_t* a = data + offsets[i];
    int64_t alen = offsets[i + 1] - offsets[i];
    const uint8_t* m = data + offsets[mi];
    int64_t mlen = offsets[mi + 1] - offsets[mi];
    int cmp = memcmp(a, m, alen < mlen ? alen : mlen);
    if (cmp < 0 || (cmp == 0 && alen < mlen)) mi = i;
    const uint8_t* x = data + offsets[ma];
    int64_t xlen = offsets[ma + 1] - offsets[ma];
    cmp = memcmp(a, x, alen < xlen ? alen : xlen);
    if (cmp > 0 || (cmp == 0 && alen > xlen)) ma = i;
  }
  *out_min = mi;
  *out_max = ma;
}

void pq_dict_first_occurrence(const int64_t* indices, int64_t n,
                              int64_t n_unique, int64_t* first_idx) {
  for (int64_t u = 0; u < n_unique; u++) first_idx[u] = -1;
  for (int64_t i = 0; i < n; i++)
    if (first_idx[indices[i]] < 0) first_idx[indices[i]] = i;
}

// ---------------------------------------------------------------------------
// Hadoop-framed LZ4 / generic frame walker is python-side; CRC32 via zlib.
// ---------------------------------------------------------------------------

}  // extern "C"
