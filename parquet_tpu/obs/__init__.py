"""Unified telemetry: the observability subsystem every layer reports
through.

- :mod:`parquet_tpu.obs.metrics` — process-wide registry of counters,
  gauges, and fixed-bucket latency histograms (p50/p95/p99); the six
  legacy per-operation stats dataclasses (``ReadStats``, ``WriteStats``,
  ``CacheStats``, ``ReadReport``, planner counters, ``RouteHistory``)
  keep their APIs and publish here too.
- :mod:`parquet_tpu.obs.trace` — span tracing with a module-level bool
  gate (near-zero overhead off) writing Chrome trace-event JSON for
  Perfetto; ``PARQUET_TPU_TRACE=/path.json`` enables per process.
- :mod:`parquet_tpu.obs.export` — Prometheus text-format rendering
  (``python -m parquet_tpu stats --prom``) and the live scrape endpoint
  (``start_metrics_server`` / ``stats --serve PORT``).
- :mod:`parquet_tpu.obs.ledger` — the process-wide resource ledger:
  every byte-holding tier keeps a named account current at its own
  mutation sites (``ledger.*`` gauges), with soft/hard memory-pressure
  watermarks (``PARQUET_TPU_MEM_SOFT``/``HARD``) that shrink the LRU
  tiers and gate admissions, and the ``/debugz`` live-residency
  endpoint on the metrics server.
- :mod:`parquet_tpu.obs.scope` — request-scoped telemetry:
  ``op_scope(name)`` gives every operation its own identity (per-op
  ``OpReport`` attribution across shared-pool workers, per-request
  Perfetto tracks), with 1-in-N head sampling
  (``PARQUET_TPU_TRACE_SAMPLE``) and slow-op tail capture
  (``PARQUET_TPU_SLOW_OP_S`` / ``PARQUET_TPU_SLOW_LOG``).
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, REGISTRY,
                      counter, gauge, histogram, metrics_delta,
                      metrics_snapshot, pool_wait_seconds, reset_metrics)
# NOTE: the live gate is ``trace.TRACE_ENABLED`` on the MODULE —
# instrumentation sites import the module and read the attribute each
# time (a re-exported copy of the bool would go stale on enable/disable)
from . import trace
from .trace import (NULL_SPAN, disable_tracing, enable_tracing, enabled,
                    flush_trace, reset_trace, span, trace_events,
                    trace_span)
from .export import (MetricsServer, debugz_snapshot, render_prometheus,
                     start_metrics_server)
from . import ledger
from .ledger import (LEDGER, ResourceLedger, ledger_account,
                     ledger_snapshot)
from . import scope
from .scope import OpScope, current_op, live_ops, maybe_op_scope, op_scope

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "metrics_delta",
           "metrics_snapshot", "pool_wait_seconds", "reset_metrics",
           "NULL_SPAN", "trace", "disable_tracing", "enable_tracing",
           "enabled", "flush_trace", "reset_trace", "span", "trace_events",
           "trace_span", "render_prometheus", "MetricsServer",
           "start_metrics_server", "debugz_snapshot", "ledger", "LEDGER",
           "ResourceLedger", "ledger_account", "ledger_snapshot", "scope",
           "OpScope", "current_op", "live_ops", "maybe_op_scope",
           "op_scope"]
