"""Prometheus text-format rendering of the metrics registry.

``python -m parquet_tpu stats --prom`` (and any embedding application
that wants to serve a ``/metrics`` endpoint) renders through here.  The
output follows the Prometheus exposition format 0.0.4:

- metric names are ``parquet_tpu_`` + the registry name with dots
  mapped to underscores; counters get the ``_total`` suffix;
- one ``# HELP`` / ``# TYPE`` pair per family (label variants share it);
- histograms render the standard cumulative ``_bucket{le="..."}`` series
  plus ``_sum`` and ``_count``.
"""

from __future__ import annotations

import math
import re
from typing import Optional

from .metrics import Counter, Gauge, Histogram, MetricsRegistry, REGISTRY

__all__ = ["render_prometheus"]

_PREFIX = "parquet_tpu_"
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _PREFIX + _BAD_CHARS.sub("_", name.replace(".", "_"))


def _prom_value(v) -> str:
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if math.isnan(v):
            return "NaN"
        return repr(v)
    return str(v)


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels, extra=None) -> str:
    parts = [f'{k}="{_esc(str(v))}"' for k, v in labels]
    if extra:
        parts.extend(f'{k}="{_esc(str(v))}"' for k, v in extra)
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """The whole registry in Prometheus exposition text format."""
    reg = registry if registry is not None else REGISTRY
    lines = []
    seen_headers = set()

    def header(fam: str, help_text: str, typ: str) -> None:
        if fam in seen_headers:
            return
        seen_headers.add(fam)
        lines.append(f"# HELP {fam} {help_text or fam}")
        lines.append(f"# TYPE {fam} {typ}")

    for m in reg.collect():
        if isinstance(m, Counter):
            fam = _prom_name(m.name) + "_total"
            header(fam, m.help, "counter")
            lines.append(f"{fam}{_label_str(m.labels)} "
                         f"{_prom_value(m.value)}")
        elif isinstance(m, Gauge):
            fam = _prom_name(m.name)
            header(fam, m.help, "gauge")
            lines.append(f"{fam}{_label_str(m.labels)} "
                         f"{_prom_value(m.value)}")
        elif isinstance(m, Histogram):
            fam = _prom_name(m.name)
            header(fam, m.help, "histogram")
            for le, cum in m.bucket_counts():
                lines.append(
                    f"{fam}_bucket"
                    f"{_label_str(m.labels, [('le', _prom_value(float(le)))])}"
                    f" {cum}")
            lines.append(f"{fam}_sum{_label_str(m.labels)} "
                         f"{_prom_value(m.sum)}")
            lines.append(f"{fam}_count{_label_str(m.labels)} {m.count}")
    return "\n".join(lines) + "\n"
