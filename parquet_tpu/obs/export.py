"""Prometheus text-format rendering of the metrics registry, and the
live scrape endpoint.

``python -m parquet_tpu stats --prom`` (and any embedding application
that wants to serve a ``/metrics`` endpoint) renders through here.  The
output follows the Prometheus exposition format 0.0.4:

- metric names are ``parquet_tpu_`` + the registry name with dots
  mapped to underscores; counters get the ``_total`` suffix;
- one ``# HELP`` / ``# TYPE`` pair per family (label variants share it);
- histograms render the standard cumulative ``_bucket{le="..."}`` series
  plus ``_sum`` and ``_count``.

:func:`start_metrics_server` makes the registry scrapeable without a CLI
hop: a stdlib ``http.server`` daemon thread serving ``/metrics``
(Prometheus 0.0.4) and ``/metrics.json`` (the ``metrics_snapshot()``
dict) — also reachable as ``python -m parquet_tpu stats --serve PORT``.
"""

from __future__ import annotations

import json
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, REGISTRY,
                      metrics_snapshot)

__all__ = ["render_prometheus", "start_metrics_server", "MetricsServer",
           "debugz_snapshot", "register_debugz_provider",
           "unregister_debugz_provider"]

_PREFIX = "parquet_tpu_"
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _PREFIX + _BAD_CHARS.sub("_", name.replace(".", "_"))


def _prom_value(v) -> str:
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if math.isnan(v):
            return "NaN"
        return repr(v)
    return str(v)


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels, extra=None) -> str:
    parts = [f'{k}="{_esc(str(v))}"' for k, v in labels]
    if extra:
        parts.extend(f'{k}="{_esc(str(v))}"' for k, v in extra)
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """The whole registry in Prometheus exposition text format."""
    reg = registry if registry is not None else REGISTRY
    lines = []
    seen_headers = set()

    def header(fam: str, help_text: str, typ: str) -> None:
        if fam in seen_headers:
            return
        seen_headers.add(fam)
        lines.append(f"# HELP {fam} {help_text or fam}")
        lines.append(f"# TYPE {fam} {typ}")

    for m in reg.collect():
        if isinstance(m, Counter):
            fam = _prom_name(m.name) + "_total"
            header(fam, m.help, "counter")
            lines.append(f"{fam}{_label_str(m.labels)} "
                         f"{_prom_value(m.value)}")
        elif isinstance(m, Gauge):
            fam = _prom_name(m.name)
            header(fam, m.help, "gauge")
            lines.append(f"{fam}{_label_str(m.labels)} "
                         f"{_prom_value(m.value)}")
        elif isinstance(m, Histogram):
            fam = _prom_name(m.name)
            header(fam, m.help, "histogram")
            for le, cum in m.bucket_counts():
                lines.append(
                    f"{fam}_bucket"
                    f"{_label_str(m.labels, [('le', _prom_value(float(le)))])}"
                    f" {cum}")
            lines.append(f"{fam}_sum{_label_str(m.labels)} "
                         f"{_prom_value(m.sum)}")
            lines.append(f"{fam}_count{_label_str(m.labels)} {m.count}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# live introspection
# ---------------------------------------------------------------------------


# extension point: subsystems that only exist in SOME processes (the
# serving daemon's tenant table) register a named section provider so
# /debugz includes them without this module importing them — the same
# lazy-answer contract as the tables/remote sections
_DEBUGZ_PROVIDERS: dict = {}


def register_debugz_provider(name: str, fn) -> None:
    """Add section ``name`` (a zero-arg callable returning a JSONable
    dict) to every future :func:`debugz_snapshot`.  A provider that
    raises renders as an error string — introspection must answer."""
    _DEBUGZ_PROVIDERS[name] = fn


def unregister_debugz_provider(name: str) -> None:
    _DEBUGZ_PROVIDERS.pop(name, None)


def debugz_snapshot(top_n: int = 10) -> dict:
    """The ``/debugz`` payload: live residency of every buffer tier.

    - ``ledger``: per-account resident/capacity/high-water bytes, the
      process total, watermark state and thresholds (obs/ledger.py);
    - ``caches``: per-cache entry/byte counts plus the top-N entries by
      bytes WITH their keys — "which file's chunks are pinning memory"
      answered from a running process;
    - ``admission``: the unified read gate — bytes in flight, queue
      depth (waiters), lifetime blocked-acquire count, high water, and
      the effective budgets;
    - ``pool``: shared-pool width, tasks running, dispatch queue depth;
    - ``ops``: the op-scope table — every currently-open operation with
      its age (a stuck op shows up here long before a timeout fires);
    - ``remote``: per-host circuit-breaker states and failure streaks,
      hedge bytes in flight, and the observed pread-latency EWMA;
    - ``tables``: open :class:`~parquet_tpu.dataset_writer.DatasetWriter`
      instances — pending (buffered) ingest rows/bytes, uncommitted
      flushed parts, committed version.

    Imported lazily: the endpoint must answer even in a process that
    never touched the IO layer (families just render empty)."""
    from ..utils.pool import pool_debug, read_admission
    from .ledger import ledger_snapshot
    from .scope import live_ops

    out = {"ledger": ledger_snapshot(), "pool": pool_debug(),
           "ops": live_ops()}
    try:
        from ..dataset_writer import table_debug

        out["tables"] = table_debug()
    except ImportError:  # pragma: no cover - the package always imports
        out["tables"] = {"writers": []}
    try:
        from ..io.remote import remote_debug

        out["remote"] = remote_debug()
    except ImportError:  # pragma: no cover - the IO layer always imports
        out["remote"] = {}
    adm = read_admission()
    out["admission"] = {
        "in_flight_bytes": adm.in_flight_bytes(),
        "queue_depth": adm.queue_depth(),
        "waits": adm.waits,
        "high_water_bytes": adm.high_water,
        "budget_bytes": {"global": adm.global_budget_bytes(),
                         "lookup": adm.budget_bytes("lookup"),
                         "scan": adm.budget_bytes("scan")},
        "tenants": adm.tenant_debug(),
    }
    try:
        from ..io import cache as _cache

        st = _cache.cache_stats()
        out["caches"] = {
            "chunk": {"entries": st.chunk_entries, "bytes": st.chunk_bytes,
                      "capacity": st.chunk_capacity,
                      "top": _cache.CHUNKS.top_entries(top_n)},
            "page": {"entries": st.page_entries, "bytes": st.page_bytes,
                     "capacity": st.page_capacity,
                     "top": _cache.PAGES.top_entries(top_n)},
            "footer": {"entries": st.footer_entries,
                       "top": _cache.FOOTERS.top_entries(top_n)},
            "neg_lookup": {"bytes": _cache.NEGS.resident_bytes,
                           "capacity": _cache.neg_lookup_cache_bytes(),
                           "top": _cache.NEGS.top_entries(top_n)},
        }
    except ImportError:  # pragma: no cover - the IO layer always imports
        out["caches"] = {}
    for name, fn in list(_DEBUGZ_PROVIDERS.items()):
        try:
            out[name] = fn()
        except Exception as e:  # introspection must answer regardless
            out[name] = {"error": str(e)}
    return out


# ---------------------------------------------------------------------------
# live scrape endpoint
# ---------------------------------------------------------------------------

_PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _MetricsHandler(BaseHTTPRequestHandler):
    """GET-only handler: ``/metrics`` (Prometheus 0.0.4), ``/metrics.json``
    (the ``metrics_snapshot()`` dict), ``/debugz`` (live buffer-tier
    residency, :func:`debugz_snapshot`), ``/healthz`` (liveness + memory
    pressure state: ``ok``/``soft``/``hard``)."""

    server_version = "parquet-tpu-metrics/1.0"

    def do_GET(self):  # noqa: N802 (http.server naming)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics.json", "/metrics/json"):
            body = json.dumps(metrics_snapshot(), sort_keys=True) \
                .encode("utf-8")
            ctype = "application/json"
        elif path in ("/metrics", "/"):
            body = render_prometheus(self.server._registry).encode("utf-8")
            ctype = _PROM_CONTENT_TYPE
        elif path == "/debugz":
            body = json.dumps(debugz_snapshot(), sort_keys=True) \
                .encode("utf-8")
            ctype = "application/json"
        elif path == "/healthz":
            from .ledger import LEDGER

            # liveness + pressure: "ok\n" when under the watermarks (the
            # PR-8 contract unchanged), "soft\n"/"hard\n" when degraded —
            # a fleet health check learns of memory pressure from the
            # same probe it already runs
            body = (LEDGER.state() + "\n").encode("utf-8")
            ctype = "text/plain; charset=utf-8"
        else:
            self.send_error(404, "unknown path (try /metrics)")
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        pass


class MetricsServer:
    """A running scrape endpoint: ``.port``/``.url`` to reach it,
    ``.close()`` to stop it.  Context-manager friendly."""

    def __init__(self, httpd: ThreadingHTTPServer, thread: threading.Thread):
        self._httpd = httpd
        self._thread = thread
        self.host, self.port = httpd.server_address[:2]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def join(self) -> None:
        """Block until the server stops (the CLI's --serve foreground)."""
        self._thread.join()

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_metrics_server(port: int = 0, host: str = "127.0.0.1",
                         registry: Optional[MetricsRegistry] = None
                         ) -> MetricsServer:
    """Serve the metrics registry over HTTP on a daemon thread:
    ``/metrics`` in Prometheus exposition 0.0.4 and ``/metrics.json`` as
    the snapshot dict.  ``port=0`` binds an ephemeral port (read it back
    from the returned server's ``.port``).  Also reachable as
    ``python -m parquet_tpu stats --serve PORT``."""
    httpd = ThreadingHTTPServer((host, port), _MetricsHandler)
    httpd.daemon_threads = True
    httpd._registry = registry if registry is not None else REGISTRY
    thread = threading.Thread(target=httpd.serve_forever,
                              name="pq-metrics-server", daemon=True)
    thread.start()
    return MetricsServer(httpd, thread)
