"""Process-wide resource ledger: one answer to "where is the memory".

Five byte-holding tiers grew up self-accounted — the decoded-chunk LRU,
the page cache, the footer cache, the prefetcher's ring/segment buffers,
and the writer's writeback/pended buffers — plus the admission gate's
in-flight grants and the trace buffer.  Each knew its own residency;
nothing knew the sum.  This module is the shared balance sheet:

- Every tier registers a named :class:`Account` (``cache.chunk``,
  ``cache.page``, ``cache.footer``, ``cache.neg_lookup``,
  ``prefetch.ring``, ``prefetch.segments``, ``write.buffer``,
  ``write.pended``, ``admission.in_flight``, ``trace.buffer``) and keeps
  it current AT THE MUTATION SITE — inside the same critical section that
  moves the tier's own bytes, so the ledger can never drift from the
  tier (the hammer test asserts exact equality under 8-worker churn).
- Accounts publish as ``ledger.resident_bytes{account=...}`` /
  ``ledger.high_water_bytes{...}`` / ``ledger.capacity_bytes{...}``
  gauges in the metrics registry, so ``stats --prom`` and
  ``/metrics.json`` answer per-tier residency without importing any
  tier, and ``/debugz`` (obs/export.py) renders the live table.
- **Pressure watermarks** (``PARQUET_TPU_MEM_SOFT`` /
  ``PARQUET_TPU_MEM_HARD``, bytes, default off): when the ledger total
  crosses the soft watermark, the registered reclaimers (the LRU cache
  tiers) shrink — evict-to-fraction, metered as
  ``ledger.pressure_evictions`` — until the total is back under; at the
  hard watermark the admission gate (utils/pool.py) additionally blocks
  new read admissions until the total drops.  Every state transition
  increments ``ledger.pressure_transitions{state=...}`` and, with
  tracing on, lands a ``ledger.pressure`` span so Perfetto shows exactly
  when and why the process degraded.

The ledger changes no bytes itself: pressure responses evict caches and
delay admissions, both of which are correctness-neutral (byte-identity
of every read path holds with watermarks and budgets enabled).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..utils.env import env_bytes
from ..utils.locks import make_lock
from .metrics import counter as _counter
from .metrics import gauge as _gauge

__all__ = ["Account", "ResourceLedger", "LEDGER", "ledger_account",
           "ledger_snapshot", "soft_watermark_bytes",
           "hard_watermark_bytes", "CORE_ACCOUNTS"]

# every byte-holding tier in the process; pre-declared so the gauge
# families render (at 0) before any operation runs — scrapers alert on
# absence, not zero, same contract as metrics._CORE_COUNTERS
CORE_ACCOUNTS = (
    ("cache.chunk", "decoded whole-chunk LRU (io/cache.py)"),
    ("cache.page", "decoded-page LRU, the lookup serving tier"),
    ("cache.page_pinned", "tenant-pinned decoded pages (eviction-exempt "
     "up to each tenant's pin cap)"),
    ("cache.footer", "parsed footers (thrift bytes at parse time)"),
    ("cache.neg_lookup", "negative-lookup memo (keys known absent)"),
    ("prefetch.ring", "in-flight/completed readahead window bytes"),
    ("prefetch.segments", "allocated readahead segment buffers"),
    ("write.buffer", "writeback bytes coalescing in BufferedSinks"),
    ("write.pended", "encoded row groups queued behind slow sinks"),
    ("admission.in_flight", "bytes granted through the read gate"),
    ("trace.buffer", "buffered trace events (estimated bytes)"),
    ("remote.hedge_in_flight", "bytes of in-flight hedged remote reads"),
    ("table.pending", "ingest bytes buffered in DatasetWriters awaiting "
     "a part-file flush"),
    ("device.staging", "raw page payloads staged (or queued for staging) "
     "H2D by mesh-sharded device reads"),
)

# soft response: each reclaimer shrinks its tier to this fraction of its
# current residency per pass (repeated passes converge to empty)
PRESSURE_EVICT_FRACTION = 0.5
_MAX_RECLAIM_PASSES = 4


def soft_watermark_bytes() -> int:
    """``PARQUET_TPU_MEM_SOFT`` (bytes; 0/unset = off).  Read per check so
    tests and long-lived servers can flip pressure live."""
    return env_bytes("PARQUET_TPU_MEM_SOFT")


def hard_watermark_bytes() -> int:
    """``PARQUET_TPU_MEM_HARD`` (bytes; 0/unset = off)."""
    return env_bytes("PARQUET_TPU_MEM_HARD")


class Account:
    """One tier's row in the ledger: resident bytes, lifetime high water,
    and (when the tier has one) its capacity.  ``set``/``add``/``sub``
    are called inside the tier's own critical section, so the account is
    exact by construction — the lock here only orders concurrent tiers'
    updates to the shared gauges."""

    __slots__ = ("name", "_lock", "_resident", "high_water", "_capacity",
                 "_g_res", "_g_hw", "_g_cap")

    def __init__(self, name: str):
        self.name = name
        self._lock = make_lock("ledger.account")
        self._resident = 0
        self.high_water = 0
        self._capacity: Optional[Callable[[], int]] = None
        self._g_res = _gauge("ledger.resident_bytes",
                             labels={"account": name},
                             help="bytes resident per ledger account")
        self._g_hw = _gauge("ledger.high_water_bytes",
                            labels={"account": name},
                            help="max bytes ever resident per account")
        self._g_cap = _gauge("ledger.capacity_bytes",
                             labels={"account": name},
                             help="configured capacity per ledger account")

    @property
    def resident(self) -> int:
        return self._resident

    def set(self, n: int) -> None:
        """Pin the account to the tier's authoritative residency (the LRU
        tiers call this with their own byte counter — idempotent, so the
        ledger can never drift from the tier)."""
        with self._lock:
            self._resident = n
            if n > self.high_water:
                self.high_water = n
                self._g_hw.set(n)
            self._g_res.set(n)

    def add(self, n: int) -> None:
        if not n:
            return
        with self._lock:
            self._resident += n
            if self._resident > self.high_water:
                self.high_water = self._resident
                self._g_hw.set(self.high_water)
            self._g_res.set(self._resident)

    def sub(self, n: int) -> None:
        if not n:
            return
        with self._lock:
            self._resident -= n
            self._g_res.set(self._resident)

    def capacity(self) -> Optional[int]:
        fn = self._capacity
        if fn is None:
            return None
        try:
            return int(fn())
        except Exception:
            return None

    def _reset(self) -> None:
        """Test isolation: forget the high-water mark (residency is owned
        by the tier and untouched)."""
        with self._lock:
            self.high_water = self._resident
            self._g_hw.set(self.high_water)


class ResourceLedger:
    """The process balance sheet: named accounts, watermark evaluation,
    and the soft-pressure reclaim loop.  One instance per process
    (:data:`LEDGER`); tiers reach it through :func:`ledger_account`."""

    def __init__(self):
        self._lock = make_lock("ledger.registry")
        self._accounts: "Dict[str, Account]" = {}
        self._reclaimers: "List[Callable[[float], int]]" = []
        self._state = "ok"
        self._responding = threading.local()
        self._g_total = _gauge("ledger.total_bytes",
                               help="sum of all ledger accounts")
        self._c_evict = _counter(
            "ledger.pressure_evictions",
            help="cache entries evicted by soft-pressure response")
        self._c_trans = {
            s: _counter("ledger.pressure_transitions",
                        labels={"state": s},
                        help="watermark state transitions")
            for s in ("ok", "soft", "hard")}
        for name, _hlp in CORE_ACCOUNTS:
            self.account(name)

    # ------------------------------------------------------------ accounts
    def account(self, name: str,
                capacity: Optional[Callable[[], int]] = None) -> Account:
        """Get-or-create the named account.  ``capacity`` (a zero-arg
        callable, read per snapshot so env repoints apply live) is
        attached by the owning tier; later callers without one leave the
        existing capacity in place."""
        with self._lock:
            acct = self._accounts.get(name)
            if acct is None:
                acct = self._accounts[name] = Account(name)
        if capacity is not None:
            acct._capacity = capacity
        return acct

    def accounts(self) -> "Dict[str, Account]":
        with self._lock:
            return dict(self._accounts)

    def register_reclaimer(self, fn: Callable[[float], int]) -> None:
        """Register a soft-pressure reclaimer: ``fn(fraction)`` shrinks
        one evictable tier to ``fraction`` of its current residency and
        returns the number of entries evicted.  The LRU cache tiers
        register at import (io/cache.py)."""
        with self._lock:
            if fn not in self._reclaimers:
                self._reclaimers.append(fn)

    def total(self) -> int:
        with self._lock:
            accounts = list(self._accounts.values())
        return sum(a.resident for a in accounts)

    # ------------------------------------------------------------ pressure
    def state(self) -> str:
        """Current watermark state — ``ok`` / ``soft`` / ``hard`` —
        recomputed from live totals (and transition counters moved when
        it changed).  Cheap: two env reads and a 10-account sum."""
        return self._refresh()

    def _classify(self, total: int) -> str:
        hard = hard_watermark_bytes()
        if hard > 0 and total >= hard:
            return "hard"
        soft = soft_watermark_bytes()
        if soft > 0 and total >= soft:
            return "soft"
        return "ok"

    def _refresh(self) -> str:
        total = self.total()
        self._g_total.set(total)
        new = self._classify(total)
        with self._lock:
            if new != self._state:
                self._state = new
                self._c_trans[new].inc()
        return new

    def check_pressure(self) -> str:
        """Evaluate the watermarks and, when over the soft one, run the
        reclaim loop (evict-to-fraction over the registered tiers until
        the total is back under, bounded passes).  Called by the growth
        sites — cache puts, sink buffering, admission, writer pend —
        OUTSIDE their own tier locks (reclaimers take cache locks).
        Returns the post-response state."""
        state = self._refresh()
        if state == "ok":
            return state
        if getattr(self._responding, "flag", False):
            return state  # a reclaimer's own accounting re-entered
        self._responding.flag = True
        try:
            # local import: trace.py holds the ledger's trace.buffer
            # account, so the dependency must point one way at import
            from . import trace as _trace

            span = (_trace.span("ledger.pressure", state=state,
                                total_bytes=self.total())
                    if _trace.TRACE_ENABLED else _trace.NULL_SPAN)
            with span:
                self._respond()
        finally:
            self._responding.flag = False
        return self._refresh()

    def _respond(self) -> None:
        soft = soft_watermark_bytes()
        hard = hard_watermark_bytes()
        target = soft if soft > 0 else hard
        with self._lock:
            reclaimers = list(self._reclaimers)
        for _ in range(_MAX_RECLAIM_PASSES):
            if self.total() < target or not reclaimers:
                return
            evicted = 0
            for fn in reclaimers:
                try:
                    evicted += int(fn(PRESSURE_EVICT_FRACTION) or 0)
                except Exception:
                    continue  # one tier's failure must not stop the rest
            if evicted:
                self._c_evict.inc(evicted)
            else:
                return  # nothing left to evict: backpressure-only now

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        """Per-account residency/capacity/high-water plus the total and
        watermark state — the ``/debugz`` ledger table."""
        out: "Dict[str, dict]" = {}
        total = 0
        for name, acct in sorted(self.accounts().items()):
            cap = acct.capacity()  # env-driven: resolved per snapshot
            total += acct.resident
            out[name] = {"resident_bytes": acct.resident,
                         "capacity_bytes": cap,
                         "high_water_bytes": acct.high_water}
            if cap is not None:
                acct._g_cap.set(cap)
        self._g_total.set(total)
        return {"accounts": out, "total_bytes": total,
                "state": self._classify(total),
                "soft_watermark_bytes": soft_watermark_bytes() or None,
                "hard_watermark_bytes": hard_watermark_bytes() or None}

    def _reset_high_water(self) -> None:
        for acct in self.accounts().values():
            acct._reset()


LEDGER = ResourceLedger()


def ledger_account(name: str,
                   capacity: Optional[Callable[[], int]] = None) -> Account:
    """The process-wide ledger's named account (tiers resolve their
    handle once at import; hot-path rule, no get-or-create per update)."""
    return LEDGER.account(name, capacity=capacity)


def ledger_snapshot() -> dict:
    """Per-account residency/capacity/high-water, total, and pressure
    state — the programmatic face of ``/debugz``'s ledger table."""
    return LEDGER.snapshot()


def maybe_check_pressure() -> None:
    """The growth-site fast path: run the watermark check (and any
    reclaim it triggers) only when a watermark is actually configured —
    two env reads otherwise.  Every tier that can GROW calls this after
    releasing its own lock: cache puts, footer/memo inserts, sink
    buffering, prefetch planning, writer pends."""
    if soft_watermark_bytes() or hard_watermark_bytes():
        LEDGER.check_pressure()
