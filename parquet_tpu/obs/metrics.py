"""Process-wide metrics registry: counters, gauges, and fixed-bucket latency
histograms with p50/p95/p99 — the one place every layer's accounting lands.

Six PRs each grew a blind-spot-shaped stats object — ``ReadStats``
(io/prefetch.py), ``WriteStats`` (io/sink.py), ``CacheStats`` (io/cache.py),
``ReadReport`` (io/faults.py), and the planner's cascade counters +
``RouteHistory`` (io/planner.py).  Those dataclasses remain the
*per-operation* views (their Python-facing APIs are unchanged), but every
one of them now also publishes into this registry, so cache hit rates,
prefetch bubbles, pool waits, retry/skip counts, planner prune counts,
route choices, and bytes in/out are all answerable from one snapshot:

- :func:`metrics_snapshot` — nested dict of every metric (the programmatic
  API; :func:`metrics_delta` diffs two snapshots to meter one operation).
- ``python -m parquet_tpu stats [--json|--prom]`` — the CLI front end;
  ``--prom`` renders Prometheus text format (obs/export.py).

Design constraints (this registry sits on hot paths — per pool task, per
prefetch window, per chunk decode):

- **lock-cheap**: one small ``threading.Lock`` per metric, held for a
  couple of arithmetic ops.  No global lock on the increment path; the
  registry-level lock guards only get-or-create.
- **shared-pool-safe**: increments from any number of pool workers account
  exactly (the concurrency tests hammer one counter from 8 workers and
  assert the exact total).
- **allocation-free increments**: ``inc``/``observe`` touch no containers
  beyond the preallocated bucket list.

Histograms use fixed bucket edges (default: a log-spaced latency ladder
from 10 µs to 60 s) and estimate percentiles by linear interpolation inside
the covering bucket, clamped to the observed min/max — the standard
fixed-bucket tradeoff (error bounded by bucket width, memory bounded by
bucket count), same contract as a Prometheus histogram.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

from ..utils.locks import make_lock

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "metrics_snapshot",
           "metrics_delta", "reset_metrics", "pool_wait_seconds",
           "DEFAULT_LATENCY_BUCKETS"]

# log-spaced 10 µs → 60 s: wide enough for a warm footer-cache hit and a
# remote-mount retry storm on one ladder; +Inf overflow is implicit
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_key(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter (int or float increments)."""

    __slots__ = ("name", "labels", "help", "_lock", "_value")

    def __init__(self, name: str, labels=(), help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        self._lock = make_lock("metrics.counter")
        self._value = 0

    def inc(self, n=1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Point-in-time value (cache residency, capacities, measured rates)."""

    __slots__ = ("name", "labels", "help", "_lock", "_value")

    def __init__(self, name: str, labels=(), help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        self._lock = make_lock("metrics.gauge")
        self._value = 0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Histogram:
    """Fixed-bucket histogram with percentile estimation.

    ``observe(v)`` is the hot path: one bisect over the (immutable) edge
    tuple, five arithmetic ops, all under the metric's own lock.  Bucket
    counts are NON-cumulative internally; snapshots and the Prometheus
    renderer derive the cumulative form."""

    __slots__ = ("name", "labels", "help", "buckets", "_lock", "_counts",
                 "_sum", "_count", "_min", "_max")

    def __init__(self, name: str, labels=(), help: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS):
        self.name = name
        self.labels = labels
        self.help = help
        self.buckets = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket edge")
        self._lock = make_lock("metrics.histogram")
        self._counts = [0] * (len(self.buckets) + 1)  # last = overflow
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None

    def observe(self, v: float) -> None:
        i = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (q in [0, 1]): linear interpolation inside
        the covering bucket, clamped to the observed [min, max] so a
        one-sample histogram answers its own value, not a bucket edge."""
        with self._lock:
            return self._percentile_locked(q)

    def _percentile_locked(self, q: float) -> Optional[float]:
        if self._count == 0:
            return None
        target = q * self._count
        cum = 0
        for i, n in enumerate(self._counts):
            if n == 0:
                continue
            if cum + n >= target:
                lo = self.buckets[i - 1] if i > 0 else self._min
                hi = self.buckets[i] if i < len(self.buckets) else self._max
                frac = (target - cum) / n
                est = lo + frac * (hi - lo)
                return min(max(est, self._min), self._max)
            cum += n
        return self._max

    def summary(self) -> dict:
        with self._lock:
            out = {"count": self._count, "sum": round(self._sum, 6),
                   "min": self._min, "max": self._max,
                   "p50": self._percentile_locked(0.50),
                   "p95": self._percentile_locked(0.95),
                   "p99": self._percentile_locked(0.99)}
            for k in ("p50", "p95", "p99"):
                if out[k] is not None:
                    out[k] = round(out[k], 6)
            return out

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """CUMULATIVE (le, count) pairs, Prometheus-style, ending at
        (inf, total)."""
        with self._lock:
            out = []
            cum = 0
            for edge, n in zip(self.buckets, self._counts):
                cum += n
                out.append((edge, cum))
            out.append((float("inf"), cum + self._counts[-1]))
            return out

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._min = None
            self._max = None


class MetricsRegistry:
    """Get-or-create home of every metric, keyed by (name, sorted labels).
    One name maps to one metric type — asking for the same name as a
    different type raises (a silent shadow would split the accounting)."""

    def __init__(self):
        self._lock = make_lock("metrics.registry")
        self._metrics: "Dict[Tuple[str, tuple], object]" = {}

    def _get(self, cls, name: str, labels: Optional[Dict[str, str]],
             help: str, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            got = self._metrics.get(key)
            if got is not None:
                if not isinstance(got, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{type(got).__name__}, not {cls.__name__}")
                return got
            m = cls(name, labels=key[1], help=help, **kw)
            self._metrics[key] = m
            return m

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None,
                help: str = "") -> Counter:
        return self._get(Counter, name, labels, help)

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None,
              help: str = "") -> Gauge:
        return self._get(Gauge, name, labels, help)

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  help: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get(Histogram, name, labels, help, buckets=buckets)

    def collect(self) -> List[object]:
        """Every registered metric, name-sorted (stable render order)."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """Nested dict of everything: ``{"counters": {key: value},
        "gauges": {key: value}, "histograms": {key: summary+buckets}}``
        where ``key`` is ``name`` or ``name{label=value,...}``."""
        counters: Dict[str, object] = {}
        gauges: Dict[str, object] = {}
        hists: Dict[str, dict] = {}
        for m in self.collect():
            key = _render_key(m.name, m.labels)
            if isinstance(m, Counter):
                counters[key] = m.value
            elif isinstance(m, Gauge):
                gauges[key] = m.value
            else:
                d = m.summary()
                d["buckets"] = [[le, n] for le, n in m.bucket_counts()]
                hists[key] = d
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}

    def reset(self) -> None:
        """Zero every metric (tests and bench isolation).  Metrics stay
        registered — pre-declared families keep rendering at 0."""
        for m in self.collect():
            m._reset()


REGISTRY = MetricsRegistry()


def counter(name: str, labels: Optional[Dict[str, str]] = None,
            help: str = "") -> Counter:
    return REGISTRY.counter(name, labels, help)


def gauge(name: str, labels: Optional[Dict[str, str]] = None,
          help: str = "") -> Gauge:
    return REGISTRY.gauge(name, labels, help)


def histogram(name: str, labels: Optional[Dict[str, str]] = None,
              help: str = "",
              buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS
              ) -> Histogram:
    return REGISTRY.histogram(name, labels, help, buckets)


def metrics_snapshot() -> dict:
    """Process-wide nested dict of every counter, gauge, and histogram
    (with p50/p95/p99).  Diff two snapshots with :func:`metrics_delta` to
    meter one operation."""
    return REGISTRY.snapshot()


def metrics_delta(before: dict, after: dict) -> dict:
    """What happened between two :func:`metrics_snapshot` calls: counter
    differences (zero-change entries dropped), gauges at their ``after``
    value, histogram count/sum deltas with the lifetime percentiles
    attached (fixed-bucket histograms cannot rewind, so per-window
    percentiles are approximated by the lifetime distribution)."""
    out = {"counters": {}, "gauges": dict(after.get("gauges", {})),
           "histograms": {}}
    b_c = before.get("counters", {})
    for k, v in after.get("counters", {}).items():
        d = v - b_c.get(k, 0)
        if d:
            out["counters"][k] = round(d, 6) if isinstance(d, float) else d
    b_h = before.get("histograms", {})
    for k, h in after.get("histograms", {}).items():
        dc = h["count"] - b_h.get(k, {}).get("count", 0)
        if dc:
            out["histograms"][k] = {
                "count": dc,
                "sum": round(h["sum"] - b_h.get(k, {}).get("sum", 0.0), 6),
                "p50": h["p50"], "p95": h["p95"], "p99": h["p99"]}
    return out


def reset_metrics() -> None:
    """Zero every registered metric (tests, bench per-config isolation)."""
    REGISTRY.reset()


def pool_wait_seconds() -> float:
    """Cumulative seconds operations spent waiting on the shared pool:
    task queue→run wait (utils/pool.py) plus prefetch-window waits
    (io/prefetch.py).  The saturation signal — diff it across one
    operation and hand the delta to ``RouteHistory.observe(...,
    pool_wait_s=)`` so a saturated pool discounts the route's effective
    GB/s, not just its wall clock.  Both components are LIVE (observed
    as each wait ends, not published at drain close), so a delta window
    sees only the waits that actually happened inside it — the
    close-time ``prefetch.pool_wait_s`` counter would lump a whole
    drain's lifetime stalls into whichever window straddled its close."""
    return float(histogram("pool.queue_wait_s").sum
                 + histogram("prefetch.wait_s").sum)


# ---------------------------------------------------------------------------
# Pre-declared core families: the operational contract of `stats --prom` is
# that the cache/prefetch/planner/route/read/write families EXIST (at 0)
# even before any operation ran — scrapers alert on absence, not on zero.
# ---------------------------------------------------------------------------
_CORE_COUNTERS = (
    ("cache.footer_hits", "footer cache hits (open skipped parse)"),
    ("cache.footer_misses", "footer cache misses"),
    ("cache.chunk_hits", "decoded-chunk LRU hits"),
    ("cache.chunk_misses", "decoded-chunk LRU misses"),
    ("cache.chunk_evictions", "decoded-chunk LRU evictions"),
    ("cache.page_hits", "decoded-page LRU hits (lookup served with no IO)"),
    ("cache.page_misses", "decoded-page LRU misses"),
    ("cache.page_evictions", "decoded-page LRU evictions"),
    ("prefetch.hits", "preads served from readahead state"),
    ("prefetch.misses", "preads read through around readahead"),
    ("prefetch.windows_issued", "readahead windows issued/hinted"),
    ("prefetch.bytes_prefetched", "bytes issued ahead of consumption"),
    ("prefetch.bytes_discarded", "prefetched bytes dropped unconsumed"),
    ("prefetch.bytes_dropbehind", "page-cache bytes released behind "
     "one-shot drains (PARQUET_TPU_MMAP_DROPBEHIND)"),
    ("prefetch.pool_wait_s", "seconds blocked on unfinished windows"),
    # "considered", not the plan-counter key "rg_total": the Prometheus
    # renderer appends _total to counters, and rg_total_total is a trap
    # for every dashboard written against the natural name
    ("planner.rg_considered", "row groups considered by the scan planner"),
    ("planner.rg_pruned_stats", "row groups pruned by footer stats"),
    ("planner.rg_pruned_pages", "row groups pruned by the page index"),
    ("planner.rg_pruned_bloom", "row groups pruned by bloom filters"),
    ("planner.rg_survivors", "row groups that survived the cascade"),
    ("planner.stats_probes", "stats-stage predicate probes"),
    ("planner.page_probes", "page-index predicate probes"),
    ("planner.bloom_probes", "bloom-filter predicate probes"),
    ("planner.pages_considered", "pages considered by the page stage"),
    ("planner.pages_selected", "pages selected by the page stage"),
    ("read.retries", "transient pread retries performed"),
    ("read.bytes_read", "bytes fetched from byte sources"),
    ("scan.rows_pruned", "candidate rows excluded before decode by pruning"),
    ("scan.rows_decoded", "survivor rows materialized by filtered scans"),
    ("read.rows_dropped", "rows lost to degraded-mode skips"),
    ("read.row_groups_skipped", "row groups dropped by degraded reads"),
    ("read.files_skipped", "whole files dropped by degraded reads"),
    ("write.row_groups", "row groups written"),
    ("write.bytes_flushed", "bytes flushed toward the OS by writers"),
    ("write.sink_flushes", "coalesced sink flushes"),
    # WriteStats publish families (io/sink.py): the encode/emit overlap
    # meters — float-seconds totals land as counters so per-op deltas
    # and rates stay derivable
    ("write.overlapped_groups", "row groups whose encode overlapped the "
     "previous group's emit"),
    ("write.encode_s", "cumulative seconds in parallel/serial encode"),
    ("write.emit_s", "cumulative seconds emitting pages to sinks"),
    ("write.pool_wait_s", "seconds writers blocked on pended encodes"),
    ("write.bytes_buffered", "bytes coalesced through BufferedSinks"),
    ("write.writev_flushes", "vectored os.writev sink flushes"),
    ("pool.tasks", "tasks dispatched to the shared pool"),
    ("trace.events_dropped", "trace events dropped at the buffer cap"),
    # sampling decisions (obs/scope.py): fleets alert on trace-buffer
    # pressure and sampler behavior from these
    ("trace.ops_sampled", "ops head-sampled into the trace"),
    ("trace.ops_skipped", "ops skipped by head sampling"),
    ("trace.ops_slow_kept", "slow ops kept by tail capture"),
    # point-lookup serving path (io/lookup.py): per-stage key attrition,
    # coalescing ratio (pages_read vs preads), and admission pressure
    ("lookup.keys", "keys probed by batched find_rows"),
    ("lookup.keys_pruned_stats", "lookup keys killed by chunk statistics"),
    ("lookup.keys_pruned_bloom", "lookup keys killed by bloom filters"),
    ("lookup.keys_pruned_pages", "lookup keys killed by the page index"),
    ("lookup.rows_matched", "rows returned by batched lookups"),
    ("lookup.preads", "ranged preads issued by the lookup page fetcher"),
    ("lookup.pages_read", "pages decoded from storage by lookups"),
    ("lookup.pages_coalesced", "extra pages riding an already-issued pread"),
    ("lookup.chunk_fallbacks", "index-less chunks decoded whole by lookups"),
    ("lookup.admission_waits", "lookup admissions that had to block"),
    ("lookup.neg_hits", "lookup keys skipped by the negative-lookup memo"),
    # the unified read gate (utils/pool.py): scan/stream-tier admissions
    # through the same FIFO budget the lookup path pioneered
    ("read.admission_waits", "scan/stream admissions that had to block"),
    # remote sources (io/remote.py): request volume, hedging, breaker
    # fail-fasts, and cache-identity movement — the serving fleet's
    # object-store health dashboard families
    ("remote.preads", "range requests served by remote sources"),
    ("remote.bytes", "bytes fetched from remote sources"),
    ("remote.hedges_issued", "hedged second attempts launched"),
    ("remote.hedges_won", "preads whose hedge finished first"),
    ("remote.breaker_fail_fast", "requests refused by an open circuit"),
    ("remote.validator_changes", "remote rewrites detected by HEAD "
     "validators (caches invalidated)"),
    # writable tables (dataset_writer.py + io/manifest.py): ingest and
    # compaction volume, commit conflicts, and recovery sweeps — the
    # continuous-ingest health dashboard families
    ("table.commits", "manifest snapshots committed"),
    ("table.files_written", "part-files committed by ingest"),
    ("table.rows_ingested", "rows committed into tables"),
    ("table.bytes_ingested", "part-file bytes committed into tables"),
    ("table.compactions", "compaction passes committed"),
    ("table.files_compacted", "part-files replaced by compaction"),
    ("table.commit_conflicts", "optimistic commits aborted by a rival"),
    ("table.compaction_errors", "background compaction passes that died"),
    ("table.orphans_swept", "orphan files removed by table recovery"),
    # point-lookup fast paths (io/lookup.py): sorted-page binary search
    # and very-large-batch key sharding
    ("lookup.binary_search_hits", "page probes answered by in-page "
     "binary search on sorted files"),
    ("lookup.key_shards", "key-shard tasks fanned out for very large "
     "lookup batches"),
    # aggregation pushdown (io/aggregate.py): per-tier resolution — how
    # many row groups each cascade tier ANSWERED (stats = zero IO/decode,
    # pages = zone-map math only, dict = dictionary + index stream,
    # decoded = exact fallback), plus manifest-level file answers
    ("agg.rg_answered_stats", "row groups answered by footer statistics "
     "(zero IO, zero decode)"),
    ("agg.rg_answered_pages", "row groups answered by page-index zone "
     "maps (no value decode)"),
    ("agg.rg_answered_dict", "row groups answered over dictionary pages "
     "without expanding values"),
    ("agg.rg_answered_decoded", "row groups resolved by the exact decode "
     "fallback"),
    ("agg.files_answered_manifest", "dataset part-files answered or "
     "dropped from manifest zone maps alone (zero footer IO)"),
    # multi-range remote reads (io/remote.py parallel_preads): ranges
    # fetched concurrently across connection-pool slots
    ("remote.parallel_preads", "disjoint ranges fetched concurrently "
     "across connection-pool slots"),
    # mmap write-sink experiment (io/sink.py MmapFileSink)
    ("write.mmap_commits", "files committed through the mmap-backed "
     "sink (PARQUET_TPU_MMAP_SINK)"),
    # tenant hot-key pinning (io/cache.py page_pin_scope): pins granted
    # vs refused at the per-tenant cap — the pin-contract health meters
    ("cache.page_pins", "decoded pages pinned by tenants "
     "(eviction-exempt)"),
    ("cache.page_pin_refusals", "pin attempts refused at the tenant's "
     "pin cap (entry fell back to the LRU)"),
    # serving daemon (parquet_tpu/serve): per-endpoint error count; the
    # per-class/per-tenant request+shed counters are label families
    # declared below
    ("serve.errors", "requests that failed with a 5xx"),
    ("serve.writes_committed", "table commits performed by /v1/write"),
    ("serve.rows_served", "rows returned across all serve endpoints"),
    # remote auth hooks (io/remote.py): 401/403 -> refresh-and-retry
    ("remote.auth_refreshes", "credential refreshes triggered by "
     "401/403 responses (auth hook re-invoked)"),
    # serving-daemon request-rate + auth gates (satellites of the fleet
    # PR): per-tenant token buckets and bearer-token checks
    ("serve.qps_rejections", "requests refused 429 by a tenant's "
     "token-bucket QPS limit"),
    ("serve.auth_failures", "requests refused 401 by the per-tenant "
     "bearer-token check"),
    # fleet mode (serve/cluster.py): consistent-hash routing,
    # scatter-gather, peer hedging, and cross-node commit arbitration
    ("fleet.forwards", "lookup key subsets / sub-requests forwarded to "
     "ring-owner peers"),
    ("fleet.gathers", "scatter-gather requests coordinated across the "
     "fleet"),
    ("fleet.peer_errors", "peer sub-requests that failed (before any "
     "local fallback)"),
    ("fleet.local_fallbacks", "peer shards recomputed locally after a "
     "peer failure or hedge win"),
    ("fleet.hedges_issued", "local hedge executions launched against "
     "slow peer sub-requests"),
    ("fleet.hedges_won", "peer sub-requests whose local hedge finished "
     "first"),
    ("fleet.peer_skips", "peer shards dropped from a degraded gather "
     "(skip accounting in the response)"),
    ("fleet.cas_commits", "manifest commits arbitrated through the CAS "
     "hook"),
    ("fleet.cas_conflicts", "CAS commit attempts aborted by a rival "
     "version (re-read and re-mutated)"),
    # fused single-pass execution (io/fused.py): page-at-a-time
    # decode+mask+fold streaming with no whole-column intermediates
    ("fused.rg_folds", "row groups resolved by the fused streaming fold"),
    ("fused.pages_folded", "pages decoded or masked-emitted through the "
     "fused fold (at most one alive per column at a time)"),
    ("fused.pages_masked_emit", "pages whose filter mask applied INSIDE "
     "the decode loop (masked-emit kernels)"),
    ("fused.fallbacks", "fused-path attempts that fell back to the "
     "materializing exact tier (unsupported layout/encoding)"),
    ("fused.scan_spans", "scan filter spans evaluated page-by-page "
     "through the fused phase-1 path"),
    ("agg.rg_answered_dict_partial", "partially-covered row groups whose "
     "covered rows answered from the dictionary while only contended "
     "pages took the exact path"),
    # device-scale dataset reads (parallel/mesh.py read_dataset_sharded):
    # files round-robined over the mesh with double-buffered H2D staging
    ("device.files_sharded", "dataset files round-robined over mesh "
     "devices by device-scale reads"),
    ("device.stage_overlapped", "files whose H2D staging overlapped the "
     "previous file's on-chip decode"),
)


def _declare_core() -> None:
    for name, hlp in _CORE_COUNTERS:
        REGISTRY.counter(name, help=hlp)
    for route in ("host", "device", "device_mesh"):
        REGISTRY.counter("route.chosen", labels={"route": route},
                         help="scans routed by the cost model")
    for cls in ("retryable", "terminal", "throttled"):
        REGISTRY.counter("remote.errors", labels={"class": cls},
                         help="remote failures by retry class")
    for state in ("open", "half_open", "closed"):
        REGISTRY.counter("remote.breaker_transitions",
                         labels={"state": state},
                         help="per-host circuit-breaker transitions")
    REGISTRY.histogram("remote.pread_s",
                       help="remote range-request latency (seeds the "
                            "adaptive hedge delay)")
    REGISTRY.histogram("pool.queue_wait_s",
                       help="shared-pool task queue->run wait")
    REGISTRY.histogram("lookup.find_rows_s",
                       help="batched point-lookup latency (p50/p99 serving "
                            "meter)")
    REGISTRY.histogram("read.admission_wait_s",
                       help="scan/stream block time on the read gate")
    REGISTRY.histogram("table.commit_s",
                       help="table commit latency (flush + zone-map "
                            "collection + manifest rename)")
    REGISTRY.histogram("agg.aggregate_s",
                       help="per-file aggregation-pushdown latency")
    REGISTRY.histogram("dataset.aggregate_s",
                       help="whole-dataset aggregation latency")
    REGISTRY.histogram("fused.fold_s",
                       help="per-row-group fused decode+mask+fold latency")
    # device-scale dataset reads: stage/decode split so the overlap win
    # (h2d hidden under decode) is measurable from a scrape alone
    REGISTRY.histogram("device.h2d_s",
                       help="per-file H2D staging latency on the "
                            "mesh-sharded device read path")
    REGISTRY.histogram("device.decode_s",
                       help="per-file on-chip decode latency on the "
                            "mesh-sharded device read path")
    # the reason axis is closed; runtime refusals outside it fold into
    # "other" (device_refusal_reason) so every series exists at 0
    for reason in ("unsupported", "policy", "budget", "error", "other"):
        REGISTRY.counter("device.route_refusals", labels={"reason": reason},
                         help="device-route refusals that fell back to "
                              "the host path, by reason")
    # --- PT001 (analysis/lint.py) pass: every family any module
    # get-or-creates must already exist here, or a process that never
    # imported that module scrapes an incomplete /metrics.  The 22
    # families below were declared only at their modules' import before
    # this pass.
    REGISTRY.histogram("prefetch.wait_s",
                       help="per-wait seconds blocked on unfinished "
                            "readahead windows (live)")
    REGISTRY.histogram("lookup.admission_wait_s",
                       help="lookup-tier block time on the read gate")
    REGISTRY.histogram("dataset.find_rows_s",
                       help="dataset-wide batched-lookup latency")
    REGISTRY.histogram("dataset.read_s",
                       help="whole-dataset read latency")
    REGISTRY.histogram("dataset.scan_s",
                       help="whole-dataset filtered-scan latency")
    REGISTRY.histogram("dataset.scan_file_s",
                       help="per-file filtered-scan latency")
    REGISTRY.histogram("read.file_s",
                       help="per-file whole-read latency")
    REGISTRY.gauge("cache.footer_entries",
                   help="footers resident in the cache")
    REGISTRY.gauge("cache.chunk_entries",
                   help="decoded chunks resident in the LRU")
    REGISTRY.gauge("cache.chunk_bytes",
                   help="decoded bytes resident in the LRU")
    REGISTRY.gauge("cache.page_entries",
                   help="decoded pages resident in the page LRU")
    REGISTRY.gauge("cache.page_bytes",
                   help="decoded bytes resident in the page LRU")
    REGISTRY.gauge("pool.active", help="pool tasks currently running")
    REGISTRY.gauge("lookup.admitted_bytes",
                   help="bytes currently admitted through the read gate")
    for route in ("host", "device", "device_mesh"):
        REGISTRY.gauge("route.gbps", labels={"route": route},
                       help="EWMA effective GB/s per route")
        REGISTRY.counter("route.observations", labels={"route": route},
                         help="measured samples folded into the route "
                              "EWMA")
    REGISTRY.gauge("cache.page_pinned_bytes",
                   help="decoded bytes pinned by tenants "
                        "(eviction-exempt)")
    # serving daemon per-class families (parquet_tpu/serve): the class
    # axis is closed (latency/default/bulk) so every class series exists
    # at 0; per-TENANT series (labels tenant+class) appear as tenants
    # arrive — same family name, so PT001 and the scrape contract hold
    for klass in ("latency", "default", "bulk"):
        REGISTRY.counter("serve.requests", labels={"class": klass},
                         help="requests served per priority class")
        REGISTRY.counter("serve.shed", labels={"class": klass},
                         help="requests shed 429 under hard pressure")
        REGISTRY.histogram("serve.request_s", labels={"class": klass},
                           help="end-to-end request latency per "
                                "priority class")


_declare_core()
