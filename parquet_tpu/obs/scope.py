"""Request-scoped telemetry: per-operation trace contexts, sampling, and
slow-op capture — the serving-fleet layer over PR 7's process-global
registry and tracer.

``metrics_delta()`` meters the whole interpreter: two concurrent
``Dataset.scan``\\s smear into one number, and ``PARQUET_TPU_TRACE`` is
all-or-nothing.  This module gives every operation its own identity:

- :func:`op_scope(name, **attrs)` — a ``contextvars``-based scope.  Code
  running inside it (including work fanned out across shared-pool
  workers: ``utils/pool.instrument_task`` propagates the context with
  ``contextvars.copy_context``) attributes its resources to the scope's
  :meth:`OpScope.report`: bytes read, pool-wait seconds, cache
  hits/misses, retries, rows pruned/decoded, routes chosen.  The
  attribution is **exact by construction**: :func:`account` increments
  the process-wide registry counter and the current scope's mirror in
  one call, so per-op sums equal the global delta for any window whose
  work all ran under scopes.
- The public surfaces (``ParquetFile.read/iter_batches``,
  ``scan_filtered``/``scan_expr``, ``Dataset.read/iter_batches/scan/
  prune``, the ``ParquetWriter`` lifecycle, ``verify_file``) open a
  scope themselves when none is active (:func:`maybe_op_scope`), so
  every operation has an identity whether or not the caller asked; a
  caller's explicit ``with op_scope(...):`` takes precedence and the
  inner surfaces join it.
- **Production sampling** — with tracing on, ``PARQUET_TPU_TRACE_SAMPLE
  =N`` head-samples 1-in-N ops at scope entry.  Sampled ops trace
  normally onto their own per-request Perfetto track (pid = op id,
  ``process_name`` metadata).  Unsampled ops divert spans into a per-op
  ring buffer (``trace.OpRing``) that is discarded allocation-cheap at
  finish — unless the op ran slower than ``PARQUET_TPU_SLOW_OP_S``
  (tail capture), in which case the ring promotes into the global trace
  and the op is kept.  Decisions are metered: ``trace.ops_sampled`` /
  ``trace.ops_skipped`` / ``trace.ops_slow_kept``.
- **Slow-op records** — any op over the threshold appends one JSON line
  to ``PARQUET_TPU_SLOW_LOG=/path.jsonl``: name, duration, attrs,
  per-stage breakdown (from span exits), and the full per-op report.
  This works with tracing off too (the stage breakdown then is empty —
  stage timings come from spans).

The env knobs are read per operation, so tests and long-lived servers
can flip them live; ops are coarse-grained enough that the reads are
free.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import random
import time
from typing import Dict, Iterator, Optional

from ..utils.env import env_int, env_opt_float, env_str
from ..utils.locks import make_lock
from . import metrics as _metrics
from . import trace as _trace
from .metrics import _render_key

__all__ = ["OpScope", "op_scope", "maybe_op_scope", "current_op",
           "scoped_iter", "account", "add_to_current", "account_bytes",
           "sample_n", "slow_op_threshold_s", "slow_log_path", "live_ops"]

_CURRENT: "contextvars.ContextVar[Optional[OpScope]]" = \
    contextvars.ContextVar("parquet_tpu_op_scope", default=None)
_IDS = itertools.count(1)
# op "pids" live far above real pid space so an op track never merges
# with the process track in Perfetto
_OP_PID_BASE = 1_000_000

# families pre-declared (with help text) in metrics._CORE_COUNTERS —
# the single source of truth; these are just resolved handles
_OPS_SAMPLED = _metrics.counter("trace.ops_sampled")
_OPS_SKIPPED = _metrics.counter("trace.ops_skipped")
_OPS_SLOW = _metrics.counter("trace.ops_slow_kept")
_BYTES_READ = _metrics.counter("read.bytes_read")

_SLOW_LOG_LOCK = make_lock("scope.slow_log")

# currently-open operations, op_id → scope: the /debugz op table.  Every
# scope registers at construction and leaves at finish(); an entry that
# lingers IS the signal (a stuck or leaked op is exactly what a live
# introspection endpoint exists to show).
_LIVE_LOCK = make_lock("scope.live_ops")
_LIVE_OPS: "Dict[int, OpScope]" = {}


def live_ops() -> list:
    """The currently-open ops, oldest first: op id, name, attrs, age in
    seconds since first activation (0 for a scope built but never
    entered), and the sampling decision.  Powers ``/debugz``."""
    with _LIVE_LOCK:
        scopes = list(_LIVE_OPS.values())
    now = time.perf_counter()
    out = []
    for s in scopes:
        with s._lock:
            t_first = s._t_first
        out.append({"op": s.op_id, "name": s.name,
                    "attrs": {k: _trace._jsonable(v)
                              for k, v in s.attrs.items()},
                    "age_s": round(now - t_first, 6)
                    if t_first is not None else 0.0,
                    "sampled": s.sampled})
    out.sort(key=lambda r: -r["age_s"])
    return out

# systematic head sampling with a random phase: exactly one sampled op
# per block of N, but WHICH position is drawn fresh each block — a plain
# `op_id % N` stride would lock onto periodic workloads (2 ops per
# request + N=2 means one op class is sampled always, the other never)
_SAMPLE_LOCK = make_lock("scope.sampler")
_SAMPLE_I = 0
_SAMPLE_N: Optional[int] = None
_SAMPLE_TARGET = 0


def _head_sampled(n: int) -> bool:
    global _SAMPLE_I, _SAMPLE_N, _SAMPLE_TARGET
    with _SAMPLE_LOCK:
        if _SAMPLE_N != n or _SAMPLE_I >= n:  # new block (or N changed)
            _SAMPLE_N = n
            _SAMPLE_I = 0
            _SAMPLE_TARGET = random.randrange(n)
        hit = _SAMPLE_I == _SAMPLE_TARGET
        _SAMPLE_I += 1
        return hit


def sample_n() -> int:
    """``PARQUET_TPU_TRACE_SAMPLE`` as an int ≥ 1 (1 = trace every op)."""
    return max(1, env_int("PARQUET_TPU_TRACE_SAMPLE"))


def slow_op_threshold_s() -> Optional[float]:
    """``PARQUET_TPU_SLOW_OP_S`` as seconds, or None (tail capture off).
    0 keeps every op — the capture-everything debugging mode."""
    return env_opt_float("PARQUET_TPU_SLOW_OP_S")


def slow_log_path() -> Optional[str]:
    """``PARQUET_TPU_SLOW_LOG``: the JSON-lines slow-op record file."""
    return env_str("PARQUET_TPU_SLOW_LOG") or None


def current_op() -> "Optional[OpScope]":
    """The active scope on this thread/context, or None."""
    return _CURRENT.get()


def add_to_current(key: str, n) -> None:
    """Mirror an already-registry-published quantity into the current
    scope (the histogram-observed seconds — pool queue wait, prefetch
    wait — whose registry side is an ``observe``, not a counter inc)."""
    if not n:
        return
    s = _CURRENT.get()
    if s is not None:
        s._add(key, n)


def account(metric, n=1) -> None:
    """Increment a registry counter AND the current scope's mirror of it,
    under the counter's rendered snapshot key — the single call that
    makes per-op sums equal the process-global ``metrics_delta()``."""
    if not n:
        return
    metric.inc(n)
    s = _CURRENT.get()
    if s is not None:
        s._add(_render_key(metric.name, metric.labels), n)


def account_bytes(n: int) -> None:
    """Terminal-source pread accounting (io/source.py): every byte fetched
    from storage lands in ``read.bytes_read`` and the current op."""
    if not n:
        return
    _BYTES_READ.inc(n)
    s = _CURRENT.get()
    if s is not None:
        s._add("read.bytes_read", n)


class _Activation:
    """Re-entrant, non-finishing activation of a scope (generator pulls,
    writer method bodies) — ``with scope.active(): ...``."""

    __slots__ = ("scope",)

    def __init__(self, scope: "OpScope"):
        self.scope = scope

    def __enter__(self) -> "OpScope":
        self.scope._activate()
        return self.scope

    def __exit__(self, *exc) -> bool:
        self.scope._deactivate()
        return False


class OpScope:
    """One operation's identity: a request-scoped accounting sink, trace
    track, sampling decision, and slow-op detector.

    Use as a context manager (``with op_scope("serving.lookup") as op:``,
    finishes on exit) or via :meth:`active` for piecewise activations
    (finish explicitly with :meth:`finish`).  Activations nest on one
    thread; pool workers join through context propagation, never by
    activating.  Counter mirrors are lock-protected — any number of
    workers account concurrently with exact totals."""

    __slots__ = ("name", "attrs", "op_id", "sampled", "duration_s",
                 "_lock", "_counters", "_stages", "_active", "_tokens",
                 "_t0", "_t_first", "_elapsed", "_finished", "_track",
                 "_ring")

    def __init__(self, name: str, attrs: Optional[Dict] = None):
        self.name = name
        self.attrs = dict(attrs or {})
        self.op_id = next(_IDS)
        self.duration_s = None
        self._lock = make_lock("scope.op")
        self._counters: Dict[str, float] = {}
        self._stages: Dict[str, list] = {}
        self._active = 0
        self._tokens: list = []
        self._t0 = None
        self._t_first = None
        self._elapsed = 0.0
        self._finished = False
        self._track = None
        self._ring = None
        self.sampled = None
        if _trace.TRACE_ENABLED:
            # head sampling, decided once at scope entry: the op either
            # traces straight into the global buffer on its own track, or
            # parks spans in a per-op ring for possible tail promotion
            n = sample_n()
            self.sampled = n <= 1 or _head_sampled(n)
            self._track = (_OP_PID_BASE + self.op_id,
                           f"op {self.op_id}: {name}")
            if self.sampled:
                _OPS_SAMPLED.inc()
            else:
                _OPS_SKIPPED.inc()
                self._ring = _trace.OpRing()
        with _LIVE_LOCK:
            _LIVE_OPS[self.op_id] = self

    # ------------------------------------------------------- activation
    def _activate(self) -> None:
        with self._lock:
            if self._active == 0:
                self._t0 = time.perf_counter()
                if self._t_first is None:
                    self._t_first = self._t0
            self._active += 1
        toks = [_CURRENT.set(self)]
        if self._track is not None:
            # set BOTH trace vars (sink may be None): an explicitly
            # nested scope must override an outer op's ring, not inherit
            toks.append(_trace._TRACK.set(self._track))
            toks.append(_trace._SINK.set(self._ring))
        self._tokens.append(toks)

    def _deactivate(self) -> None:
        toks = self._tokens.pop()
        for t in reversed(toks):
            t.var.reset(t)
        with self._lock:
            self._active -= 1
            if self._active == 0 and self._t0 is not None:
                self._elapsed += time.perf_counter() - self._t0
                self._t0 = None

    def active(self) -> _Activation:
        """A non-finishing activation (see class docstring)."""
        return _Activation(self)

    def __enter__(self) -> "OpScope":
        self._activate()
        return self

    def __exit__(self, *exc) -> bool:
        self._deactivate()
        if not self._tokens and self._active == 0:
            self.finish()
        return False

    # ------------------------------------------------------- accounting
    def _add(self, key: str, n) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def _stage(self, name: str, dur: float) -> None:
        with self._lock:
            st = self._stages.get(name)
            if st is None:
                self._stages[name] = [1, dur]
            else:
                st[0] += 1
                st[1] += dur

    # ---------------------------------------------------------- results
    def counters(self) -> Dict[str, float]:
        """Copy of the per-op counter mirrors, keyed exactly like
        ``metrics_snapshot()['counters']`` (labeled counters render as
        ``name{label=value}``)."""
        with self._lock:
            return dict(self._counters)

    def stages(self) -> Dict[str, dict]:
        """Per-stage breakdown from span exits while tracing was on:
        ``{span_name: {"count": n, "seconds": s}}``."""
        with self._lock:
            return {k: {"count": c, "seconds": round(s, 6)}
                    for k, (c, s) in self._stages.items()}

    def metrics_delta(self) -> dict:
        """This operation's counters in the shape of the process-global
        :func:`~parquet_tpu.obs.metrics.metrics_delta` — but attributed
        to this op alone, concurrency-exact (no smearing)."""
        return {"counters": self.counters(), "gauges": {},
                "histograms": {}}

    def report(self) -> dict:
        """The OpReport: headline attribution plus the raw counter
        mirrors and stage breakdown."""
        c = self.counters()
        with self._lock:  # _t0 races _deactivate() on the owning thread
            dur = self.duration_s
            if dur is None and self._t_first is not None:
                dur = self._elapsed + (time.perf_counter() - self._t0
                                       if self._t0 is not None else 0.0)
        routes = {k.split("route=", 1)[1].rstrip("}"): v
                  for k, v in c.items() if k.startswith("route.chosen{")}
        return {
            "name": self.name, "op": self.op_id, "attrs": dict(self.attrs),
            "sampled": self.sampled,
            "duration_s": round(dur, 6) if dur is not None else None,
            "bytes_read": c.get("read.bytes_read", 0),
            "pool_wait_s": round(c.get("pool.queue_wait_s", 0.0)
                                 + c.get("prefetch.wait_s", 0.0), 6),
            "cache_hits": (c.get("cache.footer_hits", 0)
                           + c.get("cache.chunk_hits", 0)
                           + c.get("cache.page_hits", 0)),
            "cache_misses": (c.get("cache.footer_misses", 0)
                             + c.get("cache.chunk_misses", 0)
                             + c.get("cache.page_misses", 0)),
            "retries": c.get("read.retries", 0),
            "rows_pruned": c.get("scan.rows_pruned", 0),
            "rows_decoded": c.get("scan.rows_decoded", 0),
            "routes": routes,
            "counters": c,
            "stages": self.stages(),
        }

    # ----------------------------------------------------------- finish
    def finish(self) -> None:
        """Finalize the op: fix its duration, run the tail-capture
        decision (ring promotion + slow-op record), emit the op-level
        span.  Idempotent; ``with op_scope(...)`` calls it on exit."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            dur = self._elapsed
            if self._t0 is not None:  # finish() inside an activation
                dur += time.perf_counter() - self._t0
            self.duration_s = dur
        thr = slow_op_threshold_s()
        slow = thr is not None and dur >= thr
        if self._track is not None and _trace.TRACE_ENABLED:
            kept = bool(self.sampled)
            if not kept and slow and self._ring is not None:
                _trace.promote_ring(self._ring, self._track)
                kept = True
            if kept:
                _trace.emit_op_event(
                    "op." + self.name, self._track,
                    self._t_first if self._t_first is not None
                    else time.perf_counter(),
                    dur, dict(self.attrs, op=self.op_id))
        if slow:
            _OPS_SLOW.inc()
            self._write_slow_record(dur)
        self._ring = None  # drop the parked spans either way
        with _LIVE_LOCK:
            _LIVE_OPS.pop(self.op_id, None)

    def _write_slow_record(self, dur: float) -> None:
        path = slow_log_path()
        if not path:
            return
        # ptlint: disable=PT004 -- wall-clock record timestamp for log
        # correlation, not deadline/backoff arithmetic
        rec = {"ts": round(time.time(), 6), "op": self.op_id,
               "name": self.name,
               "attrs": {k: _trace._jsonable(v)
                         for k, v in self.attrs.items()},
               "duration_s": round(dur, 6),
               "stages": self.stages(),
               "report": {k: (round(v, 6) if isinstance(v, float) else v)
                          for k, v in self.counters().items()}}
        line = json.dumps(rec, sort_keys=True)
        # appends are serialized in-process; O_APPEND keeps multi-process
        # writers line-atomic for records under PIPE_BUF
        with _SLOW_LOG_LOCK:
            try:
                with open(path, "a") as f:
                    f.write(line + "\n")
            except OSError:
                pass  # the slow log is best-effort, never a crash

    def __repr__(self) -> str:
        return (f"OpScope({self.name!r}, op={self.op_id}, "
                f"sampled={self.sampled}, finished={self._finished})")


def op_scope(name: str, **attrs) -> OpScope:
    """A new operation scope: ``with op_scope("lookup", user=uid) as op:``
    then ``op.report()`` / ``op.metrics_delta()`` answer for that
    operation alone.  Nesting creates a new identity that takes over
    attribution for its extent (sibling ops stay exact)."""
    return OpScope(name, attrs)


class _Ambient:
    """Pass-through for public surfaces called inside an active scope:
    the operation joins the caller's op instead of opening its own."""

    __slots__ = ()

    def __enter__(self):
        return _CURRENT.get()

    def __exit__(self, *exc):
        return False


_AMBIENT = _Ambient()


def maybe_op_scope(name: str, **attrs):
    """A new finishing scope when none is active, else a no-op that
    yields the ambient one — how the public surfaces thread scopes
    through without stealing attribution from an explicit caller
    ``op_scope``."""
    if _CURRENT.get() is not None:
        return _AMBIENT
    return OpScope(name, attrs)


def scoped_iter(name: str, gen: Iterator, **attrs):
    """Wrap a generator-shaped operation (``iter_batches``) in a scope.

    PEP 567 contexts do not isolate generators — a plain ``with
    op_scope(...)`` inside one would leak the scope to the consumer
    between yields, smearing their other work into this op.  Instead
    each pull activates the scope around ``next()`` only, so the op
    accumulates exactly its own work (consumer time excluded) and
    finishes when the generator is exhausted or closed.  (This is a
    generator itself, so the ambient-scope decision below runs lazily,
    at the first pull.)"""
    scope = OpScope(name, attrs) if _CURRENT.get() is None else None
    try:
        while True:
            if scope is None:
                try:
                    item = next(gen)
                except StopIteration:
                    return
            else:
                with scope.active():
                    try:
                        item = next(gen)
                    except StopIteration:
                        return
            yield item
    finally:
        if scope is not None:
            # close INSIDE the activation: the generator's cleanup (e.g.
            # the drain's prefetcher close publishing its ReadStats) must
            # attribute to this op, not to whatever the consumer's
            # context holds at early termination
            try:
                with scope.active():
                    gen.close()
            finally:
                scope.finish()
        else:
            gen.close()


def _on_span(name: str, dur: float) -> None:
    s = _CURRENT.get()
    if s is not None:
        s._stage(name, dur)


# bind the stage-breakdown hook (trace.py calls it per completed span
# while tracing is on; late binding avoids a circular import)
_trace._ON_SPAN = _on_span
