"""Span tracing: Chrome trace-event JSON you can drop into Perfetto.

PR 3–6 shipped pipeline claims — encode/emit overlap ratios, prefetch
bubbles, late-materialization skips — as *numbers* in stats dataclasses.
This module makes them *visible*: every load-bearing stage (footer open,
prefetch window issue/wait, per-column decode, planner cascade, host-scan
phase 1/2, encode/emit, sink flush, H2D staging, pool task queue→run) is
wrapped in a :func:`trace_span`, each completed span records its
worker-thread id, and the buffer flushes to the Chrome ``traceEvents``
JSON format (Perfetto / ``chrome://tracing`` load it directly) — so
pipeline overlap shows up as literally overlapping bars on different
thread tracks.

Overhead contract — tracing OFF is the production default and must cost
nothing measurable:

- ``TRACE_ENABLED`` is a module-level bool.  The hottest sites read it
  directly (``if trace.TRACE_ENABLED:``) and skip span construction
  entirely.
- :func:`trace_span` called while disabled returns one shared no-op
  singleton — no object allocation, no timestamps, no lock.

Enabling:

- ``PARQUET_TPU_TRACE=/path/trace.json`` (env, read at import): tracing
  on for the process, buffer flushed to that path at interpreter exit.
- :func:`enable_tracing`/:func:`disable_tracing`/:func:`flush_trace` —
  the programmatic controls (tests, notebooks).

The event buffer is bounded (:data:`MAX_EVENTS`); overflow drops new
events and counts them in the ``trace.events_dropped`` metric instead of
growing without bound.  While tracing is on, each completed span also
feeds a ``span.<name>_s`` latency histogram in the metrics registry, so
stage p50/p99 come for free with a traced run.

Request scopes (obs/scope.py) route spans through two context variables
here: ``_TRACK`` gives every span of an operation the op's own Perfetto
"process" track (pid = op id, named by a one-time ``process_name``
metadata event), and ``_SINK`` — set for ops head-sampling decided NOT to
trace — diverts completed spans into a per-op :class:`OpRing` that is
promoted to the global buffer only if the op turns out slow (tail
capture) and discarded allocation-cheap otherwise.  Both are
``contextvars``, so pool workers running an op's tasks inherit them via
the context propagation in ``utils/pool.instrument_task``.
"""

from __future__ import annotations

import atexit
import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..utils.env import env_str
from ..utils.locks import make_lock
from . import metrics as _metrics
from .ledger import ledger_account as _ledger_account

__all__ = ["TRACE_ENABLED", "trace_span", "span", "enabled",
           "enable_tracing", "disable_tracing", "flush_trace",
           "trace_events", "reset_trace", "MAX_EVENTS", "OpRing",
           "promote_ring", "emit_op_event"]

TRACE_ENABLED = False
MAX_EVENTS = 1_000_000
# per-op ring capacity: bounds the allocation a never-kept op can pin
OP_RING_EVENTS = 4096
# ledger accounting (obs/ledger.py): estimated bytes per buffered event —
# a Chrome "X" dict with name/ts/dur/pid/tid/cat runs ~200 bytes of
# python objects; exact sizing per event would cost more than the buffer
_EVENT_EST_BYTES = 200
_ACC_TRACE = _ledger_account("trace.buffer",
                             capacity=lambda: MAX_EVENTS * _EVENT_EST_BYTES)

_LOCK = make_lock("trace.buffer")
_EVENTS: List[dict] = []
_SEEN_TIDS: set = set()   # (pid, tid) pairs with thread_name metadata out
_SEEN_PIDS: Dict[int, str] = {}  # op pid -> label, process_name emitted
_TRACE_PATH: Optional[str] = None
_ATEXIT_REGISTERED = False
# one epoch per process: span timestamps are µs since this mark, so every
# thread's spans share one Perfetto timeline
_EPOCH = time.perf_counter()

# set by an active op scope (obs/scope.py): (pid, label) giving spans a
# per-request Perfetto track, and the per-op ring for unsampled ops.
# Context variables — pool workers inherit them with the op's context.
_TRACK: "contextvars.ContextVar[Optional[Tuple[int, str]]]" = \
    contextvars.ContextVar("parquet_tpu_trace_track", default=None)
_SINK: "contextvars.ContextVar[Optional[OpRing]]" = \
    contextvars.ContextVar("parquet_tpu_trace_sink", default=None)
# stage-breakdown hook, bound by obs/scope.py at import: called as
# (span_name, duration_s) for every completed span while tracing is on
_ON_SPAN = None


class _NullSpan:
    """The disabled-tracing singleton: a context manager that does nothing
    and allocates nothing.  Identity-stable so tests can assert the
    disabled path never constructs per-call objects."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# span-name -> histogram, resolved once: per-span-exit observation must not
# take the registry's get-or-create lock or rebuild the key string (the
# registry's no-global-lock-on-increment contract; a lost race just
# resolves the same get-or-create metric twice)
_SPAN_HISTS: Dict[str, object] = {}


def _span_hist(name: str):
    h = _SPAN_HISTS.get(name)
    if h is None:
        h = _SPAN_HISTS[name] = _metrics.histogram("span." + name + "_s")
    return h


class _Span:
    """One enabled span: perf_counter timestamps, the worker thread id it
    ran on, and a Chrome complete ("X") event on exit."""

    __slots__ = ("name", "attrs", "_t0", "_tid")

    def __init__(self, name: str, attrs: Optional[Dict] = None):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._tid = threading.get_ident()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if not TRACE_ENABLED:  # disabled mid-span: nothing to record into
            return False
        dur = t1 - self._t0
        _span_hist(self.name).observe(dur)
        cb = _ON_SPAN
        if cb is not None:
            # per-op stage breakdown (obs/scope.py): metrics are never
            # sampled, so the op's stage seconds accumulate even for spans
            # the sampler diverts or discards
            cb(self.name, dur)
        track = _TRACK.get()
        ev = {"name": self.name, "ph": "X",
              "pid": track[0] if track is not None else _PID,
              "tid": self._tid,
              "ts": round((self._t0 - _EPOCH) * 1e6, 3),
              "dur": round(dur * 1e6, 3),
              "cat": self.name.split(".", 1)[0]}
        if self.attrs:
            ev["args"] = {k: _jsonable(v) for k, v in self.attrs.items()}
        sink = _SINK.get()
        if sink is not None:
            # unsampled op: park in the per-op ring — no global lock, no
            # metadata bookkeeping; promote_ring pays those only on keep
            sink.append(ev, threading.current_thread().name)
            return False
        _append_global(ev, track, threading.current_thread().name)
        return False


_PID = os.getpid()


def _append_global(ev: dict, track, thread_name: str) -> None:
    with _LOCK:
        if len(_EVENTS) >= MAX_EVENTS:
            _metrics.counter("trace.events_dropped").inc()
            return
        _ensure_meta_locked(ev["pid"], ev["tid"], track, thread_name)
        _EVENTS.append(ev)
        _ACC_TRACE.set(len(_EVENTS) * _EVENT_EST_BYTES)


def _ensure_meta_locked(pid: int, tid: int, track, thread_name: str) -> None:
    """Emit the one-time Perfetto metadata naming this event's tracks:
    ``process_name`` labels an op's per-request track group (pid = op id),
    ``thread_name`` labels the worker thread inside it."""
    if track is not None and pid not in _SEEN_PIDS:
        _SEEN_PIDS[pid] = track[1]
        _EVENTS.append({"name": "process_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": track[1]}})
    key = (pid, tid)
    if key not in _SEEN_TIDS:
        # Perfetto names thread tracks from "M" metadata events —
        # emitted once per (track, thread) so pool workers are labeled
        _SEEN_TIDS.add(key)
        _EVENTS.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": thread_name}})


class OpRing:
    """Per-op span buffer for ops head sampling decided not to trace:
    bounded (oldest events drop first — a slow op's recent stages matter
    most), lock-cheap, discarded whole when the op finishes fast, and
    promoted into the global buffer by :func:`promote_ring` when tail
    capture keeps the op."""

    __slots__ = ("events", "dropped", "cap", "_lock")

    def __init__(self, cap: int = OP_RING_EVENTS):
        self.cap = cap
        self.events: deque = deque()
        self.dropped = 0
        self._lock = make_lock("trace.op_ring")

    def append(self, ev: dict, thread_name: str) -> None:
        with self._lock:
            if len(self.events) >= self.cap:
                self.events.popleft()
                self.dropped += 1
            self.events.append((ev, thread_name))


def promote_ring(ring: OpRing, track) -> None:
    """Move a kept op's ring events into the global trace buffer (with the
    metadata naming its track), accounting ring overflow and buffer-cap
    drops in ``trace.events_dropped``."""
    with ring._lock:
        items = list(ring.events)
        dropped = ring.dropped
        ring.events.clear()
        ring.dropped = 0
    with _LOCK:
        for i, (ev, tname) in enumerate(items):
            if len(_EVENTS) >= MAX_EVENTS:
                dropped += len(items) - i
                break
            _ensure_meta_locked(ev["pid"], ev["tid"], track, tname)
            _EVENTS.append(ev)
        _ACC_TRACE.set(len(_EVENTS) * _EVENT_EST_BYTES)
    if dropped:
        _metrics.counter("trace.events_dropped").inc(dropped)


def emit_op_event(name: str, track, t0: float, dur_s: float,
                  attrs: Optional[Dict] = None) -> None:
    """Record one whole-operation "X" span (obs/scope.py emits this at op
    finish, covering the op's first activation to its last)."""
    if not TRACE_ENABLED:
        return
    ev = {"name": name, "ph": "X",
          "pid": track[0] if track is not None else _PID,
          "tid": threading.get_ident(),
          "ts": round((t0 - _EPOCH) * 1e6, 3),
          "dur": round(dur_s * 1e6, 3),
          "cat": name.split(".", 1)[0]}
    if attrs:
        ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
    _append_global(ev, track, threading.current_thread().name)


def enabled() -> bool:
    return TRACE_ENABLED


def trace_span(name: str, **attrs):
    """Context manager for one traced stage: ``with trace_span("decode",
    col="x"): ...``.  With tracing disabled this returns the shared no-op
    singleton — the hottest call sites additionally guard with
    ``if trace.TRACE_ENABLED:`` to skip even the call."""
    if not TRACE_ENABLED:
        return NULL_SPAN
    return _Span(name, attrs or None)


span = trace_span  # the short form instrumentation sites import


def enable_tracing(path: Optional[str] = None) -> None:
    """Turn span collection on.  ``path`` (optional) is where
    :func:`flush_trace` and the interpreter-exit hook write the Chrome
    trace JSON; without one, events stay in memory for
    :func:`trace_events`/an explicit ``flush_trace(path)``."""
    global TRACE_ENABLED, _TRACE_PATH, _ATEXIT_REGISTERED
    with _LOCK:
        _TRACE_PATH = os.fspath(path) if path is not None else _TRACE_PATH
        TRACE_ENABLED = True
        if _TRACE_PATH is not None and not _ATEXIT_REGISTERED:
            _ATEXIT_REGISTERED = True
            atexit.register(_flush_at_exit)


def disable_tracing() -> None:
    global TRACE_ENABLED
    TRACE_ENABLED = False


def reset_trace() -> None:
    """Drop buffered events (tests; does not change the enabled state)."""
    with _LOCK:
        _EVENTS.clear()
        _SEEN_TIDS.clear()
        _SEEN_PIDS.clear()
        _ACC_TRACE.set(0)  # same critical section: no stale-gauge window


def trace_events() -> List[dict]:
    """Copy of the buffered events (tests and programmatic consumers)."""
    with _LOCK:
        return list(_EVENTS)


def flush_trace(path: Optional[str] = None) -> Optional[str]:
    """Write the buffered spans as Chrome trace-event JSON (the object
    form: ``{"traceEvents": [...]}``) — loadable by Perfetto
    (ui.perfetto.dev) and chrome://tracing.  Returns the path written, or
    None when there is no path to write to.  The buffer is kept (a later
    flush rewrites the file with the fuller trace).

    Atomic, same pattern as ``AtomicFileSink``: the JSON lands in a
    unique temp file, is fsynced, then ``os.replace``d over the
    destination — a crash mid-flush leaves the previous trace intact
    (never a truncated file Perfetto rejects), and a failed flush removes
    its temp."""
    p = os.fspath(path) if path is not None else _TRACE_PATH
    if p is None:
        return None
    with _LOCK:
        events = list(_EVENTS)
    body = {"traceEvents": events, "displayTimeUnit": "ms"}
    tmp = f"{p}.{os.getpid()}.tmp"  # unique per process: concurrent
    # flushers to one path race at the replace, not inside the write
    try:
        with open(tmp, "w") as f:
            json.dump(body, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return p


def _flush_at_exit() -> None:
    try:
        # not gated on TRACE_ENABLED: disabling tracing after a traced
        # workload must not discard the buffer the env var promised to
        # a file
        if _TRACE_PATH is not None and _EVENTS:
            flush_trace()
    except OSError:
        pass  # exit-time flush is best-effort


_env_path = env_str("PARQUET_TPU_TRACE")
if _env_path:
    enable_tracing(_env_path)
