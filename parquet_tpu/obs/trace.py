"""Span tracing: Chrome trace-event JSON you can drop into Perfetto.

PR 3–6 shipped pipeline claims — encode/emit overlap ratios, prefetch
bubbles, late-materialization skips — as *numbers* in stats dataclasses.
This module makes them *visible*: every load-bearing stage (footer open,
prefetch window issue/wait, per-column decode, planner cascade, host-scan
phase 1/2, encode/emit, sink flush, H2D staging, pool task queue→run) is
wrapped in a :func:`trace_span`, each completed span records its
worker-thread id, and the buffer flushes to the Chrome ``traceEvents``
JSON format (Perfetto / ``chrome://tracing`` load it directly) — so
pipeline overlap shows up as literally overlapping bars on different
thread tracks.

Overhead contract — tracing OFF is the production default and must cost
nothing measurable:

- ``TRACE_ENABLED`` is a module-level bool.  The hottest sites read it
  directly (``if trace.TRACE_ENABLED:``) and skip span construction
  entirely.
- :func:`trace_span` called while disabled returns one shared no-op
  singleton — no object allocation, no timestamps, no lock.

Enabling:

- ``PARQUET_TPU_TRACE=/path/trace.json`` (env, read at import): tracing
  on for the process, buffer flushed to that path at interpreter exit.
- :func:`enable_tracing`/:func:`disable_tracing`/:func:`flush_trace` —
  the programmatic controls (tests, notebooks).

The event buffer is bounded (:data:`MAX_EVENTS`); overflow drops new
events and counts them in the ``trace.events_dropped`` metric instead of
growing without bound.  While tracing is on, each completed span also
feeds a ``span.<name>_s`` latency histogram in the metrics registry, so
stage p50/p99 come for free with a traced run.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Dict, List, Optional

from . import metrics as _metrics

__all__ = ["TRACE_ENABLED", "trace_span", "span", "enabled",
           "enable_tracing", "disable_tracing", "flush_trace",
           "trace_events", "reset_trace", "MAX_EVENTS"]

TRACE_ENABLED = False
MAX_EVENTS = 1_000_000

_LOCK = threading.Lock()
_EVENTS: List[dict] = []
_SEEN_TIDS: set = set()
_TRACE_PATH: Optional[str] = None
_ATEXIT_REGISTERED = False
# one epoch per process: span timestamps are µs since this mark, so every
# thread's spans share one Perfetto timeline
_EPOCH = time.perf_counter()


class _NullSpan:
    """The disabled-tracing singleton: a context manager that does nothing
    and allocates nothing.  Identity-stable so tests can assert the
    disabled path never constructs per-call objects."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# span-name -> histogram, resolved once: per-span-exit observation must not
# take the registry's get-or-create lock or rebuild the key string (the
# registry's no-global-lock-on-increment contract; a lost race just
# resolves the same get-or-create metric twice)
_SPAN_HISTS: Dict[str, object] = {}


def _span_hist(name: str):
    h = _SPAN_HISTS.get(name)
    if h is None:
        h = _SPAN_HISTS[name] = _metrics.histogram("span." + name + "_s")
    return h


class _Span:
    """One enabled span: perf_counter timestamps, the worker thread id it
    ran on, and a Chrome complete ("X") event on exit."""

    __slots__ = ("name", "attrs", "_t0", "_tid")

    def __init__(self, name: str, attrs: Optional[Dict] = None):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._tid = threading.get_ident()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if not TRACE_ENABLED:  # disabled mid-span: nothing to record into
            return False
        dur = t1 - self._t0
        _span_hist(self.name).observe(dur)
        ev = {"name": self.name, "ph": "X", "pid": _PID, "tid": self._tid,
              "ts": round((self._t0 - _EPOCH) * 1e6, 3),
              "dur": round(dur * 1e6, 3),
              "cat": self.name.split(".", 1)[0]}
        if self.attrs:
            ev["args"] = {k: _jsonable(v) for k, v in self.attrs.items()}
        with _LOCK:
            if len(_EVENTS) >= MAX_EVENTS:
                _metrics.counter("trace.events_dropped").inc()
                return False
            if self._tid not in _SEEN_TIDS:
                # Perfetto names thread tracks from "M" metadata events —
                # emitted once per thread so pool workers are labeled
                _SEEN_TIDS.add(self._tid)
                _EVENTS.append({
                    "name": "thread_name", "ph": "M", "pid": _PID,
                    "tid": self._tid,
                    "args": {"name": threading.current_thread().name}})
            _EVENTS.append(ev)
        return False


_PID = os.getpid()


def enabled() -> bool:
    return TRACE_ENABLED


def trace_span(name: str, **attrs):
    """Context manager for one traced stage: ``with trace_span("decode",
    col="x"): ...``.  With tracing disabled this returns the shared no-op
    singleton — the hottest call sites additionally guard with
    ``if trace.TRACE_ENABLED:`` to skip even the call."""
    if not TRACE_ENABLED:
        return NULL_SPAN
    return _Span(name, attrs or None)


span = trace_span  # the short form instrumentation sites import


def enable_tracing(path: Optional[str] = None) -> None:
    """Turn span collection on.  ``path`` (optional) is where
    :func:`flush_trace` and the interpreter-exit hook write the Chrome
    trace JSON; without one, events stay in memory for
    :func:`trace_events`/an explicit ``flush_trace(path)``."""
    global TRACE_ENABLED, _TRACE_PATH, _ATEXIT_REGISTERED
    with _LOCK:
        _TRACE_PATH = os.fspath(path) if path is not None else _TRACE_PATH
        TRACE_ENABLED = True
        if _TRACE_PATH is not None and not _ATEXIT_REGISTERED:
            _ATEXIT_REGISTERED = True
            atexit.register(_flush_at_exit)


def disable_tracing() -> None:
    global TRACE_ENABLED
    TRACE_ENABLED = False


def reset_trace() -> None:
    """Drop buffered events (tests; does not change the enabled state)."""
    with _LOCK:
        _EVENTS.clear()
        _SEEN_TIDS.clear()


def trace_events() -> List[dict]:
    """Copy of the buffered events (tests and programmatic consumers)."""
    with _LOCK:
        return list(_EVENTS)


def flush_trace(path: Optional[str] = None) -> Optional[str]:
    """Write the buffered spans as Chrome trace-event JSON (the object
    form: ``{"traceEvents": [...]}``) — loadable by Perfetto
    (ui.perfetto.dev) and chrome://tracing.  Returns the path written, or
    None when there is no path to write to.  The buffer is kept (a later
    flush rewrites the file with the fuller trace)."""
    p = os.fspath(path) if path is not None else _TRACE_PATH
    if p is None:
        return None
    with _LOCK:
        events = list(_EVENTS)
    body = {"traceEvents": events, "displayTimeUnit": "ms"}
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(body, f)
    os.replace(tmp, p)
    return p


def _flush_at_exit() -> None:
    try:
        # not gated on TRACE_ENABLED: disabling tracing after a traced
        # workload must not discard the buffer the env var promised to
        # a file
        if _TRACE_PATH is not None and _EVENTS:
            flush_trace()
    except OSError:
        pass  # exit-time flush is best-effort


_env_path = os.environ.get("PARQUET_TPU_TRACE", "").strip()
if _env_path:
    enable_tracing(_env_path)
