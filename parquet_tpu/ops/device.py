"""Device (XLA/jnp) decode kernels — the TPU compute path.

Reference parity: these replace the reference's amd64 assembly kernels
(SURVEY.md §2.3: internal/bitpack, encoding/rle asm, delta asm,
bytestreamsplit asm) at the same insertion point — the ``encoding.Encoding``
registry.  Design per SURVEY.md §7:

- All kernels are pure functions of flat uint8 buffers + small metadata
  arrays, jit-compiled with static shapes (bucket-padded by the caller).
- The inherently sequential work (run-header varint scans, miniblock header
  walks) happens on host at *metadata* scale (bytes per run/miniblock), then
  the device does the wide expansion at *data* scale — the two-pass split of
  SURVEY.md §7 hard part 1.
- Everything is a gather/shift/mask/cumsum — no data-dependent control flow,
  so XLA fuses freely.  Pallas variants for the hottest kernels live in
  ``pallas_kernels.py``.

**32-bit-lane discipline (TPU-first):** TPU VPUs are 32-bit-lane machines and
this stack's TPU compile path rewrites away 64-bit element types (64-bit
``bitcast_convert_type`` is unimplemented there, and miscompiles on some CPU
builds).  So device kernels NEVER bitcast 64-bit types: 64-bit columns live on
device as ``(n, 2)`` uint32 pairs — byte-exact, converted to int64/float64 by
a zero-copy ``.view()`` at host materialization — and all bit-unpacking is
32-bit shift/mask arithmetic.  Only DELTA_BINARY_PACKED's int64 prefix-sum
uses (emulated) s64 *arithmetic*, which the rewrite does support.

int64 note: importing this module enables jax x64 (needed for s64 cumsum and
wide bit offsets) unless PARQUET_TPU_NO_X64 is set.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax

from ..utils.env import env_bool

if not env_bool("PARQUET_TPU_NO_X64"):
    jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from ..utils.debug import counters

_U32 = jnp.uint32


# ---------------------------------------------------------------------------
# Word gathers (arithmetic combine — no 64-bit bitcasts anywhere)
#
# TPU-first: per-element loads are the expensive primitive, so an unaligned
# 32-bit read is TWO aligned word gathers + shift-combine (not four byte
# gathers), and all index math runs in int32 lanes — a chunk's staged buffer
# is < 2^27 bytes (enforced at staging), so bit positions fit int32 and the
# compiler never emits emulated-64-bit index vectors on the hot path.
# ---------------------------------------------------------------------------

#: staged buffers larger than this fall back to the host path: bit offsets
#: must fit int32 (2^27 bytes → 2^30 bits), keeping index math in 32-bit lanes
MAX_DEVICE_BUF = 1 << 27


def _as_words(buf: jax.Array) -> jax.Array:
    """uint8 staged buffer → uint32 little-endian word view (zero-padded to a
    word boundary; out-of-range word gathers are clamped by XLA and the
    garbage bits always fall outside the value mask)."""
    if buf.shape[0] % 4:
        buf = jnp.pad(buf, (0, 4 - buf.shape[0] % 4))
    return jax.lax.bitcast_convert_type(buf.reshape(-1, 4), _U32)


def _word_at(bit_starts: jax.Array):
    """(aligned word index, in-word shift) for each unaligned bit position."""
    wi = (bit_starts >> 5).astype(jnp.int32)
    sh = (bit_starts.astype(jnp.int32) & 31).astype(_U32)
    return wi, sh


# ---------------------------------------------------------------------------
# PLAIN fixed-width (the config[0] minimum slice: decode == reinterpret)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n", "dtype"))
def bitcast_fixed32(buf: jax.Array, n: int, dtype: str) -> jax.Array:
    """uint8 → {int32,uint32,float32}[n] (PLAIN 4-byte types)."""
    return jax.lax.bitcast_convert_type(
        buf[: n * 4].reshape(n, 4), jnp.dtype(dtype)).reshape(n)


@partial(jax.jit, static_argnames=("n",))
def fixed64_pairs(buf: jax.Array, n: int) -> jax.Array:
    """uint8 → uint32[n,2] lo/hi pairs (PLAIN 8-byte types, byte-exact)."""
    return jax.lax.bitcast_convert_type(
        buf[: n * 8].reshape(n, 2, 4), _U32).reshape(n, 2)


@partial(jax.jit, static_argnames=("n",))
def unpack_bools(buf: jax.Array, n: int) -> jax.Array:
    """PLAIN BOOLEAN: LSB-first bit-unpack."""
    nbytes = (n + 7) // 8
    bits = (buf[:nbytes, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return bits.reshape(-1)[:n].astype(jnp.bool_)


# ---------------------------------------------------------------------------
# Generic bit-unpack: the single most load-bearing kernel (SURVEY.md §2.3)
# ---------------------------------------------------------------------------


def unpack_bits_at32(buf: jax.Array, bit_starts: jax.Array, widths) -> jax.Array:
    """One ≤32-bit LSB-first integer per element at absolute bit positions.

    ``widths`` may be scalar or per-element (mixed-width streams: a whole
    chunk of differently-packed pages decodes in ONE call).  uint32 out.
    Covers levels, dictionary indexes, and int32 deltas — the hot 99%.
    Two aligned word gathers per element; int32 index math throughout.
    """
    words = _as_words(buf)
    wi, sh = _word_at(bit_starts)
    w0 = words[wi]
    w1 = words[wi + 1]
    # sh==0 must not shift by 32 (UB): force the hi word's contribution to 0
    hi = jnp.where(sh > 0, w1 << ((_U32(32) - sh) & _U32(31)), _U32(0))
    val = (w0 >> sh) | hi
    w32 = jnp.asarray(widths).astype(_U32)
    mask = jnp.where(w32 >= 32, _U32(0xFFFFFFFF), (_U32(1) << w32) - _U32(1))
    return val & mask


def unpack_bits_at64(buf: jax.Array, bit_starts: jax.Array, widths
                     ) -> Tuple[jax.Array, jax.Array]:
    """Like :func:`unpack_bits_at32` for widths ≤ 64 → (lo, hi) uint32 pair.
    Three aligned word gathers per element."""
    words = _as_words(buf)
    wi, sh = _word_at(bit_starts)
    w0 = words[wi]
    w1 = words[wi + 1]
    w2 = words[wi + 2]
    nz = sh > 0
    inv = (_U32(32) - sh) & _U32(31)
    lo = (w0 >> sh) | jnp.where(nz, w1 << inv, _U32(0))
    hi = jnp.where(nz, (w1 >> sh) | (w2 << inv), w1)
    w32 = jnp.asarray(widths).astype(_U32)
    lo_bits = jnp.minimum(w32, _U32(32))
    hi_bits = jnp.maximum(w32, _U32(32)) - _U32(32)
    lo_mask = jnp.where(lo_bits >= 32, _U32(0xFFFFFFFF), (_U32(1) << lo_bits) - _U32(1))
    hi_mask = jnp.where(hi_bits >= 32, _U32(0xFFFFFFFF), (_U32(1) << hi_bits) - _U32(1))
    return lo & lo_mask, hi & hi_mask


@partial(jax.jit, static_argnames=("n", "width"))
def unpack_bits(buf: jax.Array, n: int, width: int, offset_bits: int = 0) -> jax.Array:
    """Dense LSB-first unpack of ``n`` ``width``-bit integers (≤32 → u32,
    else → (n,2) u32 pairs)."""
    starts = jnp.arange(n, dtype=jnp.int32) * width + offset_bits
    if width <= 32:
        return unpack_bits_at32(buf, starts, width)
    lo, hi = unpack_bits_at64(buf, starts, width)
    return jnp.stack([lo, hi], axis=1)


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid expansion (device half of the two-pass split)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n",))
def rle_expand(
    buf: jax.Array,  # uint8 payload (whole chunk, padded +12)
    n: int,  # total output values (static, padded ok)
    run_ends: jax.Array,  # int32/int64[k] cumulative output counts per run
    run_kinds: jax.Array,  # uint8[k] 0=RLE 1=bit-packed
    run_payloads: jax.Array,  # int32[k] repeated value for RLE runs
    run_bit_offsets: jax.Array,  # int32/int64[k] absolute bit offset of packed data
    run_widths: jax.Array,  # int32[k] bit width (per run: pages may differ!)
) -> jax.Array:
    """Expand a pre-scanned hybrid stream (levels / dict indexes, ≤32-bit):
    one gather-driven pass, no sequential dependencies.  int32 out."""
    ends = run_ends.astype(jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    run_id = jnp.searchsorted(ends, idx, side="right")
    run_id = jnp.minimum(run_id, ends.shape[0] - 1).astype(jnp.int32)
    counts = jnp.diff(ends, prepend=jnp.int32(0))
    starts = ends[run_id] - counts[run_id]
    within = idx - starts
    w = run_widths[run_id]
    bit_pos = run_bit_offsets[run_id].astype(jnp.int32) + within * w
    packed = unpack_bits_at32(buf, bit_pos, w).astype(jnp.int32)
    return jnp.where(run_kinds[run_id] == 0, run_payloads[run_id], packed)


# ---------------------------------------------------------------------------
# DELTA_BINARY_PACKED (miniblock unpack + cumsum — SURVEY.md §2.2: "excellent fit")
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n", "vpm"))
def delta_decode32(
    buf: jax.Array, n: int, first_value: jax.Array,
    mb_bit_offsets: jax.Array, mb_widths: jax.Array, mb_min_deltas: jax.Array,
    vpm: int,
) -> jax.Array:
    """INT32 delta decode.  All arithmetic is mod-2^32 (two's complement
    wrap), so 32-bit lanes suffice even though raw deltas span 33 bits."""
    nd = n - 1
    if nd <= 0:
        return jnp.full((max(n, 0),), first_value.astype(jnp.int32))
    i = jnp.arange(nd, dtype=jnp.int32)
    mb = i // vpm
    within = i % vpm
    w = mb_widths[mb]
    bit_pos = mb_bit_offsets[mb].astype(jnp.int32) + within * w
    raw = unpack_bits_at32(buf, bit_pos, w)
    min32 = (mb_min_deltas & jnp.int64(0xFFFFFFFF)).astype(_U32)
    deltas = raw + min32[mb]
    first32 = (first_value.astype(jnp.int64) & jnp.int64(0xFFFFFFFF)).astype(_U32)
    seq = jnp.concatenate([first32.reshape(1), deltas])
    return jax.lax.bitcast_convert_type(jnp.cumsum(seq), jnp.int32)


@partial(jax.jit, static_argnames=("n", "vpm"))
def delta_decode64(
    buf: jax.Array, n: int, first_value: jax.Array,
    mb_bit_offsets: jax.Array, mb_widths: jax.Array, mb_min_deltas: jax.Array,
    vpm: int,
) -> jax.Array:
    """INT64 delta decode → (n,2) uint32 pairs.  Unpack is 32-bit lane work;
    only the prefix-sum runs in (emulated) s64 arithmetic."""
    nd = n - 1
    if nd <= 0:
        v = first_value.astype(jnp.int64).reshape(1)
        return _i64_to_pairs(jnp.broadcast_to(v, (max(n, 1),)))[:n]
    i = jnp.arange(nd, dtype=jnp.int32)
    mb = i // vpm
    within = i % vpm
    w = mb_widths[mb]
    bit_pos = mb_bit_offsets[mb].astype(jnp.int32) + within * w
    lo, hi = unpack_bits_at64(buf, bit_pos, w)
    raw = lo.astype(jnp.int64) | (hi.astype(jnp.int64) << 32)
    deltas = raw + mb_min_deltas[mb]
    seq = jnp.concatenate([first_value.astype(jnp.int64).reshape(1), deltas])
    return _i64_to_pairs(jnp.cumsum(seq))


def _i64_to_pairs(v: jax.Array) -> jax.Array:
    lo = (v & jnp.int64(0xFFFFFFFF)).astype(_U32)
    hi = ((v >> 32) & jnp.int64(0xFFFFFFFF)).astype(_U32)
    return jnp.stack([lo, hi], axis=1)


def delta_prescan(data: np.ndarray, pos: int = 0):
    """Host pre-scan of a DELTA_BINARY_PACKED stream → device metadata.

    Returns (first_value, total, vpm, mb_bit_offsets, mb_widths,
    mb_min_deltas, end_pos).  O(miniblocks), not O(values).  Routes through
    the C++ shim (one uvarint walk); this Python body is the oracle/fallback
    and the precise-error path for malformed streams."""
    from . import ref
    from .. import native

    nat = native.delta_prescan(data, pos)
    if nat is not None:
        first, total, vpm, offsets, widths, mins, end = nat
        return (first, total, vpm, offsets, widths, mins, end)

    block_size, pos = ref.read_uvarint(data, pos)
    n_miniblocks, pos = ref.read_uvarint(data, pos)
    total, pos = ref.read_uvarint(data, pos)
    first_raw, pos = ref.read_uvarint(data, pos)
    first = ref.unzigzag(first_raw)
    if n_miniblocks == 0 or block_size == 0 or block_size % n_miniblocks:
        raise ValueError(
            f"malformed DELTA_BINARY_PACKED header: block_size={block_size}, "
            f"miniblocks={n_miniblocks}")
    vpm = block_size // n_miniblocks
    offsets, widths, mins = [], [], []
    got = 1
    while got < total:
        md_raw, pos = ref.read_uvarint(data, pos)
        min_delta = ref.unzigzag(md_raw)
        wbytes = data[pos : pos + n_miniblocks]
        pos += n_miniblocks
        for m in range(n_miniblocks):
            if got >= total:
                break
            w = int(wbytes[m])
            offsets.append(pos * 8)
            widths.append(w)
            mins.append(min_delta)
            pos += vpm * w // 8
            got += min(vpm, total - got)
    return (
        first, total, vpm,
        np.asarray(offsets, dtype=np.int64),
        np.asarray(widths, dtype=np.int32),
        np.asarray(mins, dtype=np.int64),
        pos,
    )


# ---------------------------------------------------------------------------
# BYTE_STREAM_SPLIT (plane transpose; 64-bit types → u32 pairs)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n", "width", "out_dtype"))
def byte_stream_split(buf: jax.Array, n: int, width: int,
                      out_dtype: Optional[str] = None) -> jax.Array:
    planes = buf[: width * n].reshape(width, n)
    interleaved = planes.T  # (n, width) bytes
    if out_dtype is None:
        return interleaved
    if width == 4:
        return jax.lax.bitcast_convert_type(interleaved, jnp.dtype(out_dtype)).reshape(n)
    assert width == 8
    return jax.lax.bitcast_convert_type(
        interleaved.reshape(n, 2, 4), _U32).reshape(n, 2)  # pairs; host views dtype


# ---------------------------------------------------------------------------
# DELTA_BYTE_ARRAY (front coding: host prefix-length prescan, suffix
# gather + prefix resolution by pointer jumping on chip)
# ---------------------------------------------------------------------------


def delta_byte_array_prescan(data: np.ndarray, pos: int = 0):
    """Host pre-scan of one DELTA_BYTE_ARRAY page → device-kernel inputs.

    Returns ``(prefix_lens int64, suffix bytes, suffix_offs int32, end)``.
    O(values) in the length METADATA only — no output byte is expanded on
    host; the suffix stream ships to HBM raw and
    :func:`delta_byte_array_expand` materializes the front-coded output
    there."""
    from . import ref

    return ref.decode_delta_byte_array_parts(data, pos)


def delta_byte_array_iters(prefix_lens: np.ndarray) -> int:
    """Pointer-jumping rounds :func:`delta_byte_array_expand` needs: a
    prefix byte chases parents through at most the longest consecutive
    run of entries with a nonzero prefix (the entry before any run starts
    from scratch, so its bytes all resolve to suffix bytes), and each
    round squares the resolved distance."""
    nz = np.asarray(prefix_lens) > 0
    if not nz.size or not nz.any():
        return 0
    edges = np.flatnonzero(np.diff(
        np.concatenate(([False], nz, [False])).astype(np.int8)))
    depth = int((edges[1::2] - edges[0::2]).max())
    return max(int(np.ceil(np.log2(depth + 1))), 1)


@partial(jax.jit, static_argnames=("total", "iters"))
def delta_byte_array_expand(suffix_buf: jax.Array, prefix_lens: jax.Array,
                            suffix_offs: jax.Array, entry_offs: jax.Array,
                            total: int, iters: int) -> jax.Array:
    """Expand a front-coded byte-array stream on chip.

    Every output byte either lives in the suffix stream (position ≥ the
    entry's prefix length — a direct gather) or repeats the byte at the
    same offset of the PREVIOUS entry's output.  Prefix bytes start as
    pointers into the previous entry and resolve by pointer jumping
    (``ptr = ptr[ptr]``, ``iters`` rounds — log of the deepest prefix
    chain, computed exactly on host); suffix bytes are fixed points.  One
    final gather materializes the output with no sequential dependency —
    the host oracle's entry-by-entry loop does not vectorize."""
    if total == 0:
        return jnp.zeros(0, jnp.uint8)
    pos = jnp.arange(total, dtype=jnp.int32)
    e = jnp.searchsorted(entry_offs, pos, side="right").astype(jnp.int32) - 1
    j = pos - entry_offs[e]
    in_suffix = j >= prefix_lens[e]
    direct = suffix_offs[e] + jnp.where(in_suffix, j - prefix_lens[e], 0)
    prev_start = entry_offs[jnp.maximum(e - 1, 0)]
    ptr = jnp.where(in_suffix, pos, prev_start + j)
    ptr = jax.lax.fori_loop(0, iters, lambda _, p: p[p], ptr)
    return suffix_buf[direct[ptr]]


# ---------------------------------------------------------------------------
# Dictionary gather + level math (trivial but central)
# ---------------------------------------------------------------------------


@jax.jit
def dict_gather(dictionary: jax.Array, indices: jax.Array) -> jax.Array:
    return jnp.take(dictionary, indices, axis=0)


@partial(jax.jit, static_argnames=("max_def",))
def validity_from_def(def_levels: jax.Array, max_def: int) -> jax.Array:
    return def_levels == max_def


@jax.jit
def cumsum_offsets(lengths: jax.Array) -> jax.Array:
    return jnp.concatenate([jnp.zeros(1, jnp.int64),
                            jnp.cumsum(lengths.astype(jnp.int64))])


@jax.jit
def scatter_valid(values: jax.Array, validity: jax.Array) -> jax.Array:
    """Dense present values → slot-aligned array (nulls get 0)."""
    slot_of_value = jnp.cumsum(validity.astype(jnp.int32)) - 1
    gathered = values[jnp.clip(slot_of_value, 0, values.shape[0] - 1)]
    zero = jnp.zeros((), dtype=values.dtype)
    if values.ndim > 1:
        return jnp.where(validity[:, None], gathered, zero)
    return jnp.where(validity, gathered, zero)


@partial(jax.jit, static_argnames=("is_float", "is_unsigned"))
def pair_range_mask(pairs: jax.Array, lo_pair: jax.Array, hi_pair: jax.Array,
                    has_lo: jax.Array, has_hi: jax.Array,
                    is_float: bool = False,
                    is_unsigned: bool = False) -> jax.Array:
    """lo <= value <= hi over the (n, 2) uint32 pair representation of
    64-bit values, without x64 mode.

    Comparison is lexicographic on (high word as ordering key, low word
    unsigned). For int64 the high word orders as *signed* int32 (unsigned
    logical: plain uint32); for double the IEEE total order needs the
    sign-magnitude flip (negative values order reversed), applied to both
    words of value and bounds. NaN keys are not treated specially (a range
    reaching +inf admits positive NaN bit patterns).
    """
    hw_dt = jnp.uint32 if is_unsigned else jnp.int32
    lo_w = pairs[:, 0]
    hi_w = pairs[:, 1].astype(hw_dt)
    b_lo = lo_pair[0]
    b_hi_lo = hi_pair[0]
    b_lo_hi = lo_pair[1].astype(hw_dt)
    b_hi_hi = hi_pair[1].astype(hw_dt)
    if is_float:
        # IEEE-754 total-order trick: flip all bits of negatives, flip only
        # the sign bit of non-negatives → unsigned lexicographic order
        def flip(h, l):
            neg = h < 0
            h_u = h.astype(jnp.uint32)
            fh = jnp.where(neg, ~h_u, h_u ^ jnp.uint32(0x80000000))
            fl = jnp.where(neg, ~l, l)
            return fh, fl

        hi_w_u, lo_w = flip(hi_w, lo_w)
        b_lo_hi_u, b_lo = flip(b_lo_hi, b_lo)
        b_hi_hi_u, b_hi_lo = flip(b_hi_hi, b_hi_lo)
        ge_lo = (hi_w_u > b_lo_hi_u) | ((hi_w_u == b_lo_hi_u) & (lo_w >= b_lo))
        le_hi = (hi_w_u < b_hi_hi_u) | ((hi_w_u == b_hi_hi_u) & (lo_w <= b_hi_lo))
    else:
        ge_lo = (hi_w > b_lo_hi) | ((hi_w == b_lo_hi) & (lo_w >= b_lo))
        le_hi = (hi_w < b_hi_hi) | ((hi_w == b_hi_hi) & (lo_w <= b_hi_lo))
    return (~has_lo | ge_lo) & (~has_hi | le_hi)


def assemble_single_list(def_levels: jax.Array, rep_levels: jax.Array,
                         dk: int, max_def: int):
    """Device twin of ops/levels.assemble for ONE repeated ancestor
    (SURVEY.md §7 hard part 4: level→(validity, offsets) as vector ops).

    ``dk`` is the repeated ancestor's def level. Returns
    ``(list_offsets, list_validity, leaf_validity)`` as device arrays — the
    same semantics as the host assembler: instances are row starts
    (``rep == 0``), elements are slots with ``def >= dk``, a row's list is
    non-null iff its start slot has ``def >= dk - 1``, and leaf validity
    (over elements) is ``def == max_def``.

    Shapes are data-dependent (rows, elements), so two scalar D2H syncs fix
    the sizes; all heavy math stays on device.
    """
    counts = _asl_cums(def_levels, rep_levels, dk)
    n_rows, n_elem = (int(x) for x in counts)
    return _asl_finish(def_levels, rep_levels, n_rows, n_elem, dk, max_def)


@partial(jax.jit, static_argnames=("dk",))
def _asl_cums(d: jax.Array, r: jax.Array, dk: int):
    """One dispatch for the two data-dependent sizes (rows, elements)."""
    n_elem = jnp.sum((d >= dk).astype(jnp.int32)) if d.shape[0] else jnp.int32(0)
    return jnp.stack([jnp.sum((r == 0).astype(jnp.int32)), n_elem])


@partial(jax.jit, static_argnames=("n_rows", "n_elem", "dk", "max_def"))
def _asl_finish(d, r, n_rows: int, n_elem: int, dk: int, max_def: int):
    inst_mask = r == 0
    elem = d >= dk
    cum = jnp.cumsum(elem.astype(jnp.int32))
    inst_idx = jnp.nonzero(inst_mask, size=n_rows, fill_value=0)[0].astype(jnp.int32)
    starts = cum[inst_idx] - elem[inst_idx].astype(jnp.int32)
    offsets = jnp.concatenate(
        [starts, cum[-1:] if d.shape[0] else jnp.zeros(1, jnp.int32)])
    list_validity = d[inst_idx] >= (dk - 1)
    elem_idx = jnp.nonzero(elem, size=n_elem, fill_value=0)[0].astype(jnp.int32)
    leaf_validity = (d == max_def)[elem_idx]
    return offsets, list_validity, leaf_validity


def assemble_nested(def_levels: jax.Array, rep_levels: jax.Array,
                    infos, max_def: int):
    """Device twin of ``ops/levels.assemble`` for ANY repetition depth
    (SURVEY.md §7 hard part 4, beyond the single-list case): per repeated
    level k — instances, element counts, offsets, list validity — all as
    whole-column vector ops over the expanded level streams, mirroring the
    host assembler's exact semantics (instances of level k: ``rep < k`` and
    ``def >= d_{k-1}``; elements: ``rep < k_next`` and ``def >= d_k``; a
    list is non-null iff its start slot has ``def >= d_k - 1``).

    ``infos`` is ``levels_ops.repeated_ancestors(leaf)``.  Returns
    ``(list_offsets, list_validity, leaf_validity)`` where the first two are
    LISTS with one device array per repeated level (outermost first) — the
    multi-level Column layout.  Shapes are data-dependent, so ONE count
    dispatch + D2H sync fixes every level's size; the finish pass is a
    single fused dispatch."""
    reps = tuple(int(i.rep_level) for i in infos)
    defs = tuple(int(i.def_level) for i in infos)
    counts = _an_counts(def_levels, rep_levels, reps, defs)
    sizes = tuple(int(x) for x in np.asarray(counts))
    return _an_finish(def_levels, rep_levels, sizes, reps, defs, max_def)


@partial(jax.jit, static_argnames=("reps", "defs"))
def _an_counts(d: jax.Array, r: jax.Array, reps, defs):
    outs = []
    if not d.shape[0]:
        return jnp.zeros(len(reps) + 1, jnp.int32)
    for i, k in enumerate(reps):
        inst = (r < k) if i == 0 else ((r < k) & (d >= defs[i - 1]))
        outs.append(jnp.sum(inst.astype(jnp.int32)))
    outs.append(jnp.sum((d >= defs[-1]).astype(jnp.int32)))
    return jnp.stack(outs)


@partial(jax.jit, static_argnames=("sizes", "reps", "defs", "max_def"))
def _an_finish(d, r, sizes, reps, defs, max_def: int):
    offsets = []
    validities = []
    nlev = len(reps)
    empty = not d.shape[0]
    for i, (k, dk) in enumerate(zip(reps, defs)):
        inst = (r < k) if i == 0 else ((r < k) & (d >= defs[i - 1]))
        inst_idx = jnp.nonzero(inst, size=sizes[i],
                               fill_value=0)[0].astype(jnp.int32)
        if i + 1 < nlev:
            elem = (r < reps[i + 1]) & (d >= dk)
        else:
            elem = d >= dk
        cum = jnp.cumsum(elem.astype(jnp.int32))
        starts = (jnp.where(inst_idx > 0, cum[jnp.maximum(inst_idx - 1, 0)], 0)
                  if not empty else jnp.zeros(0, jnp.int32))
        total = cum[-1:] if not empty else jnp.zeros(1, jnp.int32)
        offsets.append(jnp.concatenate([starts, total]))
        validities.append(d[inst_idx] >= (dk - 1) if not empty
                          else jnp.zeros(0, bool))
    elem_idx = jnp.nonzero(d >= defs[-1], size=sizes[-1],
                           fill_value=0)[0].astype(jnp.int32)
    leaf_validity = ((d == max_def)[elem_idx] if not empty
                     else jnp.zeros(0, bool))
    return offsets, validities, leaf_validity


def pad_to_bucket(arr: np.ndarray, extra: int = 12) -> np.ndarray:
    """Pad a host buffer to a power-of-two bucket (+slack for 12-byte gathers)
    so jit specializations are reused across similarly-sized pages."""
    n = len(arr) + extra
    bucket = 1 << max(int(n - 1).bit_length(), 6)
    if bucket == len(arr):
        return arr
    out = np.zeros(bucket, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def pairs_to_host(pairs, dtype) -> np.ndarray:
    """(n,2) u32 device pairs → host int64/float64 array (zero-copy view)."""
    return np.ascontiguousarray(np.asarray(pairs)).view(dtype).reshape(-1)
