"""Pluggable encoding registry.

Reference parity: ``encoding/encoding.go — Encoding`` (SURVEY.md §2.2, "the
TPU insertion point"): every page encoding is an interface value looked up by
id, so a third party can register one without editing the decoder.  This is
that registry for the host decode path: the built-in eight encodings register
themselves from ``io/reader.py`` at import, and
:func:`parquet_tpu.register_encoding` adds (or, with ``overwrite=True``,
replaces) entries — the page decoder dispatches purely through
:func:`lookup`.

A ``decode`` callable receives ``(raw, pos, nvals, leaf, physical,
dictionary)``:

- ``raw``: the uncompressed page body as a ``uint8`` numpy array,
- ``pos``: byte offset where the values section starts,
- ``nvals``: number of physical values to produce,
- ``leaf`` / ``physical``: schema leaf and physical type,
- ``dictionary``: the chunk's decoded dictionary (or None),

and returns the decoded value form the assembler understands: a typed numpy
array, a ``(values, offsets)`` pair for byte arrays, or a
``DictIndices(indices)`` wrapper for dictionary index streams.

An encoding may additionally carry a ``decode_masked`` callable — the
masked-emit variant the fused single-pass engine (io/fused.py) dispatches
through: same arguments plus ``take``, a sorted int64 array of PHYSICAL value
ordinals to emit, inserted after ``nvals`` — ``(raw, pos, nvals, take, leaf,
physical, dictionary)``.  It returns only the selected values (same forms as
``decode``), or None when this page can't be masked-decoded (the caller then
falls back to the full ``decode``).  ``decode_masked`` is optional; encodings
without one simply never take the fused masked path.

The accelerated device path (parallel/device_reader.py) plans only the
built-in encodings; a registered third-party encoding decodes on host and
flows into the same Column/Table machinery (identical behavior to the
reference, whose vectorized kernels also cover only the spec encodings).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

__all__ = ["EncodingSpec", "DictIndices", "register_encoding", "lookup",
           "registered_encodings"]


class DictIndices:
    """Marker wrapper: the decode produced dictionary indices, not values."""

    __slots__ = ("indices",)

    def __init__(self, indices):
        self.indices = indices


@dataclass(frozen=True)
class EncodingSpec:
    """One registered encoding: its wire id, a name for messages, the decode
    callable, and (optionally) the masked-emit ``decode_masked`` twin (see
    module docstring for both signatures)."""

    id: int
    name: str
    decode: Callable[..., Any]
    decode_masked: Optional[Callable[..., Any]] = None


_REGISTRY: Dict[int, EncodingSpec] = {}
_BUILTIN: Dict[int, EncodingSpec] = {}


def register_encoding(spec: EncodingSpec, overwrite: bool = False,
                      _builtin: bool = False) -> None:
    """Add an encoding to the decode dispatch (``overwrite=True`` replaces a
    built-in — the reference allows shadowing via its RowGroupOption list)."""
    key = int(spec.id)
    if not overwrite and key in _REGISTRY:
        raise ValueError(
            f"encoding id {key} ({_REGISTRY[key].name}) is already "
            "registered; pass overwrite=True to replace it")
    _REGISTRY[key] = spec
    if _builtin:
        _BUILTIN[key] = spec


def is_builtin_decode(encoding_id) -> bool:
    """True when the active decode for this id is the built-in one.  The
    accelerated device planner checks this and routes shadowed encodings to
    the host decoder, which dispatches through the registry."""
    key = int(encoding_id)
    return _REGISTRY.get(key) is _BUILTIN.get(key)


def lookup(encoding_id) -> Optional[EncodingSpec]:
    return _REGISTRY.get(int(encoding_id))


def registered_encodings() -> Dict[int, str]:
    """{id: name} of everything currently registered (builtins included)."""
    return {k: v.name for k, v in sorted(_REGISTRY.items())}
