"""Dremel definition/repetition level math, vectorized.

Reference parity: ``schema.go — Schema.Deconstruct / Schema.Reconstruct``
(SURVEY.md §3.1/§3.2) performs record-at-a-time shredding/assembly.  The
TPU-native formulation is whole-column vector math over the level streams
(SURVEY.md §7 hard part 4): def/rep levels → (validity bitmap, Arrow list
offsets) per nesting level, and the inverse for the write path.  Everything
here is numpy (host oracle); ``ops/device.py`` mirrors the hot direction in
jnp for on-device assembly.

Level semantics (Parquet spec):
  - each OPTIONAL ancestor adds 1 definition level; each REPEATED ancestor adds
    1 definition level AND 1 repetition level.
  - a leaf slot's def == max_def  ⇔ the value is present (non-null).
  - rep == k means the slot starts a new element of the level-k repeated
    ancestor's *innermost continuing* list; rep < k starts a new level-k list.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..format.enums import FieldRepetitionType as Rep
from ..schema.schema import Leaf


@dataclass
class LevelInfo:
    """Per-nesting-level decode plan for one leaf column."""

    rep_level: int  # repetition level of this repeated ancestor (1-based)
    def_level: int  # definition level of this repeated ancestor


def repeated_ancestors(leaf: Leaf) -> List[LevelInfo]:
    """The repeated nodes on the leaf's path, outermost first."""
    out = []
    d = 0
    r = 0
    for node in leaf.ancestors:
        if node.repetition == Rep.OPTIONAL:
            d += 1
        elif node.repetition == Rep.REPEATED:
            d += 1
            r += 1
            out.append(LevelInfo(rep_level=r, def_level=d))
    return out


@dataclass
class Assembled:
    """Arrow-style assembly of one leaf column.

    ``validity`` masks the *leaf value stream* (length = number of leaf slots
    with def >= value-def-level... trimmed to value count for flat columns).
    ``list_offsets[k]`` / ``list_validity[k]`` describe the k-th repeated
    ancestor, outermost first.  For flat columns both lists are empty.
    """

    validity: Optional[np.ndarray]  # bool[num_leaf_slots] or None if no nulls possible
    list_offsets: List[np.ndarray]
    list_validity: List[Optional[np.ndarray]]
    # map from leaf slot → dense value index is implicit: values are stored
    # densely for slots with def == max_def, in slot order.


def assemble(def_levels: Optional[np.ndarray], rep_levels: Optional[np.ndarray],
             leaf: Leaf) -> Assembled:
    """Turn level streams into per-level (offsets, validity) + leaf validity.

    Semantics (derived in the module docstring; level-k repeated ancestor has
    rep level k, def level d_k; innermost is level r):

    - *instances* of level k (entries of the k-1 layer): slots with
      ``rep < k`` and (for k>1) ``def >= d_{k-1}``.
    - an instance is a non-null list iff ``def >= d_k - 1`` at its start slot.
    - *elements* of level k: instances of level k+1; for the innermost level,
      slots with ``def >= d_r``.
    - leaf validity (over innermost elements): ``def == max_def``.

    Structs between repeated levels add def levels; their per-layer nullness
    is collapsed into the nearest list validity here (full struct reassembly is
    a table-layer concern).
    """
    max_def = leaf.max_definition_level
    max_rep = leaf.max_repetition_level
    if max_def == 0:
        return Assembled(validity=None, list_offsets=[], list_validity=[])
    if def_levels is None and max_rep == 0:
        # optional column whose pages were all all-present (the decoder's
        # fast path skips the level expansion): no nulls
        return Assembled(validity=None, list_offsets=[], list_validity=[])
    d = def_levels if def_levels is not None else np.zeros(0, dtype=np.int32)
    if max_rep == 0:
        return Assembled(validity=(d == max_def), list_offsets=[], list_validity=[])
    r = rep_levels if rep_levels is not None else np.zeros(0, dtype=np.int32)
    infos = repeated_ancestors(leaf)
    nlev = len(infos)
    if len(d) == len(r):
        from .. import native

        nat = native.assemble_levels(d, r, [i.rep_level for i in infos],
                                     [i.def_level for i in infos], max_def)
        if nat is not None:
            return Assembled(validity=nat[2], list_offsets=nat[0],
                             list_validity=nat[1])
    offsets: List[np.ndarray] = []
    validities: List[Optional[np.ndarray]] = []
    for i, info in enumerate(infos):
        k, dk = info.rep_level, info.def_level
        if i == 0:
            inst_mask = r < k
        else:
            inst_mask = (r < k) & (d >= infos[i - 1].def_level)
        inst_idx = np.flatnonzero(inst_mask)
        if i + 1 < nlev:
            knext, dknext = infos[i + 1].rep_level, infos[i + 1].def_level
            elem = (r < knext) & (d >= dk)
        else:
            elem = d >= dk
        cum = np.cumsum(elem)
        offs = np.empty(len(inst_idx) + 1, dtype=np.int64)
        offs[0] = 0
        if len(inst_idx) > 1:
            offs[1:-1] = cum[inst_idx[1:] - 1]
        offs[-1] = cum[-1] if len(cum) else 0
        valid = d[inst_idx] >= (dk - 1)
        offsets.append(offs)
        validities.append(valid)
    # leaf validity over innermost elements only
    inner_entries = d >= infos[-1].def_level
    validity = (d == max_def)[inner_entries]
    return Assembled(validity=validity, list_offsets=offsets, list_validity=validities)


def row_slot_starts(rep_levels: np.ndarray) -> np.ndarray:
    """Slot index where each row begins (rows start at rep == 0) — the one
    row→slot mapping shared by the writer's page slicer and the streaming
    reader's batch slicer."""
    return np.flatnonzero(np.asarray(rep_levels) == 0)


def slot_span(rep_levels: Optional[np.ndarray], row0: int, row1: int,
              n_slots: int, row_starts: Optional[np.ndarray] = None):
    """Slot range [s0, s1) covering rows [row0, row1).  Flat columns map
    1:1; repeated columns map through :func:`row_slot_starts` (pass a
    precomputed ``row_starts`` to amortize it across calls)."""
    if rep_levels is None:
        return row0, row1
    starts = row_starts if row_starts is not None \
        else row_slot_starts(rep_levels)
    s0 = int(starts[row0]) if row0 < len(starts) else n_slots
    s1 = int(starts[row1]) if row1 < len(starts) else n_slots
    return s0, s1


def present_count(def_levels: Optional[np.ndarray], s0: int, s1: int,
                  max_def: int) -> int:
    """Number of present (non-null) leaf values in slot range [s0, s1)."""
    if def_levels is None:
        return s1 - s0
    return int(np.count_nonzero(np.asarray(def_levels)[s0:s1] == max_def))


def leaf_slot_count_to_value_count(def_levels: np.ndarray, max_def: int) -> int:
    return int(np.count_nonzero(def_levels == max_def))


# ---------------------------------------------------------------------------
# Write direction: arrays + offsets + validity → (def, rep) level streams
# ---------------------------------------------------------------------------


def levels_for_flat(validity: Optional[np.ndarray], num_values: int,
                    max_def: int) -> Optional[np.ndarray]:
    """Def levels for a flat (max_rep==0) column.  None when nothing to write."""
    if max_def == 0:
        return None
    if validity is None:
        return np.full(num_values, max_def, dtype=np.int32)
    d = np.full(num_values, max_def, dtype=np.int32)
    d[~validity] = max_def - 1
    return d


def levels_for_list(list_offsets: np.ndarray, list_validity: Optional[np.ndarray],
                    elem_validity: Optional[np.ndarray], leaf: Leaf
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Def/rep levels for a single-level LIST column (the common case).

    list_offsets: int[n_rows+1]; list_validity: bool[n_rows] or None;
    elem_validity: bool[n_elems] or None.  Returns (def_levels, rep_levels)
    over leaf slots (one slot per element, plus one per null/empty list).
    """
    infos = repeated_ancestors(leaf)
    assert len(infos) == 1, "levels_for_list handles exactly one repeated level"
    dk = infos[0].def_level
    max_def = leaf.max_definition_level
    n_rows = len(list_offsets) - 1
    lens = (list_offsets[1:] - list_offsets[:-1]).astype(np.int64)
    if list_validity is not None:
        lens = np.where(list_validity, lens, 0)
    slot_per_row = np.maximum(lens, 1)  # null/empty lists still occupy one slot
    total = int(slot_per_row.sum())
    rep = np.ones(total, dtype=np.int32)
    row_starts = np.zeros(n_rows, dtype=np.int64)
    np.cumsum(slot_per_row[:-1], out=row_starts[1:])
    rep[row_starts] = 0
    d = np.full(total, max_def, dtype=np.int32)
    empty_rows = lens == 0
    # def for empty/null list slots
    if list_validity is not None:
        null_rows = ~list_validity.astype(bool)
        d[row_starts[null_rows]] = dk - 2  # list null (parent optional level absent)
        d[row_starts[empty_rows & ~null_rows]] = dk - 1
    else:
        d[row_starts[empty_rows]] = dk - 1
    # element nulls
    if elem_validity is not None and max_def > dk:
        # scatter element validity into slots occupied by real elements
        elem_slots = np.repeat(row_starts, lens) + _ranges(lens)
        nulls = ~elem_validity.astype(bool)
        d[elem_slots[nulls]] = max_def - 1
    return d, rep


def _ranges(lengths: np.ndarray) -> np.ndarray:
    total = int(lengths.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    starts = np.empty(len(lengths), dtype=np.int64)
    starts[0] = 0
    np.cumsum(lengths[:-1], out=starts[1:])
    return np.arange(total, dtype=np.int64) - np.repeat(starts, lengths)


def levels_for_nested(list_offsets: List[np.ndarray],
                      list_validity: List[Optional[np.ndarray]],
                      elem_validity: Optional[np.ndarray], leaf: Leaf
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Def/rep levels for an arbitrary-depth chain of LIST levels.

    ``list_offsets[k]`` / ``list_validity[k]`` describe repeated level k,
    outermost first (the layout :func:`assemble` produces and Arrow nested
    ListArrays map to); ``elem_validity`` masks the innermost elements.
    Built bottom-up: start from one slot per innermost element, then per list
    level stitch element slot-streams together, synthesizing one slot for each
    empty (def = d_k - 1) or null (def = d_k - 2) list and marking the first
    slot of each *continuing* element with rep = r_k.  Assumes the standard
    wrapper-group+repeated pattern ``list_of``/``map_of``/Arrow produce (no
    extra optional struct layers between repeated levels).
    """
    infos = repeated_ancestors(leaf)
    nlev = len(infos)
    assert nlev == len(list_offsets) == len(list_validity)
    max_def = leaf.max_definition_level
    # innermost elements: one slot each (canonical layout: null lists have
    # zero-length ranges, so the innermost offsets' end == element count)
    n_inner = int(list_offsets[-1][-1]) if len(list_offsets[-1]) else 0
    d = np.full(n_inner, max_def, dtype=np.int32)
    if elem_validity is not None and max_def > infos[-1].def_level:
        d[~np.asarray(elem_validity, dtype=bool)] = max_def - 1
    r = np.full(n_inner, infos[-1].rep_level, dtype=np.int32)  # provisional
    counts = np.ones(n_inner, dtype=np.int64)  # slots per element of this level
    for k in range(nlev - 1, -1, -1):
        rk, dk = infos[k].rep_level, infos[k].def_level
        offs = np.asarray(list_offsets[k], dtype=np.int64)
        lv = list_validity[k]
        n_inst = len(offs) - 1
        elem_starts = np.zeros(len(counts), dtype=np.int64)
        if len(counts) > 1:
            np.cumsum(counts[:-1], out=elem_starts[1:])
        # every element's first slot continues the level-k list …
        r[elem_starts] = rk
        # … except the first element of each non-empty instance (parent sets it)
        nonempty = offs[1:] > offs[:-1]
        if lv is not None:
            nonempty &= np.asarray(lv, dtype=bool)
        # instance slot spans in the current stream
        starts_ext = np.concatenate([elem_starts, [len(d)]])
        inst_start = starts_ext[offs[:-1]]
        inst_counts = starts_ext[offs[1:]] - inst_start
        # synthesize one slot per empty/null instance
        synth = ~nonempty
        if synth.any():
            pos = inst_start[synth]
            sdef = np.full(int(synth.sum()), dk - 1, dtype=np.int32)
            if lv is not None:
                sdef[~np.asarray(lv, dtype=bool)[synth]] = dk - 2
            d = np.insert(d, pos, sdef)
            r = np.insert(r, pos, np.int32(rk))  # provisional; parent overwrites
            inst_counts = np.where(synth, 1, inst_counts)
        counts = inst_counts
        if k == 0:
            inst_firsts = np.zeros(n_inst, dtype=np.int64)
            if n_inst > 1:
                np.cumsum(counts[:-1], out=inst_firsts[1:])
            r[inst_firsts] = 0
    return d, r
