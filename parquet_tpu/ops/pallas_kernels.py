"""Pallas TPU kernels for the decode hot loops.

Reference parity: the role of ``internal/bitpack/unpack_int32_amd64.s`` etc.
(SURVEY.md §2.3) — hand-tuned kernels under the same interfaces as the
portable path.  Tested in interpret mode against the numpy oracle (the
purego-equivalence pattern) and jit-compiled on the real chip by the bench.

Design note (TPU-first): data-dependent gathers are the enemy on a TPU VPU —
so the flagship kernel is a *gather-free* bit-unpack.  For a static width
``w``, output lane ``j`` of every 32-value group always reads packed word
``(j*w) >> 5`` at shift ``(j*w) & 31``: the access pattern is compile-time
static, and the kernel is 32 unrolled vector shift/or/mask column writes over
a (block, w)-word tile in VMEM.  The generic mixed-width path stays in
ops/device.py (XLA gathers); chunks whose streams are single-width (dict
indexes, most delta miniblocks after host bucketing) route here.

Measured on the real v5e (round 2, 8M values): ``unpack_bits_dense`` beats
the jnp twin 2-4x (w=1: 73ms vs 283ms; w=8: 67ms vs 167ms; w=16: 67ms vs
145ms), so it is the default TPU route for w ≤ 16 (device_reader._use_pallas).
KNOWN MOSAIC BUG: for w ≥ 17 the compiled shift-formulation kernel
deterministically corrupts the word-straddling columns whose shift is 16
(sparse wrong values; the jnp twin is correct at every width).  Minimized
standalone repro: ``scripts/mosaic_repro.py``; on-chip confirmation
2026-07-31 (``MOSAIC_REPRO_ONCHIP.json``): shift FAILS at w=17/20/24/31,
always and only at the shift-16 lanes.  The bad pattern is ``(lo >> 16) |
(hi << 16)``; :func:`unpack_bits_dense` reformulates the straddle as a
MULTIPLY (``hi * 2**(32-sh)``) for w ≥ 17 — semantically identical, and
the same trial proved it EXACT on-chip at w ∈ {16, 17, 20, 24, 31} (plus
w = 27 in an 8M-value production-kernel run), so the router now takes the
Pallas kernel at all widths on TPU (device_reader._use_pallas).
Upstream report: the complete ready-to-file issue text is
``UPSTREAM_ISSUE_mosaic.md`` at the repo root (zero-egress environment —
paste into the JAX tracker with scripts/mosaic_repro.py +
MOSAIC_REPRO_ONCHIP.json attached).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MASK32 = 0xFFFFFFFF


def _unpack_block_kernel(words_ref, out_ref, *, w: int, straddle: str):
    """One VMEM block: (B, w) packed uint32 words → (B, 32) values.

    ``straddle`` picks the word-straddle formulation: ``"shift"`` is the
    classic ``lo | (hi << (32-sh))``; ``"mul"`` replaces the left-shift with
    an equivalent multiply (``hi * 2**(32-sh)``) to dodge the Mosaic w ≥ 17
    shift-16 miscompile (scripts/mosaic_repro.py)."""
    words = words_ref[:]
    mask = jnp.uint32((1 << w) - 1 if w < 32 else _MASK32)
    cols = []
    for j in range(32):
        bitpos = j * w
        k = bitpos >> 5
        sh = bitpos & 31
        lo = words[:, k] >> jnp.uint32(sh)
        if sh + w > 32:
            if straddle == "mul":
                hi = words[:, k + 1] * jnp.uint32(1 << (32 - sh))
            else:
                hi = words[:, k + 1] << jnp.uint32(32 - sh)
            val = lo | hi
        else:
            val = lo
        cols.append((val & mask).reshape(-1, 1))
    out_ref[:] = jnp.concatenate(cols, axis=1)


@functools.partial(jax.jit,
                   static_argnames=("n", "w", "block", "interpret", "straddle"))
def unpack_bits_dense(packed_words: jax.Array, n: int, w: int,
                      block: int = 512, interpret: bool = False,
                      straddle: Optional[str] = None) -> jax.Array:
    """Unpack ``n`` LSB-first ``w``-bit integers from a dense stream.

    ``packed_words``: uint32[ceil(n/32)*w] (caller pads).  Returns uint32[n].
    Grid over groups of 32 values; each grid step unpacks ``block`` groups.
    ``straddle`` defaults to ``"shift"`` for w ≤ 16 and ``"mul"`` for wider
    widths (the Mosaic-miscompile dodge — module docstring).
    """
    if w == 32:
        return packed_words[:n]
    if straddle is None:
        straddle = "mul" if w >= 17 else "shift"
    groups = (n + 31) // 32
    gpad = (groups + block - 1) // block * block
    need_words = gpad * w
    if packed_words.shape[0] < need_words:
        packed_words = jnp.pad(packed_words, (0, need_words - packed_words.shape[0]))
    words2d = packed_words[: gpad * w].reshape(gpad, w)
    out = pl.pallas_call(
        functools.partial(_unpack_block_kernel, w=w, straddle=straddle),
        out_shape=jax.ShapeDtypeStruct((gpad, 32), jnp.uint32),
        grid=(gpad // block,),
        in_specs=[pl.BlockSpec((block, w), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((block, 32), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(words2d)
    return out.reshape(-1)[:n]


def unpack_bits_dense_jnp(packed_words: jax.Array, n: int, w: int) -> jax.Array:
    """jnp twin of :func:`unpack_bits_dense` — identical static-select
    formulation, no Pallas (runs anywhere; XLA fuses it to vector code)."""
    if w == 32:
        return packed_words[:n]
    groups = (n + 31) // 32
    need = groups * w
    if packed_words.shape[0] < need:
        packed_words = jnp.pad(packed_words, (0, need - packed_words.shape[0]))
    words = packed_words[:need].reshape(groups, w)
    mask = jnp.uint32((1 << w) - 1)
    cols = []
    for j in range(32):
        bitpos = j * w
        k = bitpos >> 5
        sh = bitpos & 31
        val = words[:, k] >> jnp.uint32(sh)
        if sh + w > 32:
            val = val | (words[:, k + 1] << jnp.uint32(32 - sh))
        cols.append(val & mask)
    return jnp.stack(cols, axis=1).reshape(-1)[:n]


# ---------------------------------------------------------------------------
# Fused dictionary expand+gather for single-width bit-packed index streams
# ---------------------------------------------------------------------------


def _dict_unpack_gather_kernel(words_ref, dict_ref, out_ref, *, w: int):
    """Unpack 32-bit-group indexes and gather from a VMEM-resident dictionary
    via one-hot matmul (MXU-friendly for small dictionaries)."""
    words = words_ref[:]
    mask = jnp.uint32((1 << w) - 1 if w < 32 else _MASK32)
    cols = []
    for j in range(32):
        bitpos = j * w
        k = bitpos >> 5
        sh = bitpos & 31
        val = words[:, k] >> jnp.uint32(sh)
        if sh + w > 32:
            val = val | (words[:, k + 1] << jnp.uint32(32 - sh))
        cols.append((val & mask).reshape(-1, 1))
    idx = jnp.concatenate(cols, axis=1).astype(jnp.int32)  # (B, 32)
    d = dict_ref[:]  # (D,) values in VMEM
    flat = idx.reshape(-1)
    onehot = (flat[:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (flat.shape[0], d.shape[0]), 1))
    vals = jnp.sum(jnp.where(onehot, d[None, :], 0), axis=1)
    out_ref[:] = vals.reshape(idx.shape)


@functools.partial(jax.jit, static_argnames=("n", "w", "block", "interpret"))
def dict_unpack_gather(packed_words: jax.Array, dictionary: jax.Array, n: int,
                       w: int, block: int = 128, interpret: bool = False
                       ) -> jax.Array:
    """Fused: bit-unpack dictionary indexes + gather values, one VMEM pass
    (no HBM round-trip for the index stream).  For small dictionaries."""
    groups = (n + 31) // 32
    gpad = (groups + block - 1) // block * block
    need_words = gpad * max(w, 1)
    if packed_words.shape[0] < need_words:
        packed_words = jnp.pad(packed_words, (0, need_words - packed_words.shape[0]))
    words2d = packed_words[: gpad * w].reshape(gpad, w)
    out = pl.pallas_call(
        functools.partial(_dict_unpack_gather_kernel, w=w),
        out_shape=jax.ShapeDtypeStruct((gpad, 32), dictionary.dtype),
        grid=(gpad // block,),
        in_specs=[
            pl.BlockSpec((block, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((dictionary.shape[0],), lambda i: (0,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, 32), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(words2d, dictionary)
    return out.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# SBBF bloom block math (vector twin of bloom.py; probes a batch of hashes
# against gathered blocks — the gather happens outside, the 8-salt block math
# is the vector part, matching the reference's AVX2 block kernel split)
# ---------------------------------------------------------------------------

_SALT = np.array([
    0x47B6137B, 0x44974D91, 0x8824AD5B, 0xA2B7289D,
    0x705495C7, 0x2DF1424B, 0x9EFC4947, 0x5C6BFB31,
], dtype=np.uint32)


def _bloom_check_kernel(blocks_ref, low_ref, salts_ref, out_ref):
    """blocks: (B, 8) gathered filter blocks; low: (B, 1) low-32 hash bits."""
    low = low_ref[:][:, 0]
    salts = salts_ref[:][0]
    bit = (low[:, None] * salts[None, :]) >> jnp.uint32(27)
    masks = jnp.uint32(1) << (bit & jnp.uint32(31))
    hit = (blocks_ref[:] & masks) == masks
    out_ref[:] = jnp.all(hit, axis=1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def bloom_check_blocks(blocks: jax.Array, low_bits: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """Check pre-gathered SBBF blocks against hash low bits (vector part of
    the probe; block gather by high bits happens in XLA)."""
    n = blocks.shape[0]
    return pl.pallas_call(
        _bloom_check_kernel,
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.bool_),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(blocks, low_bits.reshape(-1, 1), jnp.asarray(_SALT).reshape(1, 8)).reshape(-1)
