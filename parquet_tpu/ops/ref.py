"""numpy reference implementations of every Parquet encoding (the test oracle).

Reference parity: the reference pairs each amd64 assembly kernel with a pure-Go
``purego`` twin used as a correctness oracle (SURVEY.md §2.3).  This module is
that twin for the new framework: plain numpy, no JAX, byte-exact against the
Parquet spec.  The device kernels in ``ops/device.py`` / ``ops/pallas_kernels.py``
are tested against these, and pyarrow round-trips pin both to the ecosystem.

Encodings (SURVEY.md §2.2): PLAIN, RLE/bit-packed hybrid, BIT_PACKED (legacy),
DELTA_BINARY_PACKED, DELTA_LENGTH_BYTE_ARRAY, DELTA_BYTE_ARRAY,
BYTE_STREAM_SPLIT, RLE_DICTIONARY index streams.

Variable-length values use the Arrow-style (data: uint8[], offsets: int32[n+1])
layout throughout — the flat buffers that cross the host→HBM boundary
(reference analog: ``encoding/values.go — encoding.Values``).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from ..format.enums import Type

# ---------------------------------------------------------------------------
# varint / zigzag helpers (ULEB128, shared by delta + RLE headers)
# ---------------------------------------------------------------------------


def read_uvarint(buf, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = int(buf[pos])
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def write_uvarint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


# ---------------------------------------------------------------------------
# Bit packing (LSB-first, the parquet "RLE" bit order)
# Reference analog: internal/bitpack — unpack_int32_amd64.s / unpack_int64_amd64.s
# ---------------------------------------------------------------------------


def unpack_bits(data, n: int, bit_width: int, offset_bits: int = 0) -> np.ndarray:
    """Unpack ``n`` LSB-first ``bit_width``-bit integers from ``data`` starting
    at bit ``offset_bits``.  Returns uint64 array.  Fully vectorized."""
    if bit_width == 0:
        return np.zeros(n, dtype=np.uint64)
    if n == 0:
        return np.empty(0, dtype=np.uint64)
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    starts = offset_bits + np.arange(n, dtype=np.int64) * bit_width
    byte0 = starts >> 3
    shift = (starts & 7).astype(np.uint64)
    nbytes = (bit_width + 7 + 7) // 8  # enough bytes to cover shift + width
    nbytes = min(nbytes, 9)
    # gather up to 8 bytes into uint64 (+ 9th byte handled separately)
    end = int(byte0[-1]) + nbytes
    if end > len(buf):
        buf = np.concatenate([buf, np.zeros(end - len(buf), dtype=np.uint8)])
    acc = np.zeros(n, dtype=np.uint64)
    for k in range(min(nbytes, 8)):
        acc |= buf[byte0 + k].astype(np.uint64) << np.uint64(8 * k)
    vals = acc >> shift
    if bit_width + 7 > 64 and nbytes == 9:  # need the 9th byte's low bits
        hi = buf[byte0 + 8].astype(np.uint64)
        vals |= np.where(shift > 0, hi << (np.uint64(64) - shift), 0)
    if bit_width < 64:
        vals &= (np.uint64(1) << np.uint64(bit_width)) - np.uint64(1)
    return vals


def pack_bits(values: np.ndarray, bit_width: int) -> bytes:
    """Pack integers LSB-first at ``bit_width`` bits each.

    Routes through the C++ shim (the write path's hottest loop); the numpy
    formulation below is the oracle/fallback (cross-tested in test_native)."""
    n = len(values)
    if bit_width == 0 or n == 0:
        return b""
    if bit_width <= 56:
        from .. import native

        out = native.pack_bits(np.asarray(values, np.int64), bit_width)
        if out is not None:
            return out
    return pack_bits_np(values, bit_width)


def pack_bits_np(values: np.ndarray, bit_width: int) -> bytes:
    """Numpy oracle for :func:`pack_bits` (fully vectorized: per-value bit
    matrix → np.packbits little-endian; no scatter/ufunc.at)."""
    n = len(values)
    if bit_width == 0 or n == 0:
        return b""
    mask = np.uint64(0xFFFFFFFFFFFFFFFF) if bit_width >= 64 else np.uint64((1 << bit_width) - 1)
    v = values.astype(np.uint64) & mask
    bits = ((v[:, None] >> np.arange(bit_width, dtype=np.uint64)) & 1) \
        .astype(np.uint8)
    flat = bits.reshape(-1)
    pad = -len(flat) % 8
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.uint8)])
    return np.packbits(flat.reshape(-1, 8), axis=1, bitorder="little").tobytes()


# ---------------------------------------------------------------------------
# PLAIN (encoding/plain — plain.go)
# ---------------------------------------------------------------------------


def decode_plain(data, num_values: int, physical: Type, type_length: Optional[int] = None):
    """Decode PLAIN.  Fixed-width → typed array; BYTE_ARRAY → (values, offsets);
    FLBA → (n, type_length) uint8; INT96 → (n, 3) int32; BOOLEAN → bool[]."""
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    if physical == Type.BOOLEAN:
        bits = np.unpackbits(buf[: (num_values + 7) // 8], bitorder="little")
        return bits[:num_values].astype(np.bool_)
    if physical == Type.INT32:
        return buf[: 4 * num_values].view(np.int32).copy()
    if physical == Type.INT64:
        return buf[: 8 * num_values].view(np.int64).copy()
    if physical == Type.FLOAT:
        return buf[: 4 * num_values].view(np.float32).copy()
    if physical == Type.DOUBLE:
        return buf[: 8 * num_values].view(np.float64).copy()
    if physical == Type.INT96:
        return buf[: 12 * num_values].view(np.int32).reshape(num_values, 3).copy()
    if physical == Type.FIXED_LEN_BYTE_ARRAY:
        w = type_length
        return buf[: w * num_values].reshape(num_values, w).copy()
    if physical == Type.BYTE_ARRAY:
        return _decode_plain_byte_array(buf, num_values)
    raise ValueError(f"unsupported physical type {physical}")


def _decode_plain_byte_array(buf: np.ndarray, num_values: int):
    """4-byte-length-prefixed strings → (values uint8[], offsets int32[n+1]).

    The length prefixes sit at data-dependent positions (a sequential scan —
    the same loop the reference does in Go); dispatches to the C++ shim when
    built, with this numpy loop as the purego-style fallback."""
    from .. import native as _native

    res = _native.plain_byte_array(buf, num_values)
    if res is not None:
        return res
    offsets = np.empty(num_values + 1, dtype=np.int64)
    offsets[0] = 0
    pos = 0
    n = len(buf)
    lens = np.empty(num_values, dtype=np.int64)
    mv = buf
    for i in range(num_values):
        if pos + 4 > n:
            raise ValueError("PLAIN BYTE_ARRAY truncated")
        ln = int(mv[pos]) | int(mv[pos + 1]) << 8 | int(mv[pos + 2]) << 16 | int(mv[pos + 3]) << 24
        lens[i] = ln
        pos += 4 + ln
    offsets[1:] = np.cumsum(lens)
    total = int(offsets[-1])
    values = np.empty(total, dtype=np.uint8)
    # gather: positions of value bytes = 4*(i+1) + offsets[i] .. — vectorized copy
    starts = 4 * np.arange(1, num_values + 1, dtype=np.int64) + offsets[:-1]
    idx = np.repeat(starts, lens) + _ranges(lens)
    values[:] = mv[idx] if total else values
    return values, offsets.astype(np.int32)


def _ranges(lengths: np.ndarray) -> np.ndarray:
    """[0..l0), [0..l1), ... concatenated (segmented iota); zero lengths fine."""
    total = int(lengths.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    starts = np.empty(len(lengths), dtype=np.int64)
    starts[0] = 0
    np.cumsum(lengths[:-1], out=starts[1:])
    return np.arange(total, dtype=np.int64) - np.repeat(starts, lengths)


def encode_plain(values, physical: Type, offsets: Optional[np.ndarray] = None) -> bytes:
    if physical == Type.BOOLEAN:
        return np.packbits(np.asarray(values, dtype=np.uint8), bitorder="little").tobytes()
    if physical == Type.BYTE_ARRAY:
        data = np.asarray(values, dtype=np.uint8)
        offs = np.asarray(offsets, dtype=np.int64)
        from .. import native

        nat = native.encode_plain_ba(data, offs)
        if nat is not None:
            return nat
        lens = (offs[1:] - offs[:-1]).astype(np.int64)
        n = len(lens)
        out = np.empty(len(data) + 4 * n, dtype=np.uint8)
        # positions of the 4 length bytes + value bytes
        dst_starts = offs[:-1] + 4 * np.arange(1, n + 1, dtype=np.int64)
        lens32 = lens.astype(np.uint32)
        hdr_pos = offs[:-1] + 4 * np.arange(n, dtype=np.int64)
        for k in range(4):
            out[hdr_pos + k] = ((lens32 >> (8 * k)) & 0xFF).astype(np.uint8)
        if len(data):
            idx = np.repeat(dst_starts, lens) + _ranges(lens)
            out[idx] = data
        return out.tobytes()
    if physical == Type.INT96:
        return np.ascontiguousarray(values, dtype=np.int32).tobytes()
    if physical == Type.FIXED_LEN_BYTE_ARRAY:
        return np.ascontiguousarray(values, dtype=np.uint8).tobytes()
    dtype = {
        Type.INT32: np.int32,
        Type.INT64: np.int64,
        Type.FLOAT: np.float32,
        Type.DOUBLE: np.float64,
    }[physical]
    return np.ascontiguousarray(values, dtype=dtype).tobytes()


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid (encoding/rle — rle.go + rle_amd64.s)
# ---------------------------------------------------------------------------


def scan_rle_runs(data, num_values: int, bit_width: int, pos: int = 0):
    """Parse hybrid run headers → run table (the host pre-scan of SURVEY.md §7).

    Returns (kinds u8[k] (0=RLE,1=bitpacked), counts i64[k], payload i64[k],
    byte_offsets i64[k], end_pos).  payload = repeated value for RLE runs,
    unused for bit-packed (their bits start at byte_offsets).

    Dispatches to the C++ shim (native/) when built; the Python loop below is
    the purego-style fallback (end_pos is -1 on the native path — no caller
    uses it)."""
    from .. import native as _native

    buf0 = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    res = _native.scan_rle_runs(buf0[pos:] if pos else buf0, num_values, bit_width)
    if res is not None:
        kinds, counts, payloads, offsets = res
        return kinds, counts, payloads, offsets + pos, -1
    kinds: List[int] = []
    counts: List[int] = []
    payloads: List[int] = []
    offsets: List[int] = []
    vbytes = (bit_width + 7) // 8
    remaining = num_values
    while remaining > 0:
        header, pos = read_uvarint(data, pos)
        if (header >> 1) == 0:
            # zero-count run: covers no values, never decrements remaining —
            # a crafted stream of them loops forever / grows the run table
            # without bound (C++ scanner rejects identically)
            raise ValueError("malformed RLE hybrid stream: zero-count run")
        if header & 1:
            ngroups = header >> 1
            count = ngroups * 8
            kinds.append(1)
            counts.append(min(count, remaining))
            payloads.append(0)
            offsets.append(pos)
            pos += ngroups * bit_width
        else:
            count = header >> 1
            value = 0
            for k in range(vbytes):
                value |= int(data[pos + k]) << (8 * k)
            if bit_width < 64:
                # padding bits of the vbytes payload are unspecified: mask so
                # every consumer sees one value (C++ scanner does the same)
                value &= (1 << bit_width) - 1
            pos += vbytes
            kinds.append(0)
            counts.append(min(count, remaining))
            payloads.append(value)
            offsets.append(pos)
        remaining -= count
    return (
        np.array(kinds, dtype=np.uint8),
        np.array(counts, dtype=np.int64),
        np.array(payloads, dtype=np.int64),
        np.array(offsets, dtype=np.int64),
        pos,
    )


def decode_rle(data, num_values: int, bit_width: int, pos: int = 0) -> np.ndarray:
    """Decode an RLE/bit-packed hybrid stream (no length/width prefix)."""
    if bit_width == 0:
        return np.zeros(num_values, dtype=np.int64)
    kinds, counts, payloads, offsets, _ = scan_rle_runs(data, num_values, bit_width, pos)
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    from .. import native

    if bit_width <= 31 and native.get_lib() is not None:
        # values fit int32: one C expansion pass instead of a per-run loop
        nat = native.expand_runs(buf, np.cumsum(counts).astype(np.int64),
                                 kinds.astype(np.uint8),
                                 payloads.astype(np.int64),
                                 (offsets * 8).astype(np.int64),
                                 np.full(len(kinds), bit_width, np.int32),
                                 num_values)
        if nat is not None:
            return nat.astype(np.int64)
    out = np.empty(num_values, dtype=np.int64)
    w = 0
    for i in range(len(kinds)):
        c = int(counts[i])
        if kinds[i] == 0:
            out[w : w + c] = payloads[i]
        else:
            vals = unpack_bits(buf[offsets[i] :], c, bit_width)
            out[w : w + c] = vals.astype(np.int64)
        w += c
    return out


def decode_rle_len_prefixed(data, num_values: int, bit_width: int, pos: int = 0):
    """v1 def/rep levels: 4-byte LE byte-length prefix, then hybrid stream."""
    (length,) = struct.unpack_from("<I", data, pos)
    vals = decode_rle(data, num_values, bit_width, pos + 4)
    return vals, pos + 4 + length


def rle_len_prefixed_single_value(data, num_values: int, pos: int = 0):
    """Peek a v1 length-prefixed level stream: if it is ONE RLE run covering
    every value, return (payload, end_pos) without expanding — the all-present
    def-level fast path of the host scan.  Returns (None, end_pos) otherwise.
    """
    (length,) = struct.unpack_from("<I", data, pos)
    end = pos + 4 + length
    header, p = read_uvarint(data, pos + 4)
    if header & 1 == 0 and (header >> 1) >= num_values:
        return int(data[p]) if p < len(data) else 0, end
    return None, end


def decode_rle_dict_indices(data, num_values: int, pos: int = 0) -> np.ndarray:
    """RLE_DICTIONARY data page payload: 1-byte bit width, then hybrid stream."""
    bit_width = int(data[pos])
    if bit_width == 0:
        return np.zeros(num_values, dtype=np.int64)
    return decode_rle(data, num_values, bit_width, pos + 1)


# ---------------------------------------------------------------------------
# Masked-emit variants (fused decode+filter, io/fused.py)
#
# Each takes ``take`` — a sorted int64 array of physical value ordinals — and
# emits only those values, never materializing the full page.  For the hybrid
# stream this is a true skip: runs the mask never touches are not expanded
# (gather_bits reads single values at arbitrary bit offsets).
# ---------------------------------------------------------------------------


def gather_bits(data, starts_bits: np.ndarray, bit_width: int) -> np.ndarray:
    """Read one LSB-first ``bit_width``-bit integer at each bit offset in
    ``starts_bits`` (int64, need not be uniform).  Generalizes
    :func:`unpack_bits` to arbitrary per-value positions; returns uint64."""
    n = len(starts_bits)
    if bit_width == 0 or n == 0:
        return np.zeros(n, dtype=np.uint64)
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    starts = np.asarray(starts_bits, dtype=np.int64)
    byte0 = starts >> 3
    shift = (starts & 7).astype(np.uint64)
    nbytes = min((bit_width + 7 + 7) // 8, 9)
    end = int(byte0.max()) + nbytes
    if end > len(buf):
        buf = np.concatenate([buf, np.zeros(end - len(buf), dtype=np.uint8)])
    acc = np.zeros(n, dtype=np.uint64)
    for k in range(min(nbytes, 8)):
        acc |= buf[byte0 + k].astype(np.uint64) << np.uint64(8 * k)
    vals = acc >> shift
    if bit_width + 7 > 64 and nbytes == 9:
        hi = buf[byte0 + 8].astype(np.uint64)
        vals |= np.where(shift > 0, hi << (np.uint64(64) - shift), 0)
    if bit_width < 64:
        vals &= (np.uint64(1) << np.uint64(bit_width)) - np.uint64(1)
    return vals


def select_rle(data, num_values: int, bit_width: int, take: np.ndarray,
               pos: int = 0) -> np.ndarray:
    """Hybrid-stream point lookup: value at each ordinal in ``take`` (sorted
    int64) without expanding the stream.  RLE runs answer from their payload;
    bit-packed runs via :func:`gather_bits` at the exact bit position.
    Returns int64[len(take)]."""
    take = np.asarray(take, dtype=np.int64)
    if bit_width == 0 or len(take) == 0:
        return np.zeros(len(take), dtype=np.int64)
    kinds, counts, payloads, offsets, _ = scan_rle_runs(data, num_values, bit_width, pos)
    ends = np.cumsum(counts)
    run = np.searchsorted(ends, take, side="right")
    starts = ends - counts
    if len(take) * 8 >= int(counts[np.unique(run)].sum()):
        # dense takes: expanding just the touched runs (one native pass)
        # beats len(take) scattered bit reads
        from .. import native

        buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
        nat = native.select_runs(buf, kinds, counts, payloads, offsets,
                                 bit_width, take)
        if nat is not None:
            return nat
    out = payloads[run].astype(np.int64)
    bp = kinds[run] == 1
    if bp.any():
        r = run[bp]
        bits = offsets[r] * 8 + (take[bp] - starts[r]) * bit_width
        out[bp] = gather_bits(data, bits, bit_width).astype(np.int64)
    return out


def decode_rle_dict_indices_masked(data, num_values: int, take: np.ndarray,
                                   pos: int = 0) -> np.ndarray:
    """Masked-emit twin of :func:`decode_rle_dict_indices`: only the indices
    at the ``take`` ordinals, via :func:`select_rle` (no full expansion)."""
    bit_width = int(data[pos])
    if bit_width == 0:
        return np.zeros(len(take), dtype=np.int64)
    return select_rle(data, num_values, bit_width, take, pos + 1)


def decode_plain_masked(data, num_values: int, take: np.ndarray, physical: Type,
                        type_length: Optional[int] = None):
    """Masked-emit twin of :func:`decode_plain` for fixed-width physicals: the
    selected rows come straight out of a zero-copy view of the page body (the
    fancy index is the only allocation).  BYTE_ARRAY returns None — its length
    prefixes force a sequential scan, so the caller full-decodes instead."""
    take = np.asarray(take, dtype=np.int64)
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    if physical == Type.BOOLEAN:
        bits = np.unpackbits(buf[: (num_values + 7) // 8], bitorder="little")
        return bits[:num_values][take].astype(np.bool_)
    if physical == Type.INT32:
        return buf[: 4 * num_values].view(np.int32)[take]
    if physical == Type.INT64:
        return buf[: 8 * num_values].view(np.int64)[take]
    if physical == Type.FLOAT:
        return buf[: 4 * num_values].view(np.float32)[take]
    if physical == Type.DOUBLE:
        return buf[: 8 * num_values].view(np.float64)[take]
    if physical == Type.INT96:
        return buf[: 12 * num_values].view(np.int32).reshape(num_values, 3)[take]
    if physical == Type.FIXED_LEN_BYTE_ARRAY:
        w = type_length
        return buf[: w * num_values].reshape(num_values, w)[take]
    if physical == Type.BYTE_ARRAY:
        return None
    raise ValueError(f"unsupported physical type {physical}")


def decode_delta_binary_packed_masked(data, num_values: int, take: np.ndarray,
                                      pos: int = 0) -> np.ndarray:
    """Masked-emit twin for DELTA_BINARY_PACKED.  The prefix-sum chain makes a
    true skip impossible (every delta feeds the running value), so this decodes
    the stream and selects — the saving is the downstream materialization, not
    the unpack."""
    vals, _ = decode_delta_binary_packed(data, pos)
    return vals[:num_values][np.asarray(take, dtype=np.int64)]


def encode_rle(values: np.ndarray, bit_width: int, min_repeat: int = 8,
               _native: bool = True) -> bytes:
    """Encode the hybrid stream (no prefix).

    Invariant (required by the format): a bit-packed run encodes exactly
    ``ngroups * 8`` values, all of which count toward num_values — so
    mid-stream bit-packed spans must be whole groups of 8; only the final
    group may be zero-padded (readers stop at num_values).  Runs of
    >= ``min_repeat`` identical values switch to RLE runs, matching the
    common writer heuristic."""
    n = len(values)
    if n >= max(min_repeat, 8) and bit_width:
        # constant stream → one RLE run, no scan.  Def-level streams of
        # fully-present pages (the common case for optional columns without
        # nulls) hit this on every page of the write path.  Gated on
        # n >= max(min_repeat, 8) so every case where the scan encoders
        # might bit-pack instead stays with them (byte identity), and
        # masked like the scan path so out-of-range constants encode their
        # low bytes instead of raising.
        v = np.asarray(values)
        v0 = v[0]
        if v0 == v[-1] and not (v != v0).any():
            vbytes = (bit_width + 7) // 8
            vmask = (1 << (8 * vbytes)) - 1
            hdr = bytearray()
            write_uvarint(hdr, n << 1)
            return bytes(hdr) + (int(v0) & vmask).to_bytes(vbytes, "little")
    values = np.asarray(values, dtype=np.int64)
    out = bytearray()
    if n == 0 or bit_width == 0:
        return bytes(out)
    if _native:
        from .. import native

        nat = native.encode_rle(values, bit_width, min_repeat)
        if nat is not None:
            return nat
    vbytes = (bit_width + 7) // 8
    vmask = (1 << (8 * vbytes)) - 1
    # run-length decomposition (vectorized)
    change = np.empty(n, dtype=bool)
    change[0] = True
    np.not_equal(values[1:], values[:-1], out=change[1:])
    run_starts = np.flatnonzero(change)
    run_lens = np.diff(np.append(run_starts, n))

    def emit_rle(value: int, count: int):
        write_uvarint(out, count << 1)
        out.extend((value & vmask).to_bytes(vbytes, "little", signed=False))

    def emit_packed(span: np.ndarray, final: bool = False):
        cnt = len(span)
        if not cnt:
            return
        assert final or cnt % 8 == 0
        ngroups = (cnt + 7) // 8
        if cnt % 8:
            span = np.concatenate([span, np.zeros(ngroups * 8 - cnt, np.int64)])
        write_uvarint(out, (ngroups << 1) | 1)
        out.extend(pack_bits(span, bit_width))

    # The Python loop visits only RLE-eligible runs (>= min_repeat values),
    # never individual values: everything between eligible runs becomes ONE
    # bit-packed run.  Alignment: a mid-stream bit-packed run must cover
    # whole groups of 8, so an eligible run donates its first (gap % -8)
    # values to the preceding packed span (skipping RLE if that starves it).
    pos = 0
    thresh = max(min_repeat, 8)
    for ri in np.flatnonzero(run_lens >= thresh):
        s = int(run_starts[ri])
        length = int(run_lens[ri])
        pad = -(s - pos) % 8
        if length - pad < min_repeat:
            continue  # stays in the packed span
        emit_packed(values[pos : s + pad])
        emit_rle(int(values[s]), length - pad)
        pos = s + length
    emit_packed(values[pos:n], final=True)
    return bytes(out)


def encode_rle_len_prefixed(values: np.ndarray, bit_width: int) -> bytes:
    body = encode_rle(values, bit_width)
    return struct.pack("<I", len(body)) + body


def encode_rle_dict_indices(values: np.ndarray, bit_width: int) -> bytes:
    return bytes([bit_width]) + encode_rle(values, bit_width)


# ---------------------------------------------------------------------------
# BIT_PACKED (deprecated levels encoding; MSB-first bit order)
# Reference analog: encoding/bitpacked — bitpacked.go
# ---------------------------------------------------------------------------


def decode_bit_packed_levels(data, num_values: int, bit_width: int) -> np.ndarray:
    if bit_width == 0:
        return np.zeros(num_values, dtype=np.int64)
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    bits = np.unpackbits(buf, bitorder="big")
    need = num_values * bit_width
    bits = bits[:need].reshape(num_values, bit_width)
    weights = (1 << np.arange(bit_width - 1, -1, -1)).astype(np.int64)
    return bits.astype(np.int64) @ weights


def encode_bit_packed_levels(values: np.ndarray, bit_width: int) -> bytes:
    if bit_width == 0 or len(values) == 0:
        return b""
    v = np.asarray(values, dtype=np.int64)
    bits = ((v[:, None] >> np.arange(bit_width - 1, -1, -1)) & 1).astype(np.uint8)
    return np.packbits(bits.reshape(-1), bitorder="big").tobytes()


# ---------------------------------------------------------------------------
# DELTA_BINARY_PACKED (encoding/delta — binary_packed.go + asm)
# ---------------------------------------------------------------------------


def decode_delta_binary_packed(data, pos: int = 0,
                               _native: bool = True) -> Tuple[np.ndarray, int]:
    """Returns (int64 values, end position).

    Routes through the fused native prescan+decode when available (one
    multithread-capable C pass: header walk, unpack, min-add, prefix sum) —
    the per-miniblock Python loop below is the oracle (``_native=False``
    pins it, mirroring the encoder kwarg) and measured 60x slower on
    config-4's 8M-value delta pages.  Streams the native path refuses at
    either stage (prescan or the decoder's stricter bounds) fall back to
    the oracle, which owns the precise error / lenient-truncation
    semantics either way."""
    if _native:
        from .. import native

        arr = (data if isinstance(data, np.ndarray)
               else np.frombuffer(data, np.uint8))
        pre = native.delta_prescan(arr, pos)
        if pre is not None:
            first, total, vpm, offs, widths, mins, end = pre
            try:
                out = native.delta_decode(
                    arr, offs, widths, mins,
                    np.array([0, len(offs)], np.int64),
                    np.array([first], np.int64),
                    np.array([total], np.int64),
                    np.array([vpm], np.int64))
            except ValueError:
                out = None  # decoder-stage refusal: oracle decides
            if out is not None:
                return out, end
    block_size, pos = read_uvarint(data, pos)
    n_miniblocks, pos = read_uvarint(data, pos)
    total, pos = read_uvarint(data, pos)
    first_raw, pos = read_uvarint(data, pos)
    first = unzigzag(first_raw)
    out = np.empty(total, dtype=np.int64)
    if total == 0:
        return out, pos
    out[0] = first
    got = 1
    vpm = block_size // n_miniblocks  # values per miniblock
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    while got < total:
        min_delta_raw, pos = read_uvarint(data, pos)
        min_delta = unzigzag(min_delta_raw)
        widths = bytes(data[pos : pos + n_miniblocks])
        pos += n_miniblocks
        for m in range(n_miniblocks):
            if got >= total:
                break
            w = widths[m]
            take = min(vpm, total - got)
            if w == 0:
                deltas = np.zeros(take, dtype=np.int64)
            else:
                raw = unpack_bits(buf[pos:], vpm, w)[:take]
                deltas = raw.astype(np.int64)
                pos += vpm * w // 8
            if w == 0:
                pass
            out[got : got + take] = deltas + min_delta
            got += take
    # prefix sum over deltas (out currently holds first, then deltas+min)
    np.cumsum(out[: total], out=out[: total])
    return out, pos


def encode_delta_binary_packed(values: np.ndarray, block_size: int = 128,
                               n_miniblocks: int = 4,
                               _native: bool = True) -> bytes:
    """Encode int32/int64 values.  block_size=128, 4 miniblocks of 32 — the
    common writer layout (vpm=32, multiple of 32 as the spec requires).
    Routes through the C++ shim; this body is the oracle/fallback."""
    if _native and len(values):
        from .. import native

        nat = native.encode_delta(values, block_size, n_miniblocks)
        if nat is not None:
            return nat
    v = np.asarray(values, dtype=np.int64)
    total = len(v)
    out = bytearray()
    write_uvarint(out, block_size)
    write_uvarint(out, n_miniblocks)
    write_uvarint(out, total)
    if total == 0:
        write_uvarint(out, 0)
        return bytes(out)
    write_uvarint(out, zigzag(int(v[0])))
    if total == 1:
        return bytes(out)
    deltas = (v[1:].astype(np.uint64) - v[:-1].astype(np.uint64)).astype(np.int64)
    vpm = block_size // n_miniblocks
    for bstart in range(0, len(deltas), block_size):
        block = deltas[bstart : bstart + block_size]
        min_delta = int(block.min())
        write_uvarint(out, zigzag(min_delta))
        adj = (block.astype(np.uint64) - np.uint64(min_delta & 0xFFFFFFFFFFFFFFFF)).astype(np.uint64)
        widths = []
        chunks = []
        for m in range(n_miniblocks):
            mb = adj[m * vpm : (m + 1) * vpm]
            if len(mb) == 0:
                widths.append(0)
                chunks.append(b"")
                continue
            mx = int(mb.max())
            w = mx.bit_length()
            widths.append(w)
            padded = np.zeros(vpm, dtype=np.uint64)
            padded[: len(mb)] = mb
            chunks.append(pack_bits(padded, w) if w else b"")
        out += bytes(widths)
        # trailing empty miniblocks are not written
        last_nonempty = -1
        for m in range(n_miniblocks):
            if m * vpm < len(block):
                last_nonempty = m
        for m in range(last_nonempty + 1):
            out += chunks[m]
    return bytes(out)


# ---------------------------------------------------------------------------
# DELTA_LENGTH_BYTE_ARRAY (encoding/delta — length_byte_array.go)
# ---------------------------------------------------------------------------


def decode_delta_length_byte_array(data, pos: int = 0):
    lengths, pos = decode_delta_binary_packed(data, pos)
    offsets = np.empty(len(lengths) + 1, dtype=np.int64)
    offsets[0] = 0
    np.cumsum(lengths, out=offsets[1:])
    total = int(offsets[-1])
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    values = buf[pos : pos + total].copy()
    return values, offsets.astype(np.int32), pos + total


def encode_delta_length_byte_array(values: np.ndarray, offsets: np.ndarray) -> bytes:
    offs = np.asarray(offsets, dtype=np.int64)
    lengths = offs[1:] - offs[:-1]
    out = bytearray(encode_delta_binary_packed(lengths))
    out += np.asarray(values, dtype=np.uint8).tobytes()
    return bytes(out)


# ---------------------------------------------------------------------------
# DELTA_BYTE_ARRAY (encoding/delta — byte_array.go; incremental/front coding)
# ---------------------------------------------------------------------------


def decode_delta_byte_array_parts(data, pos: int = 0):
    """Front-coding prescan: the two delta-packed streams of a
    DELTA_BYTE_ARRAY page WITHOUT expanding any prefix — returns
    ``(prefix_lens int64, suffix bytes, suffix offsets int32, end)``.
    The device route (ops/device.py delta_byte_array_expand) stages the
    raw suffix stream and resolves prefixes on chip; the host decoder
    below expands from the same parts."""
    prefix_lens, pos = decode_delta_binary_packed(data, pos)
    suffixes, soffs, pos = decode_delta_length_byte_array(data, pos)
    return prefix_lens, suffixes, soffs, pos


def decode_delta_byte_array(data, pos: int = 0):
    from .. import native as _native

    prefix_lens, suffixes, soffs, pos = decode_delta_byte_array_parts(
        data, pos)
    n = len(prefix_lens)
    suffix_lens = (soffs[1:] - soffs[:-1]).astype(np.int64)
    lens = prefix_lens + suffix_lens
    offsets = np.empty(n + 1, dtype=np.int64)
    offsets[0] = 0
    np.cumsum(lens, out=offsets[1:])
    nat = _native.delta_byte_array_expand(prefix_lens, suffixes,
                                          soffs.astype(np.int64), offsets)
    if nat is not None:
        return nat, offsets.astype(np.int32), pos
    values = np.empty(int(offsets[-1]), dtype=np.uint8)
    # sequential prefix dependency (host oracle; device path uses scan variant)
    prev_start = 0
    prev_len = 0
    for i in range(n):
        pl = int(prefix_lens[i])
        sl = int(suffix_lens[i])
        o = int(offsets[i])
        if pl:
            values[o : o + pl] = values[prev_start : prev_start + pl]
        if sl:
            s = int(soffs[i])
            values[o + pl : o + pl + sl] = suffixes[s : s + sl]
        prev_start = o
        prev_len = pl + sl
    return values, offsets.astype(np.int32), pos


def encode_delta_byte_array(values: np.ndarray, offsets: np.ndarray) -> bytes:
    offs = np.asarray(offsets, dtype=np.int64)
    vals = np.asarray(values, dtype=np.uint8)
    n = len(offs) - 1
    prefix_lens = np.zeros(n, dtype=np.int64)
    prev = b""
    suffix_parts = []
    for i in range(n):
        cur = vals[offs[i] : offs[i + 1]].tobytes()
        p = 0
        m = min(len(prev), len(cur))
        while p < m and prev[p] == cur[p]:
            p += 1
        prefix_lens[i] = p
        suffix_parts.append(cur[p:])
        prev = cur
    sdata = b"".join(suffix_parts)
    soffs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(s) for s in suffix_parts], out=soffs[1:])
    out = bytearray(encode_delta_binary_packed(prefix_lens))
    out += encode_delta_length_byte_array(np.frombuffer(sdata, dtype=np.uint8), soffs)
    return bytes(out)


# ---------------------------------------------------------------------------
# BYTE_STREAM_SPLIT (encoding/bytestreamsplit + asm)
# ---------------------------------------------------------------------------


def decode_byte_stream_split(data, num_values: int, width: int) -> np.ndarray:
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    planes = buf[: width * num_values].reshape(width, num_values)
    return np.ascontiguousarray(planes.T)  # (n, width) bytes


def encode_byte_stream_split(raw_le_bytes: np.ndarray, num_values: int, width: int) -> bytes:
    b = np.asarray(raw_le_bytes, dtype=np.uint8).reshape(num_values, width)
    return np.ascontiguousarray(b.T).tobytes()


# ---------------------------------------------------------------------------
# Dictionary gather (dictionary.go read side)
# ---------------------------------------------------------------------------


def gather_dictionary(dictionary, indices: np.ndarray):
    """dictionary: typed array or (values, offsets) pair; indices int64."""
    if isinstance(dictionary, tuple):
        dvals, doffs = dictionary
        from .. import native

        nat = native.gather_ba(dvals, doffs, indices)
        if nat is not None:
            return nat[0], _offsets32(nat[1])
        indices = np.asarray(indices)
        if len(indices) and (indices.min() < 0
                             or indices.max() >= len(doffs) - 1):
            raise ValueError("dictionary index out of range")
        lens = (doffs[1:] - doffs[:-1]).astype(np.int64)
        out_lens = lens[indices]
        out_offsets = np.empty(len(indices) + 1, dtype=np.int64)
        out_offsets[0] = 0
        np.cumsum(out_lens, out=out_offsets[1:])
        total = int(out_offsets[-1])
        idx = np.repeat(doffs[:-1][indices].astype(np.int64), out_lens) + _ranges(out_lens)
        values = dvals[idx] if total else np.empty(0, dtype=np.uint8)
        return values, _offsets32(out_offsets)
    return np.asarray(dictionary)[indices]


def _offsets32(offsets: np.ndarray) -> np.ndarray:
    """int64 gather offsets → the int32 convention, refusing silent wrap
    when the concatenated byte total exceeds INT32_MAX (advisor r2)."""
    if len(offsets) and int(offsets[-1]) > np.iinfo(np.int32).max:
        raise ValueError(
            "gathered byte-array output exceeds 2 GiB; int32 offsets would "
            "wrap — gather a narrower row range")
    return offsets.astype(np.int32)
