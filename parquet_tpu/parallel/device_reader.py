"""Device decode pipeline: chunk bytes → HBM → decoded jax.Arrays.

Reference parity: this is the ``PARQUET_GO_DEVICE=tpu`` path of the north star
(BASELINE.json): the per-page decode loop of ``filePages.ReadPage`` rerouted so
that raw page payloads are staged to the device in batched transfers per chunk
and decoded by the kernels in ``ops/device.py``.  Host does only
metadata-scale work (page headers, LZ decompression, run/miniblock pre-scans);
the device does all data-scale work (bit-unpack, RLE expansion, delta cumsum,
gathers) — SURVEY.md §7 steps 4-6.

Whole-chunk single-kernel decode: every encoding family merges ALL of a
chunk's pages into ONE device call —
- PLAIN fixed-width pages are contiguous in the value stage → one bitcast;
- dictionary/bool pages become one run table (per-run widths handle per-page
  bit widths) → one :func:`rle_expand`;
- DELTA pages merge miniblock tables and use a segmented cumsum (global
  cumsum minus per-page base) → one call;
- BYTE_STREAM_SPLIT pages use a page-aware gather → one call.

Column representation stays TPU-friendly: 32-bit types native, 64-bit types as
(n,2) uint32 pairs, BYTE_ARRAY dictionary chunks stay *encoded* (device
dictionary + int32 indexes — the Arrow DictionaryArray analog).

Anything exotic (mixed dict/plain fallback chunks, byte-array deltas) falls
back to the host oracle for the whole chunk — correctness first, the hot
paths stay on device.
"""

from __future__ import annotations

import struct as _struct
from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..format import metadata as md
from ..format.enums import CompressionCodec, Encoding, PageType, Type
from ..io.column import Column
from ..io.reader import ColumnChunkReader, CorruptedError, decode_chunk_host, _bit_width
from ..ops import device as dev, levels as levels_ops, ref
from ..utils.debug import counters
from .. import native

_FIXED_WIDTH = {Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4, Type.DOUBLE: 8,
                Type.INT96: 12}
_IS_PAIR = {Type.INT64, Type.DOUBLE}


class _Unsupported(Exception):
    """Internal: chunk shape the device path doesn't cover → host fallback."""


class _LazyLevels:
    """Per-slot level stream, materialized on first array access.

    The fused list assembler (pq_assemble_list_runs) derives offsets/validity
    straight from the run tables, so most reads never touch per-slot levels;
    consumers that do (row-range trims, struct zips, batch streaming) get
    them transparently via the numpy array protocol."""

    __slots__ = ("_runs", "_buf", "_arr")

    def __init__(self, runs: _RunTable, buf: np.ndarray):
        self._runs, self._buf, self._arr = runs, buf, None

    def _materialize(self) -> np.ndarray:
        if self._arr is None:
            self._arr = self._runs.expand_host(self._buf)
        return self._arr

    def __array__(self, dtype=None, copy=None):
        a = self._materialize()
        return np.asarray(a, dtype=dtype)

    # comparisons/arithmetic materialize and delegate, so a consumer writing
    # `col.def_levels == x` gets elementwise semantics instead of a silent
    # Python identity bool (advisor r2)
    def __eq__(self, other):
        return self._materialize() == np.asarray(other)

    def __ne__(self, other):
        return self._materialize() != np.asarray(other)

    __hash__ = None  # elementwise __eq__: not hashable, like ndarray

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        inputs = tuple(np.asarray(x) if isinstance(x, _LazyLevels) else x
                       for x in inputs)
        return getattr(ufunc, method)(*inputs, **kwargs)

    def __len__(self):
        return self._runs.total

    def __getitem__(self, i):
        return self._materialize()[i]


@dataclass
class _RunTable:
    """Chunk-level merged RLE/bit-packed run table (host-scanned)."""

    ends: List[np.ndarray] = field(default_factory=list)
    kinds: List[np.ndarray] = field(default_factory=list)
    payloads: List[np.ndarray] = field(default_factory=list)
    bit_offsets: List[np.ndarray] = field(default_factory=list)
    widths: List[np.ndarray] = field(default_factory=list)
    total: int = 0

    def add_scanned(self, kinds, cnts, payloads, offs, width, base_byte, n):
        self.kinds.append(kinds)
        self.payloads.append(payloads)
        self.bit_offsets.append((offs + base_byte) * 8)
        self.widths.append(np.full(len(kinds), width, dtype=np.int32))
        self.ends.append(self.total + np.cumsum(cnts))
        self.total += n

    def add(self, data: np.ndarray, n: int, width: int, base_byte: int) -> tuple:
        single = _single_rle_run(data, n, width)
        if single is not None:
            # the common all-present/all-null stream is ONE RLE run: decode
            # inline and skip the native scan round-trip (~35us/page of
            # dispatch overhead, at every level-stream call site)
            kinds = np.zeros(1, np.uint8)
            cnts = np.array([n])
            payloads = np.array([single[0]], np.int64)
            offs = np.array([single[1]], np.int64)
        else:
            kinds, cnts, payloads, offs, _end = ref.scan_rle_runs(
                data, n, width, 0)
        self.add_scanned(kinds, cnts, payloads, offs, width, base_byte, n)
        return kinds, cnts, payloads, offs

    def add_bitpacked_span(self, n: int, width: int, base_byte: int):
        """A raw bit-packed span (e.g. PLAIN BOOLEAN page) as a single run."""
        self.kinds.append(np.ones(1, np.uint8))
        self.payloads.append(np.zeros(1, np.int64))
        self.bit_offsets.append(np.array([base_byte * 8], np.int64))
        self.widths.append(np.full(1, width, np.int32))
        self.ends.append(np.array([self.total + n], np.int64))
        self.total += n

    def tables_host(self) -> tuple:
        """(ends, kinds, payloads, bit_offsets, widths) as int64-domain host
        arrays — operands of the fused C++ run-table consumers."""
        return (np.concatenate(self.ends).astype(np.int64),
                np.concatenate(self.kinds),
                np.concatenate(self.payloads).astype(np.int64),
                np.concatenate(self.bit_offsets).astype(np.int64),
                np.concatenate(self.widths).astype(np.int32))

    def run_arrays(self) -> tuple:
        """(ends, kinds, payloads, bit_offsets, widths) as flat host arrays —
        the rle_expand kernel operands, stageable to HBM ahead of decode.
        int32 throughout: staged buffers are < 2^27 bytes (bit offsets fit)
        and chunks hold < 2^31 values, keeping device index math in 32-bit
        lanes."""
        return (np.concatenate(self.ends).astype(np.int32),
                np.concatenate(self.kinds),
                np.concatenate(self.payloads).astype(np.int32),
                np.concatenate(self.bit_offsets).astype(np.int32),
                np.concatenate(self.widths))

    def expand(self, dbuf: jax.Array, n: Optional[int] = None,
               tables: Optional[tuple] = None) -> jax.Array:
        return dev.rle_expand(dbuf, n or self.total,
                              *(tables if tables is not None
                                else self.run_arrays()))

    def expand_host(self, buf: np.ndarray, n: Optional[int] = None) -> np.ndarray:
        """Numpy twin of :meth:`expand` over the host copy of the byte stream.

        Used for nested columns, whose level streams are consumed by the host
        record assembler — expanding there avoids a D2H sync of data that is
        metadata-sized to begin with."""
        n = n or self.total
        ends, kinds, payloads, offs, widths32 = self.tables_host()
        widths = widths32.astype(np.int64)
        out = native.expand_runs(buf, ends, kinds, payloads, offs, widths32, n)
        if out is not None:
            return out
        if len(widths) and widths.max() > 24:
            # rare wide levels: per-run loop (a 4-byte gather window below
            # only covers widths <= 25 at arbitrary bit phase)
            out = np.empty(n, np.int32)
            pos = 0
            for i in range(len(kinds)):
                cnt = min(int(ends[i]) - pos, n - pos)
                if cnt <= 0:
                    continue
                if kinds[i] == 0:
                    out[pos : pos + cnt] = payloads[i]
                else:
                    bit0 = int(offs[i])
                    out[pos : pos + cnt] = ref.unpack_bits(
                        buf[bit0 // 8 :], cnt, int(widths[i]), bit0 % 8)
                pos += cnt
            return out[:pos]
        starts = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
        counts = np.maximum(np.minimum(ends, n) - starts, 0)
        rid = np.repeat(np.arange(len(kinds)), counts)
        pos = np.arange(int(counts.sum()), dtype=np.int64)
        within = pos - np.repeat(starts, counts)
        packed = kinds[rid] != 0
        # RLE runs take their payload directly; gather position only matters
        # for bit-packed runs (and would otherwise index past the stream)
        bitpos = np.where(packed, offs[rid] + within * widths[rid], 0)
        vals = _gather_bits(buf, bitpos, widths[rid])
        return np.where(packed, vals, payloads[rid]).astype(np.int32)


def _gather_bits(body: np.ndarray, bitpos: np.ndarray, widths) -> np.ndarray:
    """Unpack one value per entry of ``bitpos`` (bit offsets into ``body``)
    via a 4-byte little-endian gather window.  Valid for widths <= 24."""
    pbuf = np.concatenate([np.asarray(body, np.uint8), np.zeros(8, np.uint8)])
    b0 = bitpos >> 3
    w32 = (pbuf[b0].astype(np.uint32)
           | (pbuf[b0 + 1].astype(np.uint32) << 8)
           | (pbuf[b0 + 2].astype(np.uint32) << 16)
           | (pbuf[b0 + 3].astype(np.uint32) << 24))
    mask = (np.uint32(1) << np.asarray(widths).astype(np.uint32)) - np.uint32(1)
    return (w32 >> (bitpos & 7).astype(np.uint32)) & mask


def _count_target_in_runs(kinds, cnts, payloads, offs, body, width, target) -> int:
    """How many level values equal ``target`` (native pass, else vectorized
    numpy — the per-page present count was half of config-4's host phase)."""
    if len(kinds) == 1 and kinds[0] == 0:
        # one RLE run (the dominant all-present / all-null page): direct —
        # the native round-trip costs ~30us/page x 400 pages per 64 MB chunk
        return int(cnts[0]) if int(payloads[0]) == target else 0
    kinds = np.asarray(kinds)
    cnts = np.asarray(cnts, np.int64)
    payloads = np.asarray(payloads, np.int64)
    offs = np.asarray(offs, np.int64)
    fast = native.count_target_in_runs(
        body if isinstance(body, np.ndarray) else np.frombuffer(body, np.uint8),
        kinds, cnts, payloads, offs, width, target)
    if fast is not None:
        return fast
    total = int(cnts[(kinds == 0) & (payloads == target)].sum())
    packed = np.flatnonzero(kinds != 0)
    if not len(packed):
        return total
    if width > 24:
        for k in packed:
            vals = ref.unpack_bits(body[offs[k]:], int(cnts[k]), width)
            total += int(np.count_nonzero(vals == target))
        return total
    pcnts = cnts[packed]
    rid = np.repeat(packed, pcnts)
    starts = np.zeros(len(packed), np.int64)
    np.cumsum(pcnts[:-1], out=starts[1:])
    within = np.arange(int(pcnts.sum()), dtype=np.int64) - np.repeat(starts, pcnts)
    vals = _gather_bits(body, offs[rid] * 8 + within * width, width)
    return total + int(np.count_nonzero(vals == target))


class _ByteAccum:
    """Byte-stream accumulator holding zero-copy views, concatenated ONCE at
    staging time (bytearray.extend copies every page body twice; this class
    keeps the extend()/len() surface build_plan already uses but defers the
    copy to :meth:`padded_array`, which writes straight into the final
    bucket-padded staging buffer — one copy total per byte)."""

    __slots__ = ("_parts", "_n")

    def __init__(self):
        self._parts = []
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def extend(self, b) -> None:
        if not isinstance(b, np.ndarray):
            b = np.frombuffer(b, np.uint8)
        if len(b):
            self._parts.append(b)
            self._n += len(b)

    def array(self) -> np.ndarray:
        """Concatenated uint8 array (one copy; zero-copy for a single part)."""
        if not self._parts:
            return np.empty(0, np.uint8)
        if len(self._parts) == 1:
            return self._parts[0]
        return np.concatenate(self._parts)

    def padded_array(self, extra: int = 12) -> np.ndarray:
        """Like ``dev.pad_to_bucket(self.array(), extra)`` without the
        intermediate concatenation: parts copy directly into the padded
        staging buffer."""
        n = self._n + extra
        bucket = 1 << max(int(n - 1).bit_length(), 6)
        if len(self._parts) == 1 and bucket == self._n:
            return self._parts[0]
        out = np.zeros(bucket, dtype=np.uint8)
        pos = 0
        for p in self._parts:
            out[pos : pos + len(p)] = p
            pos += len(p)
        return out

    def tobytes(self) -> bytes:
        return self.array().tobytes()


@dataclass
class _Plan:
    """Host-built staging plan for one chunk."""

    levels: _ByteAccum = field(default_factory=_ByteAccum)
    values: _ByteAccum = field(default_factory=_ByteAccum)
    def_runs: _RunTable = field(default_factory=_RunTable)
    rep_runs: _RunTable = field(default_factory=_RunTable)
    host_def: List[np.ndarray] = field(default_factory=list)
    value_kind: Optional[str] = None  # 'plain_fixed'|'plain_flba'|'bool'|'dict'|'delta'|'bss'|'dba'|'host_ba'
    # plain
    plain_total: int = 0
    # dict / bool runs
    vruns: _RunTable = field(default_factory=_RunTable)
    # dense single-width dict-index stream (Pallas/jnp gather-free route):
    # bit-packed run payloads compacted into one LSB-first w-bit stream,
    # page-aligned to 32-value groups; (start_value, n_values) per page
    dense: _ByteAccum = field(default_factory=_ByteAccum)
    dense_w: Optional[int] = None
    dense_pages: List[Tuple[int, int]] = field(default_factory=list)
    dense_ok: bool = True
    # dict-chunk decode route, decided ONCE at plan time (build_plan) so a
    # mid-flight env flip cannot make stage/decode disagree with the plan's
    # dense accumulation decision
    dict_route: Optional[str] = None
    # delta
    d_firsts: List[int] = field(default_factory=list)
    d_counts: List[int] = field(default_factory=list)
    d_vpms: List[int] = field(default_factory=list)
    # static shape info for the dense (gather-free) delta kernel, set by
    # stage_plan when the chunk is dense-eligible
    d_dense_static: Optional[tuple] = None
    d_mb_offs: List[np.ndarray] = field(default_factory=list)
    d_mb_widths: List[np.ndarray] = field(default_factory=list)
    d_mb_mins: List[np.ndarray] = field(default_factory=list)
    d_vpm: int = 32
    # bss
    bss_pages: List[Tuple[int, int]] = field(default_factory=list)  # (base, n)
    # dba (front-coded byte arrays; suffix bytes live in `values`, the
    # per-page length tables stay host-side until stage time)
    dba_plens: List[np.ndarray] = field(default_factory=list)
    dba_soffs: List[np.ndarray] = field(default_factory=list)
    dba_pages: List[Tuple[int, int]] = field(default_factory=list)  # (base, n)
    # host byte arrays
    host_parts: List = field(default_factory=list)
    total_slots: int = 0
    total_values: int = 0
    dictionary_host = None
    # leaf/physical recorded so stage_plan can stage the dictionary with the
    # chunk instead of inside the decode phase
    leaf = None
    physical: Optional[Type] = None

    def set_kind(self, kind: str):
        if self.value_kind is None:
            self.value_kind = kind
        elif self.value_kind != kind:
            raise _Unsupported(f"mixed page encodings {self.value_kind}/{kind}")


def _single_rle_run(body, n: int, w: int):
    """Parse a level stream that is exactly ONE RLE run covering >= n values
    (the all-present / all-null page shape).  Returns (value, payload_offset)
    or None when the stream is anything else — callers fall back to the full
    run scan.  Mirrors pq_scan_rle_runs's header semantics exactly."""
    m = len(body)
    if not m:
        return None
    header = 0
    shift = 0
    i = 0
    while True:
        if i >= m or shift > 63:
            return None
        b = int(body[i])
        i += 1
        header |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if header & 1:
        return None  # bit-packed run
    count = header >> 1
    vbytes = (w + 7) // 8
    if count < n or i + vbytes > m:
        return None
    value = int.from_bytes(bytes(body[i : i + vbytes]), "little")
    if w < 64:
        value &= (1 << w) - 1
    # offset convention matches pq_scan_rle_runs: byte position AFTER the
    # run's value payload
    return value, i + vbytes


def _fused_dict_plan(reader: ColumnChunkReader):
    """One-native-call planner for the host dict route: whole-chunk
    decompress + all-present level check + index-run scan fused in C++
    (native.dict_chunk_scan).  Returns ``(plan, raw)`` on success and
    ``(None, raw_or_None)`` whenever the chunk needs the general per-page
    planner — nulls, rep levels, PLAIN-fallback pages, codecs outside
    UNCOMPRESSED/SNAPPY/ZSTD, registry-shadowed encodings, or no native
    lib; ``raw`` hands the already-read chunk buffer to the fallback so
    the bail path doesn't pread the chunk twice."""
    from ..ops.encodings import is_builtin_decode

    leaf = reader.leaf
    meta = reader.meta
    if leaf.max_repetition_level != 0:
        return None, None
    if _dict_run_route() != "host":
        return None, None
    codec_id = int(meta.codec)
    if codec_id not in (int(CompressionCodec.UNCOMPRESSED),
                        int(CompressionCodec.SNAPPY),
                        int(CompressionCodec.ZSTD)):
        return None, None
    from ..codecs import SnappyCodec, UncompressedCodec, ZstdCodec

    if type(reader.codec) not in (UncompressedCodec, SnappyCodec, ZstdCodec):
        # a substituted/subclassed codec (codecs.CODECS is an override
        # point) must keep decoding through reader.codec, not the raw
        # libsnappy/libzstd the native pass dlopens
        return None, None
    encs = set(meta.encodings or ())
    if not ({int(Encoding.RLE_DICTIONARY), int(Encoding.PLAIN_DICTIONARY)}
            & encs):
        return None, None
    if not (is_builtin_decode(Encoding.RLE_DICTIONARY)
            and is_builtin_decode(Encoding.PLAIN_DICTIONARY)):
        return None, None
    start, size = reader.byte_range
    raw = reader.file.source.pread_view(start, size)
    rows = native.scan_page_headers(raw, meta.num_values)
    if rows is None:
        return None, raw
    res = native.dict_chunk_scan(raw, rows, codec_id,
                                 leaf.max_definition_level,
                                 leaf.max_repetition_level)
    if res is None:
        return None, raw
    ends, kinds, payloads, bit_offs, widths, nvals, body = res
    physical = Type(meta.type)
    plan = _Plan()
    plan.leaf = leaf
    plan.physical = physical
    plan.set_kind("dict")
    plan.dict_route = "host"
    plan.dense_ok = False
    # dictionary page decode stays in Python (one small page)
    for row in rows:
        if row[native.PG_TYPE] == PageType.DICTIONARY_PAGE:
            rawv = raw if isinstance(raw, np.ndarray) else np.frombuffer(
                raw, np.uint8)
            payload = rawv[row[native.PG_DATA_POS]:
                           row[native.PG_DATA_POS] + row[native.PG_COMP]]
            dbody = reader.codec.decode(payload, int(row[native.PG_UNCOMP]))
            plan.dictionary_host = ref.decode_plain(
                np.frombuffer(dbody, np.uint8),
                int(row[native.PG_DICT_NVALS]), physical, leaf.type_length)
            break
    v = plan.vruns
    v.ends.append(ends)
    v.kinds.append(kinds)
    v.payloads.append(payloads)
    v.bit_offsets.append(bit_offs)
    v.widths.append(widths)
    v.total = nvals
    plan.values.extend(body)
    plan.total_slots = nvals   # all-present proven by the native scan
    plan.total_values = nvals
    counters.inc("fused_dict_plans")
    return plan, raw


def build_plan(reader: ColumnChunkReader, pages=None) -> _Plan:
    """Host prescan of a chunk's pages into a staging plan.

    ``pages`` (an iterator of PageInfo, e.g. from io/search.seek_pages)
    restricts the plan to a page subset — the pushdown scan path; the
    dictionary page must be included when the chunk is dict-encoded."""
    chunk_raw = None
    if pages is None:
        fused, chunk_raw = _fused_dict_plan(reader)
        if fused is not None:
            return fused
    leaf = reader.leaf
    codec = reader.codec
    physical = Type(reader.meta.type)
    max_def = leaf.max_definition_level
    max_rep = leaf.max_repetition_level
    plan = _Plan()
    plan.leaf = leaf
    plan.physical = physical

    for page in (reader.pages(raw=chunk_raw) if pages is None else pages):
        h = page.header
        pt = page.page_type
        if pt == PageType.DICTIONARY_PAGE:
            raw = codec.decode(page.payload, h.uncompressed_page_size)
            plan.dictionary_host = ref.decode_plain(
                np.frombuffer(raw, np.uint8), h.dictionary_page_header.num_values,
                physical, leaf.type_length)
            continue
        if pt == PageType.DATA_PAGE:
            dph = h.data_page_header
            n = dph.num_values
            raw = np.frombuffer(codec.decode(page.payload, h.uncompressed_page_size), np.uint8)
            pos = 0
            n_present = n
            if max_rep > 0:
                (length,) = _struct.unpack_from("<I", raw, pos)
                body = raw[pos + 4 : pos + 4 + length]
                plan.rep_runs.add(body, n, _bit_width(max_rep), len(plan.levels))
                plan.levels.extend(body)
                pos += 4 + length
            if max_def > 0:
                enc = Encoding(dph.definition_level_encoding)
                w = _bit_width(max_def)
                if enc == Encoding.RLE:
                    (length,) = _struct.unpack_from("<I", raw, pos)
                    body = raw[pos + 4 : pos + 4 + length]
                    scanned = plan.def_runs.add(body, n, w, len(plan.levels))
                    plan.levels.extend(body)
                    pos += 4 + length
                    n_present = _count_target_in_runs(*scanned, body, w,
                                                      max_def)
                else:  # legacy BIT_PACKED levels: host decode
                    nbytes = (n * w + 7) // 8
                    lv = ref.decode_bit_packed_levels(raw[pos:], n, w)
                    plan.host_def.append(lv)
                    pos += nbytes
                    n_present = int(np.count_nonzero(lv == max_def))
            _stage_values(plan, raw, pos, n_present, Encoding(dph.encoding),
                          physical, leaf)
            plan.total_slots += n
            plan.total_values += n_present
        elif pt == PageType.DATA_PAGE_V2:
            dph2 = h.data_page_header_v2
            n = dph2.num_values
            rl = dph2.repetition_levels_byte_length or 0
            dl = dph2.definition_levels_byte_length or 0
            if max_rep > 0:
                body = np.frombuffer(page.payload[:rl], np.uint8)
                plan.rep_runs.add(body, n, _bit_width(max_rep), len(plan.levels))
                plan.levels.extend(page.payload[:rl])
            if max_def > 0:
                body = np.frombuffer(page.payload[rl : rl + dl], np.uint8)
                plan.def_runs.add(body, n, _bit_width(max_def), len(plan.levels))
                plan.levels.extend(page.payload[rl : rl + dl])
            raw_body = page.payload[rl + dl :]
            if dph2.is_compressed is not False:
                raw_body = codec.decode(raw_body, h.uncompressed_page_size - rl - dl)
            raw = np.frombuffer(raw_body, np.uint8)
            n_present = n - (dph2.num_nulls or 0)
            _stage_values(plan, raw, 0, n_present, Encoding(dph2.encoding),
                          physical, leaf)
            plan.total_slots += n
            plan.total_values += n_present
    return plan


def _dense_mode() -> str:
    """Routing for single-width dense streams: 'auto' (default — the Pallas
    VMEM-tiled kernel on TPU at every width, the jnp twin elsewhere),
    'pallas'/'jnp' to force a path, 'off' (round-1 per-value gather path).
    'mul' is accepted for compatibility and equals 'auto' (the multiply-
    straddle it used to opt into passed its on-chip trial and is now the
    built-in w ≥ 17 formulation — scripts/mosaic_repro.py).
    PARQUET_TPU_PALLAS=1 → pallas, =0 → jnp, =off → off."""
    from ..utils.env import env_str

    v = env_str("PARQUET_TPU_PALLAS")
    if v == "1":
        return "pallas"
    if v == "0":
        return "jnp"
    if v.lower() == "off":
        return "off"
    if v.lower() in ("jnp", "pallas", "auto", "mul"):
        return v.lower()
    return "auto"


def _backend_route(env_var: str) -> str:
    """Shared host/device routing policy: an explicit env override wins,
    else 'device' on a real TPU and 'host' on every other backend (where
    the XLA emulation of gather/bitcast-shaped kernels is the measured
    pathological case)."""
    from ..utils.env import env_str

    v = env_str(env_var).lower()
    if v in ("host", "device"):
        return v
    return "device" if jax.default_backend() == "tpu" else "host"


def _plain_run_route() -> str:
    """Where PLAIN fixed-width chunks decode: 'device' (staged bitcast
    kernels — the bytes are needed in HBM anyway) or 'host' (numpy
    zero-copy views of the host accumulation; staging + an XLA bitcast
    materialization are two pure memcpy passes for an op numpy does for
    free).  PARQUET_TPU_PLAIN_RUNS overrides."""
    return _backend_route("PARQUET_TPU_PLAIN_RUNS")


def _dict_run_route() -> str:
    """Where mixed-run dictionary index streams decode: 'device' (the
    rle_expand kernel) or 'host' (C++ fused run expand + gather; BASELINE
    config 2 was the emulated route's worst case).  PARQUET_TPU_DICT_RUNS
    overrides."""
    return _backend_route("PARQUET_TPU_DICT_RUNS")


def _bss_run_route() -> str:
    """Where BYTE_STREAM_SPLIT chunks decode: 'device' (static per-page
    plane-slice kernels) or 'host' (numpy plane transpose — one pass per
    page).  PARQUET_TPU_BSS_RUNS overrides."""
    return _backend_route("PARQUET_TPU_BSS_RUNS")


def _dba_run_route() -> str:
    """Where DELTA_BYTE_ARRAY chunks decode: 'device' (host prefix-length
    prescan, suffix gather + pointer-jumping prefix resolution on chip —
    only length metadata is touched on host) or 'host' (the sequential
    front-coding expand).  PARQUET_TPU_DBA_RUNS overrides."""
    return _backend_route("PARQUET_TPU_DBA_RUNS")


def _delta_run_route() -> str:
    """Where DELTA_BINARY_PACKED chunks decode: 'device' (dense unpack +
    segmented cumsum kernels) or 'host' (C++ fused unpack + prefix sum from
    the prescan miniblock tables; BASELINE config 4).
    PARQUET_TPU_DELTA_RUNS overrides."""
    return _backend_route("PARQUET_TPU_DELTA_RUNS")


_pallas_broken = False  # set when a Pallas compile fails; jnp from then on


def _use_pallas(w: int) -> bool:
    """Whether the dense unpack of a ``w``-bit stream runs the Pallas kernel.

    Measured on the real v5e (round 2): Pallas wins 2-4x over the jnp twin
    for w ≤ 16 (8M values: ~67ms vs 140-280ms).  Mosaic DETERMINISTICALLY
    MISCOMPILES the word-straddling columns for w ≥ 17 in the shift
    formulation (sparse wrong values at shift-16 lanes; minimized repro:
    scripts/mosaic_repro.py), so unpack_bits_dense uses the equivalent
    multiply-straddle for those widths — proven exact on-chip at
    w ∈ {17, 20, 24, 27, 31} with 8M-value streams (2026-07-31 trial,
    MOSAIC_REPRO_ONCHIP.json) and since then the default TPU route at
    every width."""
    if _pallas_broken:
        return False
    mode = _dense_mode()
    if mode == "pallas":
        return True  # forced (interpret mode covers non-TPU backends)
    # w ≥ 17: unpack_bits_dense auto-selects the multiply-straddle variant,
    # proven correct on a real v5e at w=17..31 (2026-07-31 chip trial,
    # MOSAIC_REPRO_ONCHIP.json: shift variant corrupts deterministic lanes,
    # mul variant exact at every width) — so wide widths now route through
    # Pallas by default on TPU like the narrow ones. 'mul' is kept as an
    # accepted value for compatibility and behaves like 'auto'.
    return mode in ("auto", "mul") and jax.default_backend() == "tpu"


def _pallas_fallback(exc: Exception) -> None:
    """The axon remote-compile path intermittently 500s on Pallas kernels;
    a decode must degrade to the (correct, slower) jnp twin, not die."""
    global _pallas_broken
    _pallas_broken = True
    counters.inc("pallas_compile_fallback", 1)
    import sys

    print(f"parquet_tpu: Pallas kernel failed ({type(exc).__name__}); "
          "falling back to jnp twins for this process", file=sys.stderr)


def _add_dense_page(plan: _Plan, body: np.ndarray, kinds, cnts, offs,
                    width: int, nvals: int) -> None:
    """Compact one dict page's index stream into the chunk's dense w-bit
    stream when every run is bit-packed (high-cardinality data — the hot
    case). Bit-packed runs encode whole 8-value groups (8·w bits, byte
    aligned), so stripping the varint headers and concatenating payloads
    yields a contiguous LSB-first stream; pages pad to 32-value boundaries
    (4·w bytes) so unpack groups never straddle pages."""
    if not plan.dense_ok or not len(kinds) or not np.all(np.asarray(kinds) == 1):
        plan.dense_ok = False
        return
    if plan.dense_w is None:
        plan.dense_w = width
    elif plan.dense_w != width:
        plan.dense_ok = False
        return
    group_bytes = 4 * width  # 32 values
    pad = -len(plan.dense) % group_bytes
    plan.dense.extend(b"\0" * pad)
    start_val = len(plan.dense) * 8 // width
    bview = np.asarray(body)
    for cnt, off in zip(np.asarray(cnts, np.int64), np.asarray(offs, np.int64)):
        ngroups = (int(cnt) + 7) // 8
        plan.dense.extend(bview[int(off): int(off) + ngroups * width])
    plan.dense_pages.append((start_val, nvals))


def _stage_values(plan: _Plan, raw: np.ndarray, pos: int, nvals: int,
                  encoding: Encoding, physical: Type, leaf) -> None:
    from ..ops.encodings import is_builtin_decode

    if not is_builtin_decode(encoding):
        # a third-party decode shadows this id (ops/encodings.py registry):
        # the accelerated planner only understands the spec encodings, so the
        # chunk must decode on host, where dispatch honors the registry
        raise _Unsupported(
            f"encoding {encoding!r} is overridden by a registered decoder")
    if encoding in (Encoding.PLAIN_DICTIONARY, Encoding.RLE_DICTIONARY):
        plan.set_kind("dict")
        if plan.dict_route is None:
            plan.dict_route = _dict_run_route()
        if plan.dict_route == "host":
            # the fused C++ expand+gather outruns the emulated dense-unpack
            # kernels off-TPU; don't pay the dense compaction accumulation
            # for a stream that will decode from the run tables
            plan.dense_ok = False
        width = int(raw[pos]) if pos < len(raw) else 0
        body = raw[pos + 1 :]
        base = len(plan.values)
        plan.values.extend(body)
        if width == 0:  # single-entry dictionary
            plan.vruns.add_scanned(np.zeros(1, np.uint8), np.array([nvals]),
                                   np.zeros(1, np.int64), np.zeros(1, np.int64),
                                   1, base, nvals)
            plan.dense_ok = False
        else:
            kinds, cnts, _, offs = plan.vruns.add(body, nvals, width, base)
            _add_dense_page(plan, body, kinds, cnts, offs, width, nvals)
        return
    if encoding == Encoding.PLAIN:
        if physical == Type.BOOLEAN:
            plan.set_kind("bool")
            base = len(plan.values)
            plan.values.extend(raw[pos:])
            plan.vruns.add_bitpacked_span(nvals, 1, base)
            return
        if physical in _FIXED_WIDTH:
            plan.set_kind("plain_fixed")
            w = _FIXED_WIDTH[physical]
            plan.values.extend(raw[pos : pos + nvals * w])
            plan.plain_total += nvals
            return
        if physical == Type.FIXED_LEN_BYTE_ARRAY:
            plan.set_kind("plain_flba")
            w = leaf.type_length
            plan.values.extend(raw[pos : pos + nvals * w])
            plan.plain_total += nvals
            return
        plan.set_kind("host_ba")  # PLAIN BYTE_ARRAY: host offsets scan
        plan.host_parts.append(ref.decode_plain(raw[pos:], nvals, physical,
                                                leaf.type_length))
        return
    if encoding == Encoding.DELTA_BINARY_PACKED:
        plan.set_kind("delta")
        base = len(plan.values)
        plan.values.extend(raw[pos:])
        first, total, vpm, offs, widths, mins, _ = dev.delta_prescan(raw, pos)
        plan.d_firsts.append(first)
        plan.d_counts.append(total)
        plan.d_mb_offs.append(offs + (base - pos) * 8)
        plan.d_mb_widths.append(widths)
        plan.d_mb_mins.append(mins)
        plan.d_vpm = vpm
        plan.d_vpms.append(vpm)
        return
    if encoding == Encoding.BYTE_STREAM_SPLIT:
        plan.set_kind("bss")
        w = _FIXED_WIDTH.get(physical, leaf.type_length)
        if not w:  # e.g. BYTE_ARRAY: no fixed width, no BSS plane layout
            raise _Unsupported("byte-stream-split without a fixed width")
        base = len(plan.values)
        plan.values.extend(raw[pos : pos + nvals * w])
        plan.bss_pages.append((base, nvals))
        return
    if encoding == Encoding.RLE and physical == Type.BOOLEAN:
        plan.set_kind("bool")
        (length,) = _struct.unpack_from("<I", raw, pos)
        body = raw[pos + 4 : pos + 4 + length]
        base = len(plan.values)
        plan.values.extend(body)
        plan.vruns.add(body, nvals, 1, base)
        return
    if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
        plan.set_kind("host_ba")
        v, o, _ = ref.decode_delta_length_byte_array(raw, pos)
        plan.host_parts.append((v, o))
        return
    if encoding == Encoding.DELTA_BYTE_ARRAY:
        if _dba_run_route() == "device":
            plan.set_kind("dba")
            plens, suffixes, soffs, _ = dev.delta_byte_array_prescan(raw, pos)
            if len(plens) and int(plens[0]) != 0:
                # front coding is per-page (first entry stores its full
                # value); a nonzero leading prefix would chase a parent
                # in another page — malformed, let the host path raise
                # its precise error
                raise _Unsupported(
                    "delta byte array page with nonzero leading prefix")
            base = len(plan.values)
            plan.values.extend(suffixes)
            plan.dba_plens.append(plens)
            plan.dba_soffs.append(soffs.astype(np.int64))
            plan.dba_pages.append((base, len(plens)))
            return
        plan.set_kind("host_ba")
        v, o, _ = ref.decode_delta_byte_array(raw, pos)
        if physical == Type.FIXED_LEN_BYTE_ARRAY:
            plan.host_parts.append(v.reshape(-1, leaf.type_length))
        else:
            plan.host_parts.append((v, o))
        return
    raise _Unsupported(f"encoding {encoding!r}")


# ---------------------------------------------------------------------------
# Merged multi-page delta decode (segmented cumsum)
# ---------------------------------------------------------------------------


def _nonempty(parts, dtype, fill=0):
    """Concatenate per-page metadata arrays; a zero-miniblock chunk (all
    single-value pages) still needs 1-element tables so device gathers have a
    non-empty operand."""
    out = (np.concatenate(parts).astype(dtype) if parts
           else np.empty(0, dtype))
    return out if out.size else np.full(1, fill, dtype)


def _delta_gather_tables(plan: _Plan) -> tuple:
    """Gather-kernel operands (page_ends, firsts, mb_base, mb_offs, mb_widths,
    mb_mins) as int32 index tables (+ int64 value-domain tables), shared by
    stage_plan and the unstaged decode fallback so the jit traces once."""
    page_ends = np.cumsum(plan.d_counts).astype(np.int32)
    mb_base = np.zeros(len(plan.d_counts), np.int32)
    np.cumsum([len(w) for w in plan.d_mb_widths[:-1]], out=mb_base[1:])
    mb_offs = _nonempty(plan.d_mb_offs, np.int64).astype(np.int32)
    mb_widths = _nonempty(plan.d_mb_widths, np.int32, fill=1)
    mb_mins = _nonempty(plan.d_mb_mins, np.int64)
    firsts = np.asarray(plan.d_firsts, np.int64)
    return page_ends, firsts, mb_base, mb_offs, mb_widths, mb_mins


def _stage_delta_dense(plan: _Plan, meta: dict, put=None) -> bool:
    """Host half of the gather-free delta decode (the TPU-first path).

    Compacts all miniblock payloads into per-width contiguous streams with
    numpy fancy indexing (metadata-scale cost: the compacted bytes ARE the
    compressed data), so the device kernel unpacks with static reshapes and
    never gathers.  Returns False for shapes the dense kernel doesn't cover
    (mixed vpm, >32-bit delta widths, >8 distinct widths) — those use the
    gather kernel.
    """
    if put is None:
        put = jax.device_put
    if not plan.d_counts:
        return False
    vpm = plan.d_vpm
    if len(set(plan.d_vpms)) != 1 or vpm % 32:
        return False
    if len(plan.d_counts) > 512:
        # static per-page slicing unrolls O(pages) into the graph; huge page
        # counts use the O(1)-graph gather kernel instead
        return False
    widths_all = np.concatenate(plan.d_mb_widths)
    uw = np.unique(widths_all)
    n_mb = len(widths_all)
    if n_mb == 0 or len(uw) > 8 or int(uw[-1]) > 32:
        return False
    vals_np = plan.values.array()
    boffs = np.concatenate(plan.d_mb_offs) // 8
    streams, groups = [], []
    for w in uw:
        g = np.where(widths_all == w)[0]
        groups.append(g)
        nb = vpm * int(w) // 8
        # int32 index (staged buffers are < 2^27 bytes): the fancy index is a
        # transient 4x the payload bytes, not 8x
        idx = boffs[g].astype(np.int32)[:, None] + np.arange(nb, dtype=np.int32)
        # the writer may truncate the final miniblock's payload: clip (the
        # garbage lands in delta slots past the page's value count)
        np.minimum(idx, np.int32(len(vals_np) - 1), out=idx)
        streams.append(put(dev.pad_to_bucket(
            vals_np[idx].reshape(-1), extra=4)))
        counters.inc("bytes_h2d", idx.size)
    if len(uw) == 1:
        perm = None
    else:
        # d2 row j holds original miniblock concat_order[j]; restore original
        # order with the inverse permutation
        concat_order = np.concatenate(groups)
        perm = put(np.argsort(concat_order).astype(np.int32))
    mins = put(np.concatenate(plan.d_mb_mins).astype(np.int64))
    firsts = put(np.asarray(plan.d_firsts, np.int64))
    meta["delta_dense"] = (tuple(streams), perm, mins, firsts)
    plan.d_dense_static = (vpm, tuple(int(w) for w in uw),
                           tuple(len(g) for g in groups),
                           tuple(int(c) for c in plan.d_counts))
    return True


@partial(jax.jit, static_argnames=("vpm", "gw", "gk", "pcounts", "pairs",
                                   "use_pk", "interpret"))
def _delta_decode_dense(streams, perm, mins, firsts,
                        vpm: int, gw: tuple, gk: tuple, pcounts: tuple,
                        pairs: bool, use_pk: tuple = (),
                        interpret: bool = False):
    """Gather-free multi-page delta decode (device half).

    Every access pattern is compile-time static: per-width dense unpack
    (reshape + 32 unrolled shift/mask column ops), per-page reassembly by
    static slicing (page structure is host metadata), and a segmented cumsum
    whose page bases are static picks.  The only dynamic indexing is the
    miniblock row permutation for mixed-width chunks (rare).
    """
    from ..ops import pallas_kernels as pk

    parts = []
    for gi, (buf, w, k) in enumerate(zip(streams, gw, gk)):
        if w == 0:
            # constant/fixed-stride data: all deltas equal min_delta, payload
            # is empty
            parts.append(jnp.zeros((k, vpm), jnp.uint32))
            continue
        words = dev._as_words(buf)
        if gi < len(use_pk) and use_pk[gi]:
            up = pk.unpack_bits_dense(words, k * vpm, w, interpret=interpret)
        else:
            up = pk.unpack_bits_dense_jnp(words, k * vpm, w)
        parts.append(up.reshape(k, vpm))
    d2 = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if perm is not None:
        d2 = d2[perm]
    if pairs:
        deltas = (d2.astype(jnp.int64) + mins[:, None]).reshape(-1)
        dt = jnp.int64
        fvals = firsts
    else:
        # mod-2^32 arithmetic: two's-complement wrap matches the encoding
        deltas = (d2 + (mins & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)[:, None]
                  ).reshape(-1)
        dt = jnp.uint32
        fvals = (firsts & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    seq_parts = []
    mbb = 0
    for p, cnt in enumerate(pcounts):
        seq_parts.append(fvals[p].astype(dt).reshape(1))
        nd = cnt - 1
        if nd > 0:
            seq_parts.append(deltas[mbb * vpm: mbb * vpm + nd].astype(dt))
        mbb += (nd + vpm - 1) // vpm
    seq = jnp.concatenate(seq_parts) if len(seq_parts) > 1 else seq_parts[0]
    gcum = jnp.cumsum(seq)
    if len(pcounts) > 1:
        pstarts = np.concatenate([[0], np.cumsum(pcounts)[:-1]])
        base_parts = [
            jnp.broadcast_to(gcum[int(ps) - 1] if ps else jnp.zeros((), dt),
                             (int(cnt),))
            for ps, cnt in zip(pstarts, pcounts)]
        gcum = gcum - jnp.concatenate(base_parts)
    if pairs:
        return dev._i64_to_pairs(gcum)
    return jax.lax.bitcast_convert_type(gcum, jnp.int32)


@partial(jax.jit, static_argnames=("n", "vpm", "pairs"))
def _delta_decode_multi(buf, n, page_ends, firsts, mb_base, mb_offs, mb_widths,
                        mb_mins, vpm, pairs: bool):
    """All delta pages of a chunk in one call.

    seq[i] = first value of its page if i is a page start, else the unpacked
    delta.  out = cumsum(seq) - cumsum_base_of_page (segmented prefix sum).
    """
    idx = jnp.arange(n, dtype=jnp.int32)
    ends = page_ends.astype(jnp.int32)
    page = jnp.searchsorted(ends, idx, side="right")
    page = jnp.minimum(page, ends.shape[0] - 1).astype(jnp.int32)
    pcounts = jnp.diff(ends, prepend=jnp.int32(0))
    pstart = ends[page] - pcounts[page]
    within = idx - pstart
    jc = jnp.maximum(within - 1, 0)  # delta ordinal (page-start slots unused)
    mb = mb_base.astype(jnp.int32)[page] + jc // vpm
    woff = jc % vpm
    w = mb_widths[mb]
    bit_pos = mb_offs.astype(jnp.int32)[mb] + woff * w
    if pairs:
        lo, hi = dev.unpack_bits_at64(buf, bit_pos, w)
        raw = lo.astype(jnp.int64) | (hi.astype(jnp.int64) << 32)
        delta = raw + mb_mins[mb]
        seq = jnp.where(within == 0, firsts[page], delta)
        gcum = jnp.cumsum(seq)
        base = gcum[pstart] - seq[pstart]  # exclusive cumsum at page start
        return dev._i64_to_pairs(gcum - base)
    # int32 values: mod-2^32 arithmetic keeps the whole pipeline in 32-bit
    # lanes (two's-complement wrap matches the encoding's semantics)
    raw = dev.unpack_bits_at32(buf, bit_pos, w)
    min32 = (mb_mins & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    delta = raw + min32[mb]
    first32 = (firsts & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    seq = jnp.where(within == 0, first32[page], delta)
    gcum = jnp.cumsum(seq)
    base = gcum[pstart] - seq[pstart]
    return jax.lax.bitcast_convert_type(gcum - base, jnp.int32)


@partial(jax.jit,
         static_argnames=("n", "pages", "width", "flba", "dtype4"))
def _bss_decode_multi(buf, n, pages: tuple, width: int,
                      flba: bool = False, dtype4: str = "float32"):
    """Gather-free BYTE_STREAM_SPLIT: byte plane k of a page is the static
    slice [base + k*count, base + (k+1)*count) — page structure is host
    metadata, so every plane extraction is a compile-time slice and the
    transpose is one reshape per page."""
    per_page = []
    for base, cnt in pages:
        planes = buf[base: base + width * cnt].reshape(width, cnt)
        per_page.append(planes.T)  # (cnt, width) bytes
    bytes_ = per_page[0] if len(per_page) == 1 else jnp.concatenate(per_page)
    if flba:
        # FLBA (float16, decimals, ...): ALWAYS the (n, width) byte-row
        # plain_flba form — the output form follows the physical type, not
        # the byte width (an FLBA(4) decimal is not a float32)
        return bytes_
    if width == 4:
        return jax.lax.bitcast_convert_type(
            bytes_, jnp.dtype(dtype4)).reshape(n)
    return jax.lax.bitcast_convert_type(
        bytes_.reshape(n, 2, 4), jnp.uint32).reshape(n, 2)


def _dba_tables(plan: _Plan):
    """Concatenate the per-page DELTA_BYTE_ARRAY prescan tables into
    chunk-global int32 tables for the expand kernel.  Prefix chains never
    cross pages (enforced at plan time: every page's first entry has
    prefix 0), so per-page entry streams concatenate freely with suffix
    offsets rebased by each page's base in the staged suffix stream.
    Returns ``((prefix_lens, suffix_offs, entry_offs), entry_offs_host,
    iters)`` — the host copy of the entry offsets doubles as the output
    Column's int32 offsets."""
    if not plan.dba_pages:
        empty = np.zeros(0, np.int32)
        zero = np.zeros(1, np.int32)
        return (empty, empty, zero), zero, 0
    plens = np.concatenate(plan.dba_plens)
    soffs = np.concatenate([so[:-1] + base
                            for (base, _), so in zip(plan.dba_pages,
                                                     plan.dba_soffs)])
    slens = np.concatenate([so[1:] - so[:-1] for so in plan.dba_soffs])
    entry_offs = np.zeros(len(plens) + 1, np.int64)
    np.cumsum(plens + slens, out=entry_offs[1:])
    if int(entry_offs[-1]) > np.iinfo(np.int32).max:
        # the pointer-jumping kernel indexes output positions in 32-bit
        # lanes; a >2 GiB expansion decodes on host
        raise _Unsupported("front-coded output exceeds 32-bit addressing")
    eoffs32 = entry_offs.astype(np.int32)
    return (plens.astype(np.int32), soffs.astype(np.int32), eoffs32), \
        eoffs32, dev.delta_byte_array_iters(plens)


# ---------------------------------------------------------------------------
# Chunk decode driver
# ---------------------------------------------------------------------------


def stage_plan(plan: _Plan, stage_levels: bool = True, put=None) -> tuple:
    """H2D: put the plan's concatenated level/value byte streams into HBM.

    Split out of :func:`decode_chunk_device` so callers (and the benchmark)
    can overlap staging with decode, or re-run the decode phase on buffers
    already resident in HBM.  ``stage_levels=False`` skips the level stream
    (nested columns assemble levels on host).  ``put`` substitutes for
    ``jax.device_put`` — :func:`prepare_chunks_batched` passes a recorder so
    many chunks' streams ride one batched transfer.
    """
    from ..obs import trace as _otrace

    if _otrace.TRACE_ENABLED:
        # the H2D stage is the device pipeline's overlap partner: its span
        # sitting beside a decode span on another track IS the double
        # buffer working
        with _otrace.span("device.h2d", col=plan.leaf.dotted_path
                          if plan.leaf is not None else None,
                          bytes=len(plan.values) + len(plan.levels)):
            return _stage_plan_impl(plan, stage_levels, put=put)
    return _stage_plan_impl(plan, stage_levels, put=put)


def _stage_plan_impl(plan: _Plan, stage_levels: bool = True,
                     put=None) -> tuple:
    if put is None:
        put = jax.device_put
    # host value routes, decided BEFORE the device size guard (they read
    # the host accumulation directly — no 32-bit-lane constraint) and
    # recorded in the staged meta: decode must not re-derive routing from
    # mutable env/backend state and disagree with what was (not) staged.
    # The host dict route outranks the dense device route off-TPU (measured
    # 2.4x on the 200-entry-dictionary string config).  The route was fixed
    # at plan time (plan.dict_route) — mid-flight env flips cannot make the
    # stage disagree with the plan's dense accumulation decision.
    dict_host = (plan.value_kind == "dict"
                 and (plan.dict_route or _dict_run_route()) == "host")
    dense_route = (plan.value_kind == "dict" and not dict_host
                   and plan.dense_ok and plan.dense_pages
                   and _dense_mode() != "off")
    plain_host = (plan.value_kind in ("plain_fixed", "plain_flba")
                  and _plain_run_route() == "host")
    delta_host = (plan.value_kind == "delta"
                  and _delta_run_route() == "host"
                  and native.get_lib() is not None)
    bss_host = plan.value_kind == "bss" and _bss_run_route() == "host"
    host_value_route = dict_host or plain_host or delta_host or bss_host
    if (stage_levels and len(plan.levels) > dev.MAX_DEVICE_BUF) or (
            not host_value_route and len(plan.values) > dev.MAX_DEVICE_BUF):
        # device kernels index in 32-bit lanes; oversized chunks decode on host
        raise _Unsupported("chunk stream exceeds 32-bit-lane bit addressing")
    lev_dbuf = None
    if stage_levels and len(plan.levels):
        lev_dbuf = put(plan.levels.padded_array())
        counters.inc("bytes_h2d", len(plan.levels))
    meta = {}
    if dict_host:
        meta["dict_host"] = True
    if plain_host:
        meta["plain_host"] = True
    if delta_host:
        meta["delta_host"] = True
    if bss_host:
        meta["bss_host"] = True
    delta_dense = (plan.value_kind == "delta" and not delta_host
                   and _stage_delta_dense(plan, meta, put=put))
    val_dbuf = None
    if not dense_route and not delta_dense and not dict_host and \
            not plain_host and not delta_host and not bss_host and \
            plan.value_kind not in (None, "host_ba"):
        # staged even when empty (all-null chunks have no value bytes): the
        # kernels need a real buffer operand to slice [:0] from
        val_dbuf = put(plan.values.padded_array())
        counters.inc("bytes_h2d", len(plan.values))
    if dense_route:
        # compacted single-width index stream replaces the raw bodies
        meta["dense"] = put(plan.dense.padded_array(extra=4))
        counters.inc("bytes_h2d", len(plan.dense))
    if plan.value_kind == "delta" and not delta_host:
        if not delta_dense:
            if len(set(plan.d_vpms)) > 1:
                # the gather kernel assumes one values-per-miniblock across
                # all pages; reject before paying any H2D
                raise _Unsupported("mixed delta miniblock sizes across pages")
            meta["delta"] = put(_delta_gather_tables(plan))
    if plan.value_kind == "dba":
        # per-entry length tables ride to HBM with the suffix stream so
        # the decode phase is pure on-chip work
        tabs, eoffs_host, iters = _dba_tables(plan)
        meta["dba"] = (put(tabs), eoffs_host, iters)
        counters.inc("bytes_h2d", sum(int(a.nbytes) for a in tabs))
    if plan.value_kind == "dict" and plan.dictionary_host is not None:
        # dictionary pages stage with the chunk, not inside the decode phase
        meta["dictionary"] = _stage_dictionary(plan.dictionary_host,
                                               plan.physical, plan.leaf,
                                               put=put)
    if plan.vruns.total and not dict_host:
        meta["vruns"] = put(plan.vruns.run_arrays())
    if stage_levels and plan.def_runs.total:
        meta["def_runs"] = put(plan.def_runs.run_arrays())
    if stage_levels and plan.rep_runs.total:
        meta["rep_runs"] = put(plan.rep_runs.run_arrays())
    return lev_dbuf, val_dbuf, meta


def stage_levels_on_device(leaf, plan: _Plan) -> bool:
    """Whether the level streams should go to HBM: flat single-def columns
    (validity from device RLE expansion) and — behind
    ``PARQUET_TPU_DEVICE_ASM=1`` — repeated columns of ANY depth, whose
    offsets/validity then assemble on device via ``dev.assemble_nested``
    (struct layers between lists collapse into the nearest list validity,
    same as the host assembler).  Flat struct chains (max_def > 1, no
    repetition) always expand on host: the table assembler needs host def
    levels for struct nullness, so staging their bytes would be wasted H2D.

    Repeated columns assemble on device by DEFAULT on accelerator
    backends (offsets/validity land in HBM via ``dev.assemble_nested`` —
    no host round-trip in the decode pipeline) and on HOST on the cpu
    backend, where the compaction kernels are emulated scatter/sort and
    measured 10-25x slower than the C++ expand+assemble pass (8M slots:
    31 ms C++ vs 555-815 ms emulated).  ``PARQUET_TPU_DEVICE_ASM=1``
    forces device assembly everywhere (the route-soak's device leg);
    ``=0`` forces host assembly everywhere."""
    if leaf.max_repetition_level == 0:
        if plan.total_values == plan.total_slots:
            return False  # no nulls anywhere: validity is None, levels unused
        return leaf.max_definition_level <= 1
    from ..utils.env import env_str

    flag = env_str("PARQUET_TPU_DEVICE_ASM")
    if flag == "0":
        return False
    if flag != "1":
        import jax

        if jax.default_backend() == "cpu":
            return False
    # any repetition depth: dev.assemble_nested mirrors the host assembler
    # over expanded level streams (struct layers between lists collapse into
    # the nearest list validity, same as the host semantics)
    return (leaf.max_repetition_level >= 1
            and bool(plan.def_runs.total) and bool(plan.rep_runs.total)
            and not plan.host_def)


def prepare_chunk(reader: ColumnChunkReader, device=None):
    """Host phase of one chunk's device decode: prescan (pread + decompress +
    run scan) and H2D staging. Safe to call from worker threads — the host
    work releases the GIL in numpy/C++/codec calls, and ``device`` targets
    the put at a specific mesh device."""
    import contextlib

    from ..utils.debug import annotate

    with annotate("pq.prepare_chunk"):
        plan = build_plan(reader)
        ctx = (jax.default_device(device) if device is not None
               else contextlib.nullcontext())
        with ctx:
            staged = stage_plan(
                plan, stage_levels=stage_levels_on_device(reader.leaf, plan))
    return plan, staged


class _DeferredPut:
    """Placeholder a recording ``put`` returns during batched staging: an
    index into the flat list of host pytrees awaiting the one real
    transfer."""

    __slots__ = ("idx",)

    def __init__(self, idx: int):
        self.idx = idx


def _subst_deferred(obj, outs):
    """Rebuild a staged structure with every :class:`_DeferredPut` replaced
    by its transferred device pytree (containers rebuilt, leaves shared)."""
    if isinstance(obj, _DeferredPut):
        return outs[obj.idx]
    if isinstance(obj, tuple):
        return tuple(_subst_deferred(v, outs) for v in obj)
    if isinstance(obj, list):
        return [_subst_deferred(v, outs) for v in obj]
    if isinstance(obj, dict):
        return {k: _subst_deferred(v, outs) for k, v in obj.items()}
    return obj


def prepare_chunks_batched(readers, device=None):
    """Host phase of MANY chunks' device decode with ONE H2D dispatch.

    Each chunk prescans and routes exactly as :func:`prepare_chunk` (the
    staged structures are interchangeable), but every ``device_put`` a
    chunk's stage would issue is recorded against host arrays instead, and
    the whole collection rides a single batched ``jax.device_put`` at the
    end — a few hundred per-stream dispatches collapse into one.  That is
    the dataset mesh route's per-file staging call: per-chunk dispatch
    overhead is what's left once prescan work is pipelined, and it scales
    with row-group count, not bytes.

    Returns ``[(reader, (plan, staged) | None, error)]`` in input order —
    the per-chunk triple ``decode``-side consumers already handle, with
    ``_Unsupported`` chunks carried as errors rather than raised."""
    from ..utils.debug import annotate

    calls: list = []

    def put(x):
        calls.append(x)
        return _DeferredPut(len(calls) - 1)

    entries = []
    with annotate("pq.prepare_chunks_batched"):
        for reader in readers:
            try:
                plan = build_plan(reader)
                staged = stage_plan(
                    plan, stage_levels=stage_levels_on_device(reader.leaf,
                                                              plan),
                    put=put)
                entries.append((reader, plan, staged, None))
            except _Unsupported as e:
                entries.append((reader, None, None, e))
        outs = jax.device_put(calls, device) if device is not None \
            else jax.device_put(calls)
    return [(reader,
             None if err is not None else (plan, _subst_deferred(staged,
                                                                 outs)),
             err)
            for reader, plan, staged, err in entries]


def _concat_batch_columns(leaf, cols: List[Column]) -> Column:
    """Concatenate per-page-batch Columns of ONE flat chunk (device decode).

    Only shapes `decode_chunk_batched` admits reach here: max_rep == 0,
    max_def <= 1.  Arrays concatenate in whatever domain the decode produced
    (jnp for device arrays, numpy for host byte-array parts); the concat is
    itself an async device op, so it overlaps later batches' staging."""
    if len(cols) == 1:
        return cols[0]
    xp = jnp if isinstance(cols[0].values if cols[0].values is not None
                           else cols[0].dict_indices, jax.Array) else np
    num_slots = sum(c.num_slots for c in cols)
    validity = None
    if any(c.validity is not None for c in cols):
        parts = [c.validity if c.validity is not None
                 else xp.ones(c.num_slots, bool) for c in cols]
        validity = xp.concatenate(parts)
    if cols[0].is_dictionary_encoded():
        idx = xp.concatenate([c.dict_indices for c in cols])
        return Column(leaf=leaf, values=None, dictionary=cols[0].dictionary,
                      dictionary_host=cols[0].dictionary_host,
                      dict_indices=idx, validity=validity,
                      num_slots=num_slots)
    offsets = None
    if cols[0].offsets is not None:
        offs_parts = []
        base = 0
        for c in cols:
            o = c.offsets
            offs_parts.append((o[:-1] + base) if base else o[:-1])
            base += int(o[-1])
        xo = jnp if isinstance(cols[0].offsets, jax.Array) else np
        offsets = xo.concatenate(
            offs_parts + [xo.asarray([base], dtype=cols[0].offsets.dtype)])
    values = xp.concatenate([c.values for c in cols])
    return Column(leaf=leaf, values=values, offsets=offsets,
                  validity=validity, num_slots=num_slots)


def decode_chunk_batched(reader: ColumnChunkReader,
                         keep_dictionary: bool = True, workers: int = 4,
                         min_batches: int = 2, target_batches: int = 6
                         ) -> Column:
    """Intra-chunk pipelined decode: page batches plan on worker threads
    while the main thread stages and (asynchronously) dispatches each
    batch's decode — so host prescan, H2D staging, and device kernels of a
    SINGLE large chunk overlap instead of running as one serial chain
    (the measured e2e floor; SURVEY.md §7 hard part 5 applied within a
    chunk, not just across chunks).

    Flat columns only (max_rep == 0, max_def <= 1 — configs 1-3 shapes);
    anything else, too few pages, or per-batch kind divergence (e.g. a
    dict→plain fallback mid-chunk) raises _Unsupported and the caller uses
    the single-plan path."""
    from concurrent.futures import ThreadPoolExecutor

    leaf = reader.leaf
    if leaf.max_repetition_level > 0 or leaf.max_definition_level > 1:
        raise _Unsupported("batched decode: flat columns only")
    pages = list(reader.pages())
    dict_pages = [p for p in pages if p.page_type == PageType.DICTIONARY_PAGE]
    data_pages = [p for p in pages
                  if p.page_type in (PageType.DATA_PAGE, PageType.DATA_PAGE_V2)]
    per = max(8, -(-len(data_pages) // target_batches))
    batches = [data_pages[i : i + per] for i in range(0, len(data_pages), per)]
    if len(batches) < min_batches:
        raise _Unsupported("batched decode: chunk too small to pipeline")
    physical = Type(reader.meta.type)
    first_hdr = data_pages[0].header if data_pages else None
    first_enc = None
    if first_hdr is not None:
        dph = first_hdr.data_page_header or first_hdr.data_page_header_v2
        if dph is not None and dph.encoding is not None:
            first_enc = Encoding(dph.encoding)
    if (first_enc == Encoding.PLAIN and _plain_run_route() == "host"
            and (physical in _FIXED_WIDTH
                 or physical == Type.FIXED_LEN_BYTE_ARRAY)):
        # the plain host route decodes as a zero-copy view of ONE contiguous
        # accumulation — per-batch splits would only re-buy the concat copy
        raise _Unsupported("batched decode: plain host route is single-pass")

    def plan_batch(i: int, subset) -> _Plan:
        return build_plan(reader,
                          pages=iter(dict_pages + subset if i == 0 else subset))

    from ..utils.pool import instrument_task, mark_pooled

    cols: List[Column] = []
    shared_dict_host = None
    shared_dict_staged = None
    kind0 = None
    # shared-pool idioms on a caller-bounded executor: instrument_task
    # propagates the caller's op scope onto the workers (fresh ctx copy per
    # run — Contexts refuse concurrent re-entry) and lands each batch's
    # queue→run wait in pool.queue_wait_s / pool.tasks; mark_pooled keeps
    # the workers' native thread splits at 1 (utils/pool contract)
    with ThreadPoolExecutor(max_workers=max(workers, 1)) as pool:
        futs = [pool.submit(instrument_task(mark_pooled(plan_batch),
                                            "device.plan_batch"), i, b)
                for i, b in enumerate(batches)]
        for i, fut in enumerate(futs):
            plan = fut.result()
            futs[i] = None  # release: bounds live plan memory to in-flight
            if i == 0:
                kind0 = plan.value_kind
                shared_dict_host = plan.dictionary_host
            else:
                if plan.value_kind != kind0:
                    raise _Unsupported("batched decode: kind diverges across "
                                       "pages (mid-chunk encoding fallback)")
                plan.dictionary_host = None  # staged once, injected below
            stage_levels = stage_levels_on_device(leaf, plan)
            staged = stage_plan(plan, stage_levels=stage_levels)
            if i == 0:
                shared_dict_staged = (staged[2] or {}).get("dictionary")
            elif shared_dict_host is not None:
                plan.dictionary_host = shared_dict_host
                staged[2]["dictionary"] = shared_dict_staged
            cols.append(decode_staged(leaf, physical, plan, staged,
                                      keep_dictionary=keep_dictionary))
    return _concat_batch_columns(leaf, cols)


def decode_chunks_pipelined(chunks, keep_dictionary: bool = True,
                            workers: int = 2):
    """Double-buffered read: stage chunk N+1 while chunk N's kernels run.

    SURVEY.md §7 hard part 5 — the host prep (decompress + prescan) and H2D
    put of later chunks overlap the (asynchronously dispatched) device decode
    of earlier ones. A bounded thread pool keeps at most ``workers`` chunks
    in flight beyond the one decoding, bounding memory to O(workers · chunk).
    Yields decoded Columns in chunk order; falls back to host decode per
    chunk on unsupported shapes.
    """
    import contextlib

    from ..io.prefetch import make_chunk_prefetcher

    chunks = list(chunks)
    # ROADMAP follow-on (PR 3): the staging phase used to pread each chunk
    # serially on its prep thread — plan every chunk's byte range through a
    # per-file chunk prefetcher (advise-backed: madvise(WILLNEED) kernel
    # readahead) so disk readahead of later chunks overlaps the prescan +
    # H2D of earlier ones.  In-memory sources get no prefetcher (nothing to
    # hide) and the route is unchanged.
    with contextlib.ExitStack() as _stack:
        _pres: dict = {}
        for _r in chunks:
            _pf = _r.file
            if id(_pf) not in _pres:
                _pre = make_chunk_prefetcher(_pf.source,
                                             n_streams=min(len(chunks), 4))
                if _pre is not None:
                    _stack.callback(_pre.close)
                    _stack.enter_context(_pf._source_override(_pre))
                _pres[id(_pf)] = _pre
            if _pres[id(_pf)] is not None:
                _pres[id(_pf)].plan(*_r.byte_range)
        yield from _decode_chunks_pipelined_impl(chunks, keep_dictionary,
                                                 workers)


def _decode_chunks_pipelined_impl(chunks, keep_dictionary: bool,
                                  workers: int):
    from concurrent.futures import ThreadPoolExecutor

    from ..utils.pool import available_cpus

    if len(chunks) == 1 and (jax.default_backend() == "tpu"
                             or available_cpus() > 1):
        # nothing to overlap ACROSS chunks: pipeline WITHIN the chunk
        # (page batches) instead — the single-large-chunk e2e shape.
        # Only where overlap can pay: on one CPU core the batch concat
        # and pool overheads are pure loss (measured 2x on dict chunks).
        try:
            col = decode_chunk_batched(chunks[0],
                                       keep_dictionary=keep_dictionary)
            counters.inc("chunks_device_decoded")
            yield col
            return
        except _Unsupported:
            pass
        except Exception:
            counters.inc("chunk_batched_fallback")
            # any decode error falls through to the single-plan path, which
            # owns error semantics (incl. host fallback)
    from ..utils.locks import make_lock

    active = {"n": 0}
    lock = make_lock("device.stage_concurrency")

    def prep(reader):
        with lock:
            active["n"] += 1
            counters.high_water("stage_concurrency_peak", active["n"])
        try:
            try:
                return reader, prepare_chunk(reader), None
            except _Unsupported as e:
                return reader, None, e
        finally:
            with lock:
                active["n"] -= 1
    from ..utils.pool import instrument_task, mark_pooled

    # shared-pool idioms on the bounded stage executor (see
    # decode_chunk_batched): op-scope propagation, queue-wait accounting,
    # and in_shared_pool() marking for every staging task
    def _submit(pool, reader):
        return pool.submit(instrument_task(mark_pooled(prep),
                                           "device.stage"), reader)

    with ThreadPoolExecutor(max_workers=max(workers, 1)) as pool:
        pending = []
        it = iter(chunks)
        for reader in it:
            pending.append(_submit(pool, reader))
            if len(pending) > workers:
                break
        i = 0
        while i < len(pending):
            reader, prepped, err = pending[i].result()
            pending[i] = None  # release the future: keeps plan/staged memory
            i += 1             # bounded to the in-flight window
            nxt = next(it, None)
            if nxt is not None:
                pending.append(_submit(pool, nxt))
            if err is not None:
                counters.inc("chunks_host_fallback")
                yield decode_chunk_host(reader)
                continue
            plan, staged = prepped
            try:
                col = decode_staged(reader.leaf, Type(reader.meta.type), plan,
                                    staged, keep_dictionary=keep_dictionary)
                counters.inc("chunks_device_decoded")
                yield col
            except _Unsupported:
                counters.inc("chunks_host_fallback")
                yield decode_chunk_host(reader)


def decode_chunk_device(reader: ColumnChunkReader, keep_dictionary: bool = True,
                        fallback: bool = True) -> Column:
    try:
        plan = build_plan(reader)
        staged = stage_plan(plan,
                            stage_levels=stage_levels_on_device(reader.leaf, plan))
        col = decode_staged(reader.leaf, Type(reader.meta.type), plan, staged,
                            keep_dictionary=keep_dictionary)
        counters.inc("chunks_device_decoded")
        return col
    except _Unsupported:
        if not fallback:
            raise
        counters.inc("chunks_host_fallback")
        return decode_chunk_host(reader)


def decode_staged(leaf, physical: Type, plan: _Plan, staged: tuple,
                  keep_dictionary: bool = True) -> Column:
    """Device decode phase: staged HBM buffers → decoded :class:`Column`."""
    from ..utils.debug import annotate

    with annotate(f"pq.decode_staged:{plan.value_kind}"):
        return _decode_staged(leaf, physical, plan, staged, keep_dictionary)


def _decode_staged(leaf, physical: Type, plan: _Plan, staged: tuple,
                   keep_dictionary: bool = True) -> Column:
    max_def = leaf.max_definition_level
    max_rep = leaf.max_repetition_level
    lev_dbuf, val_dbuf, staged_meta = (staged if len(staged) == 3
                                       else (*staged, None))
    staged_meta = staged_meta or {}
    if not isinstance(staged_meta, dict):  # pre-dict layout: the delta tuple
        staged_meta = {"delta": staged_meta}

    # ---- levels -----------------------------------------------------------
    # Flat optional columns: expand def levels on device (validity mask stays
    # in HBM).  Simple single-level lists: expand AND assemble on device
    # (SURVEY.md §7 hard part 4 — config 4's shape).  Struct chains and
    # deeper nesting: the record assembler consumes levels on host, so
    # expand them there once — no device work, no double expansion.
    def_levels = None
    def_host = rep_host = None
    device_asm = None
    fused_asm = None
    validity = None
    if max_rep > 0:
        infos = levels_ops.repeated_ancestors(leaf)
        if lev_dbuf is not None and stage_levels_on_device(leaf, plan):
            d_dev = plan.def_runs.expand(lev_dbuf,
                                         tables=staged_meta.get("def_runs"))
            r_dev = plan.rep_runs.expand(lev_dbuf,
                                         tables=staged_meta.get("rep_runs"))
            device_asm = dev.assemble_nested(d_dev, r_dev, infos, max_def)
        else:
            lev_host = plan.levels.array()
            if (len(infos) == 1 and plan.def_runs.total and plan.rep_runs.total
                    and plan.def_runs.total == plan.rep_runs.total
                    and not plan.host_def):
                # fused path: offsets/validity straight from the run tables —
                # host work stays metadata-scale (per-run, not per-slot)
                fused_asm = native.assemble_list_runs(
                    lev_host, plan.def_runs.tables_host(),
                    plan.rep_runs.tables_host(), plan.def_runs.total,
                    infos[0].def_level, max_def)
            if fused_asm is None:
                if plan.def_runs.total:
                    def_host = plan.def_runs.expand_host(lev_host)
                elif plan.host_def:
                    def_host = np.concatenate(plan.host_def).astype(np.int32)
                if plan.rep_runs.total:
                    rep_host = plan.rep_runs.expand_host(lev_host)
                else:
                    rep_host = np.zeros(
                        len(def_host) if def_host is not None else 0, np.int32)
            else:
                def_host = _LazyLevels(plan.def_runs, lev_host)
                rep_host = _LazyLevels(plan.rep_runs, lev_host)
    elif max_def > 0 and plan.total_values == plan.total_slots:
        pass  # no nulls anywhere: validity stays None, levels never expand
    else:
        if max_def > 1 and (plan.def_runs.total or plan.host_def):
            # struct layers: the table assembler needs host def levels for
            # struct-validity zips — expand once on host and derive the leaf
            # validity from it (round 1 expanded on device AND host)
            if plan.def_runs.total:
                def_host = plan.def_runs.expand_host(
                    plan.levels.array())
            else:
                def_host = np.concatenate(plan.host_def).astype(np.int32)
            validity = jax.device_put(def_host == max_def)
        elif plan.def_runs.total:
            def_levels = plan.def_runs.expand(lev_dbuf,
                                              tables=staged_meta.get("def_runs"))
        elif plan.host_def:
            def_host = np.concatenate(plan.host_def).astype(np.int32)
            def_levels = jnp.asarray(def_host)

    if max_def > 0 and def_levels is not None:
        validity = dev.validity_from_def(def_levels, max_def)

    # ---- values -----------------------------------------------------------
    dictionary = None
    dict_indices = None
    values = None
    offsets = None
    kind = plan.value_kind
    nvals = plan.total_values

    if kind == "plain_fixed":
        if staged_meta.get("plain_host"):
            # NON-TPU backend: PLAIN fixed-width decode is a pure bitcast,
            # which numpy does as a zero-copy VIEW of the host accumulation
            # buffer — no H2D staging, no XLA output materialization (two
            # whole-chunk copies saved; see _plain_run_route)
            arr = plan.values.array()
            if physical in _IS_PAIR:
                values = arr[: nvals * 8].view(np.uint32).reshape(nvals, 2)
            elif physical == Type.INT96:
                values = arr[: nvals * 12].view(np.uint32).reshape(nvals, 3)
            else:
                dt = np.int32 if physical == Type.INT32 else np.float32
                values = arr[: nvals * 4].view(dt)
        elif physical in _IS_PAIR:
            values = dev.fixed64_pairs(val_dbuf, nvals)
        elif physical == Type.INT96:
            values = jax.lax.bitcast_convert_type(
                val_dbuf[: nvals * 12].reshape(nvals, 3, 4), jnp.uint32).reshape(nvals, 3)
        else:
            dt = {Type.INT32: "int32", Type.FLOAT: "float32"}[physical]
            values = dev.bitcast_fixed32(val_dbuf, nvals, dt)
    elif kind == "plain_flba":
        if staged_meta.get("plain_host"):
            values = plan.values.array()[: nvals * leaf.type_length].reshape(
                nvals, leaf.type_length)
        else:
            values = val_dbuf[: nvals * leaf.type_length].reshape(
                nvals, leaf.type_length)
    elif kind == "bool":
        values = plan.vruns.expand(val_dbuf,
                                    tables=staged_meta.get("vruns")).astype(jnp.bool_)
    elif kind == "dict":
        dictionary = staged_meta.get("dictionary")
        if dictionary is None:
            dictionary = _stage_dictionary(plan.dictionary_host, physical, leaf)
        if staged_meta.get("dense") is not None:
            dict_indices, values = _decode_dense_dict(plan, staged_meta["dense"],
                                                      dictionary, physical)
        elif staged_meta.get("dict_host"):
            # Mixed RLE/bit-packed index runs on a NON-TPU backend: the
            # run expand + gather is gather-shaped work the host C++ does
            # ~8x faster than the XLA CPU emulation of the device kernels
            # (BASELINE config 2 was 0.12 GB/s on the emulated route).
            # The TPU keeps the device kernels; routing is per-backend,
            # overridable via PARQUET_TPU_DICT_RUNS.
            counters.inc("dict_host_route")
            vals_host = plan.values.array()
            dict_indices = None
            values = None
            if physical != Type.BYTE_ARRAY and isinstance(
                    plan.dictionary_host, np.ndarray):
                # fused one-pass expand+gather (no index stream); indices
                # stay None — every consumer gates on is_dictionary_encoded
                values = native.expand_gather(
                    vals_host, plan.vruns.tables_host(), plan.vruns.total,
                    plan.dictionary_host)
            if values is None:
                idx_host = plan.vruns.expand_host(vals_host)
                dict_indices = idx_host.astype(np.int32, copy=False)
                if physical != Type.BYTE_ARRAY:
                    gathered = ref.gather_dictionary(
                        plan.dictionary_host, idx_host)
                    values = (gathered[0] if isinstance(gathered, tuple)
                              else gathered)
            if values is not None and physical in _IS_PAIR:
                # keep the device-path representation invariant (64-bit
                # values as (n,2) uint32 pairs) — zero-copy view
                values = np.ascontiguousarray(values).view(
                    np.uint32).reshape(-1, 2)
        else:
            dict_indices = plan.vruns.expand(val_dbuf,
                                             tables=staged_meta.get("vruns"))
            if physical == Type.BYTE_ARRAY:
                values = None  # stays encoded (Arrow dictionary form)
            else:
                values = dev.dict_gather(dictionary, dict_indices)
    elif kind == "delta":
        if staged_meta.get("delta_host"):
            # NON-TPU backend: fused C++ unpack + min-add + prefix sum from
            # the prescan miniblock tables, one threaded pass — the XLA CPU
            # emulation of the dense delta kernels was BASELINE config 4's
            # bottleneck.  Handles per-page vpm (no single-vpm constraint).
            counters.inc("delta_host_route")
            lens = [len(w) for w in plan.d_mb_widths]
            page_mb_start = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=page_mb_start[1:])
            vals = native.delta_decode(
                plan.values.array(),
                np.concatenate(plan.d_mb_offs) if plan.d_mb_offs
                else np.zeros(0, np.int64),
                np.concatenate(plan.d_mb_widths) if plan.d_mb_widths
                else np.zeros(0, np.int32),
                np.concatenate(plan.d_mb_mins) if plan.d_mb_mins
                else np.zeros(0, np.int64),
                page_mb_start, plan.d_firsts, plan.d_counts, plan.d_vpms)
            if physical == Type.INT32:
                values = vals.astype(np.int32)
            else:
                values = np.ascontiguousarray(vals).view(
                    np.uint32).reshape(-1, 2)
        elif staged_meta.get("delta_dense") is not None:
            streams, perm, mins, firsts = staged_meta["delta_dense"]
            vpm, gw, gk, pcounts = plan.d_dense_static
            use_pk = tuple(_use_pallas(w) for w in gw)
            interp = jax.default_backend() != "tpu"
            try:
                values = _delta_decode_dense(streams, perm, mins, firsts,
                                             vpm, gw, gk, pcounts,
                                             physical != Type.INT32,
                                             use_pk, interp)
            except Exception as e:
                if not any(use_pk):
                    raise
                _pallas_fallback(e)
                values = _delta_decode_dense(streams, perm, mins, firsts,
                                             vpm, gw, gk, pcounts,
                                             physical != Type.INT32,
                                             (False,) * len(gw), interp)
        else:
            if len(set(plan.d_vpms)) > 1:
                raise _Unsupported("mixed delta miniblock sizes across pages")
            tables = staged_meta.get("delta")
            if tables is None:
                tables = _delta_gather_tables(plan)
            page_ends, firsts, mb_base, mb_offs, mb_widths, mb_mins = tables
            pairs = physical != Type.INT32
            n_total = int(sum(plan.d_counts))
            values = _delta_decode_multi(val_dbuf, n_total, page_ends,
                                         firsts, mb_base, mb_offs,
                                         mb_widths, mb_mins, plan.d_vpm, pairs)
    elif kind == "bss":
        w = _FIXED_WIDTH.get(physical, leaf.type_length)
        flba = physical == Type.FIXED_LEN_BYTE_ARRAY
        if not flba and w not in (4, 8):
            # e.g. INT96: BSS is undefined for it — clean host fallback
            raise _Unsupported("byte-stream-split over unsupported width")
        if staged_meta.get("bss_host"):
            # NON-TPU backend: one plane transpose per page written straight
            # into the preallocated chunk output — one copy total (measured
            # 3x the emulated static-slice kernels)
            buf = plan.values.array()
            allb = np.empty((nvals, w), np.uint8)
            pos = 0
            for base, pn in plan.bss_pages:
                planes = buf[int(base) : int(base) + pn * w].reshape(w, pn)
                allb[pos : pos + pn] = planes.T
                pos += pn
            if flba:
                values = allb
            elif physical in _IS_PAIR:
                values = allb.view(np.uint32).reshape(nvals, 2)
            else:
                dt = np.int32 if physical == Type.INT32 else np.float32
                values = allb.view(dt).reshape(-1)
        else:
            if len(plan.bss_pages) > 512:
                # static per-page slicing unrolls O(pages) into the graph
                raise _Unsupported(
                    "byte-stream-split chunk with huge page count")
            if len(plan.bss_pages) == 1 and int(plan.bss_pages[0][0]) == 0:
                # single-page chunk (the common writer layout): the
                # canonical ops/device.py plane-transpose kernel — same
                # math as the multi-page twin without its per-page
                # static-slice unrolling
                values = dev.byte_stream_split(
                    val_dbuf, nvals, w,
                    out_dtype=None if flba else
                    ("int32" if physical == Type.INT32 else "float32")
                    if w == 4 else "uint32")
            else:
                values = _bss_decode_multi(
                    val_dbuf, nvals,
                    tuple((int(b), int(n)) for b, n in plan.bss_pages),
                    w, flba,
                    # 4-byte output dtype follows the PHYSICAL type (an
                    # INT32 BSS column is not a float32 — bug caught by
                    # the route-equality test)
                    dtype4="int32" if physical == Type.INT32 else "float32")
    elif kind == "dba":
        staged_dba = staged_meta.get("dba")
        if staged_dba is None:
            tabs_host, eoffs_host, iters = _dba_tables(plan)
            tabs = jax.device_put(tabs_host)
        else:
            tabs, eoffs_host, iters = staged_dba
        plens_d, soffs_d, eoffs_d = tabs
        out = dev.delta_byte_array_expand(val_dbuf, plens_d, soffs_d,
                                          eoffs_d, int(eoffs_host[-1]),
                                          iters)
        if physical == Type.FIXED_LEN_BYTE_ARRAY:
            values = out.reshape(-1, leaf.type_length)
        else:
            # same Column form as host_ba: device value bytes, host int32
            # offsets — every byte-array consumer already speaks it
            values = out
            offsets = eoffs_host
    elif kind == "host_ba":
        if plan.host_parts and isinstance(plan.host_parts[0], tuple):
            vals = np.concatenate([p[0] for p in plan.host_parts])
            offs_parts, base = [], 0
            for p in plan.host_parts:
                o = p[1].astype(np.int64)
                offs_parts.append(o[:-1] + base)
                base += int(o[-1])
            offsets = np.concatenate(offs_parts + [np.array([base])]).astype(np.int32)
            values = jax.device_put(vals)
            counters.inc("bytes_h2d", vals.nbytes)
        else:
            values = jax.device_put(np.concatenate(plan.host_parts))
    elif kind is None:
        values = jnp.zeros(0, jnp.int32)

    # ---- assembly ---------------------------------------------------------
    list_offsets: List[np.ndarray] = []
    list_validity: List[Optional[np.ndarray]] = []
    leaf_validity = validity
    if device_asm is not None:
        list_offsets, list_validity, leaf_validity = device_asm
    elif fused_asm is not None:
        lofs, lval, leaf_validity = fused_asm
        list_offsets, list_validity = [lofs], [lval]
    elif max_rep > 0 and def_host is not None:
        asm = levels_ops.assemble(def_host, rep_host, leaf)
        list_offsets, list_validity = asm.list_offsets, asm.list_validity
        leaf_validity = asm.validity
    col = Column(leaf=leaf, values=values, offsets=offsets,
                 validity=leaf_validity, list_offsets=list_offsets,
                 list_validity=list_validity, num_slots=plan.total_slots,
                 def_levels=def_host, rep_levels=rep_host)
    col.dictionary = dictionary
    col.dictionary_host = plan.dictionary_host
    col.dict_indices = dict_indices
    return col


def _decode_dense_dict(plan: _Plan, dense_buf: jax.Array, dictionary,
                       physical: Type):
    """Gather-free dict-index decode from the compacted dense stream
    (VERDICT r1 item 3 — the Pallas wiring, with the jnp twin as the
    portable default). Returns (indices, values-or-None)."""
    from ..ops import pallas_kernels as pk

    w = plan.dense_w
    # round UP to whole 32-value groups: the final page's tail group may be
    # partial byte-wise; the unpack kernels zero-pad missing words
    total = -(-(len(plan.dense) * 8 // w) // 32) * 32
    use_pk = _use_pallas(w)
    interpret = jax.default_backend() != "tpu"
    pages = tuple((int(s), int(n)) for s, n in plan.dense_pages)
    fused = (use_pk and _dense_mode() == "pallas"
             and physical != Type.BYTE_ARRAY
             and not isinstance(dictionary, tuple)
             and getattr(dictionary, "ndim", 0) == 1
             and dictionary.shape[0] <= 1024)
    if fused:
        # one VMEM pass: unpack + gather (small dictionaries only — the
        # one-hot matmul is O(n·D)); indices are not materialized
        nwords = (len(plan.dense) + 3) // 4
        words = jax.lax.bitcast_convert_type(
            dense_buf[: nwords * 4].reshape(nwords, 4), jnp.uint32)
        try:
            allvals = pk.dict_unpack_gather(words, dictionary, total, w,
                                            interpret=interpret)
        except Exception as e:
            _pallas_fallback(e)  # degrade to unfused unpack + gather below
            use_pk = False
            allvals = None
        if allvals is not None:
            parts = [allvals[s: s + n] for s, n in plan.dense_pages]
            values = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            return None, values
    try:
        indices = _dense_unpack_pages(dense_buf, len(plan.dense), total, w,
                                      pages, use_pk, interpret)
    except Exception as e:
        if not use_pk:
            raise
        _pallas_fallback(e)
        indices = _dense_unpack_pages(dense_buf, len(plan.dense), total, w,
                                      pages, False, interpret)
    if physical == Type.BYTE_ARRAY:
        return indices, None
    return indices, dev.dict_gather(dictionary, indices)


@partial(jax.jit, static_argnames=("nbytes", "total", "w", "pages", "pallas",
                                   "interpret"))
def _dense_unpack_pages(dense_buf, nbytes: int, total: int, w: int,
                        pages: tuple, pallas: bool, interpret: bool):
    """One dispatch for the dense dict-index decode: word view + unpack +
    per-page compaction (static slices) + dtype cast, all fused."""
    from ..ops import pallas_kernels as pk

    # round word count UP: the stream's byte length need not be 4-aligned and
    # pad_to_bucket(extra=4) guarantees ≥4 zero bytes of slack past the end
    nwords = (nbytes + 3) // 4
    words = jax.lax.bitcast_convert_type(
        dense_buf[: nwords * 4].reshape(nwords, 4), jnp.uint32)
    if pallas:
        allidx = pk.unpack_bits_dense(words, total, w, interpret=interpret)
    else:
        allidx = pk.unpack_bits_dense_jnp(words, total, w)
    parts = [allidx[s: s + n] for s, n in pages]
    return (parts[0] if len(parts) == 1
            else jnp.concatenate(parts)).astype(jnp.int32)


def _stage_dictionary(dict_host, physical, leaf, put=None):
    if put is None:
        put = jax.device_put
    if dict_host is None:
        raise _Unsupported("dictionary-encoded page without dictionary page")
    if physical == Type.BYTE_ARRAY:
        vals, offs = dict_host
        return (put(vals), put(offs.astype(np.int32)))
    if physical in _IS_PAIR:
        arr = np.ascontiguousarray(dict_host)
        return put(arr.view(np.uint32).reshape(-1, 2))
    return put(np.asarray(dict_host))
