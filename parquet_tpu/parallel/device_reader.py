"""Device decode pipeline: chunk bytes → HBM → decoded jax.Arrays.

Reference parity: this is the ``PARQUET_GO_DEVICE=tpu`` path of the north star
(BASELINE.json): the per-page decode loop of ``filePages.ReadPage`` rerouted so
that raw page payloads are staged to the device in batched transfers per chunk
and decoded by the kernels in ``ops/device.py``.  Host does only
metadata-scale work (page headers, LZ decompression, run/miniblock pre-scans);
the device does all data-scale work (bit-unpack, RLE expansion, delta cumsum,
gathers) — SURVEY.md §7 steps 4-6.

Whole-chunk single-kernel decode: every encoding family merges ALL of a
chunk's pages into ONE device call —
- PLAIN fixed-width pages are contiguous in the value stage → one bitcast;
- dictionary/bool pages become one run table (per-run widths handle per-page
  bit widths) → one :func:`rle_expand`;
- DELTA pages merge miniblock tables and use a segmented cumsum (global
  cumsum minus per-page base) → one call;
- BYTE_STREAM_SPLIT pages use a page-aware gather → one call.

Column representation stays TPU-friendly: 32-bit types native, 64-bit types as
(n,2) uint32 pairs, BYTE_ARRAY dictionary chunks stay *encoded* (device
dictionary + int32 indexes — the Arrow DictionaryArray analog).

Anything exotic (mixed dict/plain fallback chunks, byte-array deltas) falls
back to the host oracle for the whole chunk — correctness first, the hot
paths stay on device.
"""

from __future__ import annotations

import struct as _struct
from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..format import metadata as md
from ..format.enums import Encoding, PageType, Type
from ..io.column import Column
from ..io.reader import ColumnChunkReader, CorruptedError, decode_chunk_host, _bit_width
from ..ops import device as dev, levels as levels_ops, ref
from ..utils.debug import counters
from .. import native

_FIXED_WIDTH = {Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4, Type.DOUBLE: 8,
                Type.INT96: 12}
_IS_PAIR = {Type.INT64, Type.DOUBLE}


class _Unsupported(Exception):
    """Internal: chunk shape the device path doesn't cover → host fallback."""


@dataclass
class _RunTable:
    """Chunk-level merged RLE/bit-packed run table (host-scanned)."""

    ends: List[np.ndarray] = field(default_factory=list)
    kinds: List[np.ndarray] = field(default_factory=list)
    payloads: List[np.ndarray] = field(default_factory=list)
    bit_offsets: List[np.ndarray] = field(default_factory=list)
    widths: List[np.ndarray] = field(default_factory=list)
    total: int = 0

    def add_scanned(self, kinds, cnts, payloads, offs, width, base_byte, n):
        self.kinds.append(kinds)
        self.payloads.append(payloads)
        self.bit_offsets.append((offs + base_byte) * 8)
        self.widths.append(np.full(len(kinds), width, dtype=np.int32))
        self.ends.append(self.total + np.cumsum(cnts))
        self.total += n

    def add(self, data: np.ndarray, n: int, width: int, base_byte: int) -> tuple:
        kinds, cnts, payloads, offs, end = ref.scan_rle_runs(data, n, width, 0)
        self.add_scanned(kinds, cnts, payloads, offs, width, base_byte, n)
        return kinds, cnts, payloads, offs

    def add_bitpacked_span(self, n: int, width: int, base_byte: int):
        """A raw bit-packed span (e.g. PLAIN BOOLEAN page) as a single run."""
        self.kinds.append(np.ones(1, np.uint8))
        self.payloads.append(np.zeros(1, np.int64))
        self.bit_offsets.append(np.array([base_byte * 8], np.int64))
        self.widths.append(np.full(1, width, np.int32))
        self.ends.append(np.array([self.total + n], np.int64))
        self.total += n

    def run_arrays(self) -> tuple:
        """(ends, kinds, payloads, bit_offsets, widths) as flat host arrays —
        the rle_expand kernel operands, stageable to HBM ahead of decode."""
        return (np.concatenate(self.ends).astype(np.int64),
                np.concatenate(self.kinds),
                np.concatenate(self.payloads).astype(np.int32),
                np.concatenate(self.bit_offsets).astype(np.int64),
                np.concatenate(self.widths))

    def expand(self, dbuf: jax.Array, n: Optional[int] = None,
               tables: Optional[tuple] = None) -> jax.Array:
        return dev.rle_expand(dbuf, n or self.total,
                              *(tables if tables is not None
                                else self.run_arrays()))

    def expand_host(self, buf: np.ndarray, n: Optional[int] = None) -> np.ndarray:
        """Numpy twin of :meth:`expand` over the host copy of the byte stream.

        Used for nested columns, whose level streams are consumed by the host
        record assembler — expanding there avoids a D2H sync of data that is
        metadata-sized to begin with."""
        n = n or self.total
        ends = np.concatenate(self.ends).astype(np.int64)
        kinds = np.concatenate(self.kinds)
        payloads = np.concatenate(self.payloads).astype(np.int64)
        offs = np.concatenate(self.bit_offsets).astype(np.int64)
        widths = np.concatenate(self.widths).astype(np.int64)
        out = native.expand_runs(buf, ends, kinds, payloads, offs,
                                 widths.astype(np.int32), n)
        if out is not None:
            return out
        if len(widths) and widths.max() > 24:
            # rare wide levels: per-run loop (a 4-byte gather window below
            # only covers widths <= 25 at arbitrary bit phase)
            out = np.empty(n, np.int32)
            pos = 0
            for i in range(len(kinds)):
                cnt = min(int(ends[i]) - pos, n - pos)
                if cnt <= 0:
                    continue
                if kinds[i] == 0:
                    out[pos : pos + cnt] = payloads[i]
                else:
                    bit0 = int(offs[i])
                    out[pos : pos + cnt] = ref.unpack_bits(
                        buf[bit0 // 8 :], cnt, int(widths[i]), bit0 % 8)
                pos += cnt
            return out[:pos]
        starts = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
        counts = np.maximum(np.minimum(ends, n) - starts, 0)
        rid = np.repeat(np.arange(len(kinds)), counts)
        pos = np.arange(int(counts.sum()), dtype=np.int64)
        within = pos - np.repeat(starts, counts)
        packed = kinds[rid] != 0
        # RLE runs take their payload directly; gather position only matters
        # for bit-packed runs (and would otherwise index past the stream)
        bitpos = np.where(packed, offs[rid] + within * widths[rid], 0)
        vals = _gather_bits(buf, bitpos, widths[rid])
        return np.where(packed, vals, payloads[rid]).astype(np.int32)


def _gather_bits(body: np.ndarray, bitpos: np.ndarray, widths) -> np.ndarray:
    """Unpack one value per entry of ``bitpos`` (bit offsets into ``body``)
    via a 4-byte little-endian gather window.  Valid for widths <= 24."""
    pbuf = np.concatenate([np.asarray(body, np.uint8), np.zeros(8, np.uint8)])
    b0 = bitpos >> 3
    w32 = (pbuf[b0].astype(np.uint32)
           | (pbuf[b0 + 1].astype(np.uint32) << 8)
           | (pbuf[b0 + 2].astype(np.uint32) << 16)
           | (pbuf[b0 + 3].astype(np.uint32) << 24))
    mask = (np.uint32(1) << np.asarray(widths).astype(np.uint32)) - np.uint32(1)
    return (w32 >> (bitpos & 7).astype(np.uint32)) & mask


def _count_target_in_runs(kinds, cnts, payloads, offs, body, width, target) -> int:
    """How many level values equal ``target`` (host, vectorized)."""
    kinds = np.asarray(kinds)
    cnts = np.asarray(cnts, np.int64)
    payloads = np.asarray(payloads, np.int64)
    offs = np.asarray(offs, np.int64)
    total = int(cnts[(kinds == 0) & (payloads == target)].sum())
    packed = np.flatnonzero(kinds != 0)
    if not len(packed):
        return total
    if width > 24:
        for k in packed:
            vals = ref.unpack_bits(body[offs[k]:], int(cnts[k]), width)
            total += int(np.count_nonzero(vals == target))
        return total
    pcnts = cnts[packed]
    rid = np.repeat(packed, pcnts)
    starts = np.zeros(len(packed), np.int64)
    np.cumsum(pcnts[:-1], out=starts[1:])
    within = np.arange(int(pcnts.sum()), dtype=np.int64) - np.repeat(starts, pcnts)
    vals = _gather_bits(body, offs[rid] * 8 + within * width, width)
    return total + int(np.count_nonzero(vals == target))


@dataclass
class _Plan:
    """Host-built staging plan for one chunk."""

    levels: bytearray = field(default_factory=bytearray)
    values: bytearray = field(default_factory=bytearray)
    def_runs: _RunTable = field(default_factory=_RunTable)
    rep_runs: _RunTable = field(default_factory=_RunTable)
    host_def: List[np.ndarray] = field(default_factory=list)
    value_kind: Optional[str] = None  # 'plain_fixed'|'plain_flba'|'bool'|'dict'|'delta'|'bss'|'host_ba'
    # plain
    plain_total: int = 0
    # dict / bool runs
    vruns: _RunTable = field(default_factory=_RunTable)
    # dense single-width dict-index stream (Pallas/jnp gather-free route):
    # bit-packed run payloads compacted into one LSB-first w-bit stream,
    # page-aligned to 32-value groups; (start_value, n_values) per page
    dense: bytearray = field(default_factory=bytearray)
    dense_w: Optional[int] = None
    dense_pages: List[Tuple[int, int]] = field(default_factory=list)
    dense_ok: bool = True
    # delta
    d_firsts: List[int] = field(default_factory=list)
    d_counts: List[int] = field(default_factory=list)
    d_mb_offs: List[np.ndarray] = field(default_factory=list)
    d_mb_widths: List[np.ndarray] = field(default_factory=list)
    d_mb_mins: List[np.ndarray] = field(default_factory=list)
    d_vpm: int = 32
    # bss
    bss_pages: List[Tuple[int, int]] = field(default_factory=list)  # (base, n)
    # host byte arrays
    host_parts: List = field(default_factory=list)
    total_slots: int = 0
    total_values: int = 0
    dictionary_host = None

    def set_kind(self, kind: str):
        if self.value_kind is None:
            self.value_kind = kind
        elif self.value_kind != kind:
            raise _Unsupported(f"mixed page encodings {self.value_kind}/{kind}")


def build_plan(reader: ColumnChunkReader, pages=None) -> _Plan:
    """Host prescan of a chunk's pages into a staging plan.

    ``pages`` (an iterator of PageInfo, e.g. from io/search.seek_pages)
    restricts the plan to a page subset — the pushdown scan path; the
    dictionary page must be included when the chunk is dict-encoded."""
    leaf = reader.leaf
    codec = reader.codec
    physical = Type(reader.meta.type)
    max_def = leaf.max_definition_level
    max_rep = leaf.max_repetition_level
    plan = _Plan()

    for page in (reader.pages() if pages is None else pages):
        h = page.header
        pt = page.page_type
        if pt == PageType.DICTIONARY_PAGE:
            raw = codec.decode(page.payload, h.uncompressed_page_size)
            plan.dictionary_host = ref.decode_plain(
                np.frombuffer(raw, np.uint8), h.dictionary_page_header.num_values,
                physical, leaf.type_length)
            continue
        if pt == PageType.DATA_PAGE:
            dph = h.data_page_header
            n = dph.num_values
            raw = np.frombuffer(codec.decode(page.payload, h.uncompressed_page_size), np.uint8)
            pos = 0
            n_present = n
            if max_rep > 0:
                (length,) = _struct.unpack_from("<I", raw, pos)
                body = raw[pos + 4 : pos + 4 + length]
                plan.rep_runs.add(body, n, _bit_width(max_rep), len(plan.levels))
                plan.levels.extend(body.tobytes())
                pos += 4 + length
            if max_def > 0:
                enc = Encoding(dph.definition_level_encoding)
                w = _bit_width(max_def)
                if enc == Encoding.RLE:
                    (length,) = _struct.unpack_from("<I", raw, pos)
                    body = raw[pos + 4 : pos + 4 + length]
                    scanned = plan.def_runs.add(body, n, w, len(plan.levels))
                    plan.levels.extend(body.tobytes())
                    pos += 4 + length
                    n_present = _count_target_in_runs(*scanned, body, w, max_def)
                else:  # legacy BIT_PACKED levels: host decode
                    nbytes = (n * w + 7) // 8
                    lv = ref.decode_bit_packed_levels(raw[pos:], n, w)
                    plan.host_def.append(lv)
                    pos += nbytes
                    n_present = int(np.count_nonzero(lv == max_def))
            _stage_values(plan, raw, pos, n_present, Encoding(dph.encoding),
                          physical, leaf)
            plan.total_slots += n
            plan.total_values += n_present
        elif pt == PageType.DATA_PAGE_V2:
            dph2 = h.data_page_header_v2
            n = dph2.num_values
            rl = dph2.repetition_levels_byte_length or 0
            dl = dph2.definition_levels_byte_length or 0
            if max_rep > 0:
                body = np.frombuffer(page.payload[:rl], np.uint8)
                plan.rep_runs.add(body, n, _bit_width(max_rep), len(plan.levels))
                plan.levels.extend(page.payload[:rl])
            if max_def > 0:
                body = np.frombuffer(page.payload[rl : rl + dl], np.uint8)
                plan.def_runs.add(body, n, _bit_width(max_def), len(plan.levels))
                plan.levels.extend(page.payload[rl : rl + dl])
            raw_body = page.payload[rl + dl :]
            if dph2.is_compressed is not False:
                raw_body = codec.decode(raw_body, h.uncompressed_page_size - rl - dl)
            raw = np.frombuffer(raw_body, np.uint8)
            n_present = n - (dph2.num_nulls or 0)
            _stage_values(plan, raw, 0, n_present, Encoding(dph2.encoding),
                          physical, leaf)
            plan.total_slots += n
            plan.total_values += n_present
    return plan


def _dense_mode() -> str:
    """Routing for single-width dict-index streams: 'jnp' (default —
    gather-free static-select unpack, XLA-fused), 'pallas' (the VMEM-tiled
    kernel from ops/pallas_kernels.py), or 'off' (round-1 per-value gather
    path). PARQUET_TPU_PALLAS=1 → pallas, =off → off."""
    import os

    v = os.environ.get("PARQUET_TPU_PALLAS", "")
    if v == "1":
        return "pallas"
    if v.lower() == "off":
        return "off"
    return "jnp"


def _add_dense_page(plan: _Plan, body: np.ndarray, kinds, cnts, offs,
                    width: int, nvals: int) -> None:
    """Compact one dict page's index stream into the chunk's dense w-bit
    stream when every run is bit-packed (high-cardinality data — the hot
    case). Bit-packed runs encode whole 8-value groups (8·w bits, byte
    aligned), so stripping the varint headers and concatenating payloads
    yields a contiguous LSB-first stream; pages pad to 32-value boundaries
    (4·w bytes) so unpack groups never straddle pages."""
    if not plan.dense_ok or not len(kinds) or not np.all(np.asarray(kinds) == 1):
        plan.dense_ok = False
        return
    if plan.dense_w is None:
        plan.dense_w = width
    elif plan.dense_w != width:
        plan.dense_ok = False
        return
    group_bytes = 4 * width  # 32 values
    pad = -len(plan.dense) % group_bytes
    plan.dense.extend(b"\0" * pad)
    start_val = len(plan.dense) * 8 // width
    bview = body.tobytes()
    for cnt, off in zip(np.asarray(cnts, np.int64), np.asarray(offs, np.int64)):
        ngroups = (int(cnt) + 7) // 8
        plan.dense.extend(bview[int(off): int(off) + ngroups * width])
    plan.dense_pages.append((start_val, nvals))


def _stage_values(plan: _Plan, raw: np.ndarray, pos: int, nvals: int,
                  encoding: Encoding, physical: Type, leaf) -> None:
    if encoding in (Encoding.PLAIN_DICTIONARY, Encoding.RLE_DICTIONARY):
        plan.set_kind("dict")
        width = int(raw[pos]) if pos < len(raw) else 0
        body = raw[pos + 1 :]
        base = len(plan.values)
        plan.values.extend(body.tobytes())
        if width == 0:  # single-entry dictionary
            plan.vruns.add_scanned(np.zeros(1, np.uint8), np.array([nvals]),
                                   np.zeros(1, np.int64), np.zeros(1, np.int64),
                                   1, base, nvals)
            plan.dense_ok = False
        else:
            kinds, cnts, _, offs = plan.vruns.add(body, nvals, width, base)
            _add_dense_page(plan, body, kinds, cnts, offs, width, nvals)
        return
    if encoding == Encoding.PLAIN:
        if physical == Type.BOOLEAN:
            plan.set_kind("bool")
            base = len(plan.values)
            plan.values.extend(raw[pos:].tobytes())
            plan.vruns.add_bitpacked_span(nvals, 1, base)
            return
        if physical in _FIXED_WIDTH:
            plan.set_kind("plain_fixed")
            w = _FIXED_WIDTH[physical]
            plan.values.extend(raw[pos : pos + nvals * w].tobytes())
            plan.plain_total += nvals
            return
        if physical == Type.FIXED_LEN_BYTE_ARRAY:
            plan.set_kind("plain_flba")
            w = leaf.type_length
            plan.values.extend(raw[pos : pos + nvals * w].tobytes())
            plan.plain_total += nvals
            return
        plan.set_kind("host_ba")  # PLAIN BYTE_ARRAY: host offsets scan
        plan.host_parts.append(ref.decode_plain(raw[pos:], nvals, physical,
                                                leaf.type_length))
        return
    if encoding == Encoding.DELTA_BINARY_PACKED:
        plan.set_kind("delta")
        base = len(plan.values)
        plan.values.extend(raw[pos:].tobytes())
        first, total, vpm, offs, widths, mins, _ = dev.delta_prescan(raw, pos)
        plan.d_firsts.append(first)
        plan.d_counts.append(total)
        plan.d_mb_offs.append(offs + (base - pos) * 8)
        plan.d_mb_widths.append(widths)
        plan.d_mb_mins.append(mins)
        plan.d_vpm = vpm
        return
    if encoding == Encoding.BYTE_STREAM_SPLIT:
        plan.set_kind("bss")
        w = _FIXED_WIDTH.get(physical, leaf.type_length)
        base = len(plan.values)
        plan.values.extend(raw[pos : pos + nvals * w].tobytes())
        plan.bss_pages.append((base, nvals))
        return
    if encoding == Encoding.RLE and physical == Type.BOOLEAN:
        plan.set_kind("bool")
        (length,) = _struct.unpack_from("<I", raw, pos)
        body = raw[pos + 4 : pos + 4 + length]
        base = len(plan.values)
        plan.values.extend(body.tobytes())
        plan.vruns.add(body, nvals, 1, base)
        return
    if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
        plan.set_kind("host_ba")
        v, o, _ = ref.decode_delta_length_byte_array(raw, pos)
        plan.host_parts.append((v, o))
        return
    if encoding == Encoding.DELTA_BYTE_ARRAY:
        plan.set_kind("host_ba")
        v, o, _ = ref.decode_delta_byte_array(raw, pos)
        if physical == Type.FIXED_LEN_BYTE_ARRAY:
            plan.host_parts.append(v.reshape(-1, leaf.type_length))
        else:
            plan.host_parts.append((v, o))
        return
    raise _Unsupported(f"encoding {encoding!r}")


# ---------------------------------------------------------------------------
# Merged multi-page delta decode (segmented cumsum)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n", "vpm", "pairs"))
def _delta_decode_multi(buf, n, page_ends, firsts, mb_base, mb_offs, mb_widths,
                        mb_mins, vpm, pairs: bool):
    """All delta pages of a chunk in one call.

    seq[i] = first value of its page if i is a page start, else the unpacked
    delta.  out = cumsum(seq) - cumsum_base_of_page (segmented prefix sum).
    """
    idx = jnp.arange(n, dtype=jnp.int64)
    page = jnp.searchsorted(page_ends, idx, side="right")
    page = jnp.minimum(page, page_ends.shape[0] - 1)
    pcounts = jnp.diff(page_ends, prepend=jnp.int64(0))
    pstart = page_ends[page] - pcounts[page]
    within = idx - pstart
    j = within - 1  # delta ordinal within page (-1 for page-start slots)
    jc = jnp.maximum(j, 0)
    mb = mb_base[page] + jc // vpm
    woff = (jc % vpm).astype(jnp.int64)
    w = mb_widths[mb]
    bit_pos = mb_offs[mb] + woff * w.astype(jnp.int64)
    if pairs:
        lo, hi = dev.unpack_bits_at64(buf, bit_pos, w)
        raw = lo.astype(jnp.int64) | (hi.astype(jnp.int64) << 32)
    else:
        raw = dev.unpack_bits_at32(buf, bit_pos, w).astype(jnp.int64)
    delta = raw + mb_mins[mb]
    seq = jnp.where(within == 0, firsts[page], delta)
    gcum = jnp.cumsum(seq)
    base = gcum[pstart] - seq[pstart]  # exclusive cumsum at page start
    out = gcum - base
    if pairs:
        return dev._i64_to_pairs(out)
    return out.astype(jnp.int32)


@partial(jax.jit, static_argnames=("n", "width", "pairs"))
def _bss_decode_multi(buf, n, page_ends, page_bases, width, pairs: bool):
    """Page-aware BYTE_STREAM_SPLIT gather: byte k of value i lives at
    page_base + k*page_count + within_page."""
    idx = jnp.arange(n, dtype=jnp.int64)
    page = jnp.searchsorted(page_ends, idx, side="right")
    page = jnp.minimum(page, page_ends.shape[0] - 1)
    pcounts = jnp.diff(page_ends, prepend=jnp.int64(0))
    pstart = page_ends[page] - pcounts[page]
    within = idx - pstart
    cols = []
    for k in range(width):
        cols.append(buf[page_bases[page] + k * pcounts[page] + within])
    bytes_ = jnp.stack(cols, axis=1)  # (n, width)
    if width == 4:
        dt = jnp.float32 if not pairs else jnp.uint32
        return jax.lax.bitcast_convert_type(bytes_, jnp.uint32).reshape(n) if pairs else \
            jax.lax.bitcast_convert_type(bytes_, dt).reshape(n)
    return jax.lax.bitcast_convert_type(bytes_.reshape(n, 2, 4), jnp.uint32).reshape(n, 2)


# ---------------------------------------------------------------------------
# Chunk decode driver
# ---------------------------------------------------------------------------


def stage_plan(plan: _Plan, stage_levels: bool = True) -> tuple:
    """H2D: put the plan's concatenated level/value byte streams into HBM.

    Split out of :func:`decode_chunk_device` so callers (and the benchmark)
    can overlap staging with decode, or re-run the decode phase on buffers
    already resident in HBM.  ``stage_levels=False`` skips the level stream
    (nested columns assemble levels on host).
    """
    lev_dbuf = None
    if stage_levels and len(plan.levels):
        lev_dbuf = jax.device_put(dev.pad_to_bucket(
            np.frombuffer(bytes(plan.levels), np.uint8)))
        counters.inc("bytes_h2d", len(plan.levels))
    dense_route = (plan.value_kind == "dict" and plan.dense_ok
                   and plan.dense_pages and _dense_mode() != "off")
    val_dbuf = None
    if len(plan.values) and not dense_route:
        val_dbuf = jax.device_put(dev.pad_to_bucket(
            np.frombuffer(bytes(plan.values), np.uint8)))
        counters.inc("bytes_h2d", len(plan.values))
    meta = {}
    if dense_route:
        # compacted single-width index stream replaces the raw bodies
        meta["dense"] = jax.device_put(dev.pad_to_bucket(
            np.frombuffer(bytes(plan.dense), np.uint8), extra=4))
        counters.inc("bytes_h2d", len(plan.dense))
    if plan.value_kind == "delta":
        page_ends = np.cumsum(plan.d_counts).astype(np.int64)
        mb_base = np.zeros(len(plan.d_counts), np.int64)
        np.cumsum([len(w) for w in plan.d_mb_widths[:-1]], out=mb_base[1:])
        mb_offs = (np.concatenate(plan.d_mb_offs) if plan.d_mb_offs
                   else np.zeros(1, np.int64)).astype(np.int64)
        mb_widths = (np.concatenate(plan.d_mb_widths) if plan.d_mb_widths
                     else np.ones(1, np.int32))
        mb_mins = (np.concatenate(plan.d_mb_mins) if plan.d_mb_mins
                   else np.zeros(1, np.int64))
        firsts = np.asarray(plan.d_firsts, np.int64)
        meta["delta"] = jax.device_put((page_ends, firsts, mb_base, mb_offs,
                                        mb_widths, mb_mins))
    if plan.vruns.total:
        meta["vruns"] = jax.device_put(plan.vruns.run_arrays())
    if stage_levels and plan.def_runs.total:
        meta["def_runs"] = jax.device_put(plan.def_runs.run_arrays())
    if stage_levels and plan.rep_runs.total:
        meta["rep_runs"] = jax.device_put(plan.rep_runs.run_arrays())
    return lev_dbuf, val_dbuf, meta


def stage_levels_on_device(leaf, plan: _Plan) -> bool:
    """Whether the level streams should go to HBM: flat single-def columns
    (validity from device RLE expansion) and *top-level* single-level lists
    (device assembly). Struct chains (flat, max_def > 1) and lists under
    structs expand levels on host instead — the table assembler needs host
    def levels for struct nullness — so staging their level bytes would be
    wasted H2D."""
    if leaf.max_repetition_level == 0:
        return leaf.max_definition_level <= 1
    from ..format.enums import FieldRepetitionType as _Rep

    anc = leaf.ancestors  # (list group, repeated node, leaf) for a top list
    return (leaf.max_repetition_level == 1 and len(anc) == 3
            and anc[1].repetition == _Rep.REPEATED
            and bool(plan.def_runs.total) and bool(plan.rep_runs.total)
            and not plan.host_def)


def prepare_chunk(reader: ColumnChunkReader, device=None):
    """Host phase of one chunk's device decode: prescan (pread + decompress +
    run scan) and H2D staging. Safe to call from worker threads — the host
    work releases the GIL in numpy/C++/codec calls, and ``device`` targets
    the put at a specific mesh device."""
    import contextlib

    plan = build_plan(reader)
    ctx = (jax.default_device(device) if device is not None
           else contextlib.nullcontext())
    with ctx:
        staged = stage_plan(plan,
                            stage_levels=stage_levels_on_device(reader.leaf, plan))
    return plan, staged


def decode_chunks_pipelined(chunks, keep_dictionary: bool = True,
                            workers: int = 2):
    """Double-buffered read: stage chunk N+1 while chunk N's kernels run.

    SURVEY.md §7 hard part 5 — the host prep (decompress + prescan) and H2D
    put of later chunks overlap the (asynchronously dispatched) device decode
    of earlier ones. A bounded thread pool keeps at most ``workers`` chunks
    in flight beyond the one decoding, bounding memory to O(workers · chunk).
    Yields decoded Columns in chunk order; falls back to host decode per
    chunk on unsupported shapes.
    """
    import threading
    from concurrent.futures import ThreadPoolExecutor

    chunks = list(chunks)
    active = {"n": 0}
    lock = threading.Lock()

    def prep(reader):
        with lock:
            active["n"] += 1
            counters.high_water("stage_concurrency_peak", active["n"])
        try:
            try:
                return reader, prepare_chunk(reader), None
            except _Unsupported as e:
                return reader, None, e
        finally:
            with lock:
                active["n"] -= 1
    with ThreadPoolExecutor(max_workers=max(workers, 1)) as pool:
        pending = []
        it = iter(chunks)
        for reader in it:
            pending.append(pool.submit(prep, reader))
            if len(pending) > workers:
                break
        i = 0
        while i < len(pending):
            reader, prepped, err = pending[i].result()
            pending[i] = None  # release the future: keeps plan/staged memory
            i += 1             # bounded to the in-flight window
            nxt = next(it, None)
            if nxt is not None:
                pending.append(pool.submit(prep, nxt))
            if err is not None:
                counters.inc("chunks_host_fallback")
                yield decode_chunk_host(reader)
                continue
            plan, staged = prepped
            try:
                col = decode_staged(reader.leaf, Type(reader.meta.type), plan,
                                    staged, keep_dictionary=keep_dictionary)
                counters.inc("chunks_device_decoded")
                yield col
            except _Unsupported:
                counters.inc("chunks_host_fallback")
                yield decode_chunk_host(reader)


def decode_chunk_device(reader: ColumnChunkReader, keep_dictionary: bool = True,
                        fallback: bool = True) -> Column:
    try:
        plan = build_plan(reader)
        staged = stage_plan(plan,
                            stage_levels=stage_levels_on_device(reader.leaf, plan))
        col = decode_staged(reader.leaf, Type(reader.meta.type), plan, staged,
                            keep_dictionary=keep_dictionary)
        counters.inc("chunks_device_decoded")
        return col
    except _Unsupported:
        if not fallback:
            raise
        counters.inc("chunks_host_fallback")
        return decode_chunk_host(reader)


def decode_staged(leaf, physical: Type, plan: _Plan, staged: tuple,
                  keep_dictionary: bool = True) -> Column:
    """Device decode phase: staged HBM buffers → decoded :class:`Column`."""
    max_def = leaf.max_definition_level
    max_rep = leaf.max_repetition_level
    lev_dbuf, val_dbuf, staged_meta = (staged if len(staged) == 3
                                       else (*staged, None))
    staged_meta = staged_meta or {}
    if not isinstance(staged_meta, dict):  # pre-dict layout: the delta tuple
        staged_meta = {"delta": staged_meta}

    # ---- levels -----------------------------------------------------------
    # Flat optional columns: expand def levels on device (validity mask stays
    # in HBM).  Simple single-level lists: expand AND assemble on device
    # (SURVEY.md §7 hard part 4 — config 4's shape).  Struct chains and
    # deeper nesting: the record assembler consumes levels on host, so
    # expand them there once — no device work, no double expansion.
    def_levels = None
    def_host = rep_host = None
    device_asm = None
    validity = None
    if max_rep > 0:
        infos = levels_ops.repeated_ancestors(leaf)
        if lev_dbuf is not None and stage_levels_on_device(leaf, plan):
            d_dev = plan.def_runs.expand(lev_dbuf,
                                         tables=staged_meta.get("def_runs"))
            r_dev = plan.rep_runs.expand(lev_dbuf,
                                         tables=staged_meta.get("rep_runs"))
            device_asm = dev.assemble_single_list(
                d_dev, r_dev, infos[0].def_level, max_def)
        else:
            lev_host = np.frombuffer(bytes(plan.levels), np.uint8)
            if plan.def_runs.total:
                def_host = plan.def_runs.expand_host(lev_host)
            elif plan.host_def:
                def_host = np.concatenate(plan.host_def).astype(np.int32)
            if plan.rep_runs.total:
                rep_host = plan.rep_runs.expand_host(lev_host)
            else:
                rep_host = np.zeros(len(def_host) if def_host is not None else 0,
                                    np.int32)
    else:
        if max_def > 1 and (plan.def_runs.total or plan.host_def):
            # struct layers: the table assembler needs host def levels for
            # struct-validity zips — expand once on host and derive the leaf
            # validity from it (round 1 expanded on device AND host)
            if plan.def_runs.total:
                def_host = plan.def_runs.expand_host(
                    np.frombuffer(bytes(plan.levels), np.uint8))
            else:
                def_host = np.concatenate(plan.host_def).astype(np.int32)
            validity = jax.device_put(def_host == max_def)
        elif plan.def_runs.total:
            def_levels = plan.def_runs.expand(lev_dbuf,
                                              tables=staged_meta.get("def_runs"))
        elif plan.host_def:
            def_host = np.concatenate(plan.host_def).astype(np.int32)
            def_levels = jnp.asarray(def_host)

    if max_def > 0 and def_levels is not None:
        validity = dev.validity_from_def(def_levels, max_def)

    # ---- values -----------------------------------------------------------
    dictionary = None
    dict_indices = None
    values = None
    offsets = None
    kind = plan.value_kind
    nvals = plan.total_values

    if kind == "plain_fixed":
        if physical in _IS_PAIR:
            values = dev.fixed64_pairs(val_dbuf, nvals)
        elif physical == Type.INT96:
            values = jax.lax.bitcast_convert_type(
                val_dbuf[: nvals * 12].reshape(nvals, 3, 4), jnp.uint32).reshape(nvals, 3)
        else:
            dt = {Type.INT32: "int32", Type.FLOAT: "float32"}[physical]
            values = dev.bitcast_fixed32(val_dbuf, nvals, dt)
    elif kind == "plain_flba":
        values = val_dbuf[: nvals * leaf.type_length].reshape(nvals, leaf.type_length)
    elif kind == "bool":
        values = plan.vruns.expand(val_dbuf,
                                    tables=staged_meta.get("vruns")).astype(jnp.bool_)
    elif kind == "dict":
        dictionary = _stage_dictionary(plan.dictionary_host, physical, leaf)
        if staged_meta.get("dense") is not None:
            dict_indices, values = _decode_dense_dict(plan, staged_meta["dense"],
                                                      dictionary, physical)
        else:
            dict_indices = plan.vruns.expand(val_dbuf,
                                             tables=staged_meta.get("vruns"))
            if physical == Type.BYTE_ARRAY:
                values = None  # stays encoded (Arrow dictionary form)
            else:
                values = dev.dict_gather(dictionary, dict_indices)
    elif kind == "delta":
        if staged_meta.get("delta") is not None:
            page_ends, firsts, mb_base, mb_offs, mb_widths, mb_mins = \
                staged_meta["delta"]
        else:
            page_ends = np.cumsum(plan.d_counts).astype(np.int64)
            mb_base = np.zeros(len(plan.d_counts), np.int64)
            np.cumsum([len(w) for w in plan.d_mb_widths[:-1]], out=mb_base[1:])
            mb_offs = (np.concatenate(plan.d_mb_offs) if plan.d_mb_offs
                       else np.zeros(1, np.int64)).astype(np.int64)
            mb_widths = np.concatenate(plan.d_mb_widths) if plan.d_mb_widths else np.ones(1, np.int32)
            mb_mins = np.concatenate(plan.d_mb_mins) if plan.d_mb_mins else np.zeros(1, np.int64)
            firsts = np.asarray(plan.d_firsts, np.int64)
        pairs = physical != Type.INT32
        n_total = int(sum(plan.d_counts))
        values = _delta_decode_multi(val_dbuf, n_total, page_ends,
                                     firsts, mb_base, mb_offs,
                                     mb_widths, mb_mins, plan.d_vpm, pairs)
    elif kind == "bss":
        w = _FIXED_WIDTH.get(physical, leaf.type_length)
        page_ends = np.cumsum([n for _, n in plan.bss_pages]).astype(np.int64)
        page_bases = np.asarray([b for b, _ in plan.bss_pages], np.int64)
        if w in (4, 8):
            values = _bss_decode_multi(val_dbuf, nvals, page_ends, page_bases,
                                       w, physical in _IS_PAIR)
        else:
            raise _Unsupported("FLBA byte-stream-split on device")
    elif kind == "host_ba":
        if plan.host_parts and isinstance(plan.host_parts[0], tuple):
            vals = np.concatenate([p[0] for p in plan.host_parts])
            offs_parts, base = [], 0
            for p in plan.host_parts:
                o = p[1].astype(np.int64)
                offs_parts.append(o[:-1] + base)
                base += int(o[-1])
            offsets = np.concatenate(offs_parts + [np.array([base])]).astype(np.int32)
            values = jax.device_put(vals)
            counters.inc("bytes_h2d", vals.nbytes)
        else:
            values = jax.device_put(np.concatenate(plan.host_parts))
    elif kind is None:
        values = jnp.zeros(0, jnp.int32)

    # ---- assembly ---------------------------------------------------------
    list_offsets: List[np.ndarray] = []
    list_validity: List[Optional[np.ndarray]] = []
    leaf_validity = validity
    if device_asm is not None:
        lofs, lval, leaf_validity = device_asm
        list_offsets, list_validity = [lofs], [lval]
    elif max_rep > 0 and def_host is not None:
        asm = levels_ops.assemble(def_host, rep_host, leaf)
        list_offsets, list_validity = asm.list_offsets, asm.list_validity
        leaf_validity = asm.validity
    col = Column(leaf=leaf, values=values, offsets=offsets,
                 validity=leaf_validity, list_offsets=list_offsets,
                 list_validity=list_validity, num_slots=plan.total_slots,
                 def_levels=def_host, rep_levels=rep_host)
    col.dictionary = dictionary
    col.dictionary_host = plan.dictionary_host
    col.dict_indices = dict_indices
    return col


def _decode_dense_dict(plan: _Plan, dense_buf: jax.Array, dictionary,
                       physical: Type):
    """Gather-free dict-index decode from the compacted dense stream
    (VERDICT r1 item 3 — the Pallas wiring, with the jnp twin as the
    portable default). Returns (indices, values-or-None)."""
    from ..ops import pallas_kernels as pk

    w = plan.dense_w
    # round UP to whole 32-value groups: the final page's tail group may be
    # partial byte-wise; the unpack kernels zero-pad missing words
    total = -(-(len(plan.dense) * 8 // w) // 32) * 32
    # round word count UP: the stream's byte length need not be 4-aligned and
    # pad_to_bucket(extra=4) guarantees ≥4 zero bytes of slack past the end
    nwords = (len(plan.dense) + 3) // 4
    words = jax.lax.bitcast_convert_type(
        dense_buf[: nwords * 4].reshape(nwords, 4), jnp.uint32)
    mode = _dense_mode()
    interpret = jax.default_backend() != "tpu"
    fused = (mode == "pallas" and physical != Type.BYTE_ARRAY
             and not isinstance(dictionary, tuple)
             and getattr(dictionary, "ndim", 0) == 1
             and dictionary.shape[0] <= 1024)
    if fused:
        # one VMEM pass: unpack + gather (small dictionaries only — the
        # one-hot matmul is O(n·D)); indices are not materialized
        allvals = pk.dict_unpack_gather(words, dictionary, total, w,
                                        interpret=interpret)
        parts = [allvals[s: s + n] for s, n in plan.dense_pages]
        values = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        return None, values
    if mode == "pallas":
        allidx = pk.unpack_bits_dense(words, total, w, interpret=interpret)
    else:
        allidx = pk.unpack_bits_dense_jnp(words, total, w)
    parts = [allidx[s: s + n] for s, n in plan.dense_pages]
    indices = (parts[0] if len(parts) == 1
               else jnp.concatenate(parts)).astype(jnp.int32)
    if physical == Type.BYTE_ARRAY:
        return indices, None
    return indices, dev.dict_gather(dictionary, indices)


def _stage_dictionary(dict_host, physical, leaf):
    if dict_host is None:
        raise _Unsupported("dictionary-encoded page without dictionary page")
    if physical == Type.BYTE_ARRAY:
        vals, offs = dict_host
        return (jax.device_put(vals), jax.device_put(offs.astype(np.int32)))
    if physical in _IS_PAIR:
        arr = np.ascontiguousarray(dict_host)
        return jax.device_put(arr.view(np.uint32).reshape(-1, 2))
    return jax.device_put(np.asarray(dict_host))
