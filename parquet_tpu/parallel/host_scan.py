"""Threaded predicate-pushdown scan over row groups.

Reference parity: the reference has no internal parallelism — its documented
concurrency model is the *caller* fanning goroutines out over row groups /
column chunks (SURVEY.md §2.5, "caller-driven goroutine fan-out"; the read
path is immutable-after-open and goroutine-safe).  This module packages that
fan-out as a first-class API: zone-map pruning picks the covering pages
(io/search.py), a thread pool decodes the surviving (row-group, column)
chunks concurrently — the host decoders spend their time in numpy / the C++
shim / the codec libraries, all of which release the GIL — and the exact
predicate is applied to the decoded keys.
"""

from __future__ import annotations

import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..errors import CorruptedError, DeadlineError
from ..obs import scope as _oscope
from ..obs import trace as _otrace
from ..obs.metrics import counter as _ocounter
from ..obs.metrics import histogram as _ohistogram
from ..obs.metrics import pool_wait_seconds as _pool_wait_seconds

# resolved once: per-file observation must not take the registry's
# get-or-create lock (only the metric's own)
_M_SCAN_FILE_S = _ohistogram("dataset.scan_file_s")
_M_ROWS_PRUNED = _ocounter("scan.rows_pruned")
_M_ROWS_DECODED = _ocounter("scan.rows_decoded")
from ..io.faults import (FaultPolicy, ReadReport, read_context,
                         resolve_policy)
from ..io.reader import ParquetFile
from ..io.search import BA_ARRAYS, plan_scan, read_row_range

__all__ = ["scan", "scan_expr", "scan_filtered", "scan_filtered_device",
           "scan_filtered_sharded", "scan_files", "merge_scan_results",
           "expr_mask"]

from ..utils.pool import (in_shared_pool as _in_pool,
                          instrument_task as _instrument_task,
                          mark_pooled as _mark_pooled,
                          read_admission as _read_admission,
                          shared_pool as _pool)

# decoded_scan: spans between survivor-count syncs (bounds device residency
# at ~_SYNC_EVERY spans of uncompacted output while amortizing the RTT)
_SYNC_EVERY = 8


def _materialize_ba(values: np.ndarray, offs: np.ndarray,
                    sel: np.ndarray) -> List[bytes]:
    """Python bytes for the SELECTED value ordinals only (native gather of
    the survivors, then one materialization pass)."""
    if len(sel) == 0:
        return []
    from .. import native as _native

    g = _native.gather_ba(values, offs, sel)
    if g is None:  # shim unavailable: direct per-selected materialization
        return [values[offs[i]:offs[i + 1]].tobytes() for i in sel]
    gv, go = g
    return [gv[go[i]:go[i + 1]].tobytes() for i in range(len(sel))]


def scan_filtered(pf: ParquetFile, path: str, lo=None, hi=None,
                  columns: Optional[Sequence[str]] = None,
                  num_threads: Optional[int] = None,
                  use_bloom: bool = True,
                  values: Optional[Sequence] = None,
                  policy: Optional[FaultPolicy] = None,
                  report: Optional[ReadReport] = None) -> Dict[str, np.ndarray]:
    """Scan ``columns`` for rows where ``lo <= file[path] <= hi`` — or, with
    ``values``, where ``file[path] ∈ values`` (IN-list pushdown: statistics,
    zone maps and bloom filters all prune against the probe set).

    This is the single-column face of :func:`scan_expr`: the predicate
    becomes a one-leaf tree and the unified planner (io/planner.py) runs
    the pushdown cascade.  Output forms, null semantics, and the
    resilience contract are documented there; this signature is kept
    stable for existing callers."""
    from ..algebra.expr import single_pred

    return scan_expr(pf, single_pred(path, lo=lo, hi=hi, values=values),
                     columns=columns, num_threads=num_threads,
                     use_bloom=use_bloom, policy=policy, report=report)


def scan_expr(pf: ParquetFile, where, columns: Optional[Sequence[str]] = None,
              num_threads: Optional[int] = None, use_bloom: bool = True,
              policy: Optional[FaultPolicy] = None,
              report: Optional[ReadReport] = None) -> Dict[str, object]:
    """Scan ``columns`` for rows matching a predicate tree ``where``
    (:mod:`parquet_tpu.algebra.expr`): ``And``/``Or``/``Not`` over range,
    IN-list, equality, and null-ness leaves across any number of columns.

    The unified planner prunes cheapest-first — chunk statistics, then
    page-index zone maps (intersected/unioned through the tree), then
    bloom filters for equality leaves — and the scan then **late-
    materializes**: only the filter columns' candidate pages decode first;
    output columns decode only the pages covering rows that survived the
    exact predicate, so a selective scan never touches most of its output
    bytes.

    Returns ``{column: values}`` with the predicate applied.  Rows where
    any compared column is NULL fail that leaf (SQL three-valued
    semantics; ``col(x).is_null()`` selects them).  Nullable numeric
    output columns come back as ``np.ma.MaskedArray`` (mask=True at
    nulls); BYTE_ARRAY columns as lists with ``None`` entries.  Flat
    columns only (nested columns have no single row-aligned array to
    mask; read them via :func:`read_row_range` per surviving span
    instead) — the default selection takes every flat column not used in
    the predicate.

    ``policy`` (default: the file's open-time policy) applies the
    resilience layer (io/faults.py): span reads retry transient errors,
    the whole scan runs under ``deadline_s``, and with
    ``on_corrupt='skip_row_group'`` a corrupt row group's candidate spans
    drop from the result (other groups' matches still return), accounted
    in ``report``.  Failures surface as ``ReadError`` naming
    file/row-group/column.
    """
    pol, report = resolve_policy(pf, policy, report)
    # request scope (obs/scope.py): joins the caller's (or the dataset
    # layer's) op when one is active, else this scan is its own op
    with _oscope.maybe_op_scope("file.scan", file=pf._path):
        with pf._resilient_op(policy, report, "scan_expr"):
            return _scan_expr_impl(pf, where, columns, num_threads,
                                   use_bloom, pol, report)


class _SpanFailure:
    """Sentinel for one failed (span, column) read task."""

    __slots__ = ("rg_index", "error")

    def __init__(self, rg_index, error):
        self.rg_index = rg_index
        self.error = error


def _expr_mask(expr, env: Dict[str, tuple], n: int) -> np.ndarray:
    """Exact row mask of a prepared tree over one span's aligned filter
    columns (``env[path] -> (values, validity)``)."""
    from ..algebra.expr import And as _And, Const as _Const, Pred as _Pred

    if isinstance(expr, _Const):
        return np.full(n, expr.value, bool)
    if isinstance(expr, _Pred):
        return _pred_mask(expr, env[expr.path], n)
    masks = [_expr_mask(c, env, n) for c in expr.children]
    out = masks[0].copy()
    for m in masks[1:]:
        if isinstance(expr, _And):
            out &= m
        else:
            out |= m
    return out


def expr_mask(expr, env: Dict[str, tuple], n: int) -> np.ndarray:
    """Public face of :func:`_expr_mask` for the aggregation cascade
    (io/aggregate.py): the EXACT row mask of a prepared tree over
    row-aligned ``(values, validity)`` spans — byte-for-byte the same
    order-domain comparison semantics every filtered scan applies, so a
    decoded aggregate and a scan-then-aggregate can never disagree."""
    return _expr_mask(expr, env, n)


def _fused_span_mask(pf, rg_i: int, s: int, count: int,
                     fcols: Sequence[str], expr) -> np.ndarray:
    """Phase 1, fused: the span's filter pages are decoded, evaluated,
    and DISCARDED one block at a time on the union page grid (each block
    lies inside one page per filter column; a cursor's previous page —
    and its ledger bytes — release as it advances).  The full predicate
    mask comes back without a whole filter span ever being alive.
    Raises :class:`~parquet_tpu.io.fused.FusedUnsupported` when any
    filter column lacks an offset index (caller falls back)."""
    from ..io.fused import _M_SCAN_SPANS, PageCursor

    rg = pf.row_groups[rg_i]
    cursors = {c: PageCursor(rg, pf.schema.leaf(c)) for c in fcols}
    e = s + count
    mask = np.empty(count, bool)
    cuts = sorted({cc for cur in cursors.values() for cc in cur.grid(s, e)})
    bounds = [s] + cuts + [e]
    for bs, be in zip(bounds, bounds[1:]):
        env = {c: cursors[c].aligned(bs, be) for c in fcols}
        mask[bs - s:be - s] = _expr_mask(expr, env, be - bs)
    _oscope.account(_M_SCAN_SPANS)
    return mask


def _pred_mask(pred, span_val: tuple, n: int) -> np.ndarray:
    """One leaf's exact mask, in the leaf's order domain — the same
    comparison semantics the pruning cascade used (str → bytes, decimals
    by unscaled int, unsigned keys in the unsigned view; NULL never
    matches a range/IN leaf, negated or not)."""
    from ..algebra.compare import decode_order_value, is_unsigned

    keys, key_valid = span_val
    leaf = pred.leaf
    if pred.kind == "null":
        return (np.zeros(n, bool) if key_valid is None
                else ~np.asarray(key_valid, bool))
    if pred.kind == "notnull":
        return (np.ones(n, bool) if key_valid is None
                else np.asarray(key_valid, bool))
    lo, hi = pred.lo, pred.hi
    flba_rows = (not isinstance(keys, list)
                 and getattr(keys, "ndim", 1) == 2
                 and keys.dtype == np.uint8)
    if isinstance(keys, list) or flba_rows:
        # BYTE_ARRAY / FLBA keys: Python comparisons in the order domain
        # (decode_order_value handles decimal two's-complement ordering)
        if flba_rows:
            keys = [bytes(r) for r in np.asarray(keys)]
            if key_valid is not None:
                keys = [k if v else None for k, v in zip(keys, key_valid)]
        keys = [None if x is None else decode_order_value(bytes(x), leaf)
                for x in keys]
        if pred.kind == "in":
            probe_set = set(pred.values)
            base = np.fromiter((x is not None and x in probe_set
                                for x in keys), bool, count=len(keys))
        else:
            base = np.fromiter(
                ((x is not None
                  and (lo is None or x >= lo) and (hi is None or x <= hi))
                 for x in keys), bool, count=len(keys))
        if pred.negated:
            present = np.fromiter((x is not None for x in keys), bool,
                                  count=len(keys))
            return present & ~base
        return base
    if is_unsigned(leaf) and keys.dtype in (np.dtype(np.int32),
                                            np.dtype(np.int64)):
        keys = keys.view(np.uint32 if keys.dtype == np.dtype(np.int32)
                         else np.uint64)
    if pred.kind == "in":
        probes = np.array(pred.values, dtype=keys.dtype)
        base = np.isin(keys, probes)
    else:
        base = np.ones(len(keys), bool)
        if lo is not None:
            base &= keys >= lo
        if hi is not None:
            base &= keys <= hi
    valid = None if key_valid is None else np.asarray(key_valid, bool)
    if pred.negated:
        return ~base if valid is None else valid & ~base
    if valid is not None:
        base &= valid  # SQL semantics: NULL fails the predicate
    return base


def aligned_key_mask(leaf, key, values, validity) -> np.ndarray:
    """Exact equality mask of one NORMALIZED key over a row-aligned span —
    the point-lookup face of the scan's :func:`_pred_mask`, so batched
    ``find_rows`` (io/lookup.py) matches keys with byte-for-byte the same
    order-domain comparison semantics every filtered scan uses (unsigned
    views, decimal unscaled ints, NULL never matches)."""
    from ..algebra.expr import Pred

    if isinstance(values, list) or isinstance(values, tuple):
        n = len(values)
        values = list(values)
    elif validity is not None:
        n = len(validity)
    else:
        n = len(values)
    pred = Pred(leaf.dotted_path, "range", lo=key, hi=key, leaf=leaf,
                prepared=True)
    return _pred_mask(pred, (values, validity), n)


_NESTED_MSG = ("column {c!r} is nested; scan_filtered returns row-aligned "
               "arrays — use read_row_range per plan for nested columns")


def _scan_expr_impl(pf, where, columns, num_threads, use_bloom, pol,
                    report) -> Dict[str, object]:
    from ..algebra.expr import Expr, prepare
    from ..io.planner import ScanPlanner, _collect_preds

    if not isinstance(where, Expr):
        raise TypeError("where must be an Expr tree (build with col(); "
                        f"got {type(where).__name__})")
    leaves = {leaf.dotted_path for leaf in pf.schema.leaves}
    flat = {leaf.dotted_path for leaf in pf.schema.leaves
            if leaf.max_repetition_level == 0}
    want = sorted(where.columns())
    for c in want:
        if c not in leaves:
            raise KeyError(f"unknown predicate column {c!r}")
        if c not in flat:
            raise ValueError(_NESTED_MSG.format(c=c))
    # default selection: every flat column not in the predicate (nested
    # ones have no single row-aligned array to mask — read them via
    # read_row_range per plan)
    out_cols = list(columns) if columns is not None else sorted(flat
                                                                - set(want))
    for c in out_cols:
        if c not in leaves:
            raise KeyError(f"unknown column {c!r}")
        if c not in flat:
            raise ValueError(_NESTED_MSG.format(c=c))

    expr = prepare(where, pf.schema)
    plan = ScanPlanner(pf, policy=pol, report=report).plan(
        expr, use_bloom=use_bloom)
    fcols = sorted({p.path for p in _collect_preds(expr)})

    rg_base = np.zeros(len(pf.row_groups), np.int64)
    np.cumsum([rg.num_rows for rg in pf.row_groups[:-1]], out=rg_base[1:])
    # surviving (row group, global row range) spans, in row order
    spans = [(d.rg_index, int(rg_base[d.rg_index]) + s, e - s)
             for d in plan.survivors for (s, e) in d.ranges]
    rg_cand = {}
    for rg_i, _, count in spans:
        rg_cand[rg_i] = rg_cand.get(rg_i, 0) + count

    skip = pol is not None and pol.skip_corrupt

    # unified read budget (utils/pool.py): every phase-1/2 decode span
    # admits its estimated uncompressed bytes through the same FIFO gate
    # the lookup path uses, so PARQUET_TPU_READ_BUDGET bounds scan +
    # lookup in-flight bytes together.  Estimate = the chunk's footer
    # uncompressed size prorated to the span's rows (zero IO; memoized
    # per (row group, column)).  Default budget for the scan tier is off,
    # so this costs one env read per task until an operator opts in.
    admission = _read_admission()
    bytes_per_row: Dict[tuple, float] = {}

    def _span_bytes(rg_i: int, c: str, count: int) -> int:
        got = bytes_per_row.get((rg_i, c))
        if got is None:
            rg_meta = pf.metadata.row_groups[rg_i]
            col_i = pf.schema.leaf(c).column_index
            tot = (rg_meta.columns[col_i].meta_data
                   .total_uncompressed_size or 0)
            got = tot / max(rg_meta.num_rows or 1, 1)
            bytes_per_row[(rg_i, c)] = got
        return int(got * count)

    def read_one(task):
        rg_i, start, count, c, form = task
        try:
            with read_context(path=pf._path, row_group=rg_i, column=c):
                with admission.admit(_span_bytes(rg_i, c, count),
                                     tier="scan"):
                    return read_row_range(pf, c, start, count, aligned=form)
        except DeadlineError:
            raise
        except CorruptedError as e:
            # captured per task (pool map would otherwise drop sibling
            # results on the floor); re-raised or skipped below
            return _SpanFailure(rg_i, e)

    def fan_out(fn, tasks, cells):
        # thread-pool dispatch costs ~100us/task: serial decode wins for
        # small plans (measured crossover around a few hundred thousand
        # cells).  Inside a pool worker (the dataset layer's per-FILE
        # fan-out) the scan stays serial: a nested _pool().map blocking on
        # futures no free worker can run would deadlock the shared pool.
        if num_threads == 1 or len(tasks) <= 1 or (num_threads is None
                                                   and (cells < 2_000_000
                                                        or _in_pool())):
            return [fn(t) for t in tasks]
        if num_threads is None:
            # fan out per (span, column): the decode work releases the GIL
            # in numpy/C++/codec calls.  mark_pooled keeps the per-worker
            # native decompress split at 1 (no pool x native
            # oversubscription).
            # instrument_task: this map's queue waits must reach
            # pool.queue_wait_s — the scan router's saturation delta for
            # the host route is measured from exactly these tasks
            return list(_pool().map(
                _instrument_task(_mark_pooled(fn), name="scan_read"),
                tasks))
        with ThreadPoolExecutor(max_workers=num_threads) as pool:
            return list(pool.map(_mark_pooled(fn), tasks))

    def drop_bad_rgs(failures):
        """Degraded scan: drop every span of each corrupt row group (spans
        are sub-row-group; partial groups would misalign filter vs output
        columns), account the loss, keep scanning the rest."""
        bad = {}
        for f in failures:
            bad.setdefault(f.rg_index, f.error)
        if not skip:
            raise failures[0].error
        for rg_i in sorted(bad):
            report.record_skip(rg_i, rows=rg_cand.get(rg_i, 0),
                               error=bad[rg_i])
        return set(bad)

    # ---- phase 1: decode only the FILTER columns' candidate pages and
    # evaluate the exact predicate (aligned=True: order-domain compares
    # are per-value).  Fused variant (PARQUET_TPU_FUSED / choose_fused on
    # the plan's filter-column byte estimate): each span's filter pages
    # are evaluated and DISCARDED page-by-page on the union page grid —
    # phase 1 never holds a whole filter span, at the cost of re-reading
    # filter columns that are also output columns in phase 2.
    from ..io.planner import choose_fused
    use_fused = bool(fcols) and bool(spans) \
        and choose_fused(plan.est_bytes([]))
    cand_rows = sum(count for _, _, count in spans)

    from ..io.fused import FusedUnsupported

    def mask_one(si):
        rg_i, gstart, count = spans[si]
        s = int(gstart - rg_base[rg_i])
        try:
            with read_context(path=pf._path, row_group=rg_i):
                try:
                    return _fused_span_mask(pf, rg_i, s, count, fcols,
                                            expr)
                except FusedUnsupported:
                    from ..io.fused import _M_FALLBACKS
                    _oscope.account(_M_FALLBACKS)
                    env = {}
                    for c in fcols:
                        with admission.admit(_span_bytes(rg_i, c, count),
                                             tier="scan"):
                            env[c] = read_row_range(pf, c, gstart, count,
                                                    aligned=True)
                    return _expr_mask(expr, env, count)
        except DeadlineError:
            raise
        except CorruptedError as e:
            return _SpanFailure(rg_i, e)

    p1_span = (_otrace.span("scan.phase1", file=pf._path,
                            spans=len(spans), cand_rows=cand_rows)
               if _otrace.TRACE_ENABLED else _otrace.NULL_SPAN)
    # `with`: a failing fan-out (deadline, unskippable corruption) must
    # still record the span — the failed run is the one worth tracing
    with p1_span:
        if use_fused:
            res1 = fan_out(mask_one, list(range(len(spans))),
                           cand_rows * max(len(fcols), 1))
            failures = [r for r in res1 if isinstance(r, _SpanFailure)]
            if failures:
                bad = drop_bad_rgs(failures)
                keep = [i for i, s in enumerate(spans) if s[0] not in bad]
                res1 = [res1[i] for i in keep]
                spans = [spans[i] for i in keep]
            # filter pages were folded and dropped: nothing to reuse
            envs = [{} for _ in spans]
            masks = res1
        else:
            tasks1 = [(rg_i, start, count, c, True)
                      for (rg_i, start, count) in spans for c in fcols]
            res1 = fan_out(read_one, tasks1,
                           cand_rows * max(len(fcols), 1))
            failures = [r for r in res1 if isinstance(r, _SpanFailure)]
            if failures:
                bad = drop_bad_rgs(failures)
                keep = [i for i, s in enumerate(spans) if s[0] not in bad]
                res1 = [res1[i * len(fcols) + j] for i in keep
                        for j in range(len(fcols))]
                spans = [spans[i] for i in keep]
            k = len(fcols)
            envs = [{c: res1[i * k + j] for j, c in enumerate(fcols)}
                    for i in range(len(spans))]
            masks = [_expr_mask(expr, env, count)
                     for (rg_i, start, count), env in zip(spans, envs)]

    # ---- phase 2: late materialization — output columns decode only the
    # pages covering rows that SURVIVED the exact predicate (the span is
    # trimmed to [first survivor, last survivor]; a span with no survivors
    # is never read).  Columns that also filter reuse the phase-1 decode.
    trims = []
    for mask in masks:
        idx = np.flatnonzero(mask)
        trims.append((int(idx[0]), int(idx[-1]) + 1) if len(idx) else None)
    # output columns stay columnar ("arrays"): python bytes objects are
    # materialized only for surviving rows — per-row materialization of
    # the full span was the scan's dominant cost on string output columns
    # fused phase 1 discards filter pages as it folds them, so filter
    # columns that are also output re-read (survivor-trimmed) in phase 2
    fset = set() if use_fused else set(fcols)
    read2_cols = [c for c in out_cols if c not in fset]
    tasks2 = [(spans[si][0], spans[si][1] + t0, t1 - t0, c, "arrays")
              for si, trim in enumerate(trims) if trim is not None
              for t0, t1 in [trim] for c in read2_cols]
    cells2 = sum(t1 - t0 for t in trims if t is not None
                 for t0, t1 in [t]) * max(len(read2_cols), 1)
    p2_span = (_otrace.span("scan.phase2", file=pf._path,
                            tasks=len(tasks2), cells=cells2)
               if _otrace.TRACE_ENABLED else _otrace.NULL_SPAN)
    with p2_span:  # `with`: record the span even when the fan-out raises
        res2 = fan_out(read_one, tasks2, cells2)
    failures = [r for r in res2 if isinstance(r, _SpanFailure)]
    if failures:
        bad = drop_bad_rgs(failures)
        # remove the corrupt row groups' phase-1 contributions too
        res2_by_span = {}
        ti = 0
        for si, trim in enumerate(trims):
            if trim is None:
                continue
            res2_by_span[si] = res2[ti:ti + len(read2_cols)]
            ti += len(read2_cols)
        keep = [i for i, s in enumerate(spans) if s[0] not in bad]
        spans = [spans[i] for i in keep]
        envs = [envs[i] for i in keep]
        masks = [masks[i] for i in keep]
        trims = [trims[i] for i in keep]
        res2 = [r for i in keep if i in res2_by_span
                for r in res2_by_span[i]]

    # ---- assembly: identical output forms to the historical scan
    parts: Dict[str, List] = {c: [] for c in out_cols}
    vparts: Dict[str, List] = {c: [] for c in out_cols}
    ti = 0
    for si, ((rg_i, start, count), mask, trim) in enumerate(
            zip(spans, masks, trims)):
        if trim is None:
            continue  # no survivors: output pages never decoded
        t0, t1 = trim
        span2 = {c: res2[ti + j] for j, c in enumerate(read2_cols)}
        ti += len(read2_cols)
        idx = np.flatnonzero(mask)
        m_t = mask[t0:t1]
        for c in out_cols:
            if c in envs[si]:
                vals, valid = envs[si][c]  # phase-1 aligned=True form
                if isinstance(vals, list):
                    parts[c].append([vals[i] for i in idx])
                else:
                    parts[c].append(np.asarray(vals)[mask])
                    if valid is not None:
                        vparts[c].append(np.asarray(valid, bool)[mask])
                    elif vparts[c]:  # earlier span had nulls: keep aligned
                        vparts[c].append(np.ones(int(mask.sum()), bool))
                continue
            vals, valid = span2[c]
            if isinstance(vals, tuple) and vals and vals[0] == BA_ARRAYS:
                _, v_u8, offs = vals
                idx_t = np.flatnonzero(m_t)
                if valid is None:
                    parts[c].append(_materialize_ba(v_u8, offs, idx_t))
                else:
                    ords = np.cumsum(valid) - 1  # row -> dense ordinal
                    tv = np.asarray(valid, bool)[idx_t]
                    got = _materialize_ba(v_u8, offs, ords[idx_t][tv])
                    woven = [None] * len(idx_t)
                    for p, v in zip(np.flatnonzero(tv), got):
                        woven[p] = v
                    parts[c].append(woven)
            elif isinstance(vals, list):
                parts[c].append([vals[i] for i in np.flatnonzero(m_t)])
            else:
                parts[c].append(np.asarray(vals)[m_t])
                if valid is not None:
                    vparts[c].append(np.asarray(valid, bool)[m_t])
                elif vparts[c]:  # earlier span had nulls: keep alignment
                    vparts[c].append(np.ones(int(m_t.sum()), bool))

    from ..format.enums import Type

    out: Dict[str, object] = {}
    for c in out_cols:
        if parts[c] and isinstance(parts[c][0], list):
            out[c] = [v for chunk in parts[c] for v in chunk]
        elif parts[c]:
            vals = np.concatenate(parts[c])
            if vparts[c]:
                n_missing = len(vals) - sum(len(v) for v in vparts[c])
                valid = np.concatenate(
                    ([np.ones(n_missing, bool)] if n_missing else []) + vparts[c])
                mask = ~valid
                if vals.ndim == 2:  # FLBA/INT96: (n, width) byte rows need a
                    mask = np.broadcast_to(mask[:, None], vals.shape)
                out[c] = np.ma.MaskedArray(vals, mask=mask)
            else:
                out[c] = vals
        elif pf.schema.leaf(c).physical_type == Type.BYTE_ARRAY:
            out[c] = []  # same host form as the non-empty path
        else:
            dt = pf.schema.leaf(c).np_dtype()
            out[c] = np.empty(0, dt or np.uint8)
    if report is not None and out_cols:
        report.rows_read += len(out[out_cols[0]])
    # OpReport attribution: rows the pushdown never decoded vs survivor
    # rows materialized (masks are final here — degraded drops included)
    _oscope.account(_M_ROWS_PRUNED, int(pf.num_rows) - cand_rows)
    _oscope.account(_M_ROWS_DECODED,
                    int(sum(int(m.sum()) for m in masks)))
    return out


# ---------------------------------------------------------------------------
# Multi-file scan (the dataset layer's fan-out; parquet_tpu/dataset.py)
# ---------------------------------------------------------------------------


def merge_scan_results(parts: List[Dict[str, object]],
                       out_cols: Sequence[str]) -> Dict[str, object]:
    """Concatenate per-file :func:`scan_filtered` results in list order —
    deterministic global output order for the dataset scan.  BYTE_ARRAY
    columns (python lists) chain; numeric columns concatenate, promoting to
    ``np.ma.MaskedArray`` when any file's span carried nulls.  Zero-row
    parts are dropped before concatenation: a file whose pages all pruned
    returns the 1-D typed empty even for (n, width)-shaped FLBA/INT96
    columns, and concatenating the two ranks would raise."""
    out: Dict[str, object] = {}
    for c in out_cols:
        vals = [p[c] for p in parts]
        if any(isinstance(v, list) for v in vals):
            out[c] = [x for v in vals for x in v]
            continue
        filled = [v for v in vals if len(v)]
        if not filled:
            out[c] = vals[0]
        elif len(filled) == 1:
            out[c] = filled[0]
        elif any(isinstance(v, np.ma.MaskedArray) for v in filled):
            out[c] = np.ma.concatenate(filled)
        else:
            out[c] = np.concatenate(filled)
    return out


def scan_files(pfs: Sequence[ParquetFile], path: Optional[str] = None,
               lo=None, hi=None,
               columns: Optional[Sequence[str]] = None,
               use_bloom: bool = True,
               values: Optional[Sequence] = None,
               policy: Optional[FaultPolicy] = None,
               report: Optional[ReadReport] = None,
               skip_files: bool = False, where=None,
               devices: Optional[Sequence] = None) -> Dict[str, object]:
    """:func:`scan_filtered` across many already-opened files, fanned out on
    the shared pool (each file's scan runs serial inside its worker — the
    pool parallelism moves up a level) with results merged in file order.
    ``where`` takes a predicate tree (each file then scans via
    :func:`scan_expr`; pass a PREPARED tree to normalize probe values once
    for the whole fleet).  Per-file row-group skips under a degraded
    ``policy`` are folded into ``report``.  ``skip_files=True`` extends
    the degraded contract to whole files: one whose scan fails outright
    (deleted mid-scan, footer fine but chunks unreadable) drops as a unit,
    recorded with its full row count as candidate rows — its partial
    row-group accounting is discarded so the loss is not double-counted.
    Returns ``{}`` when nothing (or no file) survived.  Deadline overruns
    and environment errors always propagate.  ``devices`` (a sequence of
    jax devices) round-robins each file's scan under
    ``jax.default_device(devices[i % n])`` — the Dataset device-scan
    route's per-chip assignment; results are unchanged."""
    from ..io.faults import NON_DATA_ERRORS
    from ..utils.pool import map_in_order

    if skip_files and report is None:
        # skipping whole files with nowhere to record them would be
        # silent, unaccounted data loss — refuse up front
        raise ValueError("skip_files=True requires a report to account "
                         "the dropped files")
    if (where is None) == (path is None):
        raise ValueError("pass exactly one of path (+ lo/hi/values) or "
                         "where= (a predicate tree)")
    if not pfs:
        return {}

    def one(item):
        import contextlib

        idx, pf = item
        sub = ReadReport() if report is not None else None
        if devices:
            import jax

            dev_ctx = jax.default_device(devices[idx % len(devices)])
        else:
            dev_ctx = contextlib.nullcontext()
        t0 = _time.perf_counter()
        try:
            with dev_ctx:
                if where is not None:
                    got = scan_expr(pf, where, columns=columns,
                                    use_bloom=use_bloom, policy=policy,
                                    report=sub)
                else:
                    got = scan_filtered(pf, path, lo=lo, hi=hi,
                                        columns=columns,
                                        use_bloom=use_bloom, values=values,
                                        policy=policy, report=sub)
        except DeadlineError:
            raise
        except NON_DATA_ERRORS:
            raise
        except (CorruptedError, OSError) as e:
            if not skip_files:
                raise
            return None, sub, e
        finally:
            # per-FILE scan latency: metrics_snapshot() answers the
            # dataset scan's p50/p99 per file (ROADMAP lookup-meter prep)
            _M_SCAN_FILE_S.observe(_time.perf_counter() - t0)
        return got, sub, None

    results = map_in_order(one, list(enumerate(pfs)))
    oks = []
    for pf, (got, sub, err) in zip(pfs, results):
        if got is None:
            if report is not None:
                if sub is not None:
                    # the skipped file's RETRIES really happened; only its
                    # row accounting is superseded by the file skip below
                    report.retries += sub.retries
                report.record_file_skip(pf._path or "<memory>",
                                        rows=pf.num_rows, error=err)
            continue
        if report is not None and sub is not None:
            report.merge(sub)
        oks.append(got)
    if not oks:
        return {}
    return merge_scan_results(oks, list(oks[0]))


# ---------------------------------------------------------------------------
# Device pushdown scan (SURVEY.md §3.3 on the chip; VERDICT r1 item 4)
# ---------------------------------------------------------------------------


def stage_scan(pf: ParquetFile, path: str, lo=None, hi=None,
               columns: Optional[Sequence[str]] = None,
               use_bloom: bool = True, devices: Optional[Sequence] = None,
               values: Optional[Sequence] = None,
               policy: Optional[FaultPolicy] = None,
               report: Optional[ReadReport] = None):
    """Pushdown plan + host prescan + H2D staging for a device scan.

    Split from :func:`scan_filtered_device` so callers (and the benchmark)
    can separate the host/transfer phase from on-device decode+filter.
    Returns an opaque staged-scan state consumed by :func:`decoded_scan`.
    ``devices`` stages surviving span i onto ``devices[i % len(devices)]``
    (the sharded scan's round-robin placement); default is jax's default
    device for everything.

    ``policy``/``report`` apply the resilience layer to the *staging*
    phase, where all file IO happens: preads retry under the policy, and
    ``on_corrupt='skip_row_group'`` drops the spans of a corrupt row group
    at stage time (recorded in ``report``) instead of failing the scan.
    Device-route refusals (``ValueError: ... use the host scan``) are
    routing signals, not corruption, and always propagate unchanged.
    """
    from ..io.prefetch import make_chunk_prefetcher

    pol, report = resolve_policy(pf, policy, report)
    with pf._resilient_op(policy, report, "stage_scan"):
        # device-route prefetch (ROADMAP follow-on, PR 3): surviving spans'
        # chunk ranges are planned through an advise-backed prefetcher so
        # kernel readahead of later chunks overlaps prescan + H2D of
        # earlier ones, instead of one cold serial pread per chunk
        pre = make_chunk_prefetcher(
            pf.source, n_streams=(len(columns) + 2 if columns else 4))
        if pre is None:
            return _stage_scan_impl(pf, path, lo, hi, columns, use_bloom,
                                    devices, values, pol, report)
        try:
            with pf._source_override(pre):
                return _stage_scan_impl(pf, path, lo, hi, columns, use_bloom,
                                        devices, values, pol, report,
                                        prefetcher=pre)
        finally:
            pre.close()


def _stage_scan_impl(pf, path, lo, hi, columns, use_bloom, devices, values,
                     pol, report, prefetcher=None):
    import contextlib

    import jax

    from . import device_reader as dr

    from ..format.enums import Type
    from ..io.search import pages_and_base

    flat = {leaf.dotted_path for leaf in pf.schema.leaves
            if leaf.max_repetition_level == 0}
    out_cols = list(columns) if columns is not None else sorted(flat - {path})
    for c in [path] + out_cols:
        if c not in flat:
            raise ValueError(f"column {c!r} is nested or unknown; the "
                             "device scan handles flat columns — use the "
                             "host scan")
    from ..schema.types import LogicalKind

    key_leaf = pf.schema.leaf(path)
    if key_leaf.physical_type in (Type.FIXED_LEN_BYTE_ARRAY, Type.INT96):
        raise ValueError(f"device scan key {path!r} has physical type "
                         f"{key_leaf.physical_type.name}; use the host scan")
    if (key_leaf.physical_type == Type.BYTE_ARRAY
            and key_leaf.logical_kind == LogicalKind.DECIMAL):
        # decimal BYTE_ARRAY orders by unscaled two's-complement value, not
        # by bytes — the per-entry bytewise predicate below would be wrong
        raise ValueError(f"device scan key {path!r} is a decimal byte array; "
                         "use the host scan")
    if values is not None and key_leaf.physical_type in (Type.INT64,
                                                         Type.DOUBLE):
        # 64-bit keys travel as (n, 2) uint32 pairs; exact IN over pairs has
        # no scalar order for the device searchsorted — use the host scan
        raise ValueError(f"device scan IN-list on 64-bit key {path!r} is not "
                         "supported; use the host scan (scan_filtered)")
    # other BYTE_ARRAY keys are fine when dictionary-encoded (per-entry
    # predicate + device gather); plain-encoded chunks are rejected per
    # chunk below
    plans = plan_scan(pf, path, lo=lo, hi=hi, use_bloom=use_bloom,
                      values=values, policy=pol, report=report)
    if prefetcher is not None:
        # pushdown already pruned: plan exactly the surviving spans' chunk
        # byte ranges (deduped — several spans can share one row group)
        seen_ranges = set()
        for p0 in plans:
            for c in [path] + out_cols:
                br = pf.row_group(p0.rg_index).column(c).byte_range
                if br not in seen_ranges:
                    seen_ranges.add(br)
                    prefetcher.plan(*br)
    from ..algebra.compare import normalize_probe

    probe = (sorted({normalize_probe(key_leaf, v) for v in values} - {None})
             if values is not None else None)
    rg_base = np.zeros(len(pf.row_groups), np.int64)
    np.cumsum([rg.num_rows for rg in pf.row_groups[:-1]], out=rg_base[1:])
    skip = pol is not None and pol.skip_corrupt
    failed_rgs: Dict[int, object] = {}
    spans = []
    jit_cache: Dict[tuple, object] = {}
    for si, plan in enumerate(plans):
        if plan.rg_index in failed_rgs:
            continue
        rg = pf.row_group(plan.rg_index)
        row_start, row_end = plan.first_row, plan.first_row + plan.row_count
        per_col = {}
        ctx = (jax.default_device(devices[si % len(devices)]) if devices
               else contextlib.nullcontext())
        try:
            with ctx:
                for c in [path] + out_cols:
                    # kinds narrows the wrap to IO/decode failures — the
                    # device-route refusal ValueErrors below pass through
                    # unwrapped, keeping their type for scan()'s host
                    # fallback
                    with read_context(path=pf._path,
                                      row_group=plan.rg_index, column=c,
                                      kinds=(CorruptedError, OSError)):
                        chunk = rg.column(c)
                        pages, first = pages_and_base(chunk, row_start,
                                                      row_end)
                        try:
                            dplan = dr.build_plan(chunk, pages=iter(pages))
                            unsupported = (
                                chunk.leaf.physical_type == Type.BYTE_ARRAY
                                and dplan.value_kind != "dict")
                            if not unsupported:
                                staged = dr.stage_plan(dplan)
                        except dr._Unsupported as e:
                            raise ValueError(
                                f"device scan column {c!r}: {e}; use the "
                                "host scan (scan_filtered)") from None
                        if unsupported:
                            if c == path:
                                raise ValueError(
                                    f"device scan key {c!r}: plain-encoded "
                                    "BYTE_ARRAY has no row-aligned device "
                                    "form; use the host scan")
                            # plain-string OUTPUT column: keep it
                            # host-resident (slot-aligned ragged pair); the
                            # device filters on the key and only SURVIVORS'
                            # bytes materialize — the same survivor-only
                            # rule as the host scan
                            per_col[c] = ("host_ragged",) + _host_ragged_span(
                                pf, c, rg_base, plan)
                            continue
                        per_col[c] = (chunk, dplan, staged, row_start - first)
        except DeadlineError:
            raise
        except CorruptedError as e:
            if not skip:
                raise
            failed_rgs[plan.rg_index] = e
            continue
        fused = None
        if all(per_col[c][0] != "host_ragged"
               and per_col[c][1].value_kind != "dict"
               for c in [path] + out_cols):
            # lazily-built fused program, shared across same-shape spans
            # via the signature cache; the jit is only constructed from the
            # second decoded_scan call on this state (use_count below), so
            # one-shot queries never pay a trace+compile per span
            sig = (plan.row_count,
                   tuple((c, per_col[c][3],
                          per_col[c][1].total_values
                          == per_col[c][1].total_slots)
                         for c in [path] + out_cols))
            fused = _FusedFactory(jit_cache, sig, path, out_cols, per_col,
                                  lo, hi, probe, plan.row_count)
        spans.append((plan, per_col, fused))
    if failed_rgs:
        for rg_i, e in sorted(failed_rgs.items()):
            report.record_skip(
                rg_i, rows=sum(p.row_count for p in plans
                               if p.rg_index == rg_i), error=e)
        spans = [s for s in spans if s[0].rg_index not in failed_rgs]
    # per-COLUMN form consistency: a column dict-encoded in one row group
    # and plain in another must not mix device-dict and host-ragged parts
    # (the assemble routes a column by its first part's shape) — demote
    # every span of such a column to the host-ragged form
    for c in out_cols:
        kinds = {per_col[c][0] == "host_ragged"
                 for _, per_col, _ in spans}
        if kinds == {True, False}:
            for plan, per_col, _f in spans:
                if per_col[c][0] != "host_ragged":
                    per_col[c] = ("host_ragged",) + _host_ragged_span(
                        pf, c, rg_base, plan)
            # fused programs were built against the device form: disable
            # them (host_ragged spans run the eager path)
            spans = [(plan, per_col, None) for plan, per_col, _f in spans]
    return {"path": path, "out_cols": out_cols, "lo": lo, "hi": hi,
            "values": probe, "spans": spans, "use_count": [0],
            "leaves": {c: pf.schema.leaf(c) for c in out_cols}}


def _empty_device_result(leaf):
    """Typed empty matching the documented per-column output forms."""
    import jax.numpy as jnp

    from ..format.enums import Type

    t = leaf.physical_type
    if t == Type.BYTE_ARRAY:
        return ((jnp.zeros(0, jnp.uint8), jnp.zeros(1, jnp.int32)),
                jnp.zeros(0, jnp.int32))
    if t in (Type.INT64, Type.DOUBLE):
        return jnp.zeros((0, 2), jnp.uint32)
    dt = {Type.INT32: jnp.int32, Type.FLOAT: jnp.float32,
          Type.BOOLEAN: jnp.bool_}.get(t, jnp.uint8)
    return jnp.zeros(0, dt)


def _concat_dictionaries(parts):
    """Per-span (dictionary, gathered indices) → one rebased dictionary +
    concatenated indices.  Each row group carries its own dictionary page, so
    indices from span i are offset by the sizes of dictionaries 0..i-1 and
    the dictionaries concatenated (duplicate entries across spans are kept —
    correctness over minimality)."""
    import jax.numpy as jnp

    if len(parts) == 1:
        return parts[0]
    rebased, base = [], 0
    flba_or_fixed = not isinstance(parts[0][0], tuple)
    for dictionary, indices in parts:
        rebased.append(indices + base)
        if flba_or_fixed:
            base += dictionary.shape[0]
        else:
            base += dictionary[1].shape[0] - 1
    indices = jnp.concatenate(rebased)
    if flba_or_fixed:
        return jnp.concatenate([d for d, _ in parts], axis=0), indices
    # (values, offsets) byte-array form: concat values, rebase offsets
    vals_parts = [d[0] for d, _ in parts]
    off_parts, vbase = [], 0
    for d, _ in parts:
        off = d[1]
        off_parts.append(off[:-1] + vbase)
        vbase += int(off[-1])
    offsets = jnp.concatenate(off_parts + [jnp.asarray([vbase], off.dtype)])
    return (jnp.concatenate(vals_parts), offsets), indices


class _ScanCarrier:
    """In-flight per-span results between the dispatch and finalize phases."""

    def __init__(self, out_cols):
        self.parts: Dict[str, List] = {c: [] for c in out_cols}
        self.vparts: Dict[str, List] = {c: [] for c in out_cols}
        self.any_valid = {c: False for c in out_cols}
        self.counts: List = []
        self.ks_all: List[int] = []
        self.flushed = 0

    def flush(self, out_cols, upto: int) -> None:
        """Sync survivor counts for spans [flushed, upto) — ONE blocking
        stack — then trim each span's outputs with cheap device slices."""
        import jax
        import jax.numpy as jnp

        if upto <= self.flushed:
            return
        ks = [int(k) for k in np.asarray(jax.block_until_ready(
            jnp.stack(self.counts[self.flushed:upto])))]
        self.ks_all.extend(ks)
        for si, k in zip(range(self.flushed, upto), ks):
            for c in out_cols:
                p = self.parts[c][si]
                if isinstance(p, tuple) and p and p[0] == "host_ragged":
                    # trim only the device index leg; host arrays stay
                    self.parts[c][si] = p[:4] + (p[4][:k],)
                elif isinstance(p, tuple):
                    self.parts[c][si] = (p[0], p[1][:k])
                else:
                    self.parts[c][si] = p[:k]
                if self.vparts[c][si] is not None:
                    self.vparts[c][si] = self.vparts[c][si][:k]
        self.flushed = upto


def _compact(arr, tgt):
    """Stable prefix-compaction by scatter: row i lands at tgt[i]; dropped
    rows target index n (out of bounds, mode='drop').  O(n), an order of
    magnitude cheaper than the argsort-permutation it replaces (the sort
    lowers to an O(n log²n) network on TPU)."""
    import jax.numpy as jnp

    return jnp.zeros_like(arr).at[tgt].set(arr, mode="drop")


class _FlatForm:
    """Minimal column shim for the fused span filter: the traced helpers
    only touch these members on non-dictionary columns."""

    __slots__ = ("values", "validity")

    def __init__(self, values, validity):
        self.values = values
        self.validity = validity

    def is_dictionary_encoded(self):
        return False


def _make_fused_span(path, out_cols, per_col, lo, hi, probe, n_rows):
    """One jitted program for a span's whole filter phase (mask + cumsum +
    prefix-compaction of every output column).  Eagerly these are ~a dozen
    separate dispatches of ~100k-element ops, and dispatch overhead — not
    compute — dominated the device scan (measured 3 ms of 6 ms per span on
    the config-5 shape).  Built once at stage time; the jit object lives in
    the staged state, so repeated decoded_scan calls reuse the compile.
    Only non-dictionary spans qualify (the dictionary key path folds host
    dictionary entries at trace time via a different route)."""
    import jax
    import jax.numpy as jnp

    key_chunk, key_dplan, _, key_trim = per_col[path]
    key_no_nulls = key_dplan.total_values == key_dplan.total_slots
    infos = [(c, per_col[c][0], per_col[c][1], per_col[c][3]) for c in out_cols]

    def run(key_form, col_forms):
        kcol = _FlatForm(*key_form)
        mask = _key_mask_device(key_chunk.leaf, kcol, lo, hi, key_trim,
                                n_rows, key_no_nulls, values=probe)
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        tgt = jnp.where(mask, pos, n_rows)
        outs = {}
        vouts = {}
        for c, chunk_c, dplan_c, trim_c in infos:
            vals, valid = _row_aligned_device(
                _FlatForm(*col_forms[c]), trim_c, n_rows,
                no_nulls=dplan_c.total_values == dplan_c.total_slots)
            outs[c] = _compact(vals, tgt)
            vouts[c] = _compact(valid, tgt) if valid is not None else None
        return jnp.sum(mask.astype(jnp.int32)), outs, vouts

    return jax.jit(run)


def _host_ragged_span(pf, c, rg_base, plan):
    """Host (dense values, dense offsets, validity) for one span of a
    plain-string output column — aligned=\"arrays\" keeps it columnar:
    offsets cover the DENSE present values and ``validity`` maps rows to
    value ordinals (None when null-free)."""
    start = int(rg_base[plan.rg_index]) + plan.first_row
    vals_form, valid = read_row_range(pf, c, start, plan.row_count,
                                      aligned="arrays")
    tag, vals, offs = vals_form
    assert tag == "ba_arrays", tag
    return (np.asarray(vals), np.asarray(offs, np.int64),
            None if valid is None else np.asarray(valid, bool))


class _FusedFactory:
    """Builds (once) and returns the span's fused jitted program.  Spans
    with the same shape signature share one program via ``cache``."""

    __slots__ = ("cache", "sig", "args")

    def __init__(self, cache, sig, *args):
        self.cache = cache
        self.sig = sig
        self.args = args

    def __call__(self):
        fn = self.cache.get(self.sig)
        if fn is None:
            fn = _make_fused_span(*self.args)
            self.cache[self.sig] = fn
        return fn


def _scan_dispatch(state, carrier: _ScanCarrier,
                   sync_every: Optional[int] = None) -> None:
    """Phase A — dispatch with (almost) no syncs: per span, survivors are
    compacted to a prefix with one cumsum + stable scatter of the predicate
    mask (device-shape-static; no data-dependent host round-trip per span).
    With ``sync_every``, counts are synced in batches so device residency
    stays bounded by a few spans' worth of uncompacted output."""
    import jax.numpy as jnp

    from ..format.enums import Type
    from . import device_reader as dr

    path, out_cols = state["path"], state["out_cols"]
    lo, hi = state["lo"], state["hi"]
    probe = state.get("values")
    # the fused program is only worth its compile when the staged state is
    # reused; callers bump use_count once per scan call (decoded_scan /
    # sharded), so one-shot queries stay on the eager path
    amortized = state.get("use_count", [2])[0] >= 2
    for plan, per_col, fused in state["spans"]:
        n_rows = plan.row_count
        chunk, dplan, staged, trim = per_col[path]
        key = dr.decode_staged(chunk.leaf, Type(chunk.meta.type), dplan, staged)
        cols = {}
        ragged_cols = [c for c in out_cols
                       if per_col[c][0] == "host_ragged"]
        for c in out_cols:
            if per_col[c][0] == "host_ragged":
                continue
            chunk_c, dplan_c, staged_c, trim_c = per_col[c]
            cols[c] = dr.decode_staged(chunk_c.leaf, Type(chunk_c.meta.type),
                                       dplan_c, staged_c)
        if fused is not None and amortized:
            cnt, outs, vouts = fused()(
                (key.values, key.validity),
                {c: (col.values, col.validity) for c, col in cols.items()})
        else:
            no_nulls = dplan.total_values == dplan.total_slots
            mask = _key_mask_device(chunk.leaf, key, lo, hi, trim, n_rows,
                                    no_nulls, values=probe)
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
            tgt = jnp.where(mask, pos, n_rows)  # survivors -> prefix
            cnt = jnp.sum(mask.astype(jnp.int32))
            ragged_idx = (_compact(jnp.arange(n_rows, dtype=jnp.int32), tgt)
                          if ragged_cols else None)
            outs, vouts = {}, {}
            for c in out_cols:
                if per_col[c][0] == "host_ragged":
                    # survivor ROW indices ride the device; byte gather
                    # happens host-side at assemble (survivor-only)
                    _, hv, ho, hvalid = per_col[c]
                    outs[c] = ("host_ragged", hv, ho, hvalid, ragged_idx)
                    vouts[c] = None
                    continue
                chunk_c, dplan_c, staged_c, trim_c = per_col[c]
                vals, valid = _row_aligned_device(
                    cols[c], trim_c, n_rows,
                    no_nulls=dplan_c.total_values == dplan_c.total_slots)
                if isinstance(vals, tuple):  # dictionary form: compact indices
                    dictionary, indices = vals
                    outs[c] = (dictionary, _compact(indices, tgt))
                else:
                    outs[c] = _compact(vals, tgt)
                vouts[c] = _compact(valid, tgt) if valid is not None else None
        carrier.counts.append(cnt)
        for c in out_cols:
            carrier.parts[c].append(outs[c])
            if vouts[c] is not None:
                carrier.any_valid[c] = True
            carrier.vparts[c].append(vouts[c])
        if sync_every and len(carrier.counts) - carrier.flushed >= sync_every:
            carrier.flush(out_cols, len(carrier.counts))


def _assemble_host_ragged(col_parts, carrier):
    """Host-side survivor gather for a plain-string output column: per
    span, take the device-compacted row indices (already trimmed to the
    synced counts), map rows → dense value ordinals through the span
    validity, and emit ONE (uint8 values, int64 offsets) pair over all
    survivors — null survivors are zero-length entries — wrapped as
    ``(form, validity)`` when any null survives."""
    from .. import native as _nat
    from ..ops import ref as _ref

    pieces = []
    valid_parts = []
    any_nulls = False
    for i, part in enumerate(col_parts):
        _, hv, ho, hvalid, idx_dev = part
        k = int(carrier.ks_all[i])
        rows = np.asarray(idx_dev)[:k].astype(np.int64)
        if hvalid is None:
            v = np.ones(k, bool)
            ords = rows
        else:
            v = hvalid[rows]
            ords = (np.cumsum(hvalid.astype(np.int64)) - 1)[rows]
            any_nulls = any_nulls or not bool(v.all())
        sel = ords[v]
        got = _nat.gather_ba(hv, ho, sel)
        if got is not None:
            gvals = np.asarray(got[0])
        else:  # shim unavailable: numpy gather
            lens_d = ho[sel + 1] - ho[sel]
            idx = np.repeat(ho[sel], lens_d) + _ref._ranges(lens_d)
            gvals = np.asarray(hv)[idx]
        lens = np.zeros(max(k, 1), np.int64)[:k]
        lens[v] = ho[sel + 1] - ho[sel]
        offs = np.zeros(k + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        pieces.append((gvals, offs))
        valid_parts.append(v)
    vals = (np.concatenate([p[0] for p in pieces])
            if len(pieces) > 1 else pieces[0][0])
    offs_parts = [pieces[0][1]]
    base = int(pieces[0][1][-1])
    for vo in pieces[1:]:
        offs_parts.append(vo[1][1:] + base)
        base += int(vo[1][-1])
    offs = (np.concatenate(offs_parts) if len(offs_parts) > 1
            else offs_parts[0])
    form = (vals, offs)
    if any_nulls:
        return form, np.concatenate(valid_parts)
    return form


def _scan_assemble(state, carrier: _ScanCarrier) -> Dict[str, object]:
    """Phase B — sync remaining counts, slice, concatenate across spans."""
    import jax.numpy as jnp

    out_cols = state["out_cols"]
    carrier.flush(out_cols, len(carrier.counts))
    parts, vparts = carrier.parts, carrier.vparts
    out: Dict[str, object] = {}
    for c in out_cols:
        if not parts[c]:
            out[c] = _empty_device_result(state["leaves"][c])
            continue
        if (isinstance(parts[c][0], tuple)
                and parts[c][0][0] == "host_ragged"):
            out[c] = _assemble_host_ragged(parts[c], carrier)
            continue
        if isinstance(parts[c][0], tuple):  # dictionary-encoded
            form = _concat_dictionaries(parts[c])
        else:
            form = (parts[c][0] if len(parts[c]) == 1
                    else jnp.concatenate(parts[c]))
        if carrier.any_valid[c]:
            lens = [(p[1] if isinstance(p, tuple) else p).shape[0]
                    for p in parts[c]]
            valid = jnp.concatenate(
                [v if v is not None else jnp.ones(n, bool)
                 for v, n in zip(vparts[c], lens)])
            out[c] = (form, valid)
        else:
            out[c] = form
    return out


def decoded_scan(state) -> Dict[str, object]:
    """On-device phase of the pushdown scan: decode staged pages, evaluate
    the range predicate on the chip, and gather the surviving rows.

    Per-column output forms (typed empties when nothing survives):
    fixed-width → ``jax.Array`` (64-bit types in the (n, 2) uint32 pair
    representation — ``ops.device.pairs_to_host`` converts); dictionary-
    encoded byte arrays → ``(dictionary, indices)`` with per-row-group
    dictionaries rebased into one; PLAIN (non-dictionary) byte arrays →
    a host ``(uint8 values, int64 offsets)`` pair over the survivors
    (the chip filters on the key and compacts row indices; only
    survivors' bytes materialize, host-side); nullable columns wrap
    their form in a ``(form, validity)`` tuple.
    """
    state.setdefault("use_count", [0])[0] += 1
    carrier = _ScanCarrier(state["out_cols"])
    _scan_dispatch(state, carrier, sync_every=_SYNC_EVERY)
    return _scan_assemble(state, carrier)


def scan(pf: ParquetFile, path: str, lo=None, hi=None,
         columns: Optional[Sequence[str]] = None, use_bloom: bool = True,
         values: Optional[Sequence] = None,
         policy: Optional[FaultPolicy] = None,
         report: Optional[ReadReport] = None):
    """Pushdown scan, host-vs-device routed by the planner's COST MODEL
    (:func:`parquet_tpu.io.planner.choose_route`): backend, static shape
    support (the footer-level mirror of the device route's documented
    refusals — checked up front, not by throwing), estimated bytes to
    decode and stats-level selectivity from a zero-IO plan, and the
    process-wide :class:`~parquet_tpu.io.planner.RouteHistory` of measured
    per-route throughput.  On the cpu backend the threaded host route
    always wins (measured 1.8-2.7x pyarrow vs the device route's emulated
    kernels); ``PARQUET_TPU_ROUTE=host|device`` pins the choice.  The
    documented-refusal fallback (``ValueError: ... use the host scan``)
    is retained as a safety net for shapes only visible at page level
    (e.g. a dictionary chunk that fell back to plain mid-file), but it is
    no longer the router.
    NOTE the two routes' output forms differ (decoded_scan device forms
    vs scan_filtered host arrays / byte lists), and on accelerator
    backends the chosen route — hence the result form — can change with
    the plan's size and the measured history.  Callers that need ONE
    stable form should call :func:`scan_filtered` /
    :func:`scan_filtered_device` directly, or pin
    ``PARQUET_TPU_ROUTE=host|device``.  Plain-string OUTPUT columns ride
    the device route as host (values, offsets) survivor pairs."""
    # request scope over route + attempt(s): the route decision and any
    # device-attempt fallback all attribute to one op
    with _oscope.maybe_op_scope("file.scan", file=pf._path):
        return _scan_routed(pf, path, lo, hi, columns, use_bloom, values,
                            policy, report)


def _scan_routed(pf, path, lo, hi, columns, use_bloom, values, policy,
                 report):
    import dataclasses
    import time

    from ..io.planner import route_history, route_scan

    pol = policy if policy is not None else pf.policy
    decision = route_scan(pf, path, lo=lo, hi=hi, columns=columns,
                          values=values)
    t0 = time.monotonic()
    w0 = _pool_wait_seconds()
    if decision.route == "device":
        # the device attempt works on a scratch report: a refusal fallback
        # discards its staging-phase skips (the host scan re-plans and
        # re-records them — the same report twice would double-count every
        # skipped row group) but keeps its retries, which really happened
        scratch = ReadReport() if report is not None else None
        if scratch is not None:
            # scratch skips don't publish to the metrics registry at
            # record time: a refusal fallback discards them (the host scan
            # re-records, which would double the registry totals); the
            # success path below publishes them in one shot instead
            scratch._publish = False
        try:
            got = scan_filtered_device(pf, path, lo=lo, hi=hi,
                                       columns=columns, use_bloom=use_bloom,
                                       values=values, policy=policy,
                                       report=scratch)
            route_history().observe("device", decision.est_bytes,
                                    time.monotonic() - t0,
                                    pool_wait_s=_pool_wait_seconds() - w0)
            if report is not None:
                report.merge(scratch)
                scratch.publish_skips()
            return got
        except ValueError as e:
            # only the DOCUMENTED device-route refusals fall back (their
            # messages all direct to the host scan); any other ValueError
            # is a real failure and must surface, not silently change the
            # caller's result forms
            if "use the host scan" not in str(e):
                raise
            if report is not None and scratch is not None:
                report.retries += scratch.retries
        if pol is not None and pol.deadline_s is not None:
            # the fallback continues the SAME scan: it runs on whatever
            # budget the device attempt left, not a fresh deadline
            remaining = pol.deadline_s - (time.monotonic() - t0)
            if remaining <= 0:
                raise DeadlineError(
                    "deadline exceeded during scan (device attempt spent "
                    "the budget before falling back to the host scan)")
            policy = dataclasses.replace(pol, deadline_s=remaining)
    t0 = time.monotonic()
    w0 = _pool_wait_seconds()
    got = scan_filtered(pf, path, lo=lo, hi=hi, columns=columns,
                        use_bloom=use_bloom, values=values, policy=policy,
                        num_threads=decision.pool_width, report=report)
    # hand the router the measured pool saturation of THIS scan (queue
    # waits + prefetch stalls, process-wide deltas): RouteHistory then
    # discounts the host route's effective GB/s, not just its wall clock
    route_history().observe("host", decision.est_bytes,
                            time.monotonic() - t0,
                            pool_wait_s=_pool_wait_seconds() - w0)
    return got


def scan_filtered_device(pf: ParquetFile, path: str, lo=None, hi=None,
                         columns: Optional[Sequence[str]] = None,
                         use_bloom: bool = True,
                         values: Optional[Sequence] = None,
                         policy: Optional[FaultPolicy] = None,
                         report: Optional[ReadReport] = None) -> Dict[str, object]:
    """Device-mode :func:`scan_filtered`: pushdown selects pages, the chip
    decodes them, evaluates ``lo <= key <= hi`` (or ``key ∈ values``), and
    gathers survivors — the TPU analog of SURVEY.md §3.3's
    Find→SeekToRow→decode flow.  ``policy``/``report`` guard the staging
    phase (see :func:`stage_scan`)."""
    return decoded_scan(stage_scan(pf, path, lo=lo, hi=hi, columns=columns,
                                   use_bloom=use_bloom, values=values,
                                   policy=policy, report=report))


def _key_mask_device(leaf, col, lo, hi, trim: int, n_rows: int,
                     no_nulls: bool = False, values=None):
    """Row-aligned predicate mask on device for the key column; lo/hi (or an
    IN-list ``values``) are normalized to the leaf's order domain (unsigned-
    logical keys compare in the unsigned view, matching zone-map pruning)."""
    import jax
    import jax.numpy as jnp

    from ..algebra.compare import is_unsigned, normalize
    from ..format.enums import Type
    from ..ops import device as dev

    lo, hi = normalize(leaf, lo), normalize(leaf, hi)
    vals, valid = _row_aligned_device(col, trim, n_rows, no_nulls=no_nulls)
    if isinstance(vals, tuple):
        # dictionary-encoded byte-array key: evaluate the predicate once per
        # dictionary entry on host (metadata-scale), then one device gather
        # maps entry verdicts onto the index stream
        dvals, doffs = col.dictionary_host
        doffs = np.asarray(doffs, np.int64)
        entries = [bytes(dvals[doffs[i]: doffs[i + 1]])
                   for i in range(len(doffs) - 1)]
        if values is not None:
            probe_set = set(values)
            match = np.array([e in probe_set for e in entries], bool)
        else:
            match = np.array([(lo is None or e >= lo)
                              and (hi is None or e <= hi)
                              for e in entries], bool)
        _, indices = vals
        mask = jnp.take(jnp.asarray(match), indices, axis=0)
        if valid is not None:
            mask &= valid
        return mask
    if values is not None:
        # single-word numeric key: exact IN via device searchsorted over the
        # (host-sorted) probe array — O(n log k), no probabilistic filter
        unsigned = is_unsigned(leaf)
        np_dt = {Type.INT32: np.uint32 if unsigned else np.int32,
                 Type.FLOAT: np.float32,
                 Type.BOOLEAN: np.bool_}.get(leaf.physical_type)
        if np_dt is None:
            raise ValueError("device IN-list needs a single-word key")
        probes = np.array(values, dtype=np_dt)
        if unsigned and vals.dtype == jnp.int32:
            vals = jax.lax.bitcast_convert_type(vals, jnp.uint32)
        pv = jnp.asarray(np.sort(probes))
        idx = jnp.clip(jnp.searchsorted(pv, vals), 0, len(pv) - 1)
        mask = jnp.take(pv, idx) == vals
        if valid is not None:
            mask &= valid
        return mask
    physical = leaf.physical_type
    unsigned = is_unsigned(leaf)
    if vals.ndim == 2 and vals.shape[-1] == 2 and vals.dtype == jnp.uint32:
        is_float = physical == Type.DOUBLE

        def pair_of(v):
            if v is None:
                return np.zeros(2, np.uint32)
            host = np.array([v], np.float64 if is_float
                            else np.uint64 if unsigned else np.int64)
            return host.view(np.uint32)

        mask = dev.pair_range_mask(vals, jnp.asarray(pair_of(lo)),
                                   jnp.asarray(pair_of(hi)),
                                   jnp.asarray(lo is not None),
                                   jnp.asarray(hi is not None),
                                   is_float=is_float, is_unsigned=unsigned)
    else:
        if unsigned and vals.dtype == jnp.int32:
            vals = jax.lax.bitcast_convert_type(vals, jnp.uint32)

            def bound(v):
                return jnp.uint32(np.uint32(v))
        else:
            def bound(v):
                return v
        mask = jnp.ones(vals.shape[0], bool)
        if lo is not None:
            mask &= vals >= bound(lo)
        if hi is not None:
            mask &= vals <= bound(hi)
    if valid is not None:
        mask &= valid  # SQL semantics: NULL never matches
    return mask


def _row_aligned_device(col, trim: int, n_rows: int, no_nulls: bool = False):
    """Decoded flat Column → row-aligned (values, validity) device arrays,
    trimmed to the plan's row span (pages may cover extra leading rows).
    ``no_nulls`` (known host-side from the staging plan's slot/value counts,
    so no device sync) drops the all-true validity a nullable-but-null-free
    column carries, skipping the dense→slot scatter."""
    import dataclasses

    from ..ops import device as dev

    if no_nulls and col.validity is not None:
        col = dataclasses.replace(col, validity=None)
    if col.is_dictionary_encoded():
        idx = col.dict_indices
        if col.validity is not None:
            idx = dev.scatter_valid(idx, col.validity)
        return ((col.dictionary, idx[trim:trim + n_rows]),
                None if col.validity is None
                else col.validity[trim:trim + n_rows])
    vals = col.values
    if col.validity is not None:
        vals = dev.scatter_valid(vals, col.validity)
        return (vals[trim:trim + n_rows],
                col.validity[trim:trim + n_rows])
    return vals[trim:trim + n_rows], None


def scan_filtered_sharded(pf: ParquetFile, path: str, lo=None, hi=None,
                          columns: Optional[Sequence[str]] = None,
                          mesh=None, use_bloom: bool = True,
                          policy: Optional[FaultPolicy] = None,
                          report: Optional[ReadReport] = None):
    """Distributed pushdown scan: surviving row-group spans are staged
    round-robin across the mesh's devices and decoded+filtered there —
    BASELINE.md config 5 at v5e-8 scale (SURVEY.md §2.5 data parallelism
    over row groups, applied to the §3.3 Find→decode flow).

    Returns ``{column: [per-device results]}`` plus ``"#rows"`` (total
    survivors).  Each per-device entry follows :func:`decoded_scan`'s
    per-column forms and stays resident on its device; concatenation
    across devices is the caller's choice (host gather or collectives).
    """
    import jax

    from .mesh import default_mesh

    mesh = mesh or default_mesh()
    devs = list(mesh.devices.flat)
    state = stage_scan(pf, path, lo=lo, hi=hi, columns=columns,
                       use_bloom=use_bloom, devices=devs, policy=policy,
                       report=report)
    state["use_count"][0] += 1
    out_cols = state["out_cols"]
    if "#rows" in out_cols:
        raise ValueError('a column named "#rows" collides with the result '
                         "total; select it via scan_filtered instead")
    shards = []  # (device, sub-state, carrier)
    for di, dev in enumerate(devs):
        spans = [sp for si, sp in enumerate(state["spans"])
                 if si % len(devs) == di]
        if spans:
            shards.append((dev, dict(state, spans=spans),
                           _ScanCarrier(out_cols)))
    # dispatch EVERY device's phase A before any sync, so the chips decode
    # concurrently; the per-device finalize then only waits, it doesn't idle
    # the rest of the mesh.  (Residency is bounded per device by its own
    # span share — the single-device sync_every batching doesn't apply.)
    for dev, sub, carrier in shards:
        # staged bytes are uncommitted: pin this shard's execution (and its
        # outputs) to its device
        with jax.default_device(dev):
            _scan_dispatch(sub, carrier)
    per_dev: Dict[str, List] = {c: [] for c in out_cols}
    total = 0
    for dev, sub, carrier in shards:
        with jax.default_device(dev):
            got = _scan_assemble(sub, carrier)
        for c in out_cols:
            per_dev[c].append(got[c])
        total += sum(carrier.ks_all)
    result: Dict[str, object] = dict(per_dev)
    result["#rows"] = total
    return result
