"""Threaded predicate-pushdown scan over row groups.

Reference parity: the reference has no internal parallelism — its documented
concurrency model is the *caller* fanning goroutines out over row groups /
column chunks (SURVEY.md §2.5, "caller-driven goroutine fan-out"; the read
path is immutable-after-open and goroutine-safe).  This module packages that
fan-out as a first-class API: zone-map pruning picks the covering pages
(io/search.py), a thread pool decodes the surviving (row-group, column)
chunks concurrently — the host decoders spend their time in numpy / the C++
shim / the codec libraries, all of which release the GIL — and the exact
predicate is applied to the decoded keys.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..io.reader import ParquetFile
from ..io.search import plan_scan, read_row_range

__all__ = ["scan_filtered"]


def scan_filtered(pf: ParquetFile, path: str, lo=None, hi=None,
                  columns: Optional[Sequence[str]] = None,
                  num_threads: Optional[int] = None,
                  use_bloom: bool = False) -> Dict[str, np.ndarray]:
    """Scan ``columns`` for rows where ``lo <= file[path] <= hi``.

    Pushdown happens at three levels: row groups are pruned by chunk
    statistics (and optionally bloom filters for point lookups), pages by
    column-index zone maps, and finally the decoded key column is compared
    exactly.  Only pages covering candidate rows are ever decompressed.

    Returns ``{column: values}`` with the predicate applied.  Rows where the
    key is NULL never match (SQL comparison semantics).  Nullable numeric
    output columns come back as ``np.ma.MaskedArray`` (mask=True at nulls);
    BYTE_ARRAY columns as lists with ``None`` entries.  Flat columns only
    (nested columns have no single row-aligned array to mask; read them via
    :func:`read_row_range` per surviving span instead) — the default
    selection takes every flat column.
    """
    leaves = {leaf.dotted_path for leaf in pf.schema.leaves}
    flat = {leaf.dotted_path for leaf in pf.schema.leaves
            if leaf.max_repetition_level == 0}
    if path not in leaves:
        raise KeyError(f"unknown predicate column {path!r}")
    # default selection: every flat column (nested ones have no single
    # row-aligned array to mask — read them via read_row_range per plan)
    out_cols = list(columns) if columns is not None else sorted(flat - {path})
    for c in [path] + out_cols:
        if c not in leaves:
            raise KeyError(f"unknown column {c!r}")
        if c not in flat:
            raise ValueError(
                f"column {c!r} is nested; scan_filtered returns row-aligned "
                "arrays — use read_row_range per plan for nested columns")

    plans = plan_scan(pf, path, lo=lo, hi=hi, use_bloom=use_bloom)
    rg_base = np.zeros(len(pf.row_groups), np.int64)
    np.cumsum([rg.num_rows for rg in pf.row_groups[:-1]], out=rg_base[1:])

    read_cols = [path] + [c for c in out_cols if c != path]

    def read_span(plan):
        start = int(rg_base[plan.rg_index]) + plan.first_row
        return {c: read_row_range(pf, c, start, plan.row_count, aligned=True)
                for c in read_cols}

    if num_threads == 1 or len(plans) <= 1:
        spans = [read_span(p) for p in plans]
    else:
        with ThreadPoolExecutor(max_workers=num_threads) as pool:
            spans = list(pool.map(read_span, plans))

    parts: Dict[str, List] = {c: [] for c in out_cols}
    vparts: Dict[str, List] = {c: [] for c in out_cols}
    for span in spans:
        keys, key_valid = span[path]
        if isinstance(keys, list):  # BYTE_ARRAY keys: Python bytes comparisons
            mask = np.fromiter(
                ((x is not None
                  and (lo is None or x >= lo) and (hi is None or x <= hi))
                 for x in keys), bool, count=len(keys))
        else:
            mask = np.ones(len(keys), bool)
            if lo is not None:
                mask &= keys >= lo
            if hi is not None:
                mask &= keys <= hi
            if key_valid is not None:  # SQL semantics: NULL fails the predicate
                mask &= key_valid
        for c in out_cols:
            vals, valid = span[c]
            if isinstance(vals, list):
                idx = np.flatnonzero(mask)
                parts[c].append([vals[i] for i in idx])
            else:
                parts[c].append(np.asarray(vals)[mask])
                if valid is not None:
                    vparts[c].append(valid[mask])
                elif vparts[c]:  # earlier span had nulls: keep alignment
                    vparts[c].append(np.ones(int(mask.sum()), bool))

    from ..format.enums import Type

    out: Dict[str, np.ndarray] = {}
    for c in out_cols:
        if parts[c] and isinstance(parts[c][0], list):
            out[c] = [v for chunk in parts[c] for v in chunk]
        elif parts[c]:
            vals = np.concatenate(parts[c])
            if vparts[c]:
                n_missing = len(vals) - sum(len(v) for v in vparts[c])
                valid = np.concatenate(
                    ([np.ones(n_missing, bool)] if n_missing else []) + vparts[c])
                out[c] = np.ma.MaskedArray(vals, mask=~valid)
            else:
                out[c] = vals
        elif pf.schema.leaf(c).physical_type == Type.BYTE_ARRAY:
            out[c] = []  # same host form as the non-empty path
        else:
            dt = pf.schema.leaf(c).np_dtype()
            out[c] = np.empty(0, dt or np.uint8)
    return out
