"""Multi-chip sharded reads over a jax.sharding.Mesh.

Reference parity: the reference's only parallelism is caller-driven goroutine
fan-out over row groups / column chunks (SURVEY.md §2.5).  The TPU-native
equivalent: a ``Mesh`` over chips, row groups round-robined across the
``data`` axis, per-chip staging + decode, and the decoded chunks exposed as
global sharded ``jax.Array``s (``make_array_from_single_device_arrays``), so
downstream pjit computations consume them without resharding.  Collectives
ride ICI only if a consumer asks for replication — decode itself is
embarrassingly parallel, exactly like the reference's design.

Also home of ``decode_step_sharded``: a ``shard_map``-based batched decode
step over a mesh (the "training step" analog, exercised by the driver's
``dryrun_multichip``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..format.enums import Encoding
from ..io.column import Column
from ..io.reader import ParquetFile
from ..obs.ledger import ledger_account
from ..obs.metrics import counter as _ocounter, histogram as _ohistogram
from ..obs.scope import account as _oaccount
from ..ops import device as dev
from ..utils import pool as _pool
from ..utils.debug import counters
from ..utils.env import env_str

# resolved once at import (hot-path rule: no registry get-or-create per
# file); the ledger account is owned HERE (analysis/lint.py PT003)
_M_H2D_S = _ohistogram("device.h2d_s")
_M_DECODE_S = _ohistogram("device.decode_s")
_M_FILES_SHARDED = _ocounter("device.files_sharded")
_M_STAGE_OVERLAPPED = _ocounter("device.stage_overlapped")
_ACC_STAGING = ledger_account("device.staging")


def default_mesh(n: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def dataset_process_shard(dataset, process_index: Optional[int] = None,
                          process_count: Optional[int] = None):
    """This host's file shard of a multi-host dataset: files are
    round-robined across JAX processes (``Dataset.shard(i, n)``), so every
    process of a multi-controller mesh reads a disjoint, deterministic
    subset and the union covers the corpus exactly once.  Defaults come
    from the runtime (``jax.process_index()`` / ``jax.process_count()``);
    pass both explicitly to shard by something other than processes (e.g.
    one shard per chip for a caller-driven device fan-out)."""
    i = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    return dataset.shard(i, n)


@dataclass(frozen=True)
class ShardedTable:
    """Row-sharded decode result over a mesh.

    ``arrays[path]`` is a global jax.Array sharded on rows (leading axis)
    over the mesh's first axis; every shard is padded to ``shard_rows`` so
    the global array exists, and ``row_counts[i]`` gives shard i's REAL row
    count (``row_mask()`` materializes the padding mask with the same
    sharding). ``validity[path]`` (present only for columns with nulls) is a
    row-aligned bool array sharded identically; padded and null slots hold
    zero fill in ``arrays[path]``. 64-bit columns use the (n, 2) uint32 pair
    representation (``ops.device.pairs_to_host``).

    Dictionary-encoded BYTE_ARRAY columns shard their int32 INDEX stream in
    ``arrays[path]``; the row-group dictionaries are UNIFIED (deduplicated
    across groups — equal ids mean equal strings, so filters, group-bys and
    joins on the index stream are exact on device) into one host
    ``dictionaries[path] = (uint8 values, int64 offsets)`` shared by every
    shard — ``lookup_strings(path, ids)`` materializes entries.

    PLAIN (non-dictionary) BYTE_ARRAY columns shard as the arrow ragged
    pair in ``ragged[path]`` (see field comment); a column whose chunks mix
    dictionary and plain encodings (pyarrow's mid-file dictionary-overflow
    fallback) densifies the dictionary chunks so the whole column ships
    ragged.
    """

    arrays: Dict[str, jax.Array]
    validity: Dict[str, jax.Array]
    row_counts: tuple
    mesh: Mesh
    dictionaries: Dict[str, tuple] = field(default_factory=dict)
    # PLAIN (non-dictionary) BYTE_ARRAY columns: ragged[path] =
    # (bytes_global, offsets_global) — per-shard value bytes padded to the
    # byte-widest shard, and per-shard slot-aligned int64 offsets (null
    # slots zero-length) padded to shard_rows+1 entries, both sharded on
    # the mesh's first axis like arrays[path]
    ragged: Dict[str, tuple] = field(default_factory=dict)
    # schema leaves by path: to_arrow recombines 64-bit pairs and restores
    # logical types (dates, timestamps, decimals, FLBA) through these
    leaves: Dict[str, object] = field(default_factory=dict)

    def lookup_strings(self, path: str, ids) -> list:
        """Materialize dictionary entries for index values of ``path``."""
        dvals, doffs = self.dictionaries[path]
        return [bytes(dvals[doffs[i]:doffs[i + 1]]) for i in np.asarray(ids)]

    def to_arrow(self):
        """Gather every shard back to host as one pyarrow.Table (padding
        rows dropped, 64-bit pairs recombined, dictionary-index columns as
        DictionaryArray over the unified dictionary).  Conversion routes
        through the leaf-aware ``_leaf_to_arrow`` so logical types (dates,
        timestamps, decimals, FLBA, binary-vs-string) survive exactly as
        in ``ParquetFile.read().to_arrow()``."""
        import pyarrow as pa

        from ..io.column import _leaf_to_arrow

        mask = np.asarray(self.row_mask())
        cols, names = [], []
        for path, arr in self.arrays.items():
            leaf = self.leaves.get(path)
            host = np.asarray(arr)
            valid = (np.asarray(self.validity[path])[mask]
                     if path in self.validity else None)
            if path in self.dictionaries:
                dvals, doffs = self.dictionaries[path]
                entries = _leaf_to_arrow(leaf, np.asarray(dvals),
                                         np.asarray(doffs, np.int64), None)
                ids = host[mask].astype(np.int32)
                ia = (pa.array(ids, mask=~valid) if valid is not None
                      else pa.array(ids))
                a = pa.DictionaryArray.from_arrays(ia, entries)
            else:
                if host.ndim == 2 and host.dtype == np.uint32 \
                        and host.shape[-1] == 2:
                    host = dev.pairs_to_host(
                        host, np.dtype(leaf.np_dtype()) if leaf is not None
                        else np.int64)
                rowvals = host[mask]
                if leaf is None:  # externally built table: generic numpy
                    a = (pa.array(rowvals, mask=~valid)
                         if valid is not None else pa.array(rowvals))
                elif valid is not None:
                    # _leaf_to_arrow takes DENSE values + slot validity
                    a = _leaf_to_arrow(leaf, rowvals[valid], None, valid)
                else:
                    a = _leaf_to_arrow(leaf, rowvals, None, None)
            cols.append(a)
            names.append(path)
        R = self.shard_rows
        nd = len(self.row_counts)

        def _offs32(o):
            if len(o) and int(o[-1]) > np.iinfo(np.int32).max:
                raise NotImplementedError(
                    "ragged shard holds more than 2 GiB of value bytes; "
                    "int32 arrow offsets cannot address it — use smaller "
                    "row groups or more shards")
            return o.astype(np.int32)

        for path, (b_g, o_g) in self.ragged.items():
            leaf = self.leaves.get(path)
            bh = np.asarray(b_g)
            oh = np.asarray(o_g)
            mb = len(bh) // nd if nd else 0
            valid_all = (np.asarray(self.validity[path])
                         if path in self.validity else None)
            chunks = []
            for d in range(nd):
                rc = self.row_counts[d]
                o = oh[d * (R + 1): d * (R + 1) + rc + 1].astype(np.int64)
                seg = bh[d * mb: d * mb + (int(o[-1]) if rc else 0)]
                if valid_all is not None:
                    v = np.asarray(valid_all[d * R: d * R + rc], bool)
                    # null slots are zero-length, so the dense offsets are
                    # the slot offsets with null entries dropped
                    dense_offs = np.concatenate([o[:-1][v], o[-1:]])
                    chunks.append(_leaf_to_arrow(leaf, seg,
                                                 _offs32(dense_offs), v))
                else:
                    chunks.append(_leaf_to_arrow(leaf, seg, _offs32(o),
                                                 None))
            cols.append(pa.chunked_array(chunks))
            names.append(path)
        # file schema order (self.leaves is insertion-ordered by schema)
        if self.leaves:
            want = [p for p in self.leaves if p in names]
            want += [p for p in names if p not in self.leaves]
            lookup = dict(zip(names, cols))
            names, cols = want, [lookup[p] for p in want]
        return pa.table(dict(zip(names, cols)))

    @property
    def shard_rows(self) -> int:
        return max(self.row_counts) if self.row_counts else 0

    @property
    def num_rows(self) -> int:
        return int(sum(self.row_counts))

    def row_mask(self) -> jax.Array:
        """Global bool array marking real (non-padding) rows."""
        host = np.concatenate(
            [np.arange(self.shard_rows) < c for c in self.row_counts]) \
            if self.row_counts else np.zeros(0, bool)
        sharding = NamedSharding(self.mesh, P(self.mesh.axis_names[0]))
        return jax.device_put(host, sharding)


def _decode_prepped(reader, prep_out):
    """Device-decode a prepared chunk, or fall back to host decode when the
    prescan/decode hit an unsupported shape (mixed page encodings, missing
    dictionary page, ...) — parity with decode_chunk_device(fallback=True).
    Returns (Column, null count)."""
    from ..format.enums import Type
    from ..io.reader import decode_chunk_host
    from .device_reader import _Unsupported, decode_staged

    if prep_out is not None:
        plan, staged = prep_out
        try:
            col = decode_staged(reader.leaf, Type(reader.meta.type), plan,
                                staged)
            counters.inc("chunks_device_decoded")
            return col, plan.total_slots - plan.total_values
        except _Unsupported:
            pass
    counters.inc("chunks_host_fallback")
    col = decode_chunk_host(reader)
    n_nulls = 0
    if col.validity is not None:
        v = np.asarray(col.validity)
        n_nulls = int(len(v) - v.sum())
    return col, n_nulls


def _unify_dictionaries(dv_parts: List[np.ndarray],
                        do_parts: List[np.ndarray]):
    """Deduplicate per-row-group dictionaries into one unified dictionary.

    Returns ``(values, offsets, remap)`` where ``remap[concat_id] ->
    unified id`` over the concatenation of the input dictionaries in order.
    Unified ids are first-occurrence ordered, so equal ids ⇔ equal strings
    across every row group — the property device-side filters/joins on the
    sharded index stream rely on."""
    from .. import native as _native
    from ..io.column import concat_byte_arrays
    from ..ops import ref

    cat_vals, cat_offs = concat_byte_arrays(dv_parts, do_parts)
    n = len(cat_offs) - 1
    res = _native.dict_build_ba(cat_vals, cat_offs, n + 1,
                                sample_bail=False)
    if res is None or isinstance(res, str):
        # shim unavailable: python dedup, same semantics
        seen: Dict[bytes, int] = {}
        remap = np.empty(n, np.int64)
        keep = []
        for i in range(n):
            key = bytes(cat_vals[cat_offs[i]:cat_offs[i + 1]])
            uid = seen.setdefault(key, len(seen))
            if uid == len(keep):
                keep.append(i)
            remap[i] = uid
        first_rows = np.array(keep, np.int64)
    else:
        remap, first_rows = res
        remap = np.asarray(remap, np.int64)
    uvals, uoffs = ref.gather_dictionary((cat_vals, cat_offs),
                                         np.asarray(first_rows, np.int64))
    return uvals, np.asarray(uoffs, np.int64), remap


def _slot_ragged(vals: np.ndarray, offs: np.ndarray, validity,
                 n_nulls: int):
    """Dense (values, offsets) → slot-aligned offsets where null slots are
    zero-length entries (the arrow convention the sharded ragged form
    uses); values are untouched."""
    if validity is None or not n_nulls:
        return vals, offs
    valid = np.asarray(validity, bool)
    lens = np.zeros(len(valid), np.int64)
    lens[valid] = offs[1:] - offs[:-1]
    so = np.zeros(len(valid) + 1, np.int64)
    np.cumsum(lens, out=so[1:])
    return vals, so


def read_table_sharded(source, mesh: Optional[Mesh] = None,
                       columns: Optional[Sequence[str]] = None,
                       axis: str = "data",
                       num_threads: Optional[int] = None) -> ShardedTable:
    """Read fixed-width columns of a file as a :class:`ShardedTable`.

    Row groups are assigned round-robin to the mesh's devices. The host
    phase (pread + decompress + prescan + H2D put targeted at each chunk's
    device) fans out across a thread pool so all devices stage concurrently
    (SURVEY.md §2.5 data-parallel row); decode dispatches are async, so
    device work overlaps too. Columns must be flat: fixed-width values
    shard directly (BOOLEAN/INT32/INT64/FLOAT/DOUBLE/FLBA — 64-bit as
    (n, 2) uint32 pairs), and dictionary-encoded BYTE_ARRAY columns shard
    their int32 index stream with the per-row-group dictionaries UNIFIED
    (first-occurrence dedup — id equality is string equality on every
    shard) into ``ShardedTable.dictionaries[path]``.
    PLAIN-encoded (non-dictionary) string columns shard as the ragged
    (bytes, slot-offsets) pair in ``ShardedTable.ragged``; nested columns
    raise ValueError (read them with ``ParquetFile.read(device=True)``,
    which keeps ragged forms).
    """
    from concurrent.futures import ThreadPoolExecutor

    from ..format.enums import Type
    from .device_reader import _Unsupported, prepare_chunk

    mesh = mesh or default_mesh(axis=axis)
    devs = list(mesh.devices.reshape(-1))
    pf = source if isinstance(source, ParquetFile) else ParquetFile(source)
    leaves = (pf.schema.leaves if columns is None
              else [pf.schema.leaf(c) for c in columns])
    n_rg = len(pf.metadata.row_groups or [])
    for leaf in leaves:
        if leaf.max_repetition_level > 0:
            raise ValueError(
                f"read_table_sharded: column {leaf.dotted_path!r} is "
                "nested; use ParquetFile.read(device=True)")
    if n_rg == 0:
        return ShardedTable(arrays={}, validity={},
                            row_counts=(0,) * len(devs), mesh=mesh,
                            dictionaries={})
    tasks = [(leaf, rg) for leaf in leaves for rg in range(n_rg)]

    def prep(task):
        leaf, rg = task
        reader = pf.row_group(rg).column(leaf.column_index)
        if leaf.physical_type == Type.BYTE_ARRAY:
            encs = reader.meta.encodings or []
            if not any(int(e) in (int(Encoding.PLAIN_DICTIONARY),
                                  int(Encoding.RLE_DICTIONARY))
                       for e in encs):
                # fully PLAIN chunk: it ships as the host-assembled ragged
                # pair anyway — device-staging it first would be a wasted
                # H2D+D2H round trip
                return None, reader
        try:
            return prepare_chunk(reader, device=devs[rg % len(devs)]), reader
        except _Unsupported:
            return None, reader  # host fallback at decode time

    workers = num_threads or min(len(devs) * 2, 16)
    with ThreadPoolExecutor(max_workers=max(workers, 1)) as pool:
        prepped = list(pool.map(prep, tasks))

    arrays: Dict[str, jax.Array] = {}
    validities: Dict[str, jax.Array] = {}
    dictionaries: Dict[str, tuple] = {}
    ragged: Dict[str, tuple] = {}
    rg_rows = [pf.row_group(i).num_rows for i in range(n_rg)]
    shard_counts = [sum(rg_rows[rg] for rg in range(n_rg)
                        if rg % len(devs) == d) for d in range(len(devs))]
    maxlen = max(shard_counts) if shard_counts else 0
    for leaf in leaves:
        is_ba = leaf.physical_type == Type.BYTE_ARRAY
        per_dev_vals: Dict[int, List[jax.Array]] = {}
        per_dev_valid: Dict[int, List[jax.Array]] = {}
        has_nulls = False
        ba_parts = []  # (rg, device, indices, validity, n_nulls) per row group
        ragged_parts = []  # (rg, device, bytes, slot_offsets, validity, n_nulls)
        dict_vals_parts: List[np.ndarray] = []
        dict_offs_parts: List[np.ndarray] = []
        for (prep_out, reader), (l2, rg) in zip(prepped, tasks):
            if l2 is not leaf:
                continue
            d = rg % len(devs)
            with jax.default_device(devs[d]):
                col, n_nulls = _decode_prepped(reader, prep_out)
                if is_ba:
                    if not col.is_dictionary_encoded():
                        # PLAIN chunk: ship the arrow ragged pair; slot
                        # alignment (nulls zero-length) happens on host at
                        # staging scale
                        ragged_parts.append(
                            (rg, d) + _slot_ragged(
                                np.asarray(col.values),
                                np.asarray(col.offsets, np.int64),
                                col.validity, n_nulls)
                            + (col.validity, n_nulls))
                        continue
                    dvals, doffs = col._host_dictionary()
                    dict_vals_parts.append(np.asarray(dvals))
                    dict_offs_parts.append(np.asarray(doffs, np.int64))
                    # index placement deferred until the dictionaries are
                    # unified below (ids must mean the same string on
                    # every shard for device-side filters/joins)
                    ba_parts.append((rg, d, col.dict_indices, col.validity,
                                     n_nulls))
                    continue
                vals = col.values
                if col.is_dictionary_encoded():
                    vals = dev.dict_gather(col.dictionary,
                                           col.dict_indices)
                if not isinstance(vals, jax.Array):
                    vals = jnp.asarray(vals)
                valid = col.validity
                if valid is not None and n_nulls:
                    if not isinstance(valid, jax.Array):
                        valid = jnp.asarray(valid)
                    vals = dev.scatter_valid(vals, valid)  # row-align
                    has_nulls = True
                elif valid is not None:
                    valid = None  # nullable schema, no actual nulls
            per_dev_vals.setdefault(d, []).append(vals)
            per_dev_valid.setdefault(d, []).append(valid)
        if is_ba and ragged_parts:
            if ba_parts:
                # mixed dictionary/plain chunks (pyarrow's mid-file
                # dictionary-overflow fallback): densify the dictionary
                # chunks host-side so the whole column ships ragged
                from ..ops import ref as _ref

                for (rg, d, idx, valid, n_nulls), dvals, doffs in zip(
                        ba_parts, dict_vals_parts, dict_offs_parts):
                    g = _ref.gather_dictionary(
                        (np.asarray(dvals), np.asarray(doffs, np.int64)),
                        np.asarray(idx, np.int64))
                    ragged_parts.append(
                        (rg, d) + _slot_ragged(np.asarray(g[0]),
                                               np.asarray(g[1], np.int64),
                                               valid, n_nulls)
                        + (valid, n_nulls))
                ba_parts = []
            per_dev_r: Dict[int, List[tuple]] = {}
            col_has_nulls = any(nn and v is not None
                                for *_, v, nn in ragged_parts)
            for rg, d, vb, so, valid, nn in sorted(ragged_parts,
                                                   key=lambda p: p[0]):
                per_dev_r.setdefault(d, []).append((vb, so, valid, nn))
            shard_bytes, shard_offs, shard_valids = [], [], []
            for d in range(len(devs)):
                parts = per_dev_r.get(d, [])
                b = (np.concatenate([p[0] for p in parts]) if parts
                     else np.zeros(0, np.uint8))
                off_parts = [np.zeros(1, np.int64)]
                base = 0
                for vb, so, _, _ in parts:
                    off_parts.append(so[1:] + base)
                    base += int(so[-1])
                o = np.concatenate(off_parts)
                if len(o) < maxlen + 1:  # padding rows are zero-length
                    o = np.concatenate(
                        [o, np.full(maxlen + 1 - len(o), o[-1], np.int64)])
                shard_bytes.append(b)
                shard_offs.append(o)
                if col_has_nulls:
                    vps = [np.asarray(v, bool) if v is not None and nn
                           else np.ones(len(so) - 1, bool)
                           for vb, so, v, nn in parts]
                    va = (np.concatenate(vps) if vps
                          else np.zeros(0, bool))
                    shard_valids.append(np.pad(va, (0, maxlen - len(va))))
            max_bytes = max((len(b) for b in shard_bytes), default=0) or 1
            gb, go, gv = [], [], []
            for d in range(len(devs)):
                with jax.default_device(devs[d]):
                    b = shard_bytes[d]
                    if len(b) < max_bytes:
                        b = np.pad(b, (0, max_bytes - len(b)))
                    gb.append(jax.device_put(jnp.asarray(b), devs[d]))
                    go.append(jax.device_put(jnp.asarray(shard_offs[d]),
                                             devs[d]))
                    if col_has_nulls:
                        gv.append(jax.device_put(
                            jnp.asarray(shard_valids[d]), devs[d]))
            sh1 = NamedSharding(mesh, P(mesh.axis_names[0]))
            ragged[leaf.dotted_path] = (
                jax.make_array_from_single_device_arrays(
                    (max_bytes * len(devs),), sh1, gb),
                jax.make_array_from_single_device_arrays(
                    ((maxlen + 1) * len(devs),), sh1, go))
            if col_has_nulls:
                validities[leaf.dotted_path] = \
                    jax.make_array_from_single_device_arrays(
                        (maxlen * len(devs),), sh1, gv)
            continue
        if is_ba and dict_vals_parts:
            uvals, uoffs, remap = _unify_dictionaries(dict_vals_parts,
                                                      dict_offs_parts)
            dictionaries[leaf.dotted_path] = (uvals, uoffs)
            base = 0
            for (rg, d, idx, valid, n_nulls), doffs in zip(ba_parts,
                                                           dict_offs_parts):
                n_i = len(doffs) - 1
                sub = remap[base:base + n_i].astype(np.int32)
                base += n_i
                with jax.default_device(devs[d]):
                    if isinstance(idx, jax.Array):  # device route: gather
                        vals = jnp.asarray(sub)[idx]
                    else:
                        vals = jnp.asarray(sub[np.asarray(idx, np.int64)])
                    if valid is not None and n_nulls:
                        if not isinstance(valid, jax.Array):
                            valid = jnp.asarray(valid)
                        vals = dev.scatter_valid(vals, valid)
                        has_nulls = True
                    else:
                        valid = None
                per_dev_vals.setdefault(d, []).append(vals)
                per_dev_valid.setdefault(d, []).append(valid)
        template = next(p[0] for p in per_dev_vals.values() if p)
        shard_arrays, shard_valid = [], []
        for d in range(len(devs)):
            parts = per_dev_vals.get(d, [])
            with jax.default_device(devs[d]):
                if parts:
                    arr = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
                else:  # more devices than row groups: typed empty shard
                    arr = jnp.zeros((0,) + tuple(template.shape[1:]),
                                    template.dtype)
                if arr.shape[0] < maxlen:
                    padw = [(0, maxlen - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                    arr = jnp.pad(arr, padw)
                shard_arrays.append(jax.device_put(arr, devs[d]))
                if has_nulls:
                    vparts = [v if v is not None else jnp.ones(p.shape[0], bool)
                              for v, p in zip(per_dev_valid.get(d, []), parts)]
                    va = (jnp.concatenate(vparts) if len(vparts) > 1
                          else vparts[0] if vparts else jnp.zeros(0, bool))
                    if va.shape[0] < maxlen:
                        va = jnp.pad(va, (0, maxlen - va.shape[0]))
                    shard_valid.append(jax.device_put(va, devs[d]))
        nd = shard_arrays[0].ndim
        sharding = NamedSharding(mesh, P(mesh.axis_names[0],
                                         *(None,) * (nd - 1)))
        global_shape = (maxlen * len(shard_arrays),) + tuple(shard_arrays[0].shape[1:])
        arrays[leaf.dotted_path] = jax.make_array_from_single_device_arrays(
            global_shape, sharding, shard_arrays)
        if has_nulls:
            vsharding = NamedSharding(mesh, P(mesh.axis_names[0]))
            validities[leaf.dotted_path] = \
                jax.make_array_from_single_device_arrays(
                    (maxlen * len(shard_valid),), vsharding, shard_valid)
    return ShardedTable(arrays=arrays, validity=validities,
                        row_counts=tuple(shard_counts), mesh=mesh,
                        dictionaries=dictionaries, ragged=ragged,
                        leaves={leaf.dotted_path: leaf for leaf in leaves})


# ---------------------------------------------------------------------------
# shard_map decode step — the pjit'd "training step" analog
# ---------------------------------------------------------------------------


def decode_step_sharded(mesh: Mesh, n_per_shard: int, axis: str = "data"):
    """Build a jitted, mesh-sharded batched decode step.

    Input: per-device staging buffers ``bytes_in [n_dev, B]`` (uint8, each
    device's batch of PLAIN INT64 page bytes), level buffers and run tables
    likewise stacked on the leading mesh axis.  Each device decodes its shard
    (bitcast + RLE def-level expand + validity + null scatter); a psum'd
    row-count rides the ICI as the collective (the "global row count" a
    distributed scan wants).  This is the full per-step compute of the decode
    "model" under real dp sharding.
    """
    from jax.experimental.shard_map import shard_map

    spec = P(axis)
    rep = P()

    def step(vbuf, lbuf, run_ends, run_kinds, run_payloads, run_offs, run_widths):
        # one device's shard: drop the leading axis of size 1
        vb = vbuf.reshape(vbuf.shape[-1])
        lb = lbuf.reshape(lbuf.shape[-1])
        pairs = dev.fixed64_pairs(vb, n_per_shard)
        defs = dev.rle_expand(lb, n_per_shard, run_ends.reshape(-1),
                              run_kinds.reshape(-1), run_payloads.reshape(-1),
                              run_offs.reshape(-1), run_widths.reshape(-1))
        validity = defs == 1
        lo = jnp.where(validity, pairs[:, 0], 0)
        hi = jnp.where(validity, pairs[:, 1], 0)
        nrows = jax.lax.psum(jnp.sum(validity.astype(jnp.int32)), axis)
        return lo[None], hi[None], validity[None], nrows

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=(spec, spec, spec, rep),
        check_rep=False)
    return jax.jit(sharded)


# ---------------------------------------------------------------------------
# Device-scale dataset reads — files round-robined over the mesh
# ---------------------------------------------------------------------------


def _overlap_enabled(n_files: int) -> bool:
    """PARQUET_TPU_DEVICE_OVERLAP: 0/off = stage then decode sequentially,
    auto = overlap when the shard has more than one file (a single file has
    no next stage to hide), force = always submit stage N+1 before decode
    N (chaos/identity tests pin both paths)."""
    mode = (env_str("PARQUET_TPU_DEVICE_OVERLAP") or "auto").strip().lower()
    if mode in ("0", "off", "false", "no"):
        return False
    if mode == "force":
        return True
    return n_files > 1


class _HostRoute(Exception):
    """Stage-phase verdict: this file must take the host path.  Carries the
    refusal reason/detail for ``device.route_refusals`` accounting."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason
        self.detail = detail


@dataclass
class _FileStage:
    """One file's staged device state: every (leaf, row-group) chunk
    prepared (prescan + H2D put targeted at ``device``), admission grant
    and ``device.staging`` ledger residency held until :meth:`release`."""

    index: int
    pf: ParquetFile
    leaves: list
    rg_sel: list
    device: object
    est_bytes: int
    grant: int
    preps: list = field(default_factory=list)
    _released: bool = False

    def release(self) -> None:
        from ..utils.pool import read_admission

        if self._released:
            return
        self._released = True
        _ACC_STAGING.sub(self.est_bytes)
        read_admission().release(self.grant, tier="scan")


def _stage_dataset_file(dataset, i: int, columns, device) -> _FileStage:
    """Host phase of one dataset file's device read, run on a shared-pool
    worker: admission under the unified read budget, chunk-range prefetch
    (advise-backed readahead under the prescan + H2D), and a batched
    ``prepare_chunks_batched`` over every (leaf, row-group) targeted at
    ``device`` — one H2D dispatch per file.  Raises
    ``_HostRoute`` when the static encoding scan refuses the file; a chunk
    the stage plan refuses individually records its error and decodes on
    host at decode time (parity with ``decode_chunks_pipelined``)."""
    import contextlib

    from ..io.planner import device_encoding_supported
    from ..io.prefetch import make_chunk_prefetcher
    from ..io.reader import _select_leaves
    from ..utils.pool import read_admission
    from .device_reader import prepare_chunks_batched

    pf = dataset.file(i)
    dataset._check_schema(pf, dataset.paths[i])
    ok, why = device_encoding_supported(pf, columns)
    if not ok:
        raise _HostRoute("unsupported", why)
    leaves = _select_leaves(pf.schema, columns)
    rg_sel = list(range(len(pf.metadata.row_groups or [])))
    chunks = [pf.row_group(g).column(leaf.column_index)
              for leaf in leaves for g in rg_sel]
    est = sum(int(r.byte_range[1]) for r in chunks)
    # raw page payloads queue under the unified read budget and sit in the
    # device.staging account until the decode phase consumed them
    grant = read_admission().acquire(est, tier="scan")
    _ACC_STAGING.add(est)
    st = _FileStage(index=i, pf=pf, leaves=leaves, rg_sel=rg_sel,
                    device=device, est_bytes=est, grant=grant)
    try:
        t0 = time.perf_counter()
        with contextlib.ExitStack() as stack:
            pre = make_chunk_prefetcher(pf.source,
                                        n_streams=min(len(chunks), 4) or 1)
            if pre is not None:
                stack.enter_context(pf._source_override(pre))
                stack.callback(pre.close)
                pre.plan_many(r.byte_range for r in chunks)
            # every chunk's streams ride ONE batched device_put at the
            # file's chip — per-chunk H2D dispatch overhead scales with
            # row-group count, and the mesh route amortizes it per file
            st.preps.extend(prepare_chunks_batched(chunks, device=device))
        _M_H2D_S.observe(time.perf_counter() - t0)
    except BaseException:
        st.release()
        raise
    return st


def _decode_dataset_file(st: _FileStage):
    """Device phase: decode every staged chunk of one file (host fallback
    per refused chunk) and assemble the same per-file Table
    ``ParquetFile.read(device=True)`` returns."""
    from ..io.column import empty_column
    from ..io.faults import read_context
    from ..io.planner import count_device_refusal
    from ..io.reader import Table, decode_chunk_host

    pf = st.pf
    if not st.rg_sel:
        return Table(pf.schema, {leaf.dotted_path: empty_column(leaf)
                                 for leaf in st.leaves}, 0)
    t0 = time.perf_counter()
    n_rg = len(st.rg_sel)
    it = iter(st.preps)
    parts: Dict[str, list] = {}
    with jax.default_device(st.device):
        for leaf in st.leaves:
            cols = []
            for _ in range(n_rg):
                reader, prep, err = next(it)
                with read_context(path=pf._path, row_group=reader.rg_index,
                                  column=reader.leaf.dotted_path):
                    if err is not None:
                        count_device_refusal("unsupported", str(err))
                        counters.inc("chunks_host_fallback")
                        col = decode_chunk_host(reader)
                    else:
                        col, _nn = _decode_prepped(reader, prep)
                cols.append(col)
            parts[leaf.dotted_path] = cols
    tbl = Table(pf.schema, None, pf.num_rows, parts=parts)
    _M_DECODE_S.observe(time.perf_counter() - t0)
    return tbl


def read_dataset_device(dataset, columns=None, with_reports: bool = False,
                        host_read=None, mesh: Optional[Mesh] = None,
                        axis: str = "data"):
    """Per-file results for ``Dataset.read(device=True)``, yielded in file
    order as the same ``(table, sub_report, rows, error)`` tuples the host
    fan-out produces — ``Dataset._read_all`` merges both identically, so
    byte identity with the host path is structural, per-file host fallback
    included.

    Files round-robin over the mesh devices: file i's chunks stage H2D at
    ``devices[i % n]`` — the ``Dataset.shard(i, n)`` split a multi-host
    fleet applies per process (:func:`dataset_process_shard`) applied once
    more, per chip, inside the process.  Each file's stage→decode chain
    runs as one shared-pool task pinned to its chip and, when
    :func:`_overlap_enabled` allows, up to a window of later files run
    ahead of the consume frontier — file i+1 stages (and its chip decodes)
    while file i's decode completes, the write path's encode/emit
    double-buffering applied at the device boundary.  A file the static
    encoding scan refuses, or whose
    stage/decode dies on corrupt data, reroutes to ``host_read`` (the
    caller's plain per-file host read — fault policy, retries, and
    row-group skip semantics all apply there), with the refusal counted in
    ``device.route_refusals``.  Measured mesh throughput feeds
    ``RouteHistory`` under the ``"device_mesh"`` route, bucketed by mesh
    size."""
    from ..errors import CorruptedError, DeadlineError
    from ..io.faults import NON_DATA_ERRORS, ReadReport
    from ..io.planner import count_device_refusal, route_history
    from ..obs.metrics import pool_wait_seconds

    from concurrent.futures import Future

    mesh = mesh or default_mesh(axis=axis)
    devs = list(mesh.devices.reshape(-1))
    n = len(dataset.paths)
    overlap = _overlap_enabled(n)
    # nested inside a shared-pool worker: stage inline — blocking on
    # fut.result() from one of the pool's own workers while the pool is
    # saturated is the deadlock map_in_order's nested-submit guard exists
    # for (overlap degrades to sequential; correctness is unchanged)
    inline = _pool.in_shared_pool()

    def _stage_decode(i, device):
        # one file's full device chain on a pool worker: stage (prefetch +
        # prescan + H2D put) then decode on the file's chip.  Running the
        # decode here too is what lets files on DIFFERENT chips decode
        # concurrently instead of serializing on the consumer thread.
        st = _stage_dataset_file(dataset, i, columns, device)
        try:
            return st, _decode_dataset_file(st)
        except BaseException:
            st.release()
            raise

    def _submit(i):
        if inline:
            f = Future()
            try:
                f.set_result(_stage_decode(i, devs[i % len(devs)]))
            # ptlint: disable=PT005 -- capture-and-forward: the error
            # resurfaces at the driver's futs.pop(i).result() call below
            except BaseException as e:
                f.set_exception(e)
            return f
        return _pool.submit(_stage_decode, i, devs[i % len(devs)])

    def _host_one(i, reason, detail):
        count_device_refusal(reason, detail)
        return host_read(i)

    device_bytes = 0
    t_start = time.perf_counter()
    w0 = pool_wait_seconds()
    # overlap keeps up to min(mesh, 4) files in flight ahead of the
    # consume frontier — one per chip up to a memory-bounding cap; results
    # are still consumed strictly in file order, and the admission gate
    # (not the window) is what bounds resident staged bytes under a budget
    window = min(len(devs), 4) if overlap else 1
    futs: Dict[int, object] = {}
    try:
        for i in range(n):
            for j in range(i, min(i + window, n)):
                if j not in futs:
                    futs[j] = _submit(j)
                    if j > i:
                        # file j runs ahead while file i is still in
                        # flight / being consumed: the overlap the knob
                        # turns off
                        _oaccount(_M_STAGE_OVERLAPPED)
            res = None
            refusal = None
            try:
                res = futs.pop(i).result()
            except _HostRoute as e:
                refusal = (e.reason, e.detail)
            except DeadlineError:
                raise
            except NON_DATA_ERRORS:
                raise
            except (CorruptedError, OSError) as e:
                refusal = ("error", str(e))
            if res is None:
                yield _host_one(i, *refusal)
            else:
                st, tbl = res
                st.release()
                _oaccount(_M_FILES_SHARDED)
                device_bytes += st.est_bytes
                sub = ReadReport() if with_reports else None
                yield tbl, sub, st.pf.num_rows, None
    finally:
        for f in futs.values():
            # abandoned in-flight files (consumer stopped early, or an
            # exception above): wait them out and hand back their grants
            try:
                f.result()[0].release()
            except Exception:
                pass
        elapsed = time.perf_counter() - t_start
        if device_bytes:
            route_history().observe("device_mesh", device_bytes, elapsed,
                                    pool_wait_s=pool_wait_seconds() - w0,
                                    mesh_size=len(devs))
