"""Multi-chip sharded reads over a jax.sharding.Mesh.

Reference parity: the reference's only parallelism is caller-driven goroutine
fan-out over row groups / column chunks (SURVEY.md §2.5).  The TPU-native
equivalent: a ``Mesh`` over chips, row groups round-robined across the
``data`` axis, per-chip staging + decode, and the decoded chunks exposed as
global sharded ``jax.Array``s (``make_array_from_single_device_arrays``), so
downstream pjit computations consume them without resharding.  Collectives
ride ICI only if a consumer asks for replication — decode itself is
embarrassingly parallel, exactly like the reference's design.

Also home of ``decode_step_sharded``: a ``shard_map``-based batched decode
step over a mesh (the "training step" analog, exercised by the driver's
``dryrun_multichip``).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..io.column import Column
from ..io.reader import ParquetFile
from ..ops import device as dev
from ..utils.debug import counters


def default_mesh(n: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def read_table_sharded(source, mesh: Optional[Mesh] = None,
                       columns: Optional[Sequence[str]] = None,
                       axis: str = "data") -> Dict[str, jax.Array]:
    """Read fixed-width columns of a file as row-sharded global jax.Arrays.

    Row groups are assigned round-robin to mesh devices; each device's chunks
    are decoded on that device (device_put targets the specific device), then
    stitched into one global array sharded along rows.  Ragged (byte-array)
    columns come back dictionary-encoded with sharded index arrays when
    possible, else host-side.
    """
    from .device_reader import decode_chunk_device

    mesh = mesh or default_mesh(axis=axis)
    devs = list(mesh.devices.reshape(-1))
    pf = source if isinstance(source, ParquetFile) else ParquetFile(source)
    leaves = (pf.schema.leaves if columns is None
              else [pf.schema.leaf(c) for c in columns])
    n_rg = len(pf.metadata.row_groups or [])
    out: Dict[str, jax.Array] = {}
    row_counts: Dict[str, List[int]] = {}
    for leaf in leaves:
        per_dev: Dict[int, List[np.ndarray]] = {i: [] for i in range(len(devs))}
        for rg in range(n_rg):
            d = rg % len(devs)
            with jax.default_device(devs[d]):
                col = decode_chunk_device(pf.row_group(rg).column(leaf.column_index))
            if col.is_dictionary_encoded():
                col.materialize_host()
            arr = col.values
            per_dev[d].append(arr if isinstance(arr, jax.Array) else jnp.asarray(arr))
        # per-device concat, then build the global sharded array
        shards = []
        for i in range(len(devs)):
            if not per_dev[i]:
                continue
            parts = per_dev[i]
            shard = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            shards.append(jax.device_put(shard, devs[i]))
        if not shards:
            continue
        lens = [s.shape[0] for s in shards]
        maxlen = max(lens)
        # pad shards to uniform length so a global sharded array exists;
        # callers get (array, row_counts) semantics via out["#rows"]
        padded = []
        for s in shards:
            if s.shape[0] < maxlen:
                pad = [(0, maxlen - s.shape[0])] + [(0, 0)] * (s.ndim - 1)
                s = jnp.pad(s, pad)
            padded.append(s)
        sharding = NamedSharding(mesh, P(mesh.axis_names[0],
                                         *(None,) * (padded[0].ndim - 1)))
        global_shape = (maxlen * len(padded),) + tuple(padded[0].shape[1:])
        arrs = [jax.device_put(p, d) for p, d in zip(padded, devs)]
        out[leaf.dotted_path] = jax.make_array_from_single_device_arrays(
            global_shape, sharding, arrs)
        row_counts[leaf.dotted_path] = lens
    return out, row_counts


# ---------------------------------------------------------------------------
# shard_map decode step — the pjit'd "training step" analog
# ---------------------------------------------------------------------------


def decode_step_sharded(mesh: Mesh, n_per_shard: int, axis: str = "data"):
    """Build a jitted, mesh-sharded batched decode step.

    Input: per-device staging buffers ``bytes_in [n_dev, B]`` (uint8, each
    device's batch of PLAIN INT64 page bytes), level buffers and run tables
    likewise stacked on the leading mesh axis.  Each device decodes its shard
    (bitcast + RLE def-level expand + validity + null scatter); a psum'd
    row-count rides the ICI as the collective (the "global row count" a
    distributed scan wants).  This is the full per-step compute of the decode
    "model" under real dp sharding.
    """
    from jax.experimental.shard_map import shard_map

    spec = P(axis)
    rep = P()

    def step(vbuf, lbuf, run_ends, run_kinds, run_payloads, run_offs, run_widths):
        # one device's shard: drop the leading axis of size 1
        vb = vbuf.reshape(vbuf.shape[-1])
        lb = lbuf.reshape(lbuf.shape[-1])
        pairs = dev.fixed64_pairs(vb, n_per_shard)
        defs = dev.rle_expand(lb, n_per_shard, run_ends.reshape(-1),
                              run_kinds.reshape(-1), run_payloads.reshape(-1),
                              run_offs.reshape(-1), run_widths.reshape(-1))
        validity = defs == 1
        lo = jnp.where(validity, pairs[:, 0], 0)
        hi = jnp.where(validity, pairs[:, 1], 0)
        nrows = jax.lax.psum(jnp.sum(validity.astype(jnp.int32)), axis)
        return lo[None], hi[None], validity[None], nrows

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=(spec, spec, spec, rep),
        check_rep=False)
    return jax.jit(sharded)
