"""Row model: Value / Row transport + full Dremel shredding and assembly.

Reference parity (SURVEY.md §2.1): ``value.go — Value, ValueReader,
ValueWriter, CopyValues`` (tagged scalar + def/rep levels + column index),
``row.go — Row, CopyRows, RowReader/RowWriter``, ``row_builder.go —
RowBuilder``, and the record-at-a-time ``schema.go — Schema.Deconstruct /
Schema.Reconstruct`` pair (SURVEY.md §3.1/§3.2).

The TPU framework is columnar-first: the vectorized level math in
``ops/levels.py`` covers the hot path.  This module is the *row transport*
layer on top of it — arbitrary-depth nested records (optional groups, lists
of lists, maps) shredded to per-leaf slot streams and back, one record at a
time, host-side.  ``columns_from_rows`` converts rows into the writer's
columnar form carrying raw def/rep level streams, which is also the only
write path for schemas deeper than one repeated level.

Record representation is plain Python: dicts for groups, lists for repeated
fields, ``None`` for nulls.  LIST/MAP logical wrappers accept/produce the
natural Python forms (a list / a dict) instead of the 3-level strict shape.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .format.enums import FieldRepetitionType as Rep, Type
from .schema.schema import Leaf, Node, Schema
from .schema.types import LogicalKind


# ---------------------------------------------------------------------------
# Value / Row
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Value:
    """One leaf slot: scalar payload + Dremel levels + column ordinal.

    ``value is None`` for null/absent slots; ``definition_level`` then records
    how deep the path was defined (which ancestor went null)."""

    column: int
    value: Any
    definition_level: int = 0
    repetition_level: int = 0

    @property
    def is_null(self) -> bool:
        return self.value is None

    def __repr__(self):
        return (f"Value(col={self.column}, {self.value!r}, "
                f"d={self.definition_level}, r={self.repetition_level})")


class Row(list):
    """A list of :class:`Value` slots, ordered by column then slot order."""

    def for_column(self, column: int) -> List[Value]:
        return [v for v in self if v.column == column]


# ---------------------------------------------------------------------------
# Chain math (per-leaf ancestor metadata)
# ---------------------------------------------------------------------------


@dataclass
class _Chain:
    leaf: Leaf
    nodes: Tuple[Node, ...]  # top-level field ... leaf (inclusive)
    cum_def: Tuple[int, ...]  # def level after *entering* nodes[i]
    cum_rep: Tuple[int, ...]  # rep level after entering nodes[i]
    rep_positions: Tuple[int, ...]  # chain indexes of REPEATED nodes
    rep_defs: Tuple[int, ...]  # cum_def at each repeated node (D_k)

    @property
    def max_def(self) -> int:
        return self.leaf.max_definition_level

    @property
    def max_rep(self) -> int:
        return self.leaf.max_repetition_level


def _chain_of(leaf: Leaf) -> _Chain:
    cd: List[int] = []
    cr: List[int] = []
    d = r = 0
    reps: List[int] = []
    rep_defs: List[int] = []
    for i, n in enumerate(leaf.ancestors):
        if n.repetition == Rep.OPTIONAL:
            d += 1
        elif n.repetition == Rep.REPEATED:
            d += 1
            r += 1
            reps.append(i)
            rep_defs.append(d)
        cd.append(d)
        cr.append(r)
    return _Chain(leaf, leaf.ancestors, tuple(cd), tuple(cr), tuple(reps),
                  tuple(rep_defs))


def _chains(schema: Schema) -> List[_Chain]:
    return [_chain_of(leaf) for leaf in schema.leaves]


# ---------------------------------------------------------------------------
# Deconstruct: record → per-leaf slot streams (Dremel shredding)
# ---------------------------------------------------------------------------


def _leaves_under(node: Node, schema: Schema) -> List[int]:
    """Column ordinals of all leaves in node's subtree (by identity walk)."""
    out: List[int] = []

    def walk(n: Node):
        if n.is_leaf:
            for leaf in schema.leaves:
                if leaf.node is n:
                    out.append(leaf.column_index)
                    return
        else:
            for c in n.children:
                walk(c)

    walk(node)
    return out


class _Shredder:
    def __init__(self, schema: Schema):
        self.schema = schema
        self.leaf_of_node: Dict[int, int] = {
            id(leaf.node): leaf.column_index for leaf in schema.leaves
        }
        self.subtree_leaves: Dict[int, List[int]] = {}
        # rep level of each REPEATED node (for non-first elements)
        self.rep_level_of: Dict[int, int] = {}
        for leaf in schema.leaves:
            chain = _chain_of(leaf)
            for i, n in enumerate(chain.nodes):
                if n.repetition == Rep.REPEATED:
                    self.rep_level_of[id(n)] = chain.cum_rep[i]

    def _subtree(self, node: Node) -> List[int]:
        key = id(node)
        if key not in self.subtree_leaves:
            self.subtree_leaves[key] = _leaves_under(node, self.schema)
        return self.subtree_leaves[key]

    def shred(self, record: Any) -> List[List[Tuple[Any, int, int]]]:
        out: List[List[Tuple[Any, int, int]]] = [[] for _ in self.schema.leaves]
        self._walk_children(self.schema.root, record, 0, 0, out)
        return out

    # -- helpers ------------------------------------------------------------
    def _emit_nulls(self, node: Node, d: int, r: int, out) -> None:
        for col in self._subtree(node):
            out[col].append((None, d, r))

    def _strict(self, node: Node, value: Any) -> Any:
        """Convert LIST/MAP Python sugar into the strict tree shape."""
        if value is None or node.is_leaf:
            return value
        if node.logical_kind == LogicalKind.LIST and not isinstance(value, dict):
            if not isinstance(value, (list, tuple)):
                raise TypeError(
                    f"LIST field {node.name!r} expects a list, "
                    f"got {type(value).__name__}")
            inner = node.children[0]  # repeated group "list" (or legacy)
            if inner.repetition == Rep.REPEATED:
                if inner.is_leaf or len(inner.children or ()) != 1:
                    return {inner.name: list(value)}
                elem = inner.children[0]
                return {inner.name: [{elem.name: v} for v in value]}
        if node.logical_kind == LogicalKind.MAP and isinstance(value, dict):
            inner = node.children[0]  # repeated group key_value
            if inner.repetition == Rep.REPEATED and not inner.is_leaf:
                kname = inner.children[0].name
                vname = inner.children[1].name if len(inner.children) > 1 else "value"
                if set(value.keys()) == {inner.name} and isinstance(
                        value[inner.name], (list, tuple)) and all(
                        isinstance(e, dict) and kname in e
                        for e in value[inner.name]):
                    return value  # already the strict 3-level shape
                return {inner.name: [{kname: k, vname: v} for k, v in value.items()]}
        return value

    def _walk_children(self, node: Node, value: Any, d: int, r: int, out):
        value = self._strict(node, value)
        if not isinstance(value, dict):
            raise TypeError(
                f"group {node.name!r} expects a dict record, got {type(value).__name__}")
        for child in node.children:
            cv = value.get(child.name)
            if child.repetition == Rep.REPEATED:
                self._shred_repeated(child, cv, d, r, out)
            else:
                self._shred_node(child, cv, d, r, out)

    def _shred_node(self, node: Node, value: Any, d: int, r: int, out):
        if node.repetition == Rep.OPTIONAL:
            if value is None:
                self._emit_nulls(node, d, r, out)
                return
            d += 1
        elif value is None and node.is_leaf:
            raise ValueError(f"required leaf {node.name!r} is None")
        if node.is_leaf:
            out[self.leaf_of_node[id(node)]].append((value, d, r))
        else:
            self._walk_children(node, value, d, r, out)

    def _shred_repeated(self, node: Node, elems: Any, d: int, r: int, out):
        if elems is None:
            elems = []
        if not isinstance(elems, (list, tuple)):
            raise TypeError(
                f"repeated field {node.name!r} expects a list, got {type(elems).__name__}")
        if len(elems) == 0:
            self._emit_nulls(node, d, r, out)
            return
        own_rep = self.rep_level_of[id(node)]
        for i, e in enumerate(elems):
            ri = r if i == 0 else own_rep
            if node.is_leaf:
                if e is None:
                    raise ValueError(
                        f"repeated leaf {node.name!r} cannot hold null elements")
                out[self.leaf_of_node[id(node)]].append((e, d + 1, ri))
            else:
                self._walk_children(node, e, d + 1, ri, out)

def _shredder_of(schema: Schema) -> _Shredder:
    """Per-schema cached shredder (rebuilding caches per record is pure
    overhead in the write hot path)."""
    s = getattr(schema, "_row_shredder", None)
    if s is None or s.schema is not schema:
        s = _Shredder(schema)
        schema._row_shredder = s
    return s


def deconstruct(schema: Schema, record: Any) -> Row:
    """Shred one record into a :class:`Row` of leaf slots (Dremel encode)."""
    slots = _shredder_of(schema).shred(record)
    row = Row()
    for col, lst in enumerate(slots):
        for (v, d, r) in lst:
            row.append(Value(column=col, value=v, definition_level=d,
                             repetition_level=r))
    return row


# ---------------------------------------------------------------------------
# Reconstruct: per-leaf slot streams → record (Dremel assembly)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _Null:
    """Skeleton marker: path defined down to def level ``depth`` only."""

    depth: int


def _skeleton(chain: _Chain, slots: Sequence[Tuple[Any, int, int]]) -> Any:
    """Assemble ONE row's slots of ONE leaf into a nested-list skeleton.

    Lists appear only at REPEATED chain nodes; groups/optionals are collapsed
    (their nullness is preserved in :class:`_Null` payload depths)."""
    R = len(chain.rep_positions)
    D = chain.rep_defs  # 1-based via D[k-1]
    max_def = chain.max_def
    holder: List[Any] = []
    lists: List[Any] = [holder] + [None] * R
    for (v, d, r) in slots:
        k = r + 1
        while True:
            if k > R:
                lists[R].append(v if d == max_def else _Null(d))
                break
            parent = lists[k - 1]
            if d >= D[k - 1] - 1:
                new: List[Any] = []
                parent.append(new)
                lists[k] = new
                if d >= D[k - 1]:
                    k += 1
                    continue
                break  # empty list
            parent.append(_Null(d))
            break
    return holder[0] if holder else _Null(0)


class _Assembler:
    def __init__(self, schema: Schema):
        self.schema = schema
        self.chains = _chains(schema)
        self._sub: Dict[int, List[int]] = {}

    def _subtree(self, node: Node) -> List[int]:
        key = id(node)
        if key not in self._sub:
            self._sub[key] = _leaves_under(node, self.schema)
        return self._sub[key]

    def assemble(self, row: Row) -> Dict[str, Any]:
        by_col: List[List[Tuple[Any, int, int]]] = [[] for _ in self.chains]
        for v in row:  # single pass, not a rescan per column
            by_col[v.column].append(
                (v.value, v.definition_level, v.repetition_level))
        parts = {chain.leaf.column_index: _skeleton(chain, by_col[i])
                 for i, chain in enumerate(self.chains)}
        return self._merge_children(self.schema.root, parts, 0)

    # -- merge --------------------------------------------------------------
    def _merge_children(self, node: Node, parts: Dict[int, Any], d: int):
        out: Dict[str, Any] = {}
        for child in node.children:
            cols = self._subtree(child)
            cp = {c: parts[c] for c in cols}
            if child.repetition == Rep.REPEATED:
                out[child.name] = self._merge_repeated(child, cp, d)
            elif child.is_leaf:
                out[child.name] = _payload(cp[cols[0]], child)
            else:
                dc = d + (1 if child.repetition == Rep.OPTIONAL else 0)
                if child.repetition == Rep.OPTIONAL and all(
                        isinstance(s, _Null) and s.depth < dc
                        for s in cp.values()):
                    out[child.name] = None
                else:
                    out[child.name] = self._merge_children(child, cp, dc)
        return self._sugar(node, out)

    def _merge_repeated(self, child: Node, cp: Dict[int, Any], d: int):
        skels = list(cp.values())
        n = len(skels[0])
        if any(len(s) != n for s in skels):
            raise ValueError(
                f"misaligned repetition under {child.name!r}: "
                f"{[len(s) for s in skels]}")
        cols = list(cp.keys())
        if child.is_leaf:
            return [_payload(e, child) for e in cp[cols[0]]]
        dk = d + 1
        out = []
        for i in range(n):
            ep = {c: cp[c][i] for c in cols}
            if all(isinstance(s, _Null) for s in ep.values()):
                # element exists but its content subtree is absent (an optional
                # group directly under the repeated node went null)
                out.append(self._null_element(child, ep, dk))
            else:
                out.append(self._merge_children(child, ep, dk))
        return out

    def _null_element(self, child: Node, ep: Dict[int, Any], dk: int):
        # distinguish "element is an all-null group" from deeper nulls
        if all(s.depth < dk for s in ep.values()):
            return None
        return self._merge_children(child, ep, dk)

    def _sugar(self, node: Node, out: Dict[str, Any]):
        if node.logical_kind == LogicalKind.LIST and len(out) == 1:
            inner_node = node.children[0]
            inner = next(iter(out.values()))
            if inner_node.repetition == Rep.REPEATED and isinstance(inner, list):
                if (not inner_node.is_leaf and inner_node.children is not None
                        and len(inner_node.children) == 1):
                    ename = inner_node.children[0].name
                    return [None if e is None else e[ename] for e in inner]
                return inner
        if node.logical_kind == LogicalKind.MAP and len(out) == 1:
            inner_node = node.children[0]
            inner = next(iter(out.values()))
            if (inner_node.repetition == Rep.REPEATED and isinstance(inner, list)
                    and not inner_node.is_leaf and len(inner_node.children) >= 2):
                kname = inner_node.children[0].name
                vname = inner_node.children[1].name
                return {e[kname]: e[vname] for e in inner if e is not None}
        return out


def _payload(skel: Any, node: Node):
    if isinstance(skel, _Null):
        return None
    if isinstance(skel, (bytes, bytearray, np.bytes_)):
        if node.logical_kind in (LogicalKind.STRING, LogicalKind.ENUM,
                                 LogicalKind.JSON):
            return bytes(skel).decode("utf-8")
        return bytes(skel)
    if isinstance(skel, np.generic):
        return skel.item()
    return skel


def _assembler_of(schema: Schema) -> "_Assembler":
    """Per-schema cached assembler (mirror of :func:`_shredder_of` on the
    read side — rebuilding the chains/subtree cache per record is overhead)."""
    a = getattr(schema, "_row_assembler", None)
    if a is None or a.schema is not schema:
        a = _Assembler(schema)
        schema._row_assembler = a
    return a


def reconstruct(schema: Schema, row: Row) -> Dict[str, Any]:
    """Assemble one :class:`Row` of leaf slots back into a record (Dremel
    decode) — the inverse of :func:`deconstruct`."""
    return _assembler_of(schema).assemble(row)


# ---------------------------------------------------------------------------
# RowBuilder
# ---------------------------------------------------------------------------


class RowBuilder:
    """Build rows field-by-field (reference: ``row_builder.go — RowBuilder``).

    ``set`` accepts dotted paths for nested fields; ``row()`` shreds the
    accumulated record and resets the builder."""

    def __init__(self, schema: Schema):
        self.schema = schema
        self._record: Dict[str, Any] = {}

    def set(self, path: str, value: Any) -> "RowBuilder":
        parts = path.split(".")
        cur = self._record
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = value
        return self

    def update(self, record: Dict[str, Any]) -> "RowBuilder":
        self._record.update(record)
        return self

    def row(self) -> Row:
        r = deconstruct(self.schema, self._record)
        self._record = {}
        return r


# ---------------------------------------------------------------------------
# Rows ↔ columnar conversion (bridge to the writer/reader)
# ---------------------------------------------------------------------------


def columns_from_rows(schema: Schema, rows: Iterable[Row]):
    """Convert rows → per-leaf ``ColumnData`` with raw def/rep level streams.

    Returns ``(columns: Dict[path, ColumnData], num_rows)``.  This is the
    write path for arbitrarily nested schemas (the vectorized ColumnData
    builders cover flat + single-level lists only)."""
    from .io.writer import ColumnData
    from .schema import types as _types

    chains = _chains(schema)
    per_leaf: List[List[Tuple[Any, int, int]]] = [[] for _ in schema.leaves]
    num_rows = 0
    for row in rows:
        num_rows += 1
        for v in row:
            per_leaf[v.column].append(
                (v.value, v.definition_level, v.repetition_level))
    columns: Dict[str, ColumnData] = {}
    for chain, slots in zip(chains, per_leaf):
        leaf = chain.leaf
        max_def, max_rep = chain.max_def, chain.max_rep
        defs = np.fromiter((d for (_, d, _) in slots), np.int32, len(slots))
        reps = np.fromiter((r for (_, _, r) in slots), np.int32, len(slots))
        present = [v for (v, d, _) in slots if d == max_def]
        values, offsets = _dense_values(leaf, present)
        cd = ColumnData(values=values, offsets=offsets)
        if max_def > 0:
            cd.def_levels = defs
        if max_rep > 0:
            cd.rep_levels = reps
        if max_def > 0 and max_rep == 0:
            cd.validity = defs == max_def
        columns[leaf.dotted_path] = cd
    return columns, num_rows


def _dense_values(leaf: Leaf, present: List[Any]):
    phys = leaf.physical_type
    if phys == Type.BYTE_ARRAY:
        enc = [v.encode("utf-8") if isinstance(v, str) else bytes(v)
               for v in present]
        offsets = np.zeros(len(enc) + 1, np.int64)
        if enc:
            np.cumsum([len(b) for b in enc], out=offsets[1:])
        values = np.frombuffer(b"".join(enc), np.uint8).copy()
        return values, offsets
    if phys == Type.FIXED_LEN_BYTE_ARRAY:
        w = leaf.type_length or 0
        parts = []
        for v in present:
            b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
            if len(b) > w:
                raise ValueError(
                    f"field {leaf.dotted_path!r}: FIXED_LEN_BYTE_ARRAY({w}) "
                    f"value has {len(b)} bytes")
            parts.append(b.ljust(w, b"\0"))
        buf = b"".join(parts)
        return np.frombuffer(buf, np.uint8).reshape(-1, w).copy(), None
    if phys == Type.INT96:
        arr = np.zeros((len(present), 3), np.uint32)
        for i, v in enumerate(present):
            iv = int(v)
            arr[i, 0] = iv & 0xFFFFFFFF
            arr[i, 1] = (iv >> 32) & 0xFFFFFFFF
            arr[i, 2] = (iv >> 64) & 0xFFFFFFFF
        return arr, None
    np_dt = {Type.BOOLEAN: np.bool_, Type.INT32: np.int32, Type.INT64: np.int64,
             Type.FLOAT: np.float32, Type.DOUBLE: np.float64}[phys]
    if leaf.logical_kind == LogicalKind.INT and not leaf.logical_params.get(
            "signed", True):
        np_dt = {Type.INT32: np.uint32, Type.INT64: np.uint64}.get(phys, np_dt)
    return np.asarray(present, dtype=np_dt), None


def rows_from_columns(schema: Schema, columns: Dict[str, "object"],
                      num_rows: int) -> Iterator[Row]:
    """Iterate rows out of decoded :class:`~parquet_tpu.io.column.Column`s.

    Requires columns decoded with raw level streams attached (the host decode
    path sets them); flat columns fall back to validity masks."""
    per_leaf_slots: List[List[Tuple[Any, int, int]]] = []
    chains = _chains(schema)
    for chain in chains:
        col = columns[chain.leaf.dotted_path]
        per_leaf_slots.append(_column_slots(chain, col))
    # row boundaries: slots with rep == 0 (or every slot for flat leaves)
    cursors = [0] * len(chains)
    for _ in range(num_rows):
        row = Row()
        for ci, (chain, slots) in enumerate(zip(chains, per_leaf_slots)):
            i = cursors[ci]
            n = len(slots)
            j = i + 1
            if chain.max_rep > 0:
                while j < n and slots[j][2] != 0:
                    j += 1
            for (v, d, r) in slots[i:j]:
                row.append(Value(column=chain.leaf.column_index, value=v,
                                 definition_level=d, repetition_level=r))
            cursors[ci] = j
        yield row


def _column_slots(chain: _Chain, col) -> List[Tuple[Any, int, int]]:
    leaf = chain.leaf
    defs = getattr(col, "def_levels", None)
    reps = getattr(col, "rep_levels", None)
    values = _host_values(col, leaf)
    max_def = chain.max_def
    if defs is None:
        validity = None if col.validity is None else np.asarray(col.validity)
        n = col.num_slots or (len(validity) if validity is not None else len(values))
        out: List[Tuple[Any, int, int]] = []
        vi = 0
        for i in range(n):
            if validity is None or validity[i]:
                out.append((values[vi], max_def, 0))
                vi += 1
            else:
                out.append((None, max_def - 1, 0))
        return out
    defs = np.asarray(defs)
    reps = (np.asarray(reps) if reps is not None
            else np.zeros(len(defs), np.int32))
    out = []
    vi = 0
    for d, r in zip(defs.tolist(), reps.tolist()):
        if d == max_def:
            out.append((values[vi], int(d), int(r)))
            vi += 1
        else:
            out.append((None, int(d), int(r)))
    return out


def _host_values(col, leaf: Leaf) -> List[Any]:
    if col.is_dictionary_encoded():
        col.materialize_host()
    values = np.asarray(col.values)
    if values.ndim == 2 and values.dtype == np.uint32 and values.shape[1] == 2:
        host_dt = {Type.INT64: np.int64, Type.DOUBLE: np.float64}.get(
            leaf.physical_type, np.int64)
        values = np.ascontiguousarray(values).view(host_dt).reshape(-1)
    if (leaf.logical_kind == LogicalKind.INT
            and not leaf.logical_params.get("signed", True)
            and values.dtype in (np.int32, np.int64)):
        values = values.view({np.dtype(np.int32): np.uint32,
                              np.dtype(np.int64): np.uint64}[values.dtype])
    if col.offsets is not None:
        offs = np.asarray(col.offsets, np.int64)
        raw = values
        return [raw[offs[i]:offs[i + 1]].tobytes() for i in range(len(offs) - 1)]
    if leaf.physical_type == Type.FIXED_LEN_BYTE_ARRAY and values.ndim == 2:
        return [values[i].tobytes() for i in range(len(values))]
    if leaf.physical_type == Type.INT96 and values.ndim == 2:
        return [int(values[i, 0]) | (int(values[i, 1]) << 32)
                | (int(values[i, 2]) << 64) for i in range(len(values))]
    return list(values)


# ---------------------------------------------------------------------------
# RowReader / RowWriter transport (reference: row.go — CopyRows)
# ---------------------------------------------------------------------------


class RowReader:
    """Anything with ``read_rows(n) -> List[Row]`` (empty list = EOF)."""

    def read_rows(self, n: int) -> List[Row]:  # pragma: no cover - interface
        raise NotImplementedError


class RowWriter:
    """Anything with ``write_rows(rows: List[Row]) -> int``."""

    def write_rows(self, rows: List[Row]) -> int:  # pragma: no cover
        raise NotImplementedError


class FileRows(RowReader):
    """Row cursor over a ParquetFile (decodes row groups host-side)."""

    def __init__(self, pf):
        self.pf = pf
        self.schema = pf.schema
        self._rg = 0
        self._iter: Optional[Iterator[Row]] = None

    def _next_group(self) -> bool:
        from .io.reader import decode_chunk_host

        if self._rg >= len(self.pf.row_groups):
            return False
        rg = self.pf.row_group(self._rg)
        self._rg += 1
        cols = {}
        for i, leaf in enumerate(self.schema.leaves):
            cols[leaf.dotted_path] = decode_chunk_host(rg.column(i))
        self._iter = rows_from_columns(self.schema, cols, rg.num_rows)
        return True

    def seek_to_row(self, row: int) -> None:
        """Position the cursor at global row ``row`` (reference parity:
        ``Rows.SeekToRow``).  Decodes the pages covering [row, end of its
        row group) per column and trims level streams to the exact row —
        with a page index, page selection skips everything before the
        target; without one (pyarrow's write default) the whole group's
        pages decode, since no page boundaries are known.  Seeking at or
        past the end leaves the cursor at EOF."""
        if row < 0:
            raise ValueError("row must be >= 0")
        base = 0
        for i in range(len(self.pf.row_groups)):
            rg = self.pf.row_group(i)
            nr = rg.num_rows
            if row < base + nr:
                offset = row - base
                if offset == 0:
                    self._rg = i  # decode lazily at the first read_rows
                    self._iter = None
                    return
                self._rg = i + 1  # read_rows resumes at the next group
                from .io.reader import decode_chunk_host
                from .io.search import pages_and_base
                from .io.stream import _slice_rows, piece_from_column

                cols = {}
                for j, leaf in enumerate(self.schema.leaves):
                    chunk = rg.column(j)
                    pages, first = pages_and_base(chunk, offset, nr)
                    piece = piece_from_column(
                        decode_chunk_host(chunk, pages=iter(pages)))
                    cols[leaf.dotted_path] = _slice_rows(
                        piece, offset - first, piece.rows)
                self._iter = rows_from_columns(self.schema, cols,
                                               nr - offset)
                return
            base += nr
        self._rg = len(self.pf.row_groups)
        self._iter = None

    def read_rows(self, n: int) -> List[Row]:
        out: List[Row] = []
        while len(out) < n:
            if self._iter is None and not self._next_group():
                break
            assert self._iter is not None
            got = False
            for row in self._iter:
                out.append(row)
                got = True
                if len(out) >= n:
                    break
            if len(out) < n or not got:
                self._iter = None
        return out

    def __iter__(self) -> Iterator[Row]:
        while True:
            batch = self.read_rows(1024)
            if not batch:
                return
            yield from batch


class BufferRows(RowReader):
    """RowReader over an in-memory list of rows."""

    def __init__(self, rows: Sequence[Row]):
        self._rows = list(rows)
        self._pos = 0

    def read_rows(self, n: int) -> List[Row]:
        out = self._rows[self._pos:self._pos + n]
        self._pos += len(out)
        return list(out)


class WriterRows(RowWriter):
    """RowWriter adapter over a ParquetWriter: buffers rows, flushes row
    groups at ``row_group_size`` (reference: GenericWriter[T].Write)."""

    def __init__(self, writer, schema: Optional[Schema] = None):
        self.writer = writer
        self.schema = schema or writer.schema
        self._rows: List[Row] = []

    def write_rows(self, rows: List[Row]) -> int:
        self._rows.extend(rows)
        limit = self.writer.options.row_group_size
        while len(self._rows) >= limit:
            self._flush(self._rows[:limit])
            self._rows = self._rows[limit:]
        return len(rows)

    def _flush(self, rows: List[Row]) -> None:
        if not rows:
            return
        columns, n = columns_from_rows(self.schema, rows)
        self.writer.write_row_group(columns, n)

    def flush(self) -> None:
        self._flush(self._rows)
        self._rows = []

    def close(self) -> None:
        self.flush()
        self.writer.close()


def copy_rows(dst: RowWriter, src: RowReader, batch: int = 4096) -> int:
    """Stream all rows from ``src`` into ``dst`` (reference: CopyRows)."""
    total = 0
    while True:
        rows = src.read_rows(batch)
        if not rows:
            break
        total += dst.write_rows(rows)
    if hasattr(dst, "flush"):
        dst.flush()
    return total


# ---------------------------------------------------------------------------
# Convenience front ends
# ---------------------------------------------------------------------------


def write_rows(sink, schema: Schema, records: Iterable[Dict[str, Any]],
               options=None):
    """Write an iterable of Python records to a Parquet file via the row
    path (supports arbitrary nesting).  Returns the closed writer (like
    :func:`~parquet_tpu.io.writer.write_table`), whose ``write_stats``
    meters the encode/emit pipeline — the row path rides the same
    double-buffered ``write_row_group`` as the columnar front ends."""
    from .io.writer import ParquetWriter, WriterOptions

    w = ParquetWriter(sink, schema, options or WriterOptions())
    try:
        rw = WriterRows(w, schema)
        for rec in records:
            rw.write_rows([deconstruct(schema, rec)])
        rw.close()
    except BaseException:
        w.abort()  # path sinks unlink their temp/partial file
        raise
    return w


def read_rows(source) -> Iterator[Dict[str, Any]]:
    """Iterate records from a Parquet file via the row path."""
    from .io.reader import ParquetFile

    pf = source if hasattr(source, "row_group") else ParquetFile(source)
    asm = _Assembler(pf.schema)
    for row in FileRows(pf):
        yield asm.assemble(row)
