"""Schema tree + Dremel level math.

Reference parity: ``schema.go — Schema, SchemaOf, Deconstruct, Reconstruct`` and
``node.go — Node, Group, Optional/Repeated/Required`` (SURVEY.md §1 L5).  The
flat ``FileMetaData.schema`` element list is parsed into a tree; each leaf gets
its column ordinal, dotted path, and max definition/repetition levels — the
inputs to the vectorized Dremel assembly in ``ops/levels.py`` (the reference
does record-at-a-time Reconstruct; we do whole-column vector math instead,
which is the TPU-friendly formulation).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..errors import ColumnTooDeepError, MAX_COLUMN_DEPTH
from ..format import enums, metadata as md
from ..format.enums import FieldRepetitionType as Rep, Type
from . import types as _types
from .types import LogicalKind


@dataclass
class Node:
    """One element of the schema tree (group or leaf)."""

    name: str
    repetition: Rep = Rep.REQUIRED
    # leaf fields
    physical_type: Optional[Type] = None
    type_length: Optional[int] = None  # FIXED_LEN_BYTE_ARRAY width
    logical_kind: str = LogicalKind.NONE
    logical_params: dict = field(default_factory=dict)
    # group fields
    children: Optional[List["Node"]] = None
    field_id: Optional[int] = None
    element: Optional[md.SchemaElement] = None  # original, when read from a file

    @property
    def is_leaf(self) -> bool:
        return self.children is None

    def __repr__(self):
        if self.is_leaf:
            return (
                f"Leaf({self.name!r}, {Type(self.physical_type).name}, "
                f"{Rep(self.repetition).name}, {self.logical_kind})"
            )
        return f"Group({self.name!r}, {Rep(self.repetition).name}, {len(self.children)} children)"


@dataclass
class Leaf:
    """Flattened leaf info: everything the column decoder needs."""

    column_index: int
    path: Tuple[str, ...]
    node: Node
    max_definition_level: int
    max_repetition_level: int
    # definition level at which this leaf's *value* is present (== max_def)
    # and the list of (def_level, rep_level) of each ancestor, for assembly
    ancestors: Tuple[Node, ...] = ()

    @property
    def physical_type(self) -> Type:
        return self.node.physical_type

    @property
    def type_length(self):
        return self.node.type_length

    @property
    def logical_kind(self):
        return self.node.logical_kind

    @property
    def logical_params(self):
        return self.node.logical_params

    @property
    def dotted_path(self) -> str:
        return ".".join(self.path)

    def np_dtype(self):
        return _types.logical_np_dtype(
            self.node.physical_type,
            self.node.logical_kind,
            self.node.logical_params,
            self.node.type_length,
        )


class Schema:
    """Parsed schema tree with per-leaf Dremel levels.

    Construct via :meth:`from_elements` (reading) or :meth:`from_node`
    (writing), or the :func:`schema_of` builder helpers below.
    """

    def __init__(self, root: Node):
        self.root = root
        self.leaves: List[Leaf] = []
        self._by_path: Dict[Tuple[str, ...], Leaf] = {}
        self._walk(root, (), 0, 0, ())
        for i, leaf in enumerate(self.leaves):
            leaf.column_index = i
            self._by_path[leaf.path] = leaf

    def _walk(self, n: Node, path, def_level, rep_level, ancestors):
        if n is not self.root:
            if n.repetition == Rep.OPTIONAL:
                def_level += 1
            elif n.repetition == Rep.REPEATED:
                def_level += 1
                rep_level += 1
            path = path + (n.name,)
            ancestors = ancestors + (n,)
        if n.is_leaf:
            if len(path) > MAX_COLUMN_DEPTH:
                raise ColumnTooDeepError(
                    f"column {'.'.join(path)!r} is {len(path)} levels deep "
                    f"(limit {MAX_COLUMN_DEPTH})")
            self.leaves.append(Leaf(-1, path, n, def_level, rep_level, ancestors))
        else:
            for c in n.children:
                self._walk(c, path, def_level, rep_level, ancestors)

    def leaf(self, path) -> Leaf:
        if isinstance(path, str):
            path = tuple(path.split("."))
        path = tuple(path)
        hit = self._by_path.get(path)
        if hit is not None:
            return hit
        # a group prefix (e.g. the list column name without ".list.element")
        # resolves when it names exactly one leaf
        under = [l for l in self.leaves if l.path[: len(path)] == path]
        if len(under) == 1:
            return under[0]
        raise KeyError(path)

    def __len__(self):
        return len(self.leaves)

    # ------------------------------------------------------------------ read
    @classmethod
    def from_elements(cls, elements: List[md.SchemaElement]) -> "Schema":
        """Parse the flat, depth-first FileMetaData.schema list into a tree."""
        pos = [0]

        def build() -> Node:
            el = elements[pos[0]]
            pos[0] += 1
            rep = Rep(el.repetition_type) if el.repetition_type is not None else Rep.REQUIRED
            if el.num_children:
                children = [build() for _ in range(el.num_children)]
                kind, params = _types._logical_from_element(el)
                return Node(
                    name=el.name or "",
                    repetition=rep,
                    children=children,
                    field_id=el.field_id,
                    logical_kind=kind,
                    logical_params=params,
                    element=el,
                )
            kind, params = _types._logical_from_element(el)
            return Node(
                name=el.name or "",
                repetition=rep,
                physical_type=Type(el.type),
                type_length=el.type_length,
                logical_kind=kind,
                logical_params=params,
                field_id=el.field_id,
                element=el,
            )

        root = build()
        if pos[0] != len(elements):
            raise ValueError(
                f"schema element list malformed: consumed {pos[0]} of {len(elements)}"
            )
        return cls(root)

    # ----------------------------------------------------------------- write
    def to_elements(self) -> List[md.SchemaElement]:
        out: List[md.SchemaElement] = []

        def emit(n: Node, is_root: bool):
            el = md.SchemaElement(name=n.name)
            if not is_root:
                el.repetition_type = int(n.repetition)
            if n.field_id is not None:
                el.field_id = n.field_id
            if n.is_leaf:
                el.type = int(n.physical_type)
                if n.physical_type == Type.FIXED_LEN_BYTE_ARRAY:
                    el.type_length = n.type_length
                el.logicalType, el.converted_type, extra = _logical_to_thrift(
                    n.logical_kind, n.logical_params
                )
                if extra:
                    el.scale = extra.get("scale")
                    el.precision = extra.get("precision")
            else:
                el.num_children = len(n.children)
                el.logicalType, el.converted_type, _ = _logical_to_thrift(
                    n.logical_kind, n.logical_params
                )
            out.append(el)
            if not n.is_leaf:
                for c in n.children:
                    emit(c, False)

        emit(self.root, True)
        return out

    def __repr__(self):
        lines = []

        def p(n, indent, is_root):
            rep = "" if is_root else Rep(n.repetition).name.lower() + " "
            if n.is_leaf:
                lt = f" ({n.logical_kind})" if n.logical_kind != LogicalKind.NONE else ""
                lines.append(f"{'  '*indent}{rep}{Type(n.physical_type).name} {n.name}{lt};")
            else:
                kw = "message" if is_root else "group"
                lines.append(f"{'  '*indent}{rep}{kw} {n.name} {{")
                for c in n.children:
                    p(c, indent + 1, False)
                lines.append(f"{'  '*indent}}}")

        p(self.root, 0, True)
        return "\n".join(lines)


def _logical_to_thrift(kind: str, params: dict):
    """Map normalized logical kind → (LogicalType, converted_type, extra)."""
    L, C = md.LogicalType, enums.ConvertedType
    K = LogicalKind
    if kind == K.NONE:
        return None, None, None
    if kind == K.STRING:
        return L(STRING=md.StringType()), int(C.UTF8), None
    if kind == K.ENUM:
        return L(ENUM=md.EnumType()), int(C.ENUM), None
    if kind == K.JSON:
        return L(JSON=md.JsonType()), int(C.JSON), None
    if kind == K.BSON:
        return L(BSON=md.BsonType()), int(C.BSON), None
    if kind == K.UUID:
        return L(UUID=md.UUIDType()), None, None
    if kind == K.FLOAT16:
        return L(FLOAT16=md.Float16Type()), None, None
    if kind == K.DATE:
        return L(DATE=md.DateType()), int(C.DATE), None
    if kind == K.DECIMAL:
        return (
            L(DECIMAL=md.DecimalType(scale=params.get("scale", 0),
                                     precision=params.get("precision", 0))),
            int(C.DECIMAL),
            {"scale": params.get("scale", 0), "precision": params.get("precision", 0)},
        )
    if kind == K.INTERVAL:
        return None, int(C.INTERVAL), None
    if kind == K.LIST:
        return L(LIST=md.ListType()), int(C.LIST), None
    if kind == K.MAP:
        return L(MAP=md.MapType()), int(C.MAP), None
    if kind == K.UNKNOWN:
        return L(UNKNOWN=md.NullType()), None, None
    unit_map = {
        "millis": md.TimeUnit(MILLIS=md.MilliSeconds()),
        "micros": md.TimeUnit(MICROS=md.MicroSeconds()),
        "nanos": md.TimeUnit(NANOS=md.NanoSeconds()),
    }
    if kind.startswith("time_"):
        unit = kind.split("_", 1)[1]
        utc = params.get("utc", True)
        ct = {"millis": int(C.TIME_MILLIS), "micros": int(C.TIME_MICROS)}.get(unit)
        return L(TIME=md.TimeType(isAdjustedToUTC=utc, unit=unit_map[unit])), ct, None
    if kind.startswith("timestamp_"):
        unit = kind.split("_", 1)[1]
        utc = params.get("utc", True)
        ct = {
            "millis": int(C.TIMESTAMP_MILLIS),
            "micros": int(C.TIMESTAMP_MICROS),
        }.get(unit)
        return L(TIMESTAMP=md.TimestampType(isAdjustedToUTC=utc, unit=unit_map[unit])), ct, None
    if kind == K.INT:
        bw = params.get("bit_width", 64)
        signed = params.get("signed", True)
        ct_map = {
            (8, True): C.INT_8, (16, True): C.INT_16, (32, True): C.INT_32,
            (64, True): C.INT_64, (8, False): C.UINT_8, (16, False): C.UINT_16,
            (32, False): C.UINT_32, (64, False): C.UINT_64,
        }
        ct = ct_map.get((bw, signed))
        return (
            L(INTEGER=md.IntType(bitWidth=bw, isSigned=signed)),
            int(ct) if ct is not None else None,
            None,
        )
    return None, None, None


# ---------------------------------------------------------------------------
# Builder helpers — the analog of the reference's parquet.Group{...} /
# Optional(...)/Repeated(...)/Required(...) node constructors (node.go).
# ---------------------------------------------------------------------------
def leaf(name: str, physical: Type, repetition: Rep = Rep.REQUIRED,
         logical: str = LogicalKind.NONE, type_length=None, **params) -> Node:
    return Node(name=name, repetition=repetition, physical_type=physical,
                type_length=type_length, logical_kind=logical, logical_params=params)


def group(name: str, children: List[Node], repetition: Rep = Rep.REQUIRED,
          logical: str = LogicalKind.NONE) -> Node:
    return Node(name=name, repetition=repetition, children=children,
                logical_kind=logical)


def optional(n: Node) -> Node:
    n.repetition = Rep.OPTIONAL
    return n


def repeated(n: Node) -> Node:
    n.repetition = Rep.REPEATED
    return n


def list_of(name: str, element: Node, repetition: Rep = Rep.OPTIONAL) -> Node:
    """Standard 3-level LIST structure: ``<name> (LIST) { repeated group list { element } }``."""
    element.name = "element"
    inner = Node(name="list", repetition=Rep.REPEATED, children=[element])
    return Node(name=name, repetition=repetition, children=[inner],
                logical_kind=LogicalKind.LIST)


def map_of(name: str, key: Node, value: Node, repetition: Rep = Rep.OPTIONAL) -> Node:
    key.name = "key"
    key.repetition = Rep.REQUIRED
    value.name = "value"
    inner = Node(name="key_value", repetition=Rep.REPEATED, children=[key, value])
    return Node(name=name, repetition=repetition, children=[inner],
                logical_kind=LogicalKind.MAP)


def message(name: str, children: List[Node]) -> Schema:
    return Schema(Node(name=name, children=children))
