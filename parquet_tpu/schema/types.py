"""Type system: physical + logical types over schema nodes.

Reference parity: ``types.go — Type, Int/Uint/String/Decimal/Date/Time/Timestamp/
UUID/Enum/JSON/BSON nodes`` and ``node.go — Node, Group, Optional/Repeated/
Required/List/Map`` (SURVEY.md §2.1).  TPU-first difference: every leaf maps to a
fixed-width numpy/JAX dtype plus (for BYTE_ARRAY) an Arrow-style values+offsets
pair, so decoded columns are flat device arrays, never Python objects.
"""

from __future__ import annotations

import numpy as np

from ..format import enums, metadata as md
from ..format.enums import ConvertedType, FieldRepetitionType, Type

__all__ = [
    "PHYSICAL_NP_DTYPE",
    "PHYSICAL_WIDTH",
    "LogicalKind",
    "logical_np_dtype",
    "node",
]

# numpy dtypes for fixed-width physical types.  BOOLEAN decodes to uint8 then
# bool; INT96 decodes to a (n, 3) int32 view; BYTE_ARRAY / FLBA are byte blobs.
PHYSICAL_NP_DTYPE = {
    Type.BOOLEAN: np.dtype(np.bool_),
    Type.INT32: np.dtype(np.int32),
    Type.INT64: np.dtype(np.int64),
    Type.FLOAT: np.dtype(np.float32),
    Type.DOUBLE: np.dtype(np.float64),
}

# byte width of one value, None for variable / bit-packed
PHYSICAL_WIDTH = {
    Type.BOOLEAN: None,  # bit-packed in PLAIN
    Type.INT32: 4,
    Type.INT64: 8,
    Type.INT96: 12,
    Type.FLOAT: 4,
    Type.DOUBLE: 8,
    Type.BYTE_ARRAY: None,
    Type.FIXED_LEN_BYTE_ARRAY: None,  # from type_length
}


class LogicalKind:
    """Normalized logical annotation for a leaf (new LogicalType and legacy
    ConvertedType collapse into one of these)."""

    NONE = "none"
    STRING = "string"
    ENUM = "enum"
    JSON = "json"
    BSON = "bson"
    UUID = "uuid"
    DECIMAL = "decimal"
    DATE = "date"
    TIME_MILLIS = "time_millis"
    TIME_MICROS = "time_micros"
    TIME_NANOS = "time_nanos"
    TIMESTAMP_MILLIS = "timestamp_millis"
    TIMESTAMP_MICROS = "timestamp_micros"
    TIMESTAMP_NANOS = "timestamp_nanos"
    INT = "int"  # carries bit_width / signed
    FLOAT16 = "float16"
    INTERVAL = "interval"
    LIST = "list"
    MAP = "map"
    UNKNOWN = "unknown"  # Null logical type (always-null column)


def _logical_from_element(el: md.SchemaElement):
    """Normalize SchemaElement.{logicalType, converted_type} → (kind, params)."""
    lt = el.logicalType
    if lt is not None:
        if lt.STRING is not None:
            return LogicalKind.STRING, {}
        if lt.ENUM is not None:
            return LogicalKind.ENUM, {}
        if lt.JSON is not None:
            return LogicalKind.JSON, {}
        if lt.BSON is not None:
            return LogicalKind.BSON, {}
        if lt.UUID is not None:
            return LogicalKind.UUID, {}
        if lt.FLOAT16 is not None:
            return LogicalKind.FLOAT16, {}
        if lt.DECIMAL is not None:
            return LogicalKind.DECIMAL, {
                "scale": lt.DECIMAL.scale or 0,
                "precision": lt.DECIMAL.precision or 0,
            }
        if lt.DATE is not None:
            return LogicalKind.DATE, {}
        if lt.TIME is not None:
            u = lt.TIME.unit
            if u.MILLIS is not None:
                return LogicalKind.TIME_MILLIS, {"utc": bool(lt.TIME.isAdjustedToUTC)}
            if u.MICROS is not None:
                return LogicalKind.TIME_MICROS, {"utc": bool(lt.TIME.isAdjustedToUTC)}
            return LogicalKind.TIME_NANOS, {"utc": bool(lt.TIME.isAdjustedToUTC)}
        if lt.TIMESTAMP is not None:
            u = lt.TIMESTAMP.unit
            utc = bool(lt.TIMESTAMP.isAdjustedToUTC)
            if u.MILLIS is not None:
                return LogicalKind.TIMESTAMP_MILLIS, {"utc": utc}
            if u.MICROS is not None:
                return LogicalKind.TIMESTAMP_MICROS, {"utc": utc}
            return LogicalKind.TIMESTAMP_NANOS, {"utc": utc}
        if lt.INTEGER is not None:
            return LogicalKind.INT, {
                "bit_width": lt.INTEGER.bitWidth or 64,
                "signed": bool(lt.INTEGER.isSigned),
            }
        if lt.LIST is not None:
            return LogicalKind.LIST, {}
        if lt.MAP is not None:
            return LogicalKind.MAP, {}
        if lt.UNKNOWN is not None:
            return LogicalKind.UNKNOWN, {}
    ct = el.converted_type
    if ct is None:
        return LogicalKind.NONE, {}
    C = ConvertedType
    table = {
        C.UTF8: (LogicalKind.STRING, {}),
        C.ENUM: (LogicalKind.ENUM, {}),
        C.JSON: (LogicalKind.JSON, {}),
        C.BSON: (LogicalKind.BSON, {}),
        C.DATE: (LogicalKind.DATE, {}),
        C.TIME_MILLIS: (LogicalKind.TIME_MILLIS, {"utc": True}),
        C.TIME_MICROS: (LogicalKind.TIME_MICROS, {"utc": True}),
        C.TIMESTAMP_MILLIS: (LogicalKind.TIMESTAMP_MILLIS, {"utc": True}),
        C.TIMESTAMP_MICROS: (LogicalKind.TIMESTAMP_MICROS, {"utc": True}),
        C.INTERVAL: (LogicalKind.INTERVAL, {}),
        C.LIST: (LogicalKind.LIST, {}),
        C.MAP: (LogicalKind.MAP, {}),
        C.DECIMAL: (
            LogicalKind.DECIMAL,
            {"scale": el.scale or 0, "precision": el.precision or 0},
        ),
    }
    if ct in table:
        return table[ct]
    if C.UINT_8 <= ct <= C.INT_64:
        signed = ct >= C.INT_8
        bit_width = {
            C.UINT_8: 8, C.UINT_16: 16, C.UINT_32: 32, C.UINT_64: 64,
            C.INT_8: 8, C.INT_16: 16, C.INT_32: 32, C.INT_64: 64,
        }[ct]
        return LogicalKind.INT, {"bit_width": bit_width, "signed": signed}
    return LogicalKind.NONE, {}


def logical_np_dtype(physical: Type, kind: str, params: dict, type_length=None):
    """The user-facing numpy dtype a decoded leaf column is presented as."""
    if physical == Type.INT32 and kind == LogicalKind.INT:
        bw, signed = params["bit_width"], params["signed"]
        return np.dtype(f"{'i' if signed else 'u'}{max(bw, 8) // 8}")
    if physical == Type.INT64 and kind == LogicalKind.INT:
        return np.dtype("i8" if params["signed"] else "u8")
    if kind == LogicalKind.FLOAT16:
        return np.dtype(np.float16)
    if physical in PHYSICAL_NP_DTYPE:
        return PHYSICAL_NP_DTYPE[physical]
    return None  # variable width: values+offsets or fixed blob


def node(el: md.SchemaElement):
    kind, params = _logical_from_element(el)
    return kind, params
