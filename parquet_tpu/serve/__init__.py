"""The serving daemon: ``python -m parquet_tpu serve --config serve.json``
or the programmatic :class:`Server` — multi-tenant QoS over lookups,
scans, aggregates, and writes (see serve/server.py for the full story).
"""

from .config import DatasetSpec, ServeConfig, load_config
from .server import Server

__all__ = ["Server", "ServeConfig", "DatasetSpec", "load_config"]
