"""The serving daemon: ``python -m parquet_tpu serve --config serve.json``
or the programmatic :class:`Server` — multi-tenant QoS over lookups,
scans, aggregates, and writes (see serve/server.py for the full story).
A ``cluster`` config turns N daemons into a shard-aware fleet
(consistent-hash routing, scatter-gather, commit arbitration — see
serve/cluster.py).
"""

from .cluster import FleetRouter, HashRing, shard_key, splitmix64
from .config import ClusterSpec, DatasetSpec, ServeConfig, load_config
from .server import Server

__all__ = ["Server", "ServeConfig", "DatasetSpec", "ClusterSpec",
           "load_config", "FleetRouter", "HashRing", "shard_key",
           "splitmix64"]
