"""Fleet layer for the serving daemon: consistent-hash routing,
scatter-gather with peer hedging, and cross-node commit arbitration.

A fleet is N daemons over shared storage, each booted with the SAME
``cluster`` config block (peer name → base URL).  Three mechanisms turn
them into one logical server:

- **Consistent-hash routing** — :class:`HashRing` places
  ``PARQUET_TPU_FLEET_VNODES`` virtual nodes per peer on a 64-bit ring.
  Point lookups route each key by the SAME splitmix64 finalizer
  ``dataset_writer._partition_ids`` shards part-files with, so a
  key-partitioned table's keys and its files hash consistently; scans
  and aggregates shard by file path.  Adding/removing a peer moves only
  the ring arcs it owned.
- **Scatter-gather** — :meth:`FleetRouter.gather` fans sub-requests to
  shard owners with a per-peer deadline carved from the request
  deadline (minus ``PARQUET_TPU_FLEET_MARGIN_S`` for the merge), hedges
  slow peers with a LOCAL execution of the shard after
  ``PARQUET_TPU_FLEET_HEDGE_S`` (unset → the adaptive p95 delay from
  :func:`~parquet_tpu.io.remote.hedge_delay_s`; storage is shared, so
  the local replica is always a valid hedge target), falls back to
  local execution when a peer fails outright, and — only when even the
  fallback fails — either skips the shard with accounting
  (``fleet.peer_skips``, surfaced in the response's fleet report) or
  fails fast when the caller demanded exactness.
- **Commit arbitration** — :meth:`FleetRouter.arbiter_resolver` routes
  each table's conditional manifest write (compare-and-swap on the
  manifest version) to the table's ring owner over ``/v1/fleet/commit``,
  making cross-node commit arbitration authoritative: two daemons
  ingesting one table converge through optimistic-concurrency abort at
  a single arbiter instead of racing the shared filesystem.

The peer transport is :class:`~parquet_tpu.io.remote.HttpTransport`
POSTs under the SAME per-host circuit breakers and failure
classification as remote preads (``breaker_for``/``classify_status``),
so a dead peer fails fast after ``PARQUET_TPU_REMOTE_BREAKER``
consecutive errors and heals through the half-open probe.  The chaos
hook (:func:`~parquet_tpu.io.faults.peer_chaos`) is consulted before
every sub-request.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.client import HTTPException
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..errors import (RemoteCircuitOpenError, RemoteError,
                      RemoteTransientError)
from ..io.faults import active_deadline, peer_chaos
from ..io.remote import (HttpTransport, breaker_for, classify_status,
                         gunzip_body, hedge_delay_s)
from ..obs.metrics import counter as _counter
from ..obs.scope import account as _account
from ..utils.env import env_float, env_int, env_opt_float
from ..utils.locks import make_lock
from .config import ClusterSpec

__all__ = ["splitmix64", "shard_key", "HashRing", "FleetRouter"]

_M_FORWARDS = _counter("fleet.forwards")
_M_GATHERS = _counter("fleet.gathers")
_M_PEER_ERRORS = _counter("fleet.peer_errors")
_M_LOCAL_FALLBACKS = _counter("fleet.local_fallbacks")
_M_HEDGES_ISSUED = _counter("fleet.hedges_issued")
_M_HEDGES_WON = _counter("fleet.hedges_won")
_M_PEER_SKIPS = _counter("fleet.peer_skips")

_MASK64 = (1 << 64) - 1


def splitmix64(x: int) -> int:
    """Scalar splitmix64 finalizer — bit-identical to the vectorized
    ``dataset_writer._partition_ids`` hash, so a key routes to the same
    ring arc the writer's key-partitioning spread it by."""
    x &= _MASK64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _MASK64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _MASK64
    x ^= x >> 31
    return x


def _fnv1a64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & _MASK64
    return h


def shard_key(value) -> int:
    """64-bit ring position for a routing key: ints go straight through
    splitmix64 (matching the writer's partitioner; NULL → 0 like
    ``_partition_ids``); strings/bytes (file paths, vnode labels) fold
    through FNV-1a first so text keys get avalanche too."""
    if value is None:
        value = 0
    if isinstance(value, bool):
        value = int(value)
    if isinstance(value, int):
        return splitmix64(value)
    if isinstance(value, float):
        # float keys route by their exact repr (NaN included) — the
        # same text a JSON round-trip preserves
        value = repr(value)
    if isinstance(value, str):
        value = value.encode("utf-8")
    if isinstance(value, (bytes, bytearray)):
        return splitmix64(_fnv1a64(bytes(value)))
    raise TypeError(f"unroutable shard key {value!r}")


class HashRing:
    """Consistent-hash ring over the fleet's peer names.  IMMUTABLE
    once built (membership is config; repointing a peer's URL does not
    move the ring), so lookups are lock-free."""

    def __init__(self, nodes, vnodes: Optional[int] = None):
        self.nodes: Tuple[str, ...] = tuple(sorted(set(nodes)))
        if not self.nodes:
            raise ValueError("hash ring needs at least one node")
        self.vnodes = (int(vnodes) if vnodes is not None
                       else max(env_int("PARQUET_TPU_FLEET_VNODES"), 1))
        points: List[Tuple[int, str]] = []
        for name in self.nodes:
            for v in range(self.vnodes):
                points.append((shard_key(f"{name}#{v}"), name))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]

    def owner(self, h: int) -> str:
        """The peer owning ring position ``h`` (first vnode clockwise)."""
        import bisect

        i = bisect.bisect_right(self._hashes, h & _MASK64)
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def owner_of_key(self, key) -> str:
        return self.owner(shard_key(key))

    def owner_of_path(self, path: str) -> str:
        return self.owner(shard_key(str(path)))

    def spread(self, items) -> Dict[str, list]:
        """Partition ``items`` (strings hashed as paths) by owner."""
        out: Dict[str, list] = {}
        for it in items:
            out.setdefault(self.owner_of_path(str(it)), []).append(it)
        return out


class _PeerDeadError(RemoteTransientError):
    """A peer sub-request that produced no result inside its carved
    deadline — same retryability class as a connection failure."""


class FleetRouter:
    """One daemon's view of the fleet: the ring, the peer transports,
    the gather engine, and the commit-arbiter resolver.  Owned by
    :class:`~parquet_tpu.serve.Server` when its config carries a
    ``cluster`` block."""

    def __init__(self, cluster: ClusterSpec,
                 tokens: Optional[Dict[str, str]] = None):
        self.spec = cluster
        self.self_name = cluster.self_name
        self.ring = HashRing(cluster.peers)
        self._lock = make_lock("serve.fleet")
        self._urls: Dict[str, Optional[str]] = dict(cluster.peers)
        self._transports: Dict[str, HttpTransport] = {}
        self._tokens = dict(tokens or {})

    # -- membership -------------------------------------------------------
    def set_peers(self, urls: Dict[str, str]) -> None:
        """Repoint peer base URLs (ephemeral-port boot: daemons bind
        first, then every member learns the realized addresses).  Only
        URLs move; ring membership is fixed by the config."""
        with self._lock:
            for name, url in urls.items():
                if name not in self._urls:
                    raise ValueError(f"unknown fleet peer {name!r}")
                old = self._transports.pop(name, None)
                if old is not None:
                    old.close()
                self._urls[name] = url or None

    def peer_url(self, name: str) -> Optional[str]:
        with self._lock:
            return self._urls.get(name)

    def is_self(self, name: str) -> bool:
        return name == self.self_name

    def peers(self) -> List[str]:
        return list(self.ring.nodes)

    def _transport(self, name: str, url: str) -> HttpTransport:
        with self._lock:
            t = self._transports.get(name)
            if t is None:
                t = self._transports[name] = HttpTransport(url)
            return t

    # -- peer protocol ----------------------------------------------------
    def post(self, peer: str, path: str, doc: dict,
             tenant: Optional[str] = None) -> dict:
        """One JSON sub-request to ``peer``: chaos hook → circuit
        breaker → POST with the fleet-internal marker (the receiver
        serves locally, and meters under the ORIGINAL tenant without
        re-charging its QPS bucket) → shared failure classification.
        Raises a :class:`~parquet_tpu.errors.RemoteError` subclass on
        any failure; the gather layer owns fallback policy."""
        url = self.peer_url(peer)
        if url is None:
            raise RemoteTransientError(
                f"fleet peer {peer!r} has no URL yet", host=peer,
                path=path)
        transport = self._transport(peer, url)
        host = transport.host
        breaker = breaker_for(host)
        if not breaker.allow():
            raise RemoteCircuitOpenError(
                f"circuit open for fleet peer {peer!r}", host=host,
                path=path)
        body = json.dumps(doc, sort_keys=True).encode("utf-8")
        headers = {"X-Fleet-Internal": "1"}
        if tenant:
            headers["X-Tenant"] = tenant
            tok = self._tokens.get(tenant)
            if tok:
                headers["Authorization"] = f"Bearer {tok}"
        _account(_M_FORWARDS)
        try:
            # the chaos hook raises ConnectionRefusedError inside the
            # breaker-counted window — a chaos-killed peer trips the
            # breaker exactly like a real refused connect
            chaos = peer_chaos()
            if chaos is not None:
                chaos.check(peer)
            status, hdrs, resp = transport.post(path, body, headers)
        except (HTTPException, socket.timeout, TimeoutError,
                OSError) as e:
            breaker.record_failure()
            raise RemoteTransientError(
                f"fleet peer {peer!r} unreachable: {e}", host=host,
                path=path) from e
        if status == 429:
            breaker.record_inconclusive()
        elif 500 <= status < 600:
            breaker.record_failure()
        else:
            breaker.record_success()
        classify_status(status, hdrs, host, path,
                        what=f"fleet sub-request to {peer!r}")
        if hdrs.get("content-encoding", "").lower() == "gzip":
            resp = gunzip_body(resp, host=host, path=path)
        try:
            return json.loads(resp.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            # a torn/garbled body is a connection artifact — retryable,
            # like a truncated gzip member
            raise RemoteTransientError(
                f"fleet peer {peer!r} sent an unparseable body: {e}",
                host=host, path=path) from e

    # -- scatter-gather ---------------------------------------------------
    def _per_peer_budget_s(self) -> float:
        dl = active_deadline()
        budget = env_float("PARQUET_TPU_FLEET_PEER_TIMEOUT_S")
        if dl is not None:
            left = dl.remaining()
            if left is not None:
                margin = env_float("PARQUET_TPU_FLEET_MARGIN_S")
                budget = min(budget, max(left - margin, 0.05))
        return budget

    def _hedge_delay_s(self, per_peer_s: float) -> Optional[float]:
        pinned = env_opt_float("PARQUET_TPU_FLEET_HEDGE_S")
        if pinned is not None:
            return pinned if pinned > 0 else None  # 0 disables
        adaptive = hedge_delay_s()  # p95-adaptive, shared with preads
        if adaptive is not None and adaptive > 0:
            return min(adaptive, per_peer_s * 0.5)
        return per_peer_s * 0.5

    def _run_one(self, peer: str, payload,
                 remote_call: Callable[[str, Any], Any],
                 local_call: Callable[[str, Any], Any],
                 per_peer_s: float) -> Tuple[str, Any, str]:
        """One shard: -> ("ok", result, via) or ("err", error, peer).
        ``via`` ∈ {"local", "peer", "hedge", "fallback"}."""
        if self.is_self(peer) or self.peer_url(peer) is None:
            try:
                return "ok", local_call(peer, payload), "local"
            except Exception as e:
                return "err", e, peer
        slot: List[Tuple[str, Any]] = []
        done = threading.Event()

        def _primary():
            try:
                slot.append(("ok", remote_call(peer, payload)))
            except Exception as e:
                slot.append(("err", e))
            finally:
                done.set()

        threading.Thread(target=_primary, name=f"pq-fleet-{peer}",
                         daemon=True).start()
        t0 = time.monotonic()
        hedge_slot: List[Tuple[str, Any]] = []
        hedge_done: Optional[threading.Event] = None
        hedge_s = self._hedge_delay_s(per_peer_s)
        if hedge_s is not None and hedge_s < per_peer_s:
            if not done.wait(hedge_s):
                # slow peer: race a local execution of its shard
                # (shared storage — the local replica is authoritative)
                _account(_M_HEDGES_ISSUED)
                hedge_done = threading.Event()

                def _hedge():
                    try:
                        hedge_slot.append(
                            ("ok", local_call(peer, payload)))
                    except Exception as e:
                        hedge_slot.append(("err", e))
                    finally:
                        hedge_done.set()

                threading.Thread(target=_hedge,
                                 name=f"pq-fleet-hedge-{peer}",
                                 daemon=True).start()
        while True:
            left = per_peer_s - (time.monotonic() - t0)
            if done.is_set() or left <= 0:
                break
            if hedge_done is not None and hedge_done.is_set() \
                    and hedge_slot and hedge_slot[0][0] == "ok":
                _account(_M_HEDGES_WON)
                return "ok", hedge_slot[0][1], "hedge"
            done.wait(min(left, 0.005))
        if done.is_set() and slot and slot[0][0] == "ok":
            return "ok", slot[0][1], "peer"
        # the peer failed or timed out
        _account(_M_PEER_ERRORS)
        if hedge_done is not None:
            left = per_peer_s - (time.monotonic() - t0)
            hedge_done.wait(max(left, 0.0) + 0.05)
            if hedge_slot and hedge_slot[0][0] == "ok":
                _account(_M_HEDGES_WON)
                return "ok", hedge_slot[0][1], "hedge"
        err = (slot[0][1] if slot and slot[0][0] == "err"
               else _PeerDeadError(
                   f"fleet peer {peer!r} produced no result in "
                   f"{per_peer_s:.3f}s", host=peer))
        try:
            result = local_call(peer, payload)
        except Exception:
            return "err", err, peer
        _account(_M_LOCAL_FALLBACKS)
        return "ok", result, "fallback"

    def gather(self, shards: Dict[str, Any],
               remote_call: Callable[[str, Any], Any],
               local_call: Callable[[str, Any], Any],
               exact: bool = False
               ) -> Tuple[Dict[str, Any], List[dict]]:
        """Scatter ``shards`` (peer → payload) and gather results:
        returns ``(results: peer → result, skips)``.  Each shard runs
        remote with hedged-local racing and local fallback
        (:meth:`_run_one`); a shard that still produced nothing is
        SKIPPED with accounting — unless ``exact``, where the first
        unservable shard raises (fail-fast, no partial answer)."""
        _account(_M_GATHERS)
        per_peer_s = self._per_peer_budget_s()
        order = sorted(shards)
        outs: Dict[str, Tuple[str, Any, str]] = {}
        threads = []

        def _drive(name, payload):
            outs[name] = self._run_one(name, payload, remote_call,
                                       local_call, per_peer_s)

        for name in order:
            t = threading.Thread(target=_drive,
                                 args=(name, shards[name]),
                                 name=f"pq-gather-{name}", daemon=True)
            threads.append(t)
            t.start()
        for t in threads:
            t.join(per_peer_s + 1.0)
        results: Dict[str, Any] = {}
        skips: List[dict] = []
        for name in order:
            got = outs.get(name)
            if got is None:
                got = ("err", _PeerDeadError(
                    f"gather thread for {name!r} never finished",
                    host=name), name)
            kind, value, via = got
            if kind == "ok":
                results[name] = value
                continue
            if exact:
                if isinstance(value, RemoteError):
                    raise value
                raise RemoteTransientError(
                    f"fleet shard {name!r} unservable: {value}",
                    host=name) from value
            _account(_M_PEER_SKIPS)
            skips.append({"peer": name, "error": f"{value}"})
        return results, skips

    # -- commit arbitration ----------------------------------------------
    def arbiter_resolver(self) -> Callable:
        """The resolver :func:`~parquet_tpu.io.manifest.
        set_commit_arbiter` installs: each table directory's conditional
        manifest write routes to its ring owner's ``/v1/fleet/commit``.
        Self-owned tables (and crash-harness commits carrying a
        ``sink_wrap``, which cannot cross a process) resolve to None —
        the local O_EXCL CAS."""
        import os

        def resolver(table_dir) -> Optional[Callable]:
            owner = self.ring.owner_of_path(
                os.path.abspath(os.fspath(table_dir)))
            if self.is_self(owner) or self.peer_url(owner) is None:
                return None

            def arbiter(td, expected_version, manifest, sink_wrap=None):
                from ..io.manifest import cas_commit_local

                if sink_wrap is not None:
                    return cas_commit_local(td, expected_version,
                                            manifest, sink_wrap)
                doc = {"table_dir": os.path.abspath(os.fspath(td)),
                       "expected_version": int(expected_version),
                       "manifest": manifest.serialize().decode("utf-8")}
                try:
                    got = self.post(owner, "/v1/fleet/commit", doc)
                except RemoteError:
                    # the arbiter is DEAD — shared storage is still
                    # there, and the O_EXCL claim file keeps the
                    # conditional write exclusive across processes
                    return cas_commit_local(td, expected_version,
                                            manifest, None)
                return bool(got.get("committed")), int(
                    got.get("version", 0))

            return arbiter

        return resolver

    # -- observability ----------------------------------------------------
    def debug(self) -> dict:
        with self._lock:
            urls = dict(self._urls)
        doc = {"self": self.self_name, "vnodes": self.ring.vnodes,
               "peers": {}}
        for name in self.ring.nodes:
            url = urls.get(name)
            entry: Dict[str, Any] = {"url": url,
                                     "self": self.is_self(name)}
            if url:
                from urllib.parse import urlsplit

                host = urlsplit(url).netloc
                if host:
                    entry["breaker"] = breaker_for(host).state
            doc["peers"][name] = entry
        return doc

    def close(self) -> None:
        with self._lock:
            transports = list(self._transports.values())
            self._transports.clear()
        for t in transports:
            t.close()
