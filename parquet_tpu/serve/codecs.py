"""Wire codecs for the serving daemon: JSON where-trees → prepared-able
``Expr``, aggregate spec strings → ``AggExpr``, and result
serialization (JSON values and Arrow IPC streams).

Shared with the CLI — ``python -m parquet_tpu aggregate --agg sum:v``
and ``POST /v1/aggregate {"aggs": ["sum:v"]}`` parse through the same
:func:`parse_agg_spec`, so the two front ends can never drift.

Where-tree wire format (one JSON object per node)::

    {"and": [node, ...]}            {"or": [node, ...]}
    {"not": node}
    {"col": "x", "ge": 1, "le": 5}  # inclusive range (either side open)
    {"col": "x", "eq": 7}           {"col": "s", "in": ["a", "b"]}
    {"col": "x", "null": true}      # is-null (false = is-not-null)

Values are JSON scalars; strings compare as utf-8 bytes (the predicate
normalizer's existing contract).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..algebra.aggregate import (AggExpr, avg, count, count_distinct,
                                 max_, min_, sum_, sum_sq, top_k,
                                 variance)
from ..algebra.expr import Expr, col

__all__ = ["expr_from_wire", "parse_agg_spec", "parse_aggs", "jsonable",
           "columns_to_jsonable", "lookup_to_jsonable",
           "columns_to_arrow_batch", "columns_to_arrow_ipc"]

_AGG_USAGE = ("count, count:COL, min:COL, max:COL, sum:COL, sum_sq:COL, "
              "avg:COL, var:COL, var:COL:sample, distinct:COL, top:COL:K")


def parse_agg_spec(spec: str) -> AggExpr:
    """One aggregate from its wire/CLI spelling (``sum:v``, ``count``,
    ``avg:v``, ``var:v[:sample]``, ``top:v:5``); clean ``ValueError`` on
    malformed specs."""
    parts = str(spec).split(":")
    kind = parts[0]
    if kind == "count":
        return count(parts[1] if len(parts) > 1 and parts[1] else None)
    if kind in ("min", "max", "sum", "sum_sq", "distinct", "avg", "var",
                "variance"):
        if len(parts) < 2 or not parts[1]:
            raise ValueError(f"--agg {spec!r} needs a column "
                             f"({_AGG_USAGE})")
        if kind in ("var", "variance"):
            sample = len(parts) > 2 and parts[2] == "sample"
            return variance(parts[1], sample=sample)
        fn = {"min": min_, "max": max_, "sum": sum_, "sum_sq": sum_sq,
              "distinct": count_distinct, "avg": avg}[kind]
        return fn(parts[1])
    if kind == "top":
        if len(parts) < 3 or not parts[1]:
            raise ValueError(f"--agg {spec!r} needs top:COL:K "
                             f"({_AGG_USAGE})")
        try:
            k = int(parts[2])
        except ValueError:
            raise ValueError(f"--agg {spec!r}: K must be an integer "
                             f"({_AGG_USAGE})") from None
        return top_k(parts[1], k)
    raise ValueError(f"unknown --agg spec {spec!r} ({_AGG_USAGE})")


def parse_aggs(specs: Sequence) -> List[AggExpr]:
    """A request's aggregate list: spec strings (or already-built
    ``AggExpr`` nodes, for programmatic callers)."""
    out = []
    for s in specs:
        out.append(s if isinstance(s, AggExpr) else parse_agg_spec(s))
    if not out:
        raise ValueError("aggs must name at least one aggregate "
                         f"({_AGG_USAGE})")
    return out


def expr_from_wire(node) -> Optional[Expr]:
    """A predicate tree from its JSON form (module docstring); ``None``
    stays None (no predicate)."""
    if node is None:
        return None
    if not isinstance(node, dict):
        raise ValueError(f"where node must be an object, got "
                         f"{type(node).__name__}")
    if "and" in node or "or" in node:
        key = "and" if "and" in node else "or"
        kids = node[key]
        if not isinstance(kids, list) or not kids:
            raise ValueError(f"'{key}' needs a non-empty list")
        exprs = [expr_from_wire(k) for k in kids]
        out = exprs[0]
        for e in exprs[1:]:
            out = (out & e) if key == "and" else (out | e)
        return out
    if "not" in node:
        return ~expr_from_wire(node["not"])
    path = node.get("col")
    if not path:
        raise ValueError(f"leaf node needs 'col': {node!r}")
    ops = set(node) - {"col"}
    if "null" in node:
        if ops != {"null"}:
            raise ValueError("'null' cannot combine with other ops")
        leaf = col(path).is_null()
        return leaf if node["null"] else ~leaf
    if "in" in node:
        if ops != {"in"}:
            raise ValueError("'in' cannot combine with other ops")
        vals = node["in"]
        if not isinstance(vals, list) or not vals:
            raise ValueError("'in' needs a non-empty value list")
        return col(path).isin(vals)
    if "eq" in node:
        if ops != {"eq"}:
            raise ValueError("'eq' cannot combine with other ops")
        return col(path) == node["eq"]
    if ops <= {"ge", "le"} and ops:
        return col(path).between(node.get("ge"), node.get("le"))
    raise ValueError(f"unknown predicate ops {sorted(ops)} on "
                     f"{path!r} (ge/le, eq, in, null)")


# ---------------------------------------------------------------------------
# result serialization
# ---------------------------------------------------------------------------


def jsonable(v):
    """One value as JSON: numpy scalars unwrap, bytes decode utf-8 with
    replacement (the wire is JSON text; binary-exact consumers use the
    Arrow IPC format instead), NaN/inf survive via python floats."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return v
    if isinstance(v, (bytes, bytearray, memoryview)):
        return bytes(v).decode("utf-8", "replace")
    if isinstance(v, (list, tuple)):
        return [jsonable(x) for x in v]
    item = getattr(v, "item", None)
    if item is not None:
        return jsonable(item())
    return str(v)


def _column_to_list(vals) -> list:
    if isinstance(vals, np.ma.MaskedArray):
        data = vals.filled(0).tolist()
        mask = np.ma.getmaskarray(vals)
        return [None if m else jsonable(d)
                for d, m in zip(data, mask.tolist())]
    if isinstance(vals, np.ndarray):
        return [jsonable(x) for x in vals.tolist()]
    return [jsonable(x) for x in vals]


def columns_to_jsonable(cols: Dict[str, object]) -> Dict[str, list]:
    """A scan result (``{column: values}``) as JSON lists: masked rows
    and BYTE_ARRAY ``None`` entries become JSON ``null``."""
    return {name: _column_to_list(vals) for name, vals in cols.items()}


def lookup_to_jsonable(res, keys) -> List[dict]:
    """A :class:`~parquet_tpu.io.lookup.LookupResult` as one JSON object
    per input key: ``{"key", "rows", "values": {col: [...]}}`` with
    values row-aligned to ``rows`` and nulls as JSON ``null``."""
    out = []
    for key, h in zip(keys, res.hits):
        values = {}
        for name, vals in h.values.items():
            valid = h.validity.get(name)
            lst = _column_to_list(vals)
            if valid is not None:
                lst = [None if not ok else v
                       for v, ok in zip(lst, np.asarray(valid, bool))]
            values[name] = lst
        out.append({"key": jsonable(key),
                    "rows": np.asarray(h.rows).tolist(),
                    "values": values})
    return out


def columns_to_arrow_batch(cols: Dict[str, object]):
    """One Arrow record batch from a scan result dict: masked numpy
    arrays carry their nulls, list-form columns (the scan's BYTE_ARRAY
    carrier) map to nullable binary — ALWAYS, even when the batch is
    empty or all-null, so every file of a multi-file stream produces
    the same schema (an inferred null-typed empty column would poison
    the IPC stream's locked schema for every later file)."""
    import pyarrow as pa

    arrays, names = [], []
    for name, vals in cols.items():
        names.append(name)
        if isinstance(vals, np.ma.MaskedArray):
            arrays.append(pa.array(vals.filled(0),
                                   mask=np.ma.getmaskarray(vals)))
        elif isinstance(vals, np.ndarray):
            arrays.append(pa.array(vals))
        else:
            arrays.append(pa.array(list(vals), type=pa.binary()))
    return pa.record_batch(arrays, names=names)


def columns_to_arrow_ipc(cols: Dict[str, object], sink) -> int:
    """Write one Arrow IPC stream containing a single record batch of
    ``cols`` into file-like ``sink``; returns the row count."""
    import pyarrow as pa
    import pyarrow.ipc

    batch = columns_to_arrow_batch(cols)
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return batch.num_rows
