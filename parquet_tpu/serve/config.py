"""Serving-daemon configuration: which datasets to host, which tenants
may query them, and each tenant's QoS contract.

The daemon is configured by one JSON document (``python -m parquet_tpu
serve --config serve.json``) or the equivalent dict handed to
:class:`~parquet_tpu.serve.Server` programmatically::

    {
      "host": "127.0.0.1",
      "port": 8818,
      "datasets": {
        "events":  {"paths": ["/data/events/*.parquet"]},
        "users":   {"table": "/data/users", "writable": true}
      },
      "tenants": {
        "online":  {"class": "latency", "weight": 2.0,
                    "budget_bytes": "64MiB", "pin_bytes": "8MiB"},
        "batch":   {"class": "bulk", "budget_bytes": "32MiB"}
      }
    }

- ``datasets`` — name → either ``paths`` (files/globs served as a
  read-only :class:`~parquet_tpu.dataset.Dataset`) or ``table`` (a
  DatasetWriter table directory, snapshot-opened; ``writable: true``
  additionally enables ``/v1/write`` ingest with manifest-atomic
  commits).
- ``tenants`` — name → QoS contract: priority ``class`` (``latency`` |
  ``default`` | ``bulk``), weighted-fair ``weight``, per-tenant
  ``budget_bytes`` clamp at the admission gate, and ``pin_bytes`` of
  page-cache hot-key pinning.  Requests carry their tenant in the
  ``X-Tenant`` header; unknown tenants ride the ``default`` contract
  (override it with a tenant literally named ``"default"``).

Byte sizes accept ints or the usual suffix strings (``"64MiB"``,
``"1GB"``); knob-backed settings (drain timeout, shed Retry-After, max
body) read their ``PARQUET_TPU_SERVE_*`` envs per call so operators can
repoint them live.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.env import env_bytes, env_float
from ..utils.pool import TenantSpec

__all__ = ["DatasetSpec", "ServeConfig", "ClusterSpec", "load_config",
           "parse_bytes", "drain_timeout_s", "shed_retry_after_s",
           "max_body_bytes"]

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([kmgt]?i?b?)\s*$", re.I)
_MULT = {"": 1, "b": 1,
         "k": 1000, "kb": 1000, "ki": 1024, "kib": 1024,
         "m": 1000 ** 2, "mb": 1000 ** 2, "mi": 1 << 20, "mib": 1 << 20,
         "g": 1000 ** 3, "gb": 1000 ** 3, "gi": 1 << 30, "gib": 1 << 30,
         "t": 1000 ** 4, "tb": 1000 ** 4, "ti": 1 << 40, "tib": 1 << 40}


def parse_bytes(v) -> Optional[int]:
    """``64 << 20`` from ``"64MiB"`` / ``"64MB"`` / ``67108864`` / None."""
    if v is None:
        return None
    if isinstance(v, bool):
        raise ValueError(f"byte size must be a number or string, got {v!r}")
    if isinstance(v, (int, float)):
        return int(v)
    m = _SIZE_RE.match(str(v))
    if not m:
        raise ValueError(f"unparseable byte size {v!r}")
    return int(float(m.group(1)) * _MULT[m.group(2).lower()])


def drain_timeout_s() -> float:
    """``PARQUET_TPU_SERVE_DRAIN_S``: seconds a graceful shutdown waits
    for in-flight requests before giving up (default 10)."""
    return env_float("PARQUET_TPU_SERVE_DRAIN_S")


def shed_retry_after_s() -> float:
    """``PARQUET_TPU_SERVE_RETRY_AFTER_S``: the ``Retry-After`` a shed
    429 advertises (default 1.0)."""
    return env_float("PARQUET_TPU_SERVE_RETRY_AFTER_S")


def max_body_bytes() -> int:
    """``PARQUET_TPU_SERVE_MAX_BODY``: request-body cap (default 64 MiB;
    a body over it is refused 413 before being read into memory)."""
    return env_bytes("PARQUET_TPU_SERVE_MAX_BODY")


@dataclass
class DatasetSpec:
    """One hosted dataset: ``paths`` (read-only file set) XOR ``table``
    (a snapshot-opened DatasetWriter table directory; ``writable``
    enables ``/v1/write``)."""

    name: str
    paths: Optional[List[str]] = None
    table: Optional[str] = None
    writable: bool = False
    sorting: Optional[str] = None  # /v1/write ingest sort key
    rows_per_file: int = 100_000

    def __post_init__(self):
        if (self.paths is None) == (self.table is None):
            raise ValueError(f"dataset {self.name!r} needs exactly one "
                             "of 'paths' or 'table'")
        if self.writable and self.table is None:
            raise ValueError(f"dataset {self.name!r}: only table-backed "
                             "datasets are writable")


@dataclass
class ClusterSpec:
    """Fleet membership: ``self_name`` (this daemon's entry in
    ``peers``) and ``peers`` (name → base URL, e.g. ``http://h1:8818``;
    an empty/None URL is a placeholder repointed later via
    :meth:`~parquet_tpu.serve.Server.set_peers` — the ephemeral-port
    boot sequence tests and check.sh use)."""

    self_name: str
    peers: Dict[str, Optional[str]] = field(default_factory=dict)

    def __post_init__(self):
        if self.self_name not in self.peers:
            raise ValueError(f"cluster 'self' {self.self_name!r} is not "
                             f"in peers {sorted(self.peers)}")
        if len(self.peers) < 1:
            raise ValueError("cluster needs at least one peer")

    @classmethod
    def from_dict(cls, doc: dict) -> "ClusterSpec":
        if not isinstance(doc, dict):
            raise ValueError("'cluster' must be an object")
        bad = set(doc) - {"self", "peers"}
        if bad:
            raise ValueError(f"cluster: unknown keys {sorted(bad)} "
                             f"(self, peers)")
        peers = doc.get("peers")
        if not isinstance(peers, dict) or not peers:
            raise ValueError("cluster 'peers' must be a non-empty "
                             "object of name -> base URL")
        for name, url in peers.items():
            if url is not None and not isinstance(url, str):
                raise ValueError(f"cluster peer {name!r}: URL must be a "
                                 f"string or null, got {url!r}")
        return cls(self_name=str(doc.get("self", "")),
                   peers={str(n): (u or None) for n, u in peers.items()})


# endpoint → the class a tenant without an explicit contract runs as:
# lookups and aggregates are the p99-sensitive surface, scans and writes
# the bulk one
DEFAULT_ENDPOINT_CLASS = {"lookup": "latency", "aggregate": "latency",
                          "scan": "bulk", "write": "bulk"}


@dataclass
class ServeConfig:
    """The parsed daemon configuration (see module docstring)."""

    host: str = "127.0.0.1"
    port: int = 8818
    datasets: Dict[str, DatasetSpec] = field(default_factory=dict)
    tenants: Dict[str, TenantSpec] = field(default_factory=dict)
    pin_bytes: Dict[str, int] = field(default_factory=dict)
    tokens: Dict[str, str] = field(default_factory=dict)
    compact_interval_s: Optional[float] = None
    cluster: Optional[ClusterSpec] = None

    @classmethod
    def from_dict(cls, doc: dict) -> "ServeConfig":
        if not isinstance(doc, dict):
            raise ValueError("serve config must be a JSON object")
        unknown = set(doc) - {"host", "port", "datasets", "tenants",
                              "compact_interval_s", "cluster"}
        if unknown:
            raise ValueError(f"unknown serve config keys: "
                             f"{sorted(unknown)}")
        datasets: Dict[str, DatasetSpec] = {}
        for name, d in (doc.get("datasets") or {}).items():
            if not isinstance(d, dict):
                raise ValueError(f"dataset {name!r} must be an object")
            bad = set(d) - {"paths", "table", "writable", "sorting",
                            "rows_per_file"}
            if bad:
                raise ValueError(f"dataset {name!r}: unknown keys "
                                 f"{sorted(bad)}")
            paths = d.get("paths")
            if isinstance(paths, str):
                paths = [paths]
            datasets[name] = DatasetSpec(
                name=name, paths=paths, table=d.get("table"),
                writable=bool(d.get("writable", False)),
                sorting=d.get("sorting"),
                rows_per_file=int(d.get("rows_per_file", 100_000)))
        tenants: Dict[str, TenantSpec] = {}
        pins: Dict[str, int] = {}
        tokens: Dict[str, str] = {}
        for name, t in (doc.get("tenants") or {}).items():
            if not isinstance(t, dict):
                raise ValueError(f"tenant {name!r} must be an object")
            bad = set(t) - {"class", "weight", "budget_bytes",
                            "pin_bytes", "token", "qps", "burst"}
            if bad:
                # a typo'd QoS key silently dropping a tenant's budget
                # would be the OPPOSITE of the operator's intent
                raise ValueError(f"tenant {name!r}: unknown keys "
                                 f"{sorted(bad)} (class, weight, "
                                 f"budget_bytes, pin_bytes, token, "
                                 f"qps, burst)")
            klass = t.get("class", "default")
            if klass not in ("latency", "default", "bulk"):
                raise ValueError(f"tenant {name!r}: unknown class "
                                 f"{klass!r} (latency|default|bulk)")
            qps = t.get("qps")
            burst = t.get("burst")
            tenants[name] = TenantSpec(
                name=name,
                budget_bytes=parse_bytes(t.get("budget_bytes")),
                weight=float(t.get("weight", 1.0)),
                klass=klass,
                qps=float(qps) if qps is not None else None,
                burst=float(burst) if burst is not None else None)
            pin = parse_bytes(t.get("pin_bytes"))
            if pin:
                pins[name] = pin
            tok = t.get("token")
            if tok is not None:
                if not isinstance(tok, str) or not tok:
                    raise ValueError(f"tenant {name!r}: token must be a "
                                     f"non-empty string")
                tokens[name] = tok
        if not datasets:
            raise ValueError("serve config hosts no datasets")
        ci = doc.get("compact_interval_s")
        cluster = doc.get("cluster")
        return cls(host=str(doc.get("host", "127.0.0.1")),
                   port=int(doc.get("port", 8818)),
                   datasets=datasets, tenants=tenants, pin_bytes=pins,
                   tokens=tokens,
                   compact_interval_s=float(ci) if ci else None,
                   cluster=(ClusterSpec.from_dict(cluster)
                            if cluster is not None else None))

    def tenant(self, name: str) -> Optional[TenantSpec]:
        return self.tenants.get(name)

    def klass_for(self, tenant: Optional[str], endpoint: str) -> str:
        """The priority class a request runs as: the tenant's declared
        class when it has a contract, else the endpoint's natural class
        (lookup/aggregate → latency, scan/write → bulk)."""
        spec = self.tenants.get(tenant) if tenant else None
        if spec is not None:
            return spec.klass
        return DEFAULT_ENDPOINT_CLASS.get(endpoint, "default")


def load_config(path: str) -> ServeConfig:
    """Parse a ``serve.json`` into a :class:`ServeConfig` (clean
    ``ValueError`` on malformed documents — the CLI renders it as a
    one-line error, not a traceback)."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except ValueError as e:
            raise ValueError(f"{path}: not valid JSON ({e})") from e
    return ServeConfig.from_dict(doc)
